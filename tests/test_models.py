"""Model zoo: every family builds, jits, and returns finite logits of the
right shape (scaled-down dims so CPU tests stay fast)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.models.registry import build_model, init_params
from colearn_federated_learning_tpu.utils.config import ModelConfig


CASES = [
    (ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2), (4, 28, 28, 1)),
    (ModelConfig(name="cnn", num_classes=10, width=16), (4, 32, 32, 3)),
    (ModelConfig(name="resnet18", num_classes=100), (2, 32, 32, 3)),
    (ModelConfig(name="bert", num_classes=4, width=64, depth=2, num_heads=4,
                 seq_len=32, vocab_size=1000), (2, 32)),
    (ModelConfig(name="vit_b16", num_classes=62, width=64, depth=2, num_heads=4,
                 patch_size=16), (2, 28, 28, 1)),
]


@pytest.mark.parametrize("cfg,shape", CASES, ids=[c.name for c, _ in CASES])
def test_model_forward_shapes(cfg, shape):
    model = build_model(cfg)
    if cfg.name == "bert":
        x = jnp.asarray(np.random.default_rng(0).integers(0, 1000, size=shape), jnp.int32)
    else:
        x = jnp.asarray(np.random.default_rng(0).normal(size=shape), jnp.float32)
    params = init_params(model, x, jax.random.PRNGKey(0))
    logits = jax.jit(lambda p, x: model.apply({"params": p}, x, train=True))(params, x)
    assert logits.shape == (shape[0], cfg.num_classes)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_bert_padding_mask_invariance():
    """Padding tokens (id 0) must not change the pooled prediction: the same
    8-token content padded to length 12 and to length 16 must agree."""
    cfg = ModelConfig(name="bert", num_classes=4, width=32, depth=1, num_heads=2,
                      seq_len=16, vocab_size=100)
    model = build_model(cfg)
    rng = np.random.default_rng(1)
    content = rng.integers(1, 100, 8)
    ids16 = np.zeros((1, 16), np.int32)
    ids16[0, :8] = content
    ids12 = np.zeros((1, 12), np.int32)
    ids12[0, :8] = content
    params = init_params(model, jnp.asarray(ids16), jax.random.PRNGKey(0))
    out16 = model.apply({"params": params}, jnp.asarray(ids16))
    out12 = model.apply({"params": params}, jnp.asarray(ids12))
    np.testing.assert_allclose(np.asarray(out16), np.asarray(out12),
                               rtol=1e-4, atol=1e-5)


def test_space_to_depth_oracle():
    from colearn_federated_learning_tpu.models.cnn import space_to_depth

    x = jnp.arange(2 * 4 * 4 * 3, dtype=jnp.float32).reshape(2, 4, 4, 3)
    y = space_to_depth(x, 2)
    assert y.shape == (2, 2, 2, 12)
    # Block (0,0) of image 0: pixels (0,0),(0,1),(1,0),(1,1) channel-major.
    expect = np.concatenate([np.asarray(x[0, i, j]) for i in (0, 1)
                             for j in (0, 1)])
    np.testing.assert_array_equal(np.asarray(y[0, 0, 0]), expect)
    # Lossless: every input value appears exactly once.
    np.testing.assert_array_equal(np.sort(np.asarray(y).ravel()),
                                  np.sort(np.asarray(x).ravel()))


@pytest.mark.parametrize("stem,norm", [("space_to_depth", "group"),
                                       ("conv", "none")])
def test_cnn_mfu_variants_forward_and_learn(stem, norm):
    # The MFU levers must preserve the contract: right logits shape and a
    # trainable model (loss decreases on a tiny separable problem).
    import optax

    cfg = ModelConfig(name="cnn", num_classes=4, width=8, stem=stem,
                      norm=norm)
    model = build_model(cfg)
    rng = np.random.default_rng(0)
    y = rng.integers(0, 4, 64)
    x = 0.1 * rng.normal(size=(64, 32, 32, 3))
    for i, yi in enumerate(y):             # class-coded bright square
        x[i, 4 * yi: 4 * yi + 4, :4, :] += 2.0
    x, y = jnp.asarray(x, jnp.float32), jnp.asarray(y)
    params = init_params(model, x[:2], jax.random.PRNGKey(0))
    logits = model.apply({"params": params}, x[:2], train=True)
    assert logits.shape == (2, 4)

    opt = optax.adam(1e-2)
    state = opt.init(params)

    def loss_fn(p):
        lg = model.apply({"params": p}, x, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(lg, y).mean()

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(loss_fn)(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    first = None
    for _ in range(30):
        params, state, l = step(params, state)
        first = first if first is not None else float(l)
    assert float(l) < 0.5 * first, (first, float(l))


def test_bfloat16_models_emit_float32_logits():
    cfg = ModelConfig(name="cnn", num_classes=10, width=16, dtype="bfloat16")
    model = build_model(cfg)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = init_params(model, x, jax.random.PRNGKey(0))
    logits = model.apply({"params": params}, x)
    assert logits.dtype == jnp.float32
    # Params stay float32 master copies.
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(params))
