"""telemetry/arrival.py: the seeded-EWMA arrival-rate estimator that
drives auto-K buffer sizing and the per-device straggler attribution
feeds.  Everything here runs on a caller-supplied clock — no time.time()
— so the tests pin exact rates, not sleeps."""

import pytest

from colearn_federated_learning_tpu.telemetry.arrival import (
    ArrivalEstimator,
)
from colearn_federated_learning_tpu.telemetry.registry import (
    MetricsRegistry,
)


def test_alpha_must_be_in_unit_interval():
    for bad in (0.0, -0.1, 1.5):
        with pytest.raises(ValueError, match="alpha"):
            ArrivalEstimator(alpha=bad)
    ArrivalEstimator(alpha=1.0)        # boundary is legal: no smoothing


def test_first_gap_seeds_the_ewma_directly():
    est = ArrivalEstimator(alpha=0.3)
    assert est.rate() == 0.0           # no arrivals yet
    est.observe(now=10.0)
    assert est.rate() == 0.0           # one arrival: no gap yet
    est.observe(now=12.0)
    # The first 2-unit gap SEEDS the EWMA (rate = 1/2), it is not
    # blended against a zero init — the whole point of the seeding.
    assert est.rate() == pytest.approx(0.5)
    assert est.count == 2


def test_later_gaps_blend_with_alpha():
    est = ArrivalEstimator(alpha=0.5)
    for t in (0.0, 2.0, 6.0):          # gaps 2 then 4
        est.observe(now=t)
    # gap_ewma = 0.5*4 + 0.5*2 = 3 -> rate 1/3
    assert est.rate() == pytest.approx(1.0 / 3.0)


def test_per_device_streams_are_independent_of_the_fleet():
    est = ArrivalEstimator()
    # Two devices interleaved: fleet sees gap 1, each device gap 2.
    for t, dev in ((0.0, "a"), (1.0, "b"), (2.0, "a"), (3.0, "b")):
        est.observe(dev, now=t)
    assert est.rate() == pytest.approx(1.0)
    assert est.device_rate("a") == pytest.approx(0.5)
    assert est.device_rates() == {
        "a": pytest.approx(0.5), "b": pytest.approx(0.5)}
    assert est.device_rate("missing") == 0.0


def test_recommend_buffer_is_rate_times_target_clamped():
    est = ArrivalEstimator()
    est.observe(now=0.0)
    est.observe(now=0.5)               # rate 2/unit
    assert est.recommend_buffer(10.0) == 20
    assert est.recommend_buffer(10.0, hi=8) == 8
    assert est.recommend_buffer(0.1, lo=4) == 4


def test_recommend_buffer_cold_fallback_holds_current():
    est = ArrivalEstimator()
    # Cold estimator: keep the caller's K (never yank the buffer around
    # before there is a measurement), or lo if the caller has none.
    assert est.recommend_buffer(10.0, current=6) == 6
    assert est.recommend_buffer(10.0, lo=2) == 2
    est.observe(now=0.0)               # still cold: one arrival, no gap
    assert est.recommend_buffer(10.0, current=6) == 6


def test_export_gauges_sets_fleet_and_top_device_children():
    est = ArrivalEstimator()
    for t, dev in ((0.0, "fast"), (0.0, "slow"),
                   (1.0, "fast"), (10.0, "slow")):
        est.observe(dev, now=t)
    reg = MetricsRegistry()
    est.export_gauges(reg, "async.arrival_rate_per_s", top=1)
    snap = reg.snapshot()
    assert snap["async.arrival_rate_per_s"] > 0.0       # fleet gauge
    # top=1 keeps only the fastest device's labeled child.
    assert "async.arrival_rate_per_s{device=fast}" in snap
    assert "async.arrival_rate_per_s{device=slow}" not in snap


def test_snapshot_is_json_safe_and_complete():
    est = ArrivalEstimator()
    est.observe("d0", now=0.0)
    est.observe("d0", now=4.0)
    snap = est.snapshot()
    assert snap["count"] == 2
    assert snap["rate"] == pytest.approx(0.25)
    assert snap["devices"]["d0"] == pytest.approx(0.25)
