"""IoT traffic TCN family (models/tcn.py + data iot_traffic).

The reference's real task domain — network-anomaly detection on IoT
traffic (SURVEY.md §0) — as a federated temporal conv net.
"""

import numpy as np
from jax.sharding import Mesh

from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
    get_config,
)


def _cfg():
    return ExperimentConfig(
        data=DataConfig(dataset="iot_traffic_tiny", num_clients=8,
                        partition="dirichlet", dirichlet_alpha=0.3,
                        max_examples_per_client=64),
        model=ModelConfig(name="tcn", num_classes=8, width=16, depth=3),
        fed=FedConfig(strategy="fedavg", rounds=6, cohort_size=0,
                      local_steps=3, batch_size=16, lr=0.05, momentum=0.9),
        run=RunConfig(name="tcn_test"),
    )


def test_traffic_dataset_shapes_and_structure():
    ds = data_registry.get_dataset("iot_traffic_tiny")
    assert ds.x_train.shape == (2000, 64, 16)
    assert ds.x_train.dtype == np.float32
    assert set(np.unique(ds.y_train)) <= set(range(8))
    # Class-conditional structure: same-class windows correlate more than
    # cross-class ones (what the TCN is supposed to exploit).
    x, y = ds.x_train, ds.y_train
    a = x[y == 0][:20].reshape(20, -1)
    b = x[y == 1][:20].reshape(20, -1)
    within = np.corrcoef(a)[np.triu_indices(20, 1)].mean()
    across = np.corrcoef(np.concatenate([a[:10], b[:10]]))[:10, 10:].mean()
    assert within > across + 0.05


def test_tcn_federated_training_learns():
    learner = FederatedLearner(_cfg())
    learner.fit(rounds=10)
    _, acc = learner.evaluate()
    assert acc > 0.5, acc          # 8-class chance = 0.125 (0.62 measured)


def test_tcn_mesh_matches_vmap(cpu_devices):
    cfg = _cfg()
    ref = FederatedLearner(cfg)
    m = FederatedLearner(cfg, mesh=Mesh(np.array(cpu_devices[:8]),
                                        ("clients",)))
    r_ref = ref.run_round()
    r_m = m.run_round()
    np.testing.assert_allclose(r_m["train_loss"], r_ref["train_loss"],
                               rtol=1e-5)


def test_iot_config_registered():
    cfg = get_config("iot_traffic_tcn_fedavg")
    assert cfg.model.name == "tcn" and cfg.data.dataset == "iot_traffic"
