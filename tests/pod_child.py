"""Child process for tests/test_pod_shape.py: a cohort-N federated round
over a D-device virtual CPU mesh (D beyond the conftest's 8).

Usage: python pod_child.py <n_devices> <cohort> <num_clients>

Prints ``POD <json>`` with the round metrics the parent asserts on.  Runs
in its own process because the virtual device count is fixed at backend
init — the test suite's 8-device platform can't grow to 16+ in-process.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    n_devices, cohort, num_clients = (int(a) for a in sys.argv[1:4])

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices} "
        "--xla_backend_optimization_level=0"
    )
    import jax
    import numpy as np
    from jax.sharding import Mesh

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_prng_impl", "unsafe_rbg")

    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    devices = jax.devices()
    assert len(devices) >= n_devices, devices
    mesh = Mesh(np.array(devices[:n_devices]), ("clients",))
    # mnist_tiny has 2,000 train rows: Dirichlet can't guarantee every
    # client >= 1 example past a few hundred clients, so large-N runs
    # (the cohort-256 / 32-device shape) deal IID instead.
    partition = "dirichlet" if num_clients <= 200 else "iid"
    config = ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition=partition, dirichlet_alpha=0.5,
                        max_examples_per_client=16),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=1),
        fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=cohort,
                      local_steps=1, batch_size=4, lr=0.1, momentum=0.9),
        run=RunConfig(name="pod_child"),
    )
    learner = FederatedLearner(config, mesh=mesh)
    hist = learner.fit(rounds=2)
    out = {
        "n_devices": n_devices,
        "num_clients": learner.num_clients,
        "cohort_per_device": learner.cohort_per_device,
        "completed": [int(r["completed"]) for r in hist],
        "train_loss": [float(r["train_loss"]) for r in hist],
        "total_weight": [float(r["total_weight"]) for r in hist],
    }
    print("POD", json.dumps(out))


if __name__ == "__main__":
    main()
