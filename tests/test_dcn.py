"""Multi-host (DCN) hybrid mesh layout (parallel/mesh.py).

The v5e-256 extrapolation leans on the hybrid-mesh branch; until round 4
it was unreachable in every test (``jax.process_count() == 1`` always).
Here it executes for real: two local processes x 4 virtual CPU devices
via ``jax.distributed.initialize``, one cross-process psum, and one full
engine round over the hybrid mesh — plus unit coverage of the
process-granule axis placement (``process_is_granule=True``, needed
because single-slice pods and CPU processes share one ``slice_index``).
"""

import socket
import subprocess
import sys
import os

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from colearn_federated_learning_tpu.parallel import mesh as mesh_lib


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# jax.distributed.initialize failures that mean "the loopback rendezvous
# never formed" (port stolen between _free_port and bind, coordination
# service timeout) — NOT an engine/mesh regression.  Only these retry.
_BOOTSTRAP_SIGNS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Address already in use",
    "Connection refused",
    "Failed to connect",
    "coordination service",
    "barrier timed out",
)

# Capability gaps in the installed jaxlib (older CPU backends reject
# cross-process collectives outright) — deterministic skip, no retry.
_UNSUPPORTED_SIGNS = (
    "Multiprocess computations aren't implemented",
)


class _Unsupported(Exception):
    pass


def _spawn_children(port: int) -> list[str]:
    """Run both DCN children against ``port``; returns their outputs.
    Raises AssertionError on a real (non-bootstrap) child failure and
    ConnectionError when the failure looks like the flaky rendezvous."""
    child = os.path.join(os.path.dirname(__file__), "dcn_child.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen([sys.executable, child, str(i), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env)
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
                raise ConnectionError(
                    f"DCN child hung (bootstrap stall): {out[-800:]}")
            outs.append(out)
            if p.returncode != 0:
                tail = out[-1500:]
                if any(sig in out for sig in _UNSUPPORTED_SIGNS):
                    raise _Unsupported(tail)
                if any(sig.lower() in out.lower()
                       for sig in _BOOTSTRAP_SIGNS):
                    raise ConnectionError(f"DCN bootstrap failed: {tail}")
                raise AssertionError(tail)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def test_two_process_hybrid_mesh_round():
    # Real 2-process distributed JAX.  Each child builds the hybrid mesh,
    # psums across the process boundary, and runs one engine round; the
    # parent checks layout, collective math, and cross-process agreement
    # against a single-process 8-device reference.  The loopback
    # rendezvous is flaky under containerized networking, so the
    # bootstrap gets a bounded retry on a FRESH port; three consecutive
    # bootstrap failures skip deterministically (the single-process mesh
    # paths this composes are covered by test_mesh_engine/test_tp), while
    # any in-round failure still fails immediately.
    outs = None
    for attempt in range(3):
        try:
            outs = _spawn_children(_free_port())
            break
        except _Unsupported as exc:
            pytest.skip("installed jaxlib rejects multiprocess CPU "
                        f"collectives: {str(exc)[-300:]}")
        except ConnectionError as exc:
            last = exc
    if outs is None:
        pytest.skip(f"2-process DCN bootstrap failed 3x on loopback: {last}")

    def field(out, tag):
        lines = [l for l in out.splitlines() if f" {tag} " in l]
        assert lines, (tag, out[-800:])
        return lines[-1].split(f" {tag} ")[1]

    for out in outs:
        # DCN layout: the first (client) axis is PROCESS-MAJOR — one
        # contiguous block per host, so per-host traffic stays on "ICI".
        assert field(out, "MESHLAYOUT") == "0,0,0,0,1,1,1,1"
        assert float(field(out, "PSUM")) == 28.0  # sum(0..7) across hosts

    losses = [float(field(out, "ROUND")) for out in outs]
    assert losses[0] == losses[1]

    # Placement independence: the same round on a single-process 8-device
    # mesh (the conftest virtual platform) produces the same loss.
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=32),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=0,
                      local_steps=2, batch_size=8, lr=0.1, momentum=0.9),
        run=RunConfig(name="dcn_test", backend="cpu"),
    )
    ref = FederatedLearner(
        cfg, mesh=Mesh(np.array(jax.devices()[:8]), ("clients",)))
    rec = ref.run_round()
    np.testing.assert_allclose(losses[0], rec["train_loss"], rtol=1e-6)


def test_hybrid_layout_without_slice_index(monkeypatch):
    # Devices without the TPU-only slice_index attribute (CPU
    # multi-process, single-slice pods) must still get the process-major
    # first axis (process_is_granule=True grouping).
    monkeypatch.setattr(jax, "process_count", lambda: 2)

    class Dev:
        device_kind = "cpu"
        platform = "cpu"

        def __init__(self, pid, did):
            self.process_index, self.id = pid, did

        def __repr__(self):
            return f"d{self.process_index}.{self.id}"

    # Shuffled input order: grouping is by process, regardless of the
    # order devices arrive in (within-host order is the granule's own —
    # physical topology on real TPUs).
    devs = [Dev(p, i) for p in (1, 0) for i in (3, 1, 2, 0)]
    mesh = mesh_lib.make_mesh(("clients",), devices=devs)
    got = [d.process_index for d in mesh.devices.ravel()]
    assert got == [0, 0, 0, 0, 1, 1, 1, 1]
    assert {d.id for d in mesh.devices.ravel()[:4]} == {0, 1, 2, 3}

    # 2-D: the trailing (seq) axis stays inside a host.
    mesh2 = mesh_lib.make_mesh(("clients", "seq"), (2, 4), devices=devs)
    arr = mesh2.devices
    assert arr.shape == (2, 4)
    for row in arr:
        assert len({d.process_index for d in row}) == 1

    # Non-divisible first axis: falls back to the plain reshape.
    mesh3 = mesh_lib.make_mesh(("clients",), devices=devs[:7])
    assert mesh3.devices.shape == (7,)


def test_hybrid_layout_uses_all_processes_blockwise(monkeypatch):
    # sizes[0]=8 over 4 "hosts" of 2: each host owns a contiguous block
    # of 2 positions on the DCN axis.
    monkeypatch.setattr(jax, "process_count", lambda: 4)

    class Dev:
        device_kind = "cpu"
        platform = "cpu"

        def __init__(self, pid, did):
            self.process_index, self.id = pid, did

    devs = [Dev(p, i) for p in range(4) for i in range(2)]
    mesh = mesh_lib.make_mesh(("clients",), devices=devs)
    got = [d.process_index for d in mesh.devices.ravel()]
    assert got == [0, 0, 1, 1, 2, 2, 3, 3]
