"""Integration: the minimum end-to-end slice from SURVEY.md §7 — MNIST-shape
data, 2-layer MLP, 10 simulated clients on one device via vmap, FedAvg
in-XLA, accuracy rising across rounds (BASELINE config #1 scaled down)."""

import dataclasses

import numpy as np

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def tiny_config(**fed_kw) -> ExperimentConfig:
    fed = dict(strategy="fedavg", rounds=4, local_epochs=1, batch_size=32,
               lr=0.05, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=10, partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="test", seed=0),
    )


def test_mnist_mlp_end_to_end_accuracy_rises():
    learner = FederatedLearner(tiny_config(rounds=8))
    _, acc0 = learner.evaluate()
    history = learner.fit(rounds=8)
    _, acc1 = learner.evaluate()
    assert len(history) == 8
    assert np.isfinite(history[-1]["train_loss"])
    assert acc1 > acc0 + 0.2, (acc0, acc1)
    assert acc1 > 0.5


def test_cohort_sampling_runs_and_learns():
    learner = FederatedLearner(tiny_config(cohort_size=4, rounds=6))
    assert learner.cohort_size == 4
    learner.fit(rounds=6)
    _, acc = learner.evaluate()
    assert acc > 0.4


def test_fedprox_and_server_opt_strategies_run():
    for strat, kw in [("fedprox", {"prox_mu": 0.01}),
                      ("fedadam", {"server_lr": 0.05}),
                      ("fedyogi", {"server_lr": 0.05})]:
        learner = FederatedLearner(tiny_config(strategy=strat, rounds=2, **kw))
        hist = learner.fit(rounds=2)
        assert np.isfinite(hist[-1]["train_loss"]), strat


def test_straggler_dropout_reduces_completed():
    cfg = tiny_config(rounds=1, straggler_prob=0.9, straggler_min_fraction=0.9)
    learner = FederatedLearner(cfg)
    rec = learner.run_round()
    assert rec["completed"] < 10  # most clients failed to finish
    assert np.isfinite(rec["train_loss"])


def test_determinism_same_seed_same_result():
    cfg = tiny_config(rounds=2)
    l1 = FederatedLearner(cfg)
    l2 = FederatedLearner(cfg)
    l1.fit(rounds=2)
    l2.fit(rounds=2)
    a1 = l1.evaluate()
    a2 = l2.evaluate()
    assert a1 == a2


def test_weighted_aggregation_respects_counts():
    # Clients with zero weight (ghosts) must not affect the average: run a
    # learner where every client's data is identical; aggregation must be
    # finite and the history well-formed.
    cfg = tiny_config(rounds=1)
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(cfg.data, num_clients=3)
    )
    learner = FederatedLearner(cfg)
    rec = learner.run_round()
    assert rec["total_weight"] > 0
