"""Integration: the minimum end-to-end slice from SURVEY.md §7 — MNIST-shape
data, 2-layer MLP, 10 simulated clients on one device via vmap, FedAvg
in-XLA, accuracy rising across rounds (BASELINE config #1 scaled down)."""

import dataclasses

import numpy as np

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def tiny_config(**fed_kw) -> ExperimentConfig:
    fed = dict(strategy="fedavg", rounds=4, local_epochs=1, batch_size=32,
               lr=0.05, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=10, partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="test", seed=0),
    )


def test_mnist_mlp_end_to_end_accuracy_rises():
    learner = FederatedLearner(tiny_config(rounds=8))
    _, acc0 = learner.evaluate()
    history = learner.fit(rounds=8)
    _, acc1 = learner.evaluate()
    assert len(history) == 8
    assert np.isfinite(history[-1]["train_loss"])
    assert acc1 > acc0 + 0.2, (acc0, acc1)
    assert acc1 > 0.5


def test_cohort_sampling_runs_and_learns():
    learner = FederatedLearner(tiny_config(cohort_size=4, rounds=6))
    assert learner.cohort_size == 4
    learner.fit(rounds=6)
    _, acc = learner.evaluate()
    assert acc > 0.4


def test_fedprox_and_server_opt_strategies_run():
    for strat, kw in [("fedprox", {"prox_mu": 0.01}),
                      ("fedadam", {"server_lr": 0.05}),
                      ("fedyogi", {"server_lr": 0.05})]:
        learner = FederatedLearner(tiny_config(strategy=strat, rounds=2, **kw))
        hist = learner.fit(rounds=2)
        assert np.isfinite(hist[-1]["train_loss"]), strat


def test_straggler_dropout_reduces_completed():
    cfg = tiny_config(rounds=1, straggler_prob=0.9, straggler_min_fraction=0.9)
    learner = FederatedLearner(cfg)
    rec = learner.run_round()
    assert rec["completed"] < 10  # most clients failed to finish
    assert np.isfinite(rec["train_loss"])


def test_determinism_same_seed_same_result():
    cfg = tiny_config(rounds=2)
    l1 = FederatedLearner(cfg)
    l2 = FederatedLearner(cfg)
    l1.fit(rounds=2)
    l2.fit(rounds=2)
    a1 = l1.evaluate()
    a2 = l2.evaluate()
    assert a1 == a2


def test_weighted_aggregation_respects_counts():
    """A zero-weight client must not affect the weighted aggregate: the
    weighted sum with weights [w0, w1, 0] equals the one with [w0, w1]."""
    import jax.numpy as jnp

    from colearn_federated_learning_tpu.utils import pytrees

    rng = np.random.default_rng(0)
    stacked3 = {"w": jnp.asarray(rng.normal(size=(3, 4, 2)), jnp.float32)}
    stacked2 = {"w": stacked3["w"][:2]}
    w3 = jnp.asarray([2.0, 5.0, 0.0])
    w2 = jnp.asarray([2.0, 5.0])
    got = pytrees.tree_weighted_sum(stacked3, w3)["w"]
    want = pytrees.tree_weighted_sum(stacked2, w2)["w"]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))
    got_m = pytrees.tree_weighted_mean(stacked3, w3)["w"]
    want_m = pytrees.tree_weighted_mean(stacked2, w2)["w"]
    np.testing.assert_allclose(np.asarray(got_m), np.asarray(want_m), rtol=1e-6)


def test_all_stragglers_round_is_noop_under_secure_agg():
    """If every sampled client is a straggler, the round must be a no-op:
    the secure-agg mask-cancellation residual must NOT be amplified by the
    near-zero total weight (regression: engine's zero-contributor gate)."""
    import jax

    cfg = tiny_config(rounds=1, straggler_prob=1.0, straggler_min_fraction=1.0,
                      secure_agg=True, dp_clip=1.0)
    learner = FederatedLearner(cfg)
    before = jax.tree.map(np.asarray, learner.server_state.params)
    rec = learner.run_round()
    assert rec["completed"] == 0
    assert rec["total_weight"] == 0
    after = jax.tree.map(np.asarray, learner.server_state.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(a, b)


def test_thousand_client_build_runs_a_round():
    """North-star scale on the client axis (BASELINE.json: 1000-client
    FedAvg): the vmap engine must build and run a cohort-64 round with
    1000 resident clients.  Tiny model/shard keeps CI fast — the point is
    the client-axis shapes, not the FLOPs."""
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, RunConfig,
    )

    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=1000,
                        partition="iid", max_examples_per_client=8),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=8, depth=1),
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=64,
                      local_steps=1, batch_size=4, lr=0.05, momentum=0.9),
        run=RunConfig(name="thousand", backend="cpu"),
    )
    learner = FederatedLearner(cfg)
    assert learner.num_clients == 1000 and learner.cohort_size == 64
    rec = learner.run_round()
    assert rec["completed"] == 64
    assert np.isfinite(rec["train_loss"])
