"""Secure-aggregation masking: exact cancellation for the complete graph
and the k-regular random ring, ring symmetry, and engine integration at a
cohort size where all-pairs masking would be the dominant cost."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.privacy import secure_agg as sa
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cohort(C, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.choice(1000, size=C, replace=False).astype(np.int32))


@pytest.mark.parametrize("C,neighbors", [(6, 0), (6, 2), (7, 4), (16, 4),
                                         (5, 8), (2, 4)])
def test_masks_cancel_in_the_sum(C, neighbors):
    """Summed over the cohort, the masks cancel to float32 round-off —
    complete graph and random ring alike (incl. cohorts too small for the
    requested degree, which fall back to the complete graph)."""
    template = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
    key = jax.random.PRNGKey(3)
    ids = _cohort(C)
    partners = sa.partner_table(key, ids, ids, 5, neighbors=neighbors)
    masks = jax.vmap(
        lambda i, prt: sa.pairwise_mask(template, key, i, prt, 5)
    )(ids, partners)
    for leaf in jax.tree.leaves(masks):
        per_mask_scale = np.abs(np.asarray(leaf)).mean()
        assert per_mask_scale > 0.1          # masks are real noise
        total = np.asarray(leaf.sum(axis=0))
        np.testing.assert_allclose(total, 0.0, atol=1e-4)


def test_ring_partnership_is_symmetric_and_exactly_k():
    """i lists j as a partner iff j lists i — the property cancellation
    rests on — and every member gets EXACTLY the configured degree."""
    key = jax.random.PRNGKey(0)
    ids = _cohort(9)
    table = np.asarray(sa.ring_partner_table(key, ids, ids, 2, neighbors=4))
    partner_sets = {
        int(i): set(row.tolist()) for i, row in zip(np.asarray(ids), table)
    }
    for i, partners in partner_sets.items():
        assert len(partners) == 4            # k-regular, no duplicates
        assert i not in partners
        for j in partners:
            assert i in partner_sets[j]


def test_ring_refuses_odd_degree_and_tiny_cohorts():
    key = jax.random.PRNGKey(0)
    ids = _cohort(8)
    with pytest.raises(ValueError, match="even"):
        sa.ring_partner_table(key, ids, ids, 0, neighbors=3)
    # cohort too small for a 4-regular ring -> signalled, caller falls back
    assert sa.ring_partner_table(key, _cohort(4), _cohort(4), 0,
                                 neighbors=4) is None
    # engine-level validation of the config knob
    cfg = _cfg(secure_agg=True, secure_agg_neighbors=3)
    with pytest.raises(ValueError, match="even"):
        FederatedLearner(cfg)


def test_ring_changes_per_round():
    key = jax.random.PRNGKey(0)
    ids = _cohort(16)
    rings = {
        r: tuple(np.asarray(
            sa.ring_partner_table(key, ids, ids, r, neighbors=2))[0].tolist())
        for r in range(6)
    }
    assert len(set(rings.values())) > 1      # permutation is per-round


def _cfg(**fed_kw):
    fed = dict(strategy="fedavg", rounds=4, cohort_size=16, local_steps=2,
               batch_size=16, lr=0.1, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=32,
                        partition="iid", max_examples_per_client=32),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=1),
        fed=FedConfig(**fed),
        run=RunConfig(name="ring_sa", backend="cpu"),
    )


def test_engine_ring_masking_learns():
    """cohort=16 with k=4 ring masks: the aggregate is unchanged by the
    masks (loss finite, accuracy rises) at 4/15th of the all-pairs PRG
    work."""
    cfg = _cfg(secure_agg=True, secure_agg_neighbors=4)
    learner = FederatedLearner(cfg)
    learner.fit()                       # config.fed.rounds
    loss, acc = learner.evaluate()
    assert np.isfinite(loss)
    # well above 10-class chance; the exact figure after 4 rounds varies
    # with the jax version's PRNG stream
    assert acc > 0.3

    # Ring masks and all-pairs masks both cancel, so the two runs see the
    # same aggregates (uniform weighting applies under SA either way).
    allpairs = FederatedLearner(cfg.replace(
        fed=dataclasses.replace(cfg.fed, secure_agg_neighbors=0)))
    allpairs.fit()
    loss_ap, acc_ap = allpairs.evaluate()
    np.testing.assert_allclose(loss, loss_ap, rtol=1e-3)
    np.testing.assert_allclose(acc, acc_ap, rtol=1e-3)
