"""Secure-aggregation masking: exact cancellation for the complete graph
and the k-regular random ring, ring symmetry, engine integration at a
cohort size where all-pairs masking would be the dominant cost, and the
dropout matrix — Shamir recovery algebra, wire-plane mask recovery
pinned against a plain-FedAvg oracle (0 / 1 / k maskers dropped), the
hard failure below the recovery threshold, and group-local masking on
hierarchical topologies.  (The async half of the matrix is the
NotImplementedError pin in tests/test_async_coordinator.py: pairwise
masks need an agreed per-round cohort the async pumps don't have.)"""

import dataclasses
import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.privacy import dropout
from colearn_federated_learning_tpu.privacy import secure_agg as sa
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cohort(C, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.choice(1000, size=C, replace=False).astype(np.int32))


@pytest.mark.parametrize("C,neighbors", [(6, 0), (6, 2), (7, 4), (16, 4),
                                         (5, 8), (2, 4)])
def test_masks_cancel_in_the_sum(C, neighbors):
    """Summed over the cohort, the masks cancel to float32 round-off —
    complete graph and random ring alike (incl. cohorts too small for the
    requested degree, which fall back to the complete graph)."""
    template = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
    key = jax.random.PRNGKey(3)
    ids = _cohort(C)
    partners = sa.partner_table(key, ids, ids, 5, neighbors=neighbors)
    masks = jax.vmap(
        lambda i, prt: sa.pairwise_mask(template, key, i, prt, 5)
    )(ids, partners)
    for leaf in jax.tree.leaves(masks):
        per_mask_scale = np.abs(np.asarray(leaf)).mean()
        assert per_mask_scale > 0.1          # masks are real noise
        total = np.asarray(leaf.sum(axis=0))
        np.testing.assert_allclose(total, 0.0, atol=1e-4)


def test_ring_partnership_is_symmetric_and_exactly_k():
    """i lists j as a partner iff j lists i — the property cancellation
    rests on — and every member gets EXACTLY the configured degree."""
    key = jax.random.PRNGKey(0)
    ids = _cohort(9)
    table = np.asarray(sa.ring_partner_table(key, ids, ids, 2, neighbors=4))
    partner_sets = {
        int(i): set(row.tolist()) for i, row in zip(np.asarray(ids), table)
    }
    for i, partners in partner_sets.items():
        assert len(partners) == 4            # k-regular, no duplicates
        assert i not in partners
        for j in partners:
            assert i in partner_sets[j]


def test_ring_refuses_odd_degree_and_tiny_cohorts():
    key = jax.random.PRNGKey(0)
    ids = _cohort(8)
    with pytest.raises(ValueError, match="even"):
        sa.ring_partner_table(key, ids, ids, 0, neighbors=3)
    # cohort too small for a 4-regular ring -> signalled, caller falls back
    assert sa.ring_partner_table(key, _cohort(4), _cohort(4), 0,
                                 neighbors=4) is None
    # engine-level validation of the config knob
    cfg = _cfg(secure_agg=True, secure_agg_neighbors=3)
    with pytest.raises(ValueError, match="even"):
        FederatedLearner(cfg)


def test_ring_changes_per_round():
    key = jax.random.PRNGKey(0)
    ids = _cohort(16)
    rings = {
        r: tuple(np.asarray(
            sa.ring_partner_table(key, ids, ids, r, neighbors=2))[0].tolist())
        for r in range(6)
    }
    assert len(set(rings.values())) > 1      # permutation is per-round


def _cfg(**fed_kw):
    fed = dict(strategy="fedavg", rounds=4, cohort_size=16, local_steps=2,
               batch_size=16, lr=0.1, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=32,
                        partition="iid", max_examples_per_client=32),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=1),
        fed=FedConfig(**fed),
        run=RunConfig(name="ring_sa", backend="cpu"),
    )


def test_engine_ring_masking_learns():
    """cohort=16 with k=4 ring masks: the aggregate is unchanged by the
    masks (loss finite, accuracy rises) at 4/15th of the all-pairs PRG
    work."""
    cfg = _cfg(secure_agg=True, secure_agg_neighbors=4)
    learner = FederatedLearner(cfg)
    learner.fit()                       # config.fed.rounds
    loss, acc = learner.evaluate()
    assert np.isfinite(loss)
    # well above 10-class chance; the exact figure after 4 rounds varies
    # with the jax version's PRNG stream
    assert acc > 0.3

    # Ring masks and all-pairs masks both cancel, so the two runs see the
    # same aggregates (uniform weighting applies under SA either way).
    allpairs = FederatedLearner(cfg.replace(
        fed=dataclasses.replace(cfg.fed, secure_agg_neighbors=0)))
    allpairs.fit()
    loss_ap, acc_ap = allpairs.evaluate()
    np.testing.assert_allclose(loss, loss_ap, rtol=1e-3)
    np.testing.assert_allclose(acc, acc_ap, rtol=1e-3)


# ------------------------------------------------ dropout recovery core --
def test_shamir_recovery_matrix():
    """t-of-n reconstruction over the full drop matrix: every share, any
    exactly-t subset, and the HARD failure one share below threshold."""
    secret = dropout.random_secret()
    xs = [1, 2, 3, 4, 5]
    t = 3
    shares = dropout.split_secret(secret, xs, t)
    assert set(shares) == set(xs)
    # 0 dropped: all n shares reconstruct.
    assert dropout.reconstruct(shares, t) == secret
    # Down to exactly t survivors, ANY subset works (Lagrange at 0 is
    # subset-independent) — this is what lets the coordinator recover
    # with whichever shareholders happen to answer.
    for keep in itertools.combinations(xs, t):
        sub = {x: shares[x] for x in keep}
        assert dropout.reconstruct(sub, t) == secret
    # t − 1 survivors: RecoveryError, never a wrong secret.
    with pytest.raises(dropout.RecoveryError):
        dropout.reconstruct({x: shares[x] for x in xs[: t - 1]}, t)
    # A degenerate threshold never reconstructs from nothing.
    with pytest.raises(dropout.RecoveryError):
        dropout.reconstruct({}, 1)


def test_threshold_count_convention():
    """t = max(1, ceil(fraction · n)); 0 only for an empty recovery set
    (solo cohort — no partners, no self-mask)."""
    assert dropout.threshold_count(4, 0.5) == 2
    assert dropout.threshold_count(4, 0.75) == 3   # the wire test's t
    assert dropout.threshold_count(5, 0.5) == 3
    assert dropout.threshold_count(1, 0.5) == 1    # floor at 1
    assert dropout.threshold_count(4, 1.0) == 4
    assert dropout.threshold_count(0, 0.5) == 0
    with pytest.raises(ValueError, match="secure_agg_threshold"):
        dropout.threshold_count(4, 0.0)
    with pytest.raises(ValueError, match="secure_agg_threshold"):
        dropout.threshold_count(4, 1.5)


def test_split_secret_validates_inputs():
    with pytest.raises(ValueError, match="out of range"):
        dropout.split_secret(5, [1, 2], 3)         # t > n
    with pytest.raises(ValueError, match="distinct and nonzero"):
        dropout.split_secret(5, [0, 1], 1)
    with pytest.raises(ValueError, match="distinct and nonzero"):
        dropout.split_secret(5, [2, 2], 1)
    with pytest.raises(ValueError, match="field range"):
        dropout.split_secret(dropout.PRIME, [1, 2], 1)


def test_oracle_plan_mirrors_trainer_losses_only():
    """The exactness oracle loses exactly the trainers the secure run
    lost: unmask silence vanishes (plain has no recovery phase),
    share_setup deafness becomes a train drop (pruned either way)."""
    from colearn_federated_learning_tpu.faults import FaultPlan, FaultSpec
    from colearn_federated_learning_tpu.faults import soak

    plan = FaultPlan([
        FaultSpec(kind="drop_request", device_id="0", round=1, op="train",
                  count=3),
        FaultSpec(kind="drop_request", device_id="1", round=2, op="unmask",
                  count=3),
        FaultSpec(kind="drop_request", device_id="2", round=3,
                  op="share_setup", count=3),
    ], seed=5)
    mirrored = soak.oracle_plan(plan)
    assert [(f.device_id, f.round, f.op) for f in mirrored.faults] == [
        ("0", 1, "train"), ("2", 3, "train")]
    assert mirrored.seed == plan.seed


def test_mask_cost_has_no_cohort_quadratic_term():
    """Group-local layering: per-device cost depends on the group and the
    ring degree, never on the cohort — the analytic model the 1M-device
    bench sweep (scripts/bench_fleet.py --mask-sweep) gates in CI."""
    small = dropout.mask_cost(10_000, 874, neighbors=0, group_size=1024)
    large = dropout.mask_cost(1_000_000, 874, neighbors=0, group_size=1024)
    for field in ("mask_flops_per_device", "share_bytes_per_device",
                  "pairs_per_device"):
        assert small[field] == large[field], field
    # System-wide pair counts DO scale with the cohort — linearly under
    # grouping, quadratically flat: the separation grows with cohort.
    ratio = large["flat_pairs_total"] / large["grouped_pairs_total"]
    assert ratio > 100
    assert ratio > small["flat_pairs_total"] / small["grouped_pairs_total"]
    # A ring degree caps the per-device cost below the full group.
    ring = dropout.mask_cost(1_000_000, 874, neighbors=4, group_size=1024)
    assert ring["pairs_per_device"] == 4
    assert ring["mask_flops_per_device"] < large["mask_flops_per_device"]


# ------------------------------------------------- wire dropout matrix --
def _flat_params(coord):
    return np.concatenate([
        np.ravel(np.asarray(a))
        for a in jax.tree.leaves(coord.server_state.params)
    ])


@pytest.mark.slow
def test_wire_dropout_matrix_exact_recovery():
    """0, 1, and 2 maskers killed mid-train across consecutive rounds:
    every post-recovery aggregate must match a plain-FedAvg oracle over
    the same survivors, with every dead masker attributed in
    privacy.masks_recovered_total and no round skipped or discarded."""
    from colearn_federated_learning_tpu.faults import FaultPlan, FaultSpec
    from colearn_federated_learning_tpu.faults import soak

    # round 1: one masker dies (d=1); round 2: two die at once (d=2,
    # folded 3/5 stays at quorum); round 3: clean again (d=0 — recovery
    # must not have corrupted cross-round state).  Round 0 is the jit
    # warmup, also d=0.  count=3 outruns the transport's 2 retries.
    plan = FaultPlan([
        FaultSpec(kind="drop_request", device_id="0", round=1, op="train",
                  count=3),
        FaultSpec(kind="drop_request", device_id="1", round=2, op="train",
                  count=3),
        FaultSpec(kind="drop_request", device_id="2", round=2, op="train",
                  count=3),
    ], seed=13)
    summary = soak.run_secure_soak(rounds=4, n_workers=5, plan=plan,
                                   round_timeout=8.0)
    assert summary["rounds_run"] == 4
    assert summary["oracle_ok"], summary["param_diffs"]
    assert summary["skipped_rounds"] == []
    assert not any(r.get("unmask_failed") for r in summary["records"])
    counters = summary["counters"]
    assert counters["privacy.masks_recovered_total"] == 3   # one per dead
    assert counters["privacy.share_recovery_failures_total"] == 0
    assert counters["fed.rounds_skipped_quorum"] == 0
    # Every clean round folded all 5; the faulted rounds folded 4 and 3.
    assert [r["completed"] for r in summary["records"]] == [5, 4, 3, 5]


@pytest.mark.slow
def test_wire_unmask_threshold_boundary():
    """The recovery threshold is sharp at t = ceil(0.75 · 4) = 3 shares:
    2 maskers silent during unmask leaves exactly 3 reachable
    shareholders per origin — just-at-threshold, exact recovery — while
    3 silent leaves 2 < t, a HARD failure that discards the round
    (params unchanged) and attributes it in
    privacy.share_recovery_failures_total."""
    from colearn_federated_learning_tpu import telemetry
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker
    from colearn_federated_learning_tpu.faults import (
        FaultPlan,
        FaultSpec,
        inject,
    )
    from colearn_federated_learning_tpu.faults import soak

    atol = 2e-4
    n = 5
    cfg_s = soak.secure_soak_config(n)
    cfg_s = cfg_s.replace(
        fed=dataclasses.replace(cfg_s.fed, secure_agg_threshold=0.75))
    cfg_p = cfg_s.replace(
        fed=dataclasses.replace(cfg_s.fed, secure_agg=False),
        run=dataclasses.replace(cfg_s.run, name="threshold_oracle"),
    )

    def silence_at_unmask(round_idx, devices):
        return FaultPlan([
            FaultSpec(kind="drop_request", device_id=str(d),
                      round=round_idx, op="unmask", count=3)
            for d in devices
        ], seed=17)

    reg = telemetry.get_registry()

    def counters():
        return {name: reg.counter(name).value  # colearn: noqa(CL005)
                for name in ("privacy.masks_recovered_total",
                             "privacy.share_recovery_failures_total")}

    fleets = []
    installed = False
    try:
        for cfg in (cfg_s, cfg_p):
            broker = MessageBroker().start()
            workers = [
                DeviceWorker(cfg, i, broker.host, broker.port).start()
                for i in range(n)
            ]
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=120.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=n, timeout=30.0)
            coord.trainers.sort(key=lambda d: int(d.device_id))
            for w in workers:
                w.await_role(timeout=10.0)
            fleets.append((broker, workers, coord))
        (_, _, coord_s), (_, _, coord_p) = fleets

        # Round 0: clean warmup on both — the d=0 baseline.
        rec0 = coord_s.run_round()
        coord_p.run_round()
        coord_s.round_timeout = coord_p.round_timeout = 8.0
        assert not rec0["unmask_failed"]
        np.testing.assert_allclose(_flat_params(coord_s),
                                   _flat_params(coord_p), atol=atol)

        # Round 1: d=2 unmask-silent — 3 answering shareholders, exactly
        # t.  All 5 updates folded, so the clean oracle is the truth.
        before = counters()
        inject.install(silence_at_unmask(1, (0, 1)))
        installed = True
        rec1 = coord_s.run_round()
        inject.uninstall()
        installed = False
        coord_p.run_round()
        assert not rec1["unmask_failed"]
        assert rec1["completed"] == n
        np.testing.assert_allclose(_flat_params(coord_s),
                                   _flat_params(coord_p), atol=atol)
        delta = {k: counters()[k] - before[k] for k in before}
        assert delta["privacy.share_recovery_failures_total"] == 0
        assert delta["privacy.masks_recovered_total"] == 0   # nobody died

        # Round 2: d=3 — 2 reachable shareholders < t=3.  The round must
        # be DISCARDED (a sum with unremoved self-masks is garbage), not
        # released approximately.
        frozen = _flat_params(coord_s)
        before = counters()
        inject.install(silence_at_unmask(2, (0, 1, 2)))
        installed = True
        rec2 = coord_s.run_round()
        inject.uninstall()
        installed = False
        assert rec2["unmask_failed"] is True
        np.testing.assert_array_equal(_flat_params(coord_s), frozen)
        delta = {k: counters()[k] - before[k] for k in before}
        assert delta["privacy.share_recovery_failures_total"] >= 1
        assert delta["privacy.masks_recovered_total"] == 0
    finally:
        if installed:
            inject.uninstall()
        for broker, workers, coord in fleets:
            for w in workers:
                w.stop()
            broker.stop()
            coord.close()


# ------------------------------------------------- hierarchical groups --
def test_hierarchical_group_local_masking_matches_plain():
    """Group-local secure aggregation on the two-tier topology: masks
    span only each edge group, cancel within it, and the synced cloud
    model matches the unmasked run — at O(group) per-device cost."""
    from colearn_federated_learning_tpu.fed.hierarchical import (
        HierarchicalLearner,
    )

    def cfg(**fed_kw):
        fed = dict(strategy="fedavg", rounds=2, cohort_size=0,
                   local_steps=2, batch_size=16, lr=0.1, momentum=0.9)
        fed.update(fed_kw)
        return ExperimentConfig(
            data=DataConfig(dataset="mnist_tiny", num_clients=8,
                            partition="iid", max_examples_per_client=32),
            model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16,
                              depth=1),
            fed=FedConfig(**fed),
            run=RunConfig(name="hier_sa", backend="cpu"),
        )

    secure = HierarchicalLearner(cfg(secure_agg=True), num_groups=2,
                                 sync_period=2)
    plain = HierarchicalLearner(cfg(), num_groups=2, sync_period=2)
    secure.fit(rounds=2)
    plain.fit(rounds=2)

    def flat(tree):
        return np.concatenate([np.ravel(np.asarray(a))
                               for a in jax.tree.leaves(tree)])

    np.testing.assert_allclose(flat(secure.global_params),
                               flat(plain.global_params), atol=2e-4)

    cost = secure.mask_cost_summary()
    assert cost["num_groups"] == 2 and cost["group_size"] == 4
    # Masks never leave the group: per-device pair count is bounded by
    # the group, not the cohort, and the system-wide pair count beats
    # the flat topology's quadratic.
    assert cost["pairs_per_device"] <= cost["group_size"] - 1
    assert cost["quadratic_ratio"] > 1.0
    assert cost["grouped_pairs_total"] < cost["flat_pairs_total"]
