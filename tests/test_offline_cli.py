"""Cross-silo file federation (fed/offline.py) and the `colearn` CLI."""

import dataclasses
import json
import subprocess

import numpy as np
import pytest

from colearn_federated_learning_tpu import cli
from colearn_federated_learning_tpu.utils import serialization
from tests.test_engine import tiny_config


def test_pytree_npz_roundtrip(tmp_path):
    tree = {"a": {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                  "b": np.ones(4, np.int32)},
            "c": np.float32(2.5)}
    path = str(tmp_path / "t.npz")
    serialization.save_pytree_npz(path, tree, meta={"round": 3})
    got, meta = serialization.load_pytree_npz(path)
    assert meta["round"] == 3
    np.testing.assert_array_equal(got["a"]["w"], tree["a"]["w"])
    np.testing.assert_array_equal(got["a"]["b"], tree["a"]["b"])
    np.testing.assert_array_equal(got["c"], tree["c"])
    # bytes plane matches the file plane
    data = serialization.pytree_to_bytes(tree, {"round": 3})
    got2, meta2 = serialization.bytes_to_pytree(data)
    assert meta2 == meta
    np.testing.assert_array_equal(got2["a"]["w"], tree["a"]["w"])


def test_offline_round_improves_and_matches_roles(tmp_path):
    """init → N client updates → aggregate → eval: the full cross-silo flow
    through the CLI entrypoints (`colearn train --role client`,
    `colearn aggregate`, BASELINE.json north_star)."""
    from colearn_federated_learning_tpu.fed import offline

    cfg = tiny_config(rounds=1)
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)

    base = offline.evaluate_global(cfg, g0)

    updates = []
    for cid in range(4):
        out = str(tmp_path / f"u{cid}.npz")
        stats = offline.client_update(cfg, cid, g0, out)
        assert np.isfinite(stats["mean_loss"])
        updates.append(out)

    g1 = str(tmp_path / "g1.npz")
    agg = offline.aggregate_updates(cfg, g0, updates, g1)
    assert agg["round"] == 1 and agg["num_updates"] == 4

    after = offline.evaluate_global(cfg, g1)
    assert after["eval_acc"] >= base["eval_acc"]  # one round of 4/10 silos


def test_cli_configs_and_train(tmp_path, capsys):
    assert cli.main(["configs"]) == 0
    out = capsys.readouterr().out
    assert "mnist_mlp_fedavg" in out and "femnist_vit_cross_silo" in out

    log = str(tmp_path / "log.jsonl")
    rc = cli.main([
        "train", "--config", "mnist_mlp_fedavg", "--dataset", "mnist_tiny",
        "--rounds", "2", "--backend", "cpu", "--log-file", log,
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["rounds"] == 2 and "rounds_per_sec" in summary
    assert len(open(log).readlines()) == 2


def test_cli_cross_silo_flow(tmp_path, capsys):
    g0 = str(tmp_path / "g.npz")
    args = ["--config", "mnist_mlp_fedavg", "--dataset", "mnist_tiny"]
    assert cli.main(["init", *args, "--out", g0]) == 0
    u0 = str(tmp_path / "u0.npz")
    assert cli.main(["train", *args, "--role", "client", "--client-id", "0",
                     "--global-model", g0, "--out", u0]) == 0
    g1 = str(tmp_path / "g1.npz")
    assert cli.main(["aggregate", *args, "--global-model", g0,
                     "--updates", u0, "--out", g1]) == 0
    assert cli.main(["eval", *args, "--global-model", g1]) == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["round"] == 1 and 0.0 <= rec["eval_acc"] <= 1.0


def test_cli_missing_client_args_errors():
    rc = cli.main(["train", "--role", "client"])
    assert rc == 2


def test_aggregate_rejects_stale_update(tmp_path):
    from colearn_federated_learning_tpu.fed import offline

    cfg = tiny_config()
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)
    u0 = str(tmp_path / "u0.npz")
    offline.client_update(cfg, 0, g0, u0)
    g1 = str(tmp_path / "g1.npz")
    offline.aggregate_updates(cfg, g0, [u0], g1)
    # u0 was computed against round 0; folding it into the round-1 model
    # must fail loudly, not corrupt the model.
    with pytest.raises(ValueError, match="stale update"):
        offline.aggregate_updates(cfg, g1, [u0], str(tmp_path / "g2.npz"))


def test_serialization_rejects_list_nodes(tmp_path):
    with pytest.raises(TypeError, match="list"):
        serialization.save_pytree_npz(
            str(tmp_path / "x.npz"), {"layers": [np.zeros(3), np.zeros(3)]}
        )


def test_cli_bench_parses_forwarded_args(monkeypatch, capsys):
    # `colearn bench` must forward its remaining argv to bench.main (it used
    # to re-parse sys.argv and die on the 'bench' token); stub the heavy
    # workload functions and check the wiring end-to-end.
    from colearn_federated_learning_tpu import bench

    monkeypatch.setattr(bench, "probe_platform", lambda *a, **k: "tpu")
    monkeypatch.setattr(bench, "_save_last_tpu", lambda out: None)
    monkeypatch.setattr(
        bench, "run_tpu_native",
        lambda rounds, warmup, workload=None, min_time_s=0.0: {
            "rounds_per_sec": float(rounds),
            "client_samples_per_sec_per_chip": 1.0,
            "n_devices": 1,
            "platform": "tpu",
        })
    rc = cli.main(["bench", "--rounds", "3", "--skip-baseline"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 3.0 and rec["unit"] == "rounds/sec"
    assert rec["platform"] == "tpu"


def test_bench_cpu_fallback_embeds_last_tpu(monkeypatch, capsys, tmp_path):
    # A dead accelerator must still yield a winning-SHAPED record: the
    # matmul-dominated BASELINE config #1 workload, the mnist_mlp metric
    # name, and the committed last-TPU measurement with provenance.
    from colearn_federated_learning_tpu import bench

    last = {"metric": "fedavg_cifar10_cnn_rounds_per_sec", "value": 3.6,
            "platform": "tpu", "provenance": "test"}
    p = tmp_path / "bench_tpu.json"
    p.write_text(json.dumps(last))
    monkeypatch.setattr(bench, "LAST_TPU_PATH", str(p))
    monkeypatch.setattr(bench, "probe_platform", lambda *a, **k: None)
    monkeypatch.setattr(bench, "force_cpu", lambda: None)
    monkeypatch.setattr(
        bench, "run_tpu_native",
        lambda rounds, warmup, workload=None, min_time_s=0.0: {
            "rounds_per_sec": 5.0,
            "client_samples_per_sec_per_chip": 1.0,
            "n_devices": 1,
            "platform": "cpu",
        })
    rc = cli.main(["bench", "--rounds", "3", "--skip-baseline"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["metric"] == "fedavg_mnist_mlp_rounds_per_sec"
    assert rec["platform"] == "cpu"
    assert rec["last_tpu"]["value"] == 3.6
    assert "provenance" in rec["last_tpu"]


def test_bench_probe_retries_within_budget(monkeypatch):
    # The tunnel flaps: a failing probe must be retried until the budget
    # runs out (bounded), not abandoned after one attempt.
    from colearn_federated_learning_tpu import bench

    calls = []

    def fake_run(*a, **k):
        calls.append(k.get("timeout"))
        if len(calls) >= 3:
            class R:  # successful third probe
                returncode, stdout = 0, "tpu\n"
            return R()
        raise subprocess.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.probe_platform(timeout_s=1.0, budget_s=3600.0) == "tpu"
    assert len(calls) == 3
