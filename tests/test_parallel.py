"""parallel/: mesh factoring + ring attention vs the dense oracle.

Ring attention runs under shard_map on a virtual CPU mesh (conftest forces
8 host devices) with the sequence dimension sharded; the dense single-device
attention over the unsharded arrays is the numerics oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from colearn_federated_learning_tpu.utils.jax_compat import shard_map

from colearn_federated_learning_tpu.parallel import factor_devices, make_mesh
from colearn_federated_learning_tpu.parallel.ring import (
    dense_attention,
    ring_attention,
)


# ---------------------------------------------------------------- mesh ----
def test_factor_devices():
    assert factor_devices(8, 1) == (8,)
    assert factor_devices(8, 2) == (4, 2)
    assert factor_devices(8, 3) == (2, 2, 2)
    assert factor_devices(6, 2) == (3, 2)
    assert factor_devices(7, 2) == (1, 7)  # prime: trailing axis gets all
    assert factor_devices(1, 2) == (1, 1)


def test_make_mesh_auto_and_explicit(cpu_devices):
    m = make_mesh(("clients", "seq"), devices=cpu_devices[:8])
    assert m.shape == {"clients": 4, "seq": 2}
    m = make_mesh(("clients", "seq"), (2, 4), devices=cpu_devices[:8])
    assert m.shape == {"clients": 2, "seq": 4}
    m = make_mesh(("a", "b"), (-1, 2), devices=cpu_devices[:8])
    assert m.shape == {"a": 4, "b": 2}
    with pytest.raises(ValueError):
        make_mesh(("a",), (3,), devices=cpu_devices[:8])


# ------------------------------------------------------- ring attention ----
def _seq_mesh(devices, n):
    return Mesh(np.array(devices[:n]), ("seq",))


def _rand_qkvm(key, B, L, H, D, frac_pad=0.25):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (B, L, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, L, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, L, H, D), jnp.float32)
    mask = jax.random.uniform(ks[3], (B, L)) > frac_pad
    return q, k, v, mask


def _run_ring(mesh, q, k, v, mask, **kw):
    fn = shard_map(
        lambda q, k, v, m: ring_attention(q, k, v, m, axis_name="seq", **kw),
        mesh=mesh,
        in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq"), P(None, "seq")),
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    return jax.jit(fn)(q, k, v, mask)


@pytest.mark.parametrize("n_dev", [4, 8])
def test_ring_matches_dense(cpu_devices, n_dev):
    mesh = _seq_mesh(cpu_devices, n_dev)
    q, k, v, mask = _rand_qkvm(jax.random.PRNGKey(0), B=2, L=32, H=2, D=8)
    out = _run_ring(mesh, q, k, v, mask)
    ref = dense_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_causal_matches_dense(cpu_devices):
    mesh = _seq_mesh(cpu_devices, 4)
    q, k, v, mask = _rand_qkvm(jax.random.PRNGKey(1), B=2, L=16, H=2, D=4,
                               frac_pad=0.0)
    out = _run_ring(mesh, q, k, v, mask, causal=True)
    ref = dense_attention(q, k, v, mask, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_no_mask(cpu_devices):
    mesh = _seq_mesh(cpu_devices, 4)
    q, k, v, _ = _rand_qkvm(jax.random.PRNGKey(2), B=1, L=16, H=1, D=4)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
        mesh=mesh,
        in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"),
        check_vma=False,
    )
    out = jax.jit(fn)(q, k, v)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_fully_masked_rows_are_zero(cpu_devices):
    mesh = _seq_mesh(cpu_devices, 4)
    q, k, v, _ = _rand_qkvm(jax.random.PRNGKey(3), B=2, L=16, H=2, D=4)
    mask = jnp.zeros((2, 16), bool).at[1].set(True)  # batch 0: all pad
    out = _run_ring(mesh, q, k, v, mask)
    assert np.allclose(np.asarray(out)[0], 0.0)
    ref = dense_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_bfloat16_io(cpu_devices):
    mesh = _seq_mesh(cpu_devices, 4)
    q, k, v, mask = _rand_qkvm(jax.random.PRNGKey(4), B=1, L=16, H=2, D=8)
    qb, kb, vb = (a.astype(jnp.bfloat16) for a in (q, k, v))
    out = _run_ring(mesh, qb, kb, vb, mask)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, mask)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=0.05, atol=0.05
    )
