"""Unit tests for the data partitioner (SURVEY.md §7 test strategy: 'unit
(partitioner stats ...)')."""

import numpy as np

from colearn_federated_learning_tpu.data import partition


def test_iid_partition_covers_everything():
    parts = partition.iid_partition(103, 10, seed=0)
    allidx = np.concatenate(parts)
    assert len(allidx) == 103
    assert len(np.unique(allidx)) == 103
    sizes = partition.partition_counts(parts)
    assert sizes.max() - sizes.min() <= 1


def test_dirichlet_partition_covers_and_skews():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, size=5000)
    parts = partition.dirichlet_partition(labels, 20, alpha=0.1, seed=1)
    allidx = np.concatenate(parts)
    assert len(allidx) == 5000
    assert len(np.unique(allidx)) == 5000
    assert min(len(p) for p in parts) >= 1

    # Low alpha must be visibly more skewed than near-IID high alpha.
    dist_lo = partition.label_distribution(labels, parts, 10)
    parts_hi = partition.dirichlet_partition(labels, 20, alpha=100.0, seed=1)
    dist_hi = partition.label_distribution(labels, parts_hi, 10)

    def mean_entropy(dist):
        p = dist / np.maximum(dist.sum(axis=1, keepdims=True), 1)
        with np.errstate(divide="ignore", invalid="ignore"):
            e = -np.nansum(np.where(p > 0, p * np.log(p), 0.0), axis=1)
        return e.mean()

    assert mean_entropy(dist_lo) < mean_entropy(dist_hi) - 0.3


def test_pack_client_shards_padding_and_counts():
    from colearn_federated_learning_tpu.data.sharding import pack_client_shards

    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10).astype(np.int32)
    parts = [np.array([0, 1, 2, 3, 4]), np.array([5, 6]), np.array([7, 8, 9])]
    shards = pack_client_shards(x, y, parts)
    assert shards.x.shape == (3, 5, 2)
    assert list(shards.counts) == [5, 2, 3]
    # Padding rows are cyclic copies of the client's own data.
    np.testing.assert_array_equal(shards.y[1], [5, 6, 5, 6, 5])


def test_pad_clients_to_multiple_ghost_clients():
    from colearn_federated_learning_tpu.data.sharding import (
        pack_client_shards,
        pad_clients_to_multiple,
    )

    x = np.zeros((12, 3), np.float32)
    y = np.zeros((12,), np.int32)
    parts = [np.arange(4), np.arange(4, 8), np.arange(8, 12)]
    shards = pad_clients_to_multiple(pack_client_shards(x, y, parts), 8)
    assert shards.num_clients == 8
    assert list(shards.counts[3:]) == [0] * 5  # ghosts have zero weight


def test_unknown_partition_name_raises():
    # A typo must not silently fall through to IID (the literature anchor
    # would then "validate" non-IID claims against the wrong split).
    import dataclasses

    import pytest

    from colearn_federated_learning_tpu.fed import setup as setup_lib
    from colearn_federated_learning_tpu.utils.config import (
        ExperimentConfig,
        DataConfig,
    )

    cfg = ExperimentConfig(data=DataConfig(partition="pathologcal"))
    with pytest.raises(ValueError, match="unknown data.partition"):
        setup_lib.partition_for_config(cfg, np.zeros(100, np.int32))
