"""Hierarchical edge→cloud federation (fed/hierarchical.py).

The reference aggregates flat; HierFAVG-style two-tier rounds are a
rebuild superset matching CoLearn's edge-gateway deployment picture.
"""

import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.hierarchical import HierarchicalLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cfg(**fed_kw):
    fed = dict(strategy="fedavg", rounds=6, cohort_size=0, local_steps=3,
               batch_size=16, lr=0.1, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=64),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="hier_test"),
    )


def _params_flat(tree):
    import jax

    return np.concatenate([np.ravel(np.asarray(a))
                           for a in jax.tree.leaves(tree)])


def test_hierarchical_learns_and_syncs():
    h = HierarchicalLearner(_cfg(), num_groups=2, sync_period=2)
    assert len(h.groups) == 2 and h.groups[0].real_num_clients == 4
    hist = h.fit(rounds=6)
    # Sync happened on every period boundary and the cloud model learns.
    assert [r["synced"] for r in hist] == [False, True] * 3
    loss, acc = h.evaluate()
    assert acc > 0.9, acc

    # After a sync boundary every group holds the identical cloud model.
    a = _params_flat(h.groups[0].server_state.params)
    b = _params_flat(h.groups[1].server_state.params)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, _params_flat(h.global_params))


def test_groups_diverge_between_syncs():
    h = HierarchicalLearner(_cfg(), num_groups=2, sync_period=4)
    h.run_round()                       # round 0: no sync
    a = _params_flat(h.groups[0].server_state.params)
    b = _params_flat(h.groups[1].server_state.params)
    assert np.abs(a - b).max() > 0.0    # distinct edge populations diverge


def test_wan_traffic_is_periodic():
    # sync_period=3 over 6 rounds: exactly 2 cloud syncs.
    h = HierarchicalLearner(_cfg(), num_groups=2, sync_period=3)
    hist = h.fit(rounds=6)
    assert sum(r["synced"] for r in hist) == 2


def test_terminal_sync_folds_the_last_partial_period():
    # rounds=5, period=2: boundary syncs after rounds 1 and 3; round 4
    # would otherwise leave the last period's training out of the
    # reported cloud model — fit() must terminally sync.
    h = HierarchicalLearner(_cfg(), num_groups=2, sync_period=2)
    hist = h.fit(rounds=5)
    assert [r["synced"] for r in hist] == [False, True, False, True, True]
    assert "eval_acc" in hist[-1]
    a = _params_flat(h.groups[0].server_state.params)
    np.testing.assert_array_equal(a, _params_flat(h.global_params))


def test_rejects_indivisible_client_count():
    with pytest.raises(ValueError, match="divisible"):
        HierarchicalLearner(_cfg(), num_groups=3)   # 8 % 3 != 0


def test_rejects_stateful_strategies():
    with pytest.raises(ValueError, match="server state"):
        HierarchicalLearner(_cfg(strategy="fedadam"), num_groups=2)
    with pytest.raises(ValueError, match="num_groups"):
        HierarchicalLearner(_cfg(), num_groups=1)


def test_hierarchical_composes_with_robust_aggregation():
    # Each edge group is a full engine: per-group Byzantine-robust
    # aggregation composes with the cloud sync for free.
    import dataclasses

    cfg = _cfg()
    cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, aggregator="median"))
    h = HierarchicalLearner(cfg, num_groups=2, sync_period=2)
    assert all(g.robust for g in h.groups)
    hist = h.fit(rounds=6)
    assert np.isfinite(hist[-1]["train_loss"])
    _, acc = h.evaluate()
    assert acc > 0.85, acc
