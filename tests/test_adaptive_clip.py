"""Adaptive DP clipping (privacy/dp.py quantile tracking + engine wiring).

The reference ships fixed clip hooks at best (SURVEY.md §2 "DP hooks");
adaptive clipping is a rebuild superset: the clip norm is a device scalar
threaded operand→metric through the jit round program, tracking a target
quantile of client update norms (Andrew et al. pattern, PAPERS.md —
formulas only).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.privacy import dp as dp_lib
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cfg(**fed_kw):
    fed = dict(strategy="fedavg", rounds=6, cohort_size=0, local_steps=2,
               batch_size=8, lr=0.1, momentum=0.0,
               dp_clip=100.0, dp_adaptive_clip=True, dp_clip_lr=0.5,
               dp_target_quantile=0.5)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=32),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="adaptive_clip_test"),
    )


def test_noise_split_formula():
    # z_delta > z always (part of the budget goes to the bit query), and
    # the joint mechanism matches z: z^-2 == z_delta^-2 + (2*sigma_b)^-2.
    z, sb = 1.0, 2.0
    zd = dp_lib.adaptive_noise_multiplier(z, sb)
    assert zd > z
    np.testing.assert_allclose(zd ** -2 + (2 * sb) ** -2, z ** -2, rtol=1e-12)
    with pytest.raises(ValueError, match="bit_noise"):
        dp_lib.adaptive_noise_multiplier(1.0, 0.4)  # needs sigma_b > z/2


def test_clip_update_direction():
    clip = jnp.float32(1.0)
    # Everyone under the clip (frac 1.0 > target 0.5): clip must shrink.
    down = dp_lib.adaptive_clip_update(clip, jnp.float32(1.0), 0.5, 0.2)
    # Nobody under (frac 0.0 < target): clip must grow.
    up = dp_lib.adaptive_clip_update(clip, jnp.float32(0.0), 0.5, 0.2)
    assert float(down) < 1.0 < float(up)


def test_engine_adapts_clip_toward_quantile():
    # Start with a clip far above every update norm: the bit fraction sits
    # at 1.0 and the clip must decay geometrically round over round.
    learner = FederatedLearner(_cfg())
    hist = learner.fit(rounds=6)
    clips = [r["dp_clip"] for r in hist]
    assert all(np.isfinite(clips))
    assert clips[-1] < clips[0] * 0.3, clips
    assert hist[0]["dp_bit_frac"] == 1.0
    # ... and training still works.
    assert np.isfinite(hist[-1]["train_loss"])


def test_engine_grows_tiny_clip():
    # Start with a clip far below every norm: fraction 0, clip must grow.
    learner = FederatedLearner(_cfg(dp_clip=1e-3))
    hist = learner.fit(rounds=4)
    assert hist[-1]["dp_clip"] > hist[0]["dp_clip"]
    assert hist[0]["dp_bit_frac"] == 0.0


def test_adaptive_with_noise_accounts_single_mechanism():
    # With noise on, the accountant keeps charging the configured z (the
    # bit query's cost is folded in by the inflated update noise).
    cfg = _cfg(dp_noise_multiplier=0.8, dp_bit_noise=2.0)
    learner = FederatedLearner(cfg)
    assert learner.dp_z > 0.8          # inflated update noise
    rec = learner.run_round()
    assert rec["dp_epsilon"] > 0.0 and np.isfinite(rec["dp_epsilon"])


def test_mesh_adaptive_matches_single_device(cpu_devices):
    from jax.sharding import Mesh

    cfg = _cfg()
    ref = FederatedLearner(cfg)
    mesh = Mesh(np.array(cpu_devices[:8]), ("clients",))
    m = FederatedLearner(cfg, mesh=mesh)
    for _ in range(3):
        r_ref = ref.run_round()
        r_m = m.run_round()
    np.testing.assert_allclose(r_m["dp_clip"], r_ref["dp_clip"], rtol=1e-6)
    np.testing.assert_allclose(r_m["train_loss"], r_ref["train_loss"],
                               rtol=1e-4)


def test_secure_agg_composition_masks_bits_and_matches():
    # Adaptive clipping composes with secure aggregation: the quantile
    # bits ride their own pairwise-mask stream and cancel in the sum, so
    # the clip trajectory matches the unmasked run up to the float32
    # mask-cancellation residual.
    plain = FederatedLearner(_cfg())
    masked = FederatedLearner(_cfg(secure_agg=True))
    for _ in range(3):
        r_p = plain.run_round()
        r_m = masked.run_round()
    np.testing.assert_allclose(r_m["dp_bit_frac"], r_p["dp_bit_frac"],
                               atol=5e-3)
    np.testing.assert_allclose(r_m["dp_clip"], r_p["dp_clip"], rtol=1e-3)
    np.testing.assert_allclose(r_m["train_loss"], r_p["train_loss"],
                               rtol=1e-3)

    # ... and each INDIVIDUAL masked bit is actually hidden: the per-lane
    # payload sits nowhere near {0, 1} (trajectory equality alone would
    # also hold if masking silently regressed to a no-op).
    from colearn_federated_learning_tpu.privacy import secure_agg as sa

    partners = jnp.asarray([1, 2], jnp.int32)
    m = sa.mask_scalar(jnp.float32(1.0), masked.base_key, jnp.int32(0),
                       partners, jnp.int32(0), std=1e3)
    assert min(abs(float(m)), abs(float(m) - 1.0)) > 1.0


def test_round_metrics_include_update_norms_only_when_private_safe():
    # Plain runs report pre-clip norm telemetry ...
    learner = FederatedLearner(_cfg(dp_clip=0.0, dp_adaptive_clip=False))
    rec = learner.run_round()
    assert rec["delta_norm_max"] >= rec["delta_norm_mean"] > 0.0
    # ... DP runs must NOT: exact un-noised norms are an unaccounted
    # release the epsilon report would not cover.
    dp = FederatedLearner(_cfg())
    rec = dp.run_round()
    assert "delta_norm_mean" not in rec and "delta_norm_max" not in rec
