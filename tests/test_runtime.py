"""telemetry/runtime.py: the XLA-introspection + live-export half of the
observability plane — CompileTracker signature fingerprinting and
recompile attribution, AOT cost analysis, the Prometheus text exposition
(validated against a strict grammar oracle — the S4 wire-format
contract), the HTTP exporter round-trip, the JSONL event stream, and the
`colearn top` renderer."""

import json
import re
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from colearn_federated_learning_tpu.telemetry import runtime
from colearn_federated_learning_tpu.telemetry.registry import (
    MetricsRegistry,
)


def fresh_registry() -> MetricsRegistry:
    return MetricsRegistry()


# ------------------------------------------------------- signatures ------
def test_abstract_signature_ignores_host_scalar_values():
    a = runtime.abstract_signature((jnp.ones((4,)), 3), {})
    b = runtime.abstract_signature((jnp.ones((4,)), 99), {})
    assert a == b                      # int VALUE change: same cache entry
    c = runtime.abstract_signature((jnp.ones((4,)), 3.0), {})
    assert a != c                      # int -> float: a re-trace


def test_abstract_signature_sees_shape_dtype_structure():
    base = runtime.abstract_signature((jnp.ones((4,)),), {})
    assert base != runtime.abstract_signature((jnp.ones((8,)),), {})
    assert base != runtime.abstract_signature(
        (jnp.ones((4,), jnp.int32),), {})
    assert base != runtime.abstract_signature(
        ((jnp.ones((4,)), jnp.ones((4,))),), {})


# --------------------------------------------------- CompileTracker ------
def test_compile_tracker_counts_distinct_signatures():
    reg = fresh_registry()
    f = runtime.CompileTracker(jax.jit(lambda x: x * 2), name="t",
                               registry=reg)
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))                  # same signature: no new compile
    assert (f.compiles, f.recompiles) == (1, 0)
    f(jnp.ones((8,)))
    assert (f.compiles, f.recompiles) == (2, 1)
    snap = reg.snapshot()
    assert snap["telemetry.compile_total{fn=t}"] == 2
    assert snap["telemetry.recompile_total{fn=t,reason=shape}"] == 1


def test_compile_tracker_attributes_recompile_reasons():
    reg = fresh_registry()
    f = runtime.CompileTracker(jax.jit(lambda x: x), name="t",
                               registry=reg)
    f(jnp.ones((4,)))
    f(jnp.ones((4,), jnp.int32))       # dtype flip
    f((jnp.ones((4,), jnp.int32), jnp.ones((2,))))  # structure flip
    snap = reg.snapshot()
    assert snap["telemetry.recompile_total{fn=t,reason=dtype}"] == 1
    assert snap["telemetry.recompile_total{fn=t,reason=structure}"] == 1
    assert f.recompiles == 2


def test_compile_tracker_forwards_calls_and_attrs():
    f = runtime.CompileTracker(jax.jit(lambda x: x + 1), name="t",
                               registry=fresh_registry())
    assert float(f(jnp.asarray(2.0))) == 3.0
    # AOT surface passes through: the perf script calls .lower() on it.
    assert hasattr(f, "lower")
    assert f.lower(jnp.asarray(2.0)) is not None


def test_cost_analysis_cached_per_signature():
    f = runtime.CompileTracker(jax.jit(lambda x: x @ x), name="t",
                               registry=fresh_registry())
    x = jnp.ones((16, 16))
    first = f.cost_analysis(x)
    again = f.cost_analysis(x)
    assert first["compile_s"] == again["compile_s"]   # cache hit: same dict
    if "flops" in first:                # CPU backend reports flops
        assert first["flops"] == pytest.approx(2 * 16 ** 3, rel=0.5)


def test_compiled_cost_handles_unjitted_functions():
    assert runtime.compiled_cost(lambda x: x, 1) == {}
    cost = runtime.compiled_cost(jax.jit(lambda x: x * x), jnp.ones((8,)))
    assert cost["compile_s"] > 0.0


def test_sample_device_memory_is_safe_on_cpu():
    reg = fresh_registry()
    stats = runtime.sample_device_memory(registry=reg)
    assert isinstance(stats, dict)     # CPU: {}; TPU: live gauges set
    if stats.get("bytes_in_use"):
        assert reg.gauge("runtime.hbm_bytes_in_use").value > 0


# ----------------------------------------------- Prometheus exposition ---
# Strict oracle for the text exposition 0.0.4 sample/comment grammar.
_PROM_LINE = re.compile(
    r"^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|summary)"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"(,[a-zA-Z_][a-zA-Z0-9_]*"
    r"=\"[^\"]*\")*\})?"
    r" -?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?)$")


def populated_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("comm.retry_total").inc(3)
    reg.counter("telemetry.recompile_total",
                labels={"fn": "engine.round", "reason": "shape"}).inc()
    reg.gauge("runtime.hbm_bytes_in_use").set(2.5 * 2**30)
    reg.gauge("runtime.hbm_bytes_limit")          # never set: excluded
    reg.histogram("fed.round_time_s").observe(0.25)
    reg.histogram("fed.round_time_s").observe(0.75)
    return reg


def test_prometheus_text_matches_exposition_grammar():
    text = runtime.prometheus_text(populated_registry().typed_snapshot())
    assert text.endswith("\n")
    for line in text.splitlines():
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"


def test_prometheus_text_families_and_values():
    text = runtime.prometheus_text(populated_registry().typed_snapshot())
    assert "# TYPE colearn_comm_retry_total counter" in text
    assert "colearn_comm_retry_total 3" in text
    # Labeled child rendered with quoted labels under the parent family.
    assert ('colearn_telemetry_recompile_total'
            '{fn="engine.round",reason="shape"} 1') in text
    # TYPE emitted once per family even with labeled children present.
    assert text.count("# TYPE colearn_telemetry_recompile_total") == 1
    # Histogram -> summary with quantiles + count/sum.
    assert "# TYPE colearn_fed_round_time_s summary" in text
    assert 'colearn_fed_round_time_s{quantile="0.5"}' in text
    assert "colearn_fed_round_time_s_count 2" in text
    assert "colearn_fed_round_time_s_sum 1" in text
    # A gauge that was never set stays out of the exposition.
    assert "colearn_runtime_hbm_bytes_limit" not in text
    assert "colearn_runtime_hbm_bytes_in_use" in text


def test_prometheus_label_values_are_escaped():
    reg = MetricsRegistry()
    reg.counter("telemetry.compile_total",
                labels={"fn": 'we"ird\\name'}).inc()
    text = runtime.prometheus_text(reg.typed_snapshot())
    assert '{fn="we\\"ird\\\\name"}' in text


# ------------------------------------------------------------ exporter ---
def test_metrics_exporter_serves_both_endpoints():
    reg = populated_registry()
    with runtime.MetricsExporter(port=0, registry=reg) as exp:
        assert exp.port                # ephemeral port bound and readable
        base = f"http://127.0.0.1:{exp.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            assert "version=0.0.4" in r.headers["Content-Type"]
            text = r.read().decode("utf-8")
        assert "colearn_comm_retry_total 3" in text
        with urllib.request.urlopen(f"{base}/snapshot.json",
                                    timeout=10) as r:
            snap = json.loads(r.read())
        assert snap["comm.retry_total"] == 3
        # Scrapes count themselves (visible on the NEXT scrape).
        assert reg.counter("export.scrapes_total").value == 2
    assert exp.port is None            # closed


def test_metrics_exporter_404_off_path():
    with runtime.MetricsExporter(port=0,
                                 registry=MetricsRegistry()) as exp:
        with pytest.raises(urllib.request.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/nope", timeout=10)


# ------------------------------------------------------------ EventLog ---
def test_event_log_appends_flushed_jsonl(tmp_path):
    path = tmp_path / "events" / "stream.jsonl"
    log = runtime.EventLog(str(path))
    log.emit("start", role="coordinator")
    log.emit("round", round=1, train_loss=0.5)
    # Flushed per line: readable BEFORE close (tail -f contract).
    docs = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [d["event"] for d in docs] == ["start", "round"]
    assert all("ts" in d for d in docs)
    assert docs[1]["round"] == 1
    log.close()
    log.emit("after_close")            # silently dropped, no crash
    assert len(path.read_text().splitlines()) == 2


# ---------------------------------------------------------- colearn top --
def test_render_top_shows_counters_and_rates():
    snap = {"fed.rounds_total": 10, "fed.clients_dropped": 2,
            "comm.retry_total": 7, "telemetry.compile_total": 3,
            "telemetry.recompile_total": 1,
            "fed.round_time_s": {"count": 10, "p50": 0.5, "p90": 0.9,
                                 "max": 1.2},
            "runtime.hbm_bytes_in_use": 2 * 2**30,
            "runtime.hbm_bytes_limit": 8 * 2**30}
    prev = {"fed.rounds_total": 6}
    body = runtime.render_top(snap, prev=prev, interval_s=2.0)
    assert "rounds total" in body and "10" in body
    assert "(2.000/s)" in body         # (10-6)/2s
    assert "p50 0.500s" in body
    assert "recompiles 1" in body
    assert "(25.0%)" in body           # 2G of 8G
    # Pure function: renders from an empty snapshot without crashing.
    assert "colearn top" in runtime.render_top({})


# ------------------------------------------------- labeled instruments --
def test_labeled_histogram_child_rolls_up_and_exposes():
    reg = MetricsRegistry()
    reg.histogram("fed.phase_time_s",
                  labels={"phase": "agg_fold"}).observe(0.2)
    reg.histogram("fed.phase_time_s",
                  labels={"phase": "downlink"}).observe(0.4)
    # every child observation also lands in the unlabeled aggregate, so
    # render_top latency lines and family-level SLO gates keep working
    assert reg.histogram("fed.phase_time_s").count == 2
    assert reg.histogram(
        "fed.phase_time_s", labels={"phase": "agg_fold"}).count == 1

    text = runtime.prometheus_text(reg.typed_snapshot())
    for line in text.splitlines():
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
    # one family: TYPE once, children keyed by merged label sets
    assert text.count("# TYPE colearn_fed_phase_time_s summary") == 1
    assert ('colearn_fed_phase_time_s'
            '{quantile="0.5",phase="agg_fold"} 0.2') in text
    assert 'colearn_fed_phase_time_s_count{phase="agg_fold"} 1' in text
    assert 'colearn_fed_phase_time_s_sum{phase="downlink"} 0.4' in text
    assert "colearn_fed_phase_time_s_count 2" in text  # the aggregate


def test_labeled_gauge_child_does_not_roll_up():
    reg = MetricsRegistry()
    reg.gauge("health.device_score", labels={"device": "2"}).set(11)
    snap = reg.snapshot()
    assert snap["health.device_score{device=2}"] == 11.0
    # "last across labels" is noise: the parent gauge stays unset and
    # out of the exposition
    text = runtime.prometheus_text(reg.typed_snapshot())
    assert 'colearn_health_device_score{device="2"} 11' in text
    assert "\ncolearn_health_device_score 1" not in text


def test_render_top_aggregator_tier_section():
    snap = {"fed.rounds_total": 4,
            "comm.agg_heartbeat_age_s{agg=0}": 0.8,
            "comm.agg_heartbeat_age_s{agg=1}": 12.5,
            "comm.agg_slice_devices{agg=0}": 3,
            "comm.agg_slice_devices{agg=1}": 2,
            "comm.agg_partials_folded_total{agg=0}": 12,
            "comm.agg_failovers_total": 1}
    body = runtime.render_top(snap)
    assert "aggregator tier" in body
    agg0 = next(ln for ln in body.splitlines() if "agg 0" in ln)
    assert "hb age" in agg0 and "0.80s" in agg0
    assert "slice    3" in agg0 and "partials     12" in agg0
    assert "failovers" in body
    # flat runs keep the old layout: no tier section at all
    assert "aggregator tier" not in runtime.render_top(
        {"fed.rounds_total": 4})


# ------------------------------------------- staleness observatory ------
def test_staleness_histogram_and_arrival_gauge_exposition():
    # The async coordinator's observatory instruments, as scraped: the
    # outcome-labeled staleness histogram must expose as ONE summary
    # family with per-outcome children plus the unlabeled roll-up, and
    # the arrival estimator's gauges must land fleet + per-device.
    from colearn_federated_learning_tpu.telemetry.arrival import (
        ArrivalEstimator,
    )
    reg = MetricsRegistry()
    for tau in (0, 1, 3):
        reg.histogram("async.staleness",
                      labels={"outcome": "folded"}).observe(tau)
    reg.histogram("async.staleness",
                  labels={"outcome": "discarded"}).observe(9)
    est = ArrivalEstimator()
    est.observe("d0", now=0.0)
    est.observe("d0", now=2.0)
    est.export_gauges(reg, "async.arrival_rate_per_s")

    text = runtime.prometheus_text(reg.typed_snapshot())
    for line in text.splitlines():
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
    assert text.count("# TYPE colearn_async_staleness summary") == 1
    assert ('colearn_async_staleness'
            '{quantile="0.5",outcome="folded"} 1') in text
    assert 'colearn_async_staleness_count{outcome="folded"} 3' in text
    assert 'colearn_async_staleness_sum{outcome="discarded"} 9' in text
    assert "colearn_async_staleness_count 4" in text    # the roll-up
    assert "# TYPE colearn_async_arrival_rate_per_s gauge" in text
    assert "colearn_async_arrival_rate_per_s 0.5" in text
    assert 'colearn_async_arrival_rate_per_s{device="d0"} 0.5' in text


def test_render_top_async_plane_section():
    snap = {"fed.rounds_total": 4,
            "async.aggregations_total": 12,
            "async.buffer_target": 8,
            "async.arrival_rate_per_s": 2.5,
            "async.updates_discarded_stale": 3,
            "async.staleness": {"count": 15, "sum": 20.0,
                                "p50": 1.0, "p90": 4.0, "p99": 6.0},
            "async.contribution_mass{outcome=folded}": 10.5,
            "async.contribution_mass{outcome=discarded}": 0.75,
            "async.pumps{state=wait}": 5,
            "async.pumps{state=train}": 3}
    body = runtime.render_top(snap)
    assert "async plane" in body
    assert "aggregations" in body and "12" in body
    assert "buffer K" in body
    assert "arrival rate" in body and "2.500/s" in body
    assert "stale discards" in body
    stale = next(ln for ln in body.splitlines() if "staleness" in ln)
    assert "p50 1.0" in stale and "p90 4.0" in stale and "p99 6.0" in stale
    mass = next(ln for ln in body.splitlines() if "mass folded" in ln)
    assert "10.50" in mass and "0.75" in mass
    pumps = next(ln for ln in body.splitlines() if "pumps" in ln)
    assert "wait 5" in pumps and "train 3" in pumps
    # flat sync snapshots keep the classic layout: no async section
    assert "async plane" not in runtime.render_top(
        {"fed.rounds_total": 4})


def test_render_top_async_plane_fleetsim_aliases():
    # fleetsim's virtual-clock async plane feeds the same section
    # through its own metric names (per-minute rate units).
    snap = {"fleetsim.async_aggregations_total": 6,
            "fleetsim.async_buffer_size": 4,
            "fleetsim.async_arrival_rate_per_min": 1.2,
            "fleetsim.async_updates_discarded_total": 2,
            "fleetsim.async_staleness": {"count": 8, "sum": 9.0,
                                         "p50": 1.0, "p90": 2.0,
                                         "p99": 3.0}}
    body = runtime.render_top(snap)
    assert "async plane" in body
    assert "1.200/min" in body
    assert "p99 3.0" in body
