"""parallel/partition.py: the regex rule engine behind the sharded server.

Rule semantics (first-match-wins precedence, scalar/indivisible fallback
to replicated, ndim constraints, per-model rule-set selection) plus the
shard/gather closure roundtrip on a forced-CPU ``(model,)`` mesh — the
partition layer every sharded-server test (test_sharded_server.py) and
the mesh-smoke bench build on.
"""

import re

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from colearn_federated_learning_tpu.parallel import partition


def _bertish_params():
    """Synthetic tree with flax-style transformer paths (the shapes the
    TRANSFORMER_RULES table documents)."""
    return {
        "params": {
            "Embed_0": {"embedding": np.arange(16 * 8, dtype=np.float32)
                        .reshape(16, 8)},
            "TransformerBlock_0": {
                "attn": {
                    "query": {"kernel": np.ones((8, 4, 2), np.float32),
                              "bias": np.ones((4, 2), np.float32)},
                    "out": {"kernel": np.ones((4, 2, 8), np.float32)},
                },
                "Dense_0": {"kernel": np.ones((8, 32), np.float32),
                            "bias": np.ones((32,), np.float32)},
                "Dense_1": {"kernel": np.ones((32, 8), np.float32)},
                "LayerNorm_0": {"scale": np.ones((8,), np.float32)},
            },
            "step": np.zeros((), np.float32),
        }
    }


def _specs(params, size=4, rules=partition.TRANSFORMER_RULES):
    return partition.match_partition_rules(
        rules, params, axis="model", sizes={"model": size})


# ------------------------------------------------------- rule matching ----
def test_transformer_rules_first_match_wins():
    sp = _specs(_bertish_params())["params"]
    blk = sp["TransformerBlock_0"]
    # qkv kernel (D, H, hd): head dim (-2) — NOT the generic qkv rule
    # below it, which would shard dim 0.  Ordering is the contract.
    assert blk["attn"]["query"]["kernel"] == P(None, "model", None)
    assert blk["attn"]["query"]["bias"] == P("model", None)
    assert blk["attn"]["out"]["kernel"] == P("model", None, None)
    assert sp["Embed_0"]["embedding"] == P("model", None)
    assert blk["Dense_0"]["kernel"] == P(None, "model")
    assert blk["Dense_0"]["bias"] == P("model")
    assert blk["Dense_1"]["kernel"] == P("model", None)
    # No specific rule: the trailing catch-all replicates.
    assert blk["LayerNorm_0"]["scale"] == P()


def test_scalar_always_replicated_even_when_a_rule_matches():
    # A greedy rule that would shard dim 0 of everything: scalars still
    # come back replicated (there is no dim to shard).
    sp = partition.match_partition_rules(
        ((r"", 0),), {"s": np.float32(3.0), "v": np.ones(8, np.float32)},
        axis="model", sizes={"model": 4})
    assert sp["s"] == P()
    assert sp["v"] == P("model")


def test_indivisible_dim_replicates_whole_leaf():
    # 6 % 4 != 0: GSPMD would pad — we replicate instead (numerics exact).
    sp = partition.match_partition_rules(
        ((r"", -1),), {"w": np.ones((8, 6), np.float32)},
        axis="model", sizes={"model": 4})
    assert sp["w"] == P()
    # Same leaf at a size that divides: sharded.
    sp = partition.match_partition_rules(
        ((r"", -1),), {"w": np.ones((8, 6), np.float32)},
        axis="model", sizes={"model": 2})
    assert sp["w"] == P(None, "model")


def test_ndim_constraint_skips_wrong_rank():
    # The vocab-embedding rule is pinned to ndim=2: a 1-D param that
    # happens to be NAMED "embedding" must fall through to the catch-all.
    sp = _specs({"pos": {"embedding": np.ones((16,), np.float32)}})
    assert sp["pos"]["embedding"] == P()


def test_no_match_raises_value_error():
    with pytest.raises(ValueError, match="no partition rule matched"):
        partition.match_partition_rules(
            ((r"kernel$", 0),), {"odd": {"bias": np.ones(4, np.float32)}},
            axis="model", sizes={"model": 2})


def test_explicit_partitionspec_right_aligned():
    # A P("model") rule on a 2-D leaf right-aligns: last dim sharded.
    sp = partition.match_partition_rules(
        ((r"", P("model")),), {"w": np.ones((4, 8), np.float32)},
        axis="model", sizes={"model": 4})
    assert sp["w"] == P(None, "model")


def test_cnn_rules_shard_output_channels():
    params = {"Conv_0": {"kernel": np.ones((3, 3, 1, 8), np.float32),
                         "bias": np.ones((8,), np.float32)},
              "Dense_0": {"kernel": np.ones((32, 4), np.float32)}}
    sp = partition.match_partition_rules(
        partition.CNN_RULES, params, axis="model", sizes={"model": 4})
    assert sp["Conv_0"]["kernel"] == P(None, None, None, "model")
    assert sp["Conv_0"]["bias"] == P("model")
    assert sp["Dense_0"]["kernel"] == P(None, "model")


def test_rules_for_model_selection():
    assert partition.rules_for_model("bert") is partition.TRANSFORMER_RULES
    assert partition.rules_for_model("moe_bert") is partition.TRANSFORMER_RULES
    assert partition.rules_for_model("vit_b16") is partition.TRANSFORMER_RULES
    assert partition.rules_for_model("cnn") is partition.CNN_RULES
    assert partition.rules_for_model("mlp") is partition.CNN_RULES
    assert partition.rules_for_model("tcn") is partition.DEFAULT_RULES
    assert partition.rules_for_model("") is partition.DEFAULT_RULES
    # Every published rule set ends with a catch-all: no tree can raise.
    for rules in (partition.TRANSFORMER_RULES, partition.CNN_RULES,
                  partition.DEFAULT_RULES):
        assert re.compile(rules[-1][0]).search("anything/at/all")


# --------------------------------------------------- mesh roundtrips ----
@pytest.fixture(scope="module")
def model_mesh4():
    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("needs the forced 8-device CPU host")
    return Mesh(np.array(devs[:4]), ("model",))


def test_shard_and_gather_fns_roundtrip(model_mesh4):
    params = _bertish_params()
    specs = _specs(params)
    shard_fns, gather_fns = partition.make_shard_and_gather_fns(
        specs, model_mesh4)
    sharded = jax.tree.map(lambda f, w: f(w), shard_fns, params)
    qk = sharded["params"]["TransformerBlock_0"]["attn"]["query"]["kernel"]
    assert len({partition._index_key(s.index)
                for s in qk.addressable_shards}) == 4
    back = jax.tree.map(lambda f, w: f(w), gather_fns, sharded)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_server_placement_slice_assemble_roundtrip(model_mesh4):
    params = _bertish_params()
    placement = partition.ServerPlacement.from_params(
        params, model_mesh4, "model", partition.TRANSFORMER_RULES)
    assert 0.0 < placement.sharded_fraction() < 1.0

    sliced = placement.slice_tree(params)
    assembled = placement.assemble(sliced)
    host = partition.host_tree(assembled)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(host)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    # shapes_tree: dtype/shape template without touching device data.
    tmpl = placement.shapes_tree()
    for a, t in zip(jax.tree.leaves(params), jax.tree.leaves(tmpl)):
        assert np.shape(a) == np.shape(t)
        assert np.asarray(a).dtype == t.dtype


def test_gather_avoided_accounting(model_mesh4):
    params = _bertish_params()
    placement = partition.ServerPlacement.from_params(
        params, model_mesh4, "model", partition.TRANSFORMER_RULES)
    sharded = placement.shard(params)
    measured = partition.tree_gather_avoided(sharded)
    assert measured > 0
    # The pure shape-math estimator (fleetsim's) agrees with the measured
    # per-shard accounting exactly.
    est = partition.estimate_gather_avoided(
        params, partition.TRANSFORMER_RULES, "model", 4)
    assert est == measured
    # Replicated host tree: nothing to avoid.
    assert partition.tree_gather_avoided(params) == 0
    assert partition.estimate_gather_avoided(
        params, partition.TRANSFORMER_RULES, "model", 1) == 0


def test_bytes_per_chip_sharded_below_replicated(model_mesh4):
    params = _bertish_params()
    placement = partition.ServerPlacement.from_params(
        params, model_mesh4, "model", partition.TRANSFORMER_RULES)
    sharded = placement.shard(params)
    replicated = partition.shard_tree(
        params, jax.tree.map(lambda _: P(), params), model_mesh4)
    assert partition.bytes_per_chip(sharded) < \
        partition.bytes_per_chip(replicated)
