"""Multi-process chaos soak (faults/procsoak.py): KillSpec validation,
the baseline-vs-faulted gate logic, and — behind ``-m slow`` — a real
subprocess federation whose coordinator takes a genuine SIGKILL
mid-round and must resume from its checkpoint + round WAL."""

import os
import sys

import pytest

from colearn_federated_learning_tpu.faults.procsoak import (
    KillSpec,
    canned_kill_schedule,
    run_proc_soak,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import chaos_soak_mp  # noqa: E402


def test_kill_spec_validation():
    KillSpec("worker:3", after_round=0)
    KillSpec("coordinator", after_round=2)
    KillSpec("broker", after_round=1)
    with pytest.raises(ValueError, match="target"):
        KillSpec("edge", after_round=0)
    with pytest.raises(ValueError, match="target"):
        KillSpec("worker:x", after_round=0)
    with pytest.raises(ValueError, match="after_round"):
        KillSpec("coordinator", after_round=-1)
    with pytest.raises(ValueError, match="restart"):
        KillSpec("coordinator", after_round=0, restart=False)
    with pytest.raises(ValueError, match="restart"):
        KillSpec("broker", after_round=0, restart=False)


def test_kill_spec_accepts_aggregator_targets():
    KillSpec("aggregator:0", after_round=1)
    # Unlike coordinator/broker, a dead aggregator need not restart: the
    # root re-homes its slice to a sibling (the failover under test).
    KillSpec("aggregator:1", after_round=0, restart=False)
    with pytest.raises(ValueError, match="target"):
        KillSpec("aggregator", after_round=0)
    with pytest.raises(ValueError, match="target"):
        KillSpec("aggregator:x", after_round=0)


def test_kill_spec_accepts_async_coordinator_target():
    # The buffered-async plane's singleton: same restart contract as the
    # sync coordinator (nobody else can fold, so the fleet would hang).
    KillSpec("async-coordinator", after_round=1)
    with pytest.raises(ValueError, match="restart"):
        KillSpec("async-coordinator", after_round=0, restart=False)


def test_run_async_soak_rejects_tiny_budgets():
    from colearn_federated_learning_tpu.faults.procsoak import run_async_soak

    # < 4 aggregations cannot fit a mid-run kill plus a meaningful tail
    # for the loss-parity gate.
    with pytest.raises(ValueError, match="aggregations"):
        run_async_soak(aggregations=3)


def test_canned_schedule_scales_with_run_length():
    short = canned_kill_schedule(3, 2)
    assert [k.target for k in short] == ["coordinator"]
    assert short[0].after_round == 0       # after the first checkpoint
    full = canned_kill_schedule(6, 3)
    assert [k.target for k in full] == ["worker:1", "coordinator", "broker"]
    assert full[1].after_round == 2
    # The broker dies a round after the coordinator resumed, with a full
    # round left to prove the federation still commits past the rebind.
    assert full[-1].after_round == 3


def _summary(**over):
    base = dict(exit_code=0, rounds_run=3, rounds_resumed=0, kills=[],
                weighted_acc=0.8, per_client_acc={"0": 0.8, "1": 0.8})
    base.update(over)
    return base


def test_check_proc_soak_gate():
    kills = [KillSpec("coordinator", after_round=0)]
    ok = chaos_soak_mp.check_proc_soak(
        _summary(),
        _summary(rounds_resumed=1,
                 kills=[{"target": "coordinator", "fired_after_round": 0}]),
        rounds=3, tol=0.1, kills=kills)
    assert ok == []

    # Coordinator was killed but never resumed.
    p = chaos_soak_mp.check_proc_soak(
        _summary(),
        _summary(kills=[{"target": "coordinator", "fired_after_round": 0}]),
        rounds=3, tol=0.1, kills=kills)
    assert any("never resumed" in x for x in p)

    # A round record was lost across the kill.
    p = chaos_soak_mp.check_proc_soak(
        _summary(), _summary(rounds_run=2, rounds_resumed=1,
                             kills=[{"target": "coordinator"}]),
        rounds=3, tol=0.1, kills=kills)
    assert any("rounds were lost" in x for x in p)

    # Accuracy drifted beyond tolerance on the common clients.
    p = chaos_soak_mp.check_proc_soak(
        _summary(),
        _summary(rounds_resumed=1, kills=[{"target": "coordinator"}],
                 per_client_acc={"0": 0.1, "1": 0.1}, weighted_acc=0.1),
        rounds=3, tol=0.1, kills=kills)
    assert any("drifted" in x for x in p)

    # A baseline that resumed means the harness itself is broken.
    p = chaos_soak_mp.check_proc_soak(
        _summary(rounds_resumed=1), _summary(rounds_resumed=1,
                                             kills=[{"t": 1}]),
        rounds=3, tol=0.1, kills=kills)
    assert any("baseline resumed" in x for x in p)


@pytest.mark.slow
def test_proc_soak_coordinator_sigkill_resumes(tmp_path):
    """The acceptance run: 2 workers, 3 rounds, a real SIGKILL to the
    coordinator process mid-round 1 — the relaunched ``--resume``
    incarnation must finish the full round budget with a final score."""
    kills = canned_kill_schedule(3, 2)
    s = run_proc_soak(rounds=3, n_workers=2, kills=kills,
                      workdir=str(tmp_path), round_timeout=120.0,
                      timeout_s=420.0)
    assert s["exit_code"] == 0
    assert s["rounds_run"] == 3
    assert s["rounds_resumed"] >= 1
    assert s["coordinator_incarnations"] == 2
    assert len(s["kills"]) == 1
    assert s["weighted_acc"] is not None
    # Flight-recorder survivability: the SIGKILLed coordinator pid must
    # have left a parseable black box (its last heartbeat rewrite), and
    # the kill ledger records which pid took the signal.
    assert all("pid" in k for k in s["kills"])
    assert s["flight_missing"] == []
    assert s["flight_dumps"] >= 1
    from colearn_federated_learning_tpu.telemetry import flight

    dumps = flight.load_flight_dumps(str(tmp_path / "flight"))
    by_pid = {d.get("pid"): d for d in dumps if "error" not in d}
    victim = by_pid[s["kills"][0]["pid"]]
    assert victim["schema"] == "colearn-flight-v1"
    assert victim["role"] == "coordinator"


@pytest.mark.slow
def test_proc_soak_broker_sigkill_heals(tmp_path):
    """Control-plane SPOF: a real SIGKILL to the broker process after
    round 1 — the harness rebinds a fresh broker on the SAME port, the
    workers' re-enrollment watchdogs and the coordinator's
    ``_rebuild_broker`` heal into it, and the remaining round budget
    still commits with a final score."""
    kills = [KillSpec("broker", after_round=1)]
    s = run_proc_soak(rounds=3, n_workers=2, kills=kills,
                      workdir=str(tmp_path), round_timeout=120.0,
                      timeout_s=420.0)
    assert s["exit_code"] == 0
    assert s["rounds_run"] == 3
    assert s["coordinator_incarnations"] == 1   # only the broker died
    assert len(s["kills"]) == 1
    assert s["kills"][0]["target"] == "broker"
    assert s["weighted_acc"] is not None
    # The broker flies the black box too: its SIGKILLed pid must have
    # left a parseable dump like any other victim.
    assert all("pid" in k for k in s["kills"])
    assert s["flight_missing"] == []
    from colearn_federated_learning_tpu.telemetry import flight

    dumps = flight.load_flight_dumps(str(tmp_path / "flight"))
    by_pid = {d.get("pid"): d for d in dumps if "error" not in d}
    assert by_pid[s["kills"][0]["pid"]]["role"] == "broker"


@pytest.mark.slow
def test_async_soak_coordinator_sigkill_resumes(tmp_path):
    """The buffered-async acceptance run: 3 workers, a real SIGKILL to
    the async coordinator mid-aggregation, relaunch with ``--resume`` —
    versions stay monotonic across both incarnations, the RDP accountant
    replay reproduces the final epsilon exactly (no double-charge), and
    the faulted run's tail loss lands within tolerance of a same-seed
    kill-free baseline."""
    from colearn_federated_learning_tpu.faults.procsoak import run_async_soak

    s = run_async_soak(aggregations=5, n_workers=3,
                       workdir=str(tmp_path), round_timeout=120.0,
                       timeout_s=600.0)
    assert s["exit_code"] == 0
    assert s["baseline_exit_code"] == 0
    assert s["aggregations_run"] >= 5
    assert s["version_monotonic"]
    assert s["resumed"] >= 1
    assert s["coordinator_incarnations"] == 2
    assert s["dp_replay_ok"], (s["dp_epsilon"], s["dp_epsilon_replayed"])
    assert s["loss_gap_ok"], s["loss_gap"]
    assert s["postmortem_attributed"]
    assert s["health_ledger_ok"]
    assert s["fault_retries"] >= 1        # the FaultPlan flap landed
    assert s["flight_missing"] == []
