"""Personalized evaluation (engine.evaluate_personalized).

FedPer-style probe the reference cannot ask: fine-tune the global model on
half of each client's shard, score global vs personalized on the held-out
half.  Under a strongly non-IID Dirichlet partition the personalized model
must beat the global one on the clients' own distributions.
"""

import numpy as np
from jax.sharding import Mesh

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cfg():
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8,
                        partition="dirichlet", dirichlet_alpha=0.1,
                        max_examples_per_client=64),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=3, cohort_size=0,
                      local_steps=3, batch_size=16, lr=0.1, momentum=0.9),
        run=RunConfig(name="pers_test"),
    )


def test_personalization_gains_under_non_iid():
    learner = FederatedLearner(_cfg())
    learner.fit(rounds=3)
    rep = learner.evaluate_personalized(steps=10)
    # Sanity: per-client arrays align and weights come from real clients.
    n = len(rep["per_client_global_acc"])
    assert n == len(rep["per_client_personalized_acc"]) == 8
    assert (rep["num_eval_examples"] > 0).all()
    # α=0.1 partitions are nearly single-class per client: a few local
    # steps on the client's own half must beat the global model there.
    assert rep["personalized_acc"] > rep["global_acc"]
    assert rep["personalization_gain"] > 0.02, rep["personalization_gain"]


def test_personalization_mesh_matches_single_device(cpu_devices):
    cfg = _cfg()
    ref = FederatedLearner(cfg)
    ref.fit(rounds=2)
    rep_ref = ref.evaluate_personalized(steps=4)

    mesh = Mesh(np.array(cpu_devices[:8]), ("clients",))
    m = FederatedLearner(cfg, mesh=mesh)
    m.fit(rounds=2)
    rep_m = m.evaluate_personalized(steps=4)
    np.testing.assert_allclose(rep_m["per_client_global_acc"],
                               rep_ref["per_client_global_acc"], atol=1e-6)
    np.testing.assert_allclose(rep_m["per_client_personalized_acc"],
                               rep_ref["per_client_personalized_acc"],
                               atol=1e-5)
