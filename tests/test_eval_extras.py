"""Per-client federated evaluation, local optimizer choice, and
end-to-end determinism."""

import dataclasses

import numpy as np
import pytest
from jax.sharding import Mesh

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cfg(**fed_kw):
    fed = dict(strategy="fedavg", rounds=2, cohort_size=0, local_steps=3,
               batch_size=16, lr=0.1, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=6,
                        partition="dirichlet", dirichlet_alpha=0.3),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="eval_extras", backend="cpu"),
    )


def test_per_client_eval_shapes_and_aggregates():
    l = FederatedLearner(_cfg())
    for _ in range(3):
        l.run_round()
    rep = l.evaluate_per_client()
    n = len(rep["per_client_acc"])
    assert n == 6
    assert rep["num_examples"].sum() > 0
    assert 0.0 <= rep["weighted_acc"] <= 1.0
    assert rep["acc_p10"] <= rep["acc_p50"] <= rep["acc_p90"]
    w = rep["num_examples"] / rep["num_examples"].sum()
    np.testing.assert_allclose(
        rep["weighted_acc"], float((rep["per_client_acc"] * w).sum()),
        rtol=1e-6,
    )


def test_per_client_eval_mesh_matches_vmap(cpu_devices):
    cfg = _cfg()
    a = FederatedLearner(cfg)
    b = FederatedLearner(cfg, mesh=Mesh(np.array(cpu_devices[:4]), ("clients",)))
    a.run_round(); b.run_round()
    ra = a.evaluate_per_client()
    rb = b.evaluate_per_client()
    # Same original-client-id order on both placements.
    np.testing.assert_array_equal(ra["num_examples"], rb["num_examples"])
    np.testing.assert_allclose(ra["per_client_acc"], rb["per_client_acc"],
                               atol=1e-5)
    np.testing.assert_allclose(ra["per_client_loss"], rb["per_client_loss"],
                               rtol=1e-4)


def test_local_adam_trains():
    l = FederatedLearner(_cfg(local_optimizer="adam", lr=0.003))
    first = l.run_round()
    for _ in range(4):
        rec = l.run_round()
    assert rec["train_loss"] < first["train_loss"]


def test_local_optimizer_validation():
    with pytest.raises(ValueError, match="unknown local optimizer"):
        FederatedLearner(_cfg(local_optimizer="lion"))
    with pytest.raises(ValueError, match="option-II"):
        FederatedLearner(_cfg(strategy="scaffold", local_optimizer="adam"))


def test_same_seed_is_bitwise_deterministic():
    cfg = _cfg(straggler_prob=0.3, cohort_size=3)
    a = FederatedLearner(cfg)
    b = FederatedLearner(cfg)
    for _ in range(3):
        ra = a.run_round()
        rb = b.run_round()
        # phase_* keys are wall-clock phase timings — observability, not
        # learning state — and legitimately differ run to run.
        assert ({k: v for k, v in ra.items() if not k.startswith("phase_")}
                == {k: v for k, v in rb.items()
                    if not k.startswith("phase_")})
    pa = np.asarray(next(iter(jax_leaves(a))))
    pb = np.asarray(next(iter(jax_leaves(b))))
    np.testing.assert_array_equal(pa, pb)


def jax_leaves(learner):
    import jax

    return jax.tree.leaves(learner.server_state.params)
