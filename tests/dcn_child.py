"""Child process for the multi-host (DCN) hybrid-mesh integration test.

Launched twice by tests/test_dcn.py with ``python dcn_child.py <pid> <port>``:
initializes 2-process distributed JAX over virtual CPU devices, builds the
DCN-aware hybrid mesh through parallel/mesh.make_mesh, runs one
cross-process psum and one full engine round, and prints machine-checkable
lines the parent asserts on.
"""

import os
import sys

pid = int(sys.argv[1])
port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from colearn_federated_learning_tpu.utils.jax_compat import (  # noqa: E402
    shard_map,
)

from colearn_federated_learning_tpu.fed.engine import (  # noqa: E402
    FederatedLearner,
)
from colearn_federated_learning_tpu.parallel.mesh import make_mesh  # noqa: E402
from colearn_federated_learning_tpu.utils.config import (  # noqa: E402
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

mesh = make_mesh(("clients",))
print(pid, "MESHLAYOUT",
      ",".join(str(d.process_index) for d in mesh.devices.ravel()),
      flush=True)

f = jax.jit(shard_map(lambda x: jax.lax.psum(x, "clients"),
                      mesh=mesh, in_specs=P("clients"), out_specs=P()))
xs = jax.device_put(jnp.arange(8, dtype=jnp.float32),
                    NamedSharding(mesh, P("clients")))
print(pid, "PSUM", float(np.asarray(f(xs).addressable_data(0))), flush=True)

cfg = ExperimentConfig(
    data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                    max_examples_per_client=32),
    model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=2),
    fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=0, local_steps=2,
                  batch_size=8, lr=0.1, momentum=0.9),
    run=RunConfig(name="dcn_test", backend="cpu"),
)
learner = FederatedLearner(cfg, mesh=mesh)
rec = learner.run_round()
print(pid, "ROUND", rec["train_loss"], flush=True)
