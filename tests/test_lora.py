"""fed/lora.py: rank-r adapter federation — partition-rule targeting,
apply/merge math (incl. tp=2 sharded merge), factor-fold bitwise parity
(flat + aggregator-tree partials), secure-agg-over-factors exactness,
validate_robustness rejection matrix, one-compile-signature factor
training, and end-to-end merge parity on the socket plane."""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.comm.aggregation import StreamingFolder
from colearn_federated_learning_tpu.comm.broker import MessageBroker
from colearn_federated_learning_tpu.comm.coordinator import (
    FederatedCoordinator,
)
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.fed import local as local_lib
from colearn_federated_learning_tpu.fed import lora
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.parallel import partition
from colearn_federated_learning_tpu.telemetry import runtime
from colearn_federated_learning_tpu.utils.config import (
    ModelConfig,
    validate_robustness,
)
from tests.test_comm import _config, _run_federation

RANK, ALPHA = 4, 16.0


@pytest.fixture(scope="module")
def bert_params():
    """Real tiny-BERT params: targeting must be exercised against the
    actual flax param paths the partition rules were written for."""
    cfg = ModelConfig(name="bert", num_classes=4, width=32, depth=2,
                      num_heads=2, seq_len=64, vocab_size=2000)
    model = model_registry.build_model(cfg)
    return model_registry.init_params(
        model, jnp.zeros((1, 64), jnp.int32), jax.random.PRNGKey(0))


def _rand_factors(params, key=7):
    """Factor tree with BOTH A and B random — exercises nonzero merges."""
    rng = np.random.default_rng(key)
    return jax.tree.map(
        lambda f: rng.standard_normal(f.shape).astype(np.float32),
        jax.tree.map(np.asarray,
                     lora.init_factors(params, RANK, model_name="bert")))


def _tree_bytes(tree):
    return [np.asarray(l).tobytes() for l in jax.tree.leaves(tree)]


# ------------------------------------------------------------ targeting ----
def test_targeting_follows_partition_rules(bert_params):
    targets = lora.target_paths(bert_params, model_name="bert")
    # Adapted: vocab embedding, every block's attention QKV/out and MLP
    # up/down kernels — 1 + 2 blocks * 6 matrices.
    assert "Embed_0/embedding" in targets
    for blk in ("TransformerBlock_0", "TransformerBlock_1"):
        for mat in ("MultiHeadAttention_0/query/kernel",
                    "MultiHeadAttention_0/key/kernel",
                    "MultiHeadAttention_0/value/kernel",
                    "MultiHeadAttention_0/out/kernel",
                    "Dense_0/kernel", "Dense_1/kernel"):
            assert f"{blk}/{mat}" in targets
    assert len(targets) == 13
    # Frozen: classifier head, norms, position embedding, and every bias
    # (reshaped-head attention biases are 2-D but have no low-rank
    # structure worth r*(m+n) bytes).
    assert "Dense_0/kernel" not in targets
    assert "pos_embed" not in targets
    assert not any("LayerNorm" in p for p in targets)
    assert not any(p.endswith("bias") for p in targets)


def test_split_point_minimizes_factor_bytes():
    assert lora.split_point((2000, 32)) == 1
    assert lora.factor_dims((2000, 32)) == (2000, 32)
    # (32, 2, 16): k=1 costs 32+32, k=2 costs 64+16 -> 80; split low.
    assert lora.split_point((32, 2, 16)) == 1
    assert lora.factor_dims((32, 2, 16)) == (32, 32)
    # (2, 16, 32): k=2 costs 32+32 beats k=1's 2+512.
    assert lora.split_point((2, 16, 32)) == 2
    assert lora.factor_dims((2, 16, 32)) == (32, 32)


def test_init_factors_identity_at_round_zero(bert_params):
    f = lora.init_factors(bert_params, RANK, key=jax.random.PRNGKey(3),
                          model_name="bert")
    idx = lora.factor_index(f)
    assert len(idx) == 13
    for a, b in idx.values():
        assert np.all(np.asarray(b) == 0.0)        # B starts zero
        assert np.any(np.asarray(a) != 0.0)        # A is seeded
    # B=0 -> the adapted model IS the base model, bitwise.
    assert _tree_bytes(lora.apply_adapters(bert_params, f, ALPHA, RANK)) \
        == _tree_bytes(bert_params)
    # key=None builds the all-zeros template (worker/bench shape source).
    tmpl = lora.init_factors(bert_params, RANK, model_name="bert")
    assert all(np.all(np.asarray(l) == 0.0) for l in jax.tree.leaves(tmpl))


def test_merge_matches_manual_oracle(bert_params):
    factors = _rand_factors(bert_params)
    merged = jax.tree.map(np.asarray,
                          lora.merge_adapters(bert_params, factors,
                                              ALPHA, RANK))
    idx = lora.factor_index(factors)
    targets = lora.target_paths(bert_params, model_name="bert")
    flat = {partition.path_str(p): np.asarray(l) for p, l in
            jax.tree_util.tree_leaves_with_path(bert_params)}
    mflat = {partition.path_str(p): np.asarray(l) for p, l in
             jax.tree_util.tree_leaves_with_path(merged)}
    for path, w in flat.items():
        if path in targets:
            a, b = idx[path]
            delta = (np.asarray(b, np.float32) @ np.asarray(a, np.float32)
                     ).reshape(w.shape) * (ALPHA / RANK)
            np.testing.assert_allclose(mflat[path], w + delta,
                                       rtol=1e-5, atol=1e-6)
        else:
            # Non-adapted leaves pass through bitwise.
            assert mflat[path].tobytes() == w.tobytes()


def test_reset_keeps_a_zeroes_b(bert_params):
    factors = _rand_factors(bert_params)
    reset = lora.reset_factors(factors)
    for path, (a, b) in lora.factor_index(reset).items():
        assert np.all(np.asarray(b) == 0.0)
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(lora.factor_index(factors)[path][0]))
    # Post-reset adapters are the identity again.
    assert _tree_bytes(lora.apply_adapters(bert_params, reset, ALPHA, RANK)) \
        == _tree_bytes(bert_params)


def test_sharded_merge_parity_tp2(bert_params):
    """The coordinator's jitted shard-wise merge on a tp=2 server equals
    the host oracle — no full-tree gather needed for correctness."""
    devs = jax.devices("cpu")
    if len(devs) < 2:
        pytest.skip("needs the forced 8-device CPU host")
    pl = partition.make_server_placement(bert_params, 2, "model", "bert",
                                         devices=devs[:2])
    assert pl is not None
    factors = _rand_factors(bert_params)
    merge = jax.jit(lambda p, f: lora.merge_adapters(p, f, ALPHA, RANK))
    out = merge(pl.shard(bert_params), factors)
    host = jax.tree.map(np.asarray, partition.host_tree(out))
    oracle = jax.tree.map(np.asarray,
                          lora.merge_adapters(bert_params, factors,
                                              ALPHA, RANK))
    for a, b in zip(jax.tree.leaves(host), jax.tree.leaves(oracle)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# ------------------------------------------------------- factor folding ----
def _factor_updates(shapes, n):
    out = []
    for i in range(n):
        rng = np.random.default_rng(100 + i)
        d = jax.tree.map(
            lambda f: rng.standard_normal(f.shape).astype(np.float32),
            shapes)
        out.append(({"client_id": str(i), "weight": 1.0 + 0.25 * i,
                     "mean_loss": 0.5 + 0.1 * i}, d))
    return out


def test_factor_fold_bitwise_arrival_invariant(bert_params):
    """StreamingFolder over the FACTOR template: any arrival order
    finalizes to the bitwise cohort-order sum (the shape-generic fold the
    coordinator builds under lora)."""
    shapes = jax.tree.map(np.asarray,
                          lora.init_factors(bert_params, RANK,
                                            model_name="bert"))
    order = [str(i) for i in range(4)]
    updates = _factor_updates(shapes, 4)
    shuffled = list(updates)
    random.Random(13).shuffle(shuffled)

    ref = StreamingFolder(shapes, order=order)
    shf = StreamingFolder(shapes, order=order)
    for meta, d in updates:
        ref.add(dict(meta), jax.tree.map(np.copy, d))
    for meta, d in shuffled:
        shf.add(dict(meta), jax.tree.map(np.copy, d))
    m_ref, w_ref, l_ref = ref.mean()
    m_shf, w_shf, l_shf = shf.mean()
    assert w_ref == w_shf and l_ref == l_shf
    assert _tree_bytes(m_ref) == _tree_bytes(m_shf)


def test_factor_fold_aggregator_partials_bitwise(bert_params):
    """Aggregator-tree composition over factor trees: slice folds shipped
    as partials combine at the root bitwise identically to a flat cohort
    fold built with the same slice layout (what the tier does when meta
    carries the lora marker)."""
    shapes = jax.tree.map(np.asarray,
                          lora.init_factors(bert_params, RANK,
                                            model_name="bert"))
    order = [str(i) for i in range(4)]
    updates = _factor_updates(shapes, 4)

    flat = StreamingFolder(shapes, order=order,
                           slices=[order[:2], order[2:]])
    for meta, d in updates:
        flat.add(dict(meta), jax.tree.map(np.copy, d))

    root = StreamingFolder(shapes, order=["agg0", "agg1"])
    for key, sl in (("agg0", updates[:2]), ("agg1", updates[2:])):
        sub = StreamingFolder(shapes, order=[m["client_id"] for m, _ in sl])
        for meta, d in sl:
            sub.add(dict(meta), jax.tree.map(np.copy, d))
        sub.finalize()
        root.add_partial(key, sub.total_w, sub.wsum, sub.loss_sum,
                         count=sub.count)
    m_flat, w_flat, l_flat = flat.mean()
    m_root, w_root, l_root = root.mean()
    assert w_flat == w_root and l_flat == l_root
    assert root.count == flat.count == 4
    assert _tree_bytes(m_flat) == _tree_bytes(m_root)


# ------------------------------------------------------------ validation ----
def _fed(**kw):
    base = dict(strategy="fedavg", lora_rank=4, lora_alpha=16.0,
                lora_merge_every=2)
    base.update(kw)
    return _config(num_clients=2, **base)


@pytest.mark.parametrize("bad", [
    dict(lora_rank=-1),
    dict(lora_alpha=0.0),
    dict(lora_alpha=-2.0),
    dict(lora_merge_every=0),
    dict(compress_down="int8"),
    dict(strategy="fedadam"),
    dict(strategy="fedyogi"),
])
def test_validate_robustness_rejects_lora_conflicts(bad):
    with pytest.raises(ValueError):
        validate_robustness(_fed(**bad))


@pytest.mark.parametrize("ok", [
    dict(),
    dict(strategy="fedprox", prox_mu=0.01),
    dict(compress="topk"),
    dict(compress="topk8", compress_feedback=True),
    dict(secure_agg=True),
])
def test_validate_robustness_allows_lora_compositions(ok):
    validate_robustness(_fed(**ok))   # must not raise


def test_dense_trainer_refuses_lora_config():
    """In-process planes (engine/offline/programs) reach the DENSE
    trainer; silently ignoring lora_rank there would train the full
    model while claiming adapter federation."""
    cfg = _fed()
    model = model_registry.build_model(cfg.model)
    with pytest.raises(ValueError, match="socket"):
        setup_lib.local_trainer_for_config(cfg, model.apply, 64)
    # fleetsim's documented dense-dynamics decoupling stays allowed.
    update, _ = setup_lib.local_trainer_for_config(cfg, model.apply, 64,
                                                   lora_dense_ok=True)
    assert callable(update)


# ------------------------------------------------- factor-only training ----
def test_lora_local_update_one_compile_signature():
    """The jitted factor trainer holds ONE XLA signature across rounds:
    factor values change, shapes never do — the compile-cost contract the
    wire plane's round latency depends on."""
    cfg = _fed()
    model = model_registry.build_model(cfg.model)
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (64, 28, 28, 1)).astype(np.float32))
    y = jnp.asarray(np.random.default_rng(1).integers(0, 10, 64))
    params = model_registry.init_params(model, x[:16],
                                        jax.random.PRNGKey(0))
    factors = lora.init_factors(params, RANK, key=jax.random.PRNGKey(2),
                                model_name=cfg.model.name)
    assert lora.count_factor_params(factors) > 0
    optimizer = local_lib.make_optimizer(0.1, 0.0, "sgd")
    update = local_lib.make_lora_local_update(
        model.apply, optimizer, num_steps=3, batch_size=16,
        rank=RANK, alpha=ALPHA)
    tracked = runtime.CompileTracker(jax.jit(update), name="lora_local")

    f = factors
    for rnd in range(3):
        res = tracked(params, f, x, y, jnp.asarray(64, jnp.int32),
                      jax.random.PRNGKey(10 + rnd),
                      jnp.asarray(3, jnp.int32))
        assert bool(res.completed) and np.isfinite(float(res.mean_loss))
        # The reply is factor-shaped (O(r*d)), not params-shaped — and a
        # real step moved the factors.
        assert jax.tree.structure(res.delta) == jax.tree.structure(factors)
        assert any(np.any(np.asarray(l) != 0.0)
                   for l in jax.tree.leaves(res.delta))
        f = jax.tree.map(jnp.add, f, res.delta)
    assert tracked.compiles == 1
    assert tracked.recompiles == 0


# ------------------------------------------------------- socket e2e ----
def _run_lora_federation(cfg, n, rounds):
    """Like tests.test_comm._run_federation but also returns the
    coordinator's factor tree (host numpy) alongside params/records."""
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(n)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=n, timeout=20.0)
            coord.trainers.sort(key=lambda d: int(d.device_id))
            for w in workers:
                w.await_role(timeout=10.0)
            recs = [coord.run_round() for _ in range(rounds)]
            params = jax.tree.map(np.asarray, coord.server_state.params)
            factors = jax.tree.map(np.asarray, coord._factors)
            coord.close()
            return recs, params, factors
        finally:
            for w in workers:
                w.stop()


def test_socket_lora_merge_parity_oracle():
    """Federated-run-then-merge == manual oracle: a no-merge run exposes
    the aggregated factors; a merge_every=2 twin (identical training —
    the merge lands AFTER round 2's broadcast) must equal
    merge_adapters(frozen base, those factors), with B re-zeroed and A
    kept."""
    cfg_hold = _fed(momentum=0.0, lr=0.05, lora_merge_every=100)
    recs_h, params_h, factors_h = _run_lora_federation(cfg_hold, 2, 2)
    assert all(r["completed"] == 2 for r in recs_h)
    assert all(not r["lora_merged"] for r in recs_h)
    assert all(np.isfinite(r["train_loss"]) for r in recs_h)
    # Factor uplink savings are real and priced per folded update.
    assert all(r["bytes_saved_uplink"] > 0 for r in recs_h)
    # No merge -> the base NEVER moves: bitwise equal to a fresh init.
    init = jax.tree.map(np.asarray, setup_lib.init_global_params(cfg_hold))
    assert _tree_bytes(params_h) == _tree_bytes(init)
    # ...but the factors did (training happened).
    assert any(np.any(np.asarray(b) != 0.0)
               for _, b in lora.factor_index(factors_h).values())

    cfg_merge = _fed(momentum=0.0, lr=0.05, lora_merge_every=2)
    recs_m, params_m, factors_m = _run_lora_federation(cfg_merge, 2, 2)
    assert [r["lora_merged"] for r in recs_m] == [False, True]
    oracle = jax.tree.map(
        np.asarray, lora.merge_adapters(params_h, factors_h, ALPHA, RANK))
    for a, b in zip(jax.tree.leaves(params_m), jax.tree.leaves(oracle)):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    # Post-merge factor state: B zeroed (fresh adapting basis), A kept.
    for path, (a, b) in lora.factor_index(factors_m).items():
        assert np.all(np.asarray(b) == 0.0)
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(lora.factor_index(factors_h)[path][0]))


def test_socket_secure_agg_over_factors_exact():
    """secure_agg masks the FACTOR tree: a masked lora federation must
    land on the plain lora run's aggregate (pairwise masks cancel over
    the factor-shaped fold template)."""
    cfg = _fed(momentum=0.0, lr=0.05, lora_merge_every=2)
    recs_p, params_p, factors_p = _run_lora_federation(cfg, 2, 2)

    cfg_sec = _fed(momentum=0.0, lr=0.05, lora_merge_every=2,
                   secure_agg=True)
    recs_s, params_s, factors_s = _run_lora_federation(cfg_sec, 2, 2)
    assert all(r["completed"] == 2 for r in recs_p + recs_s)
    assert recs_s[-1]["lora_merged"]
    for a, b in zip(jax.tree.leaves(factors_p), jax.tree.leaves(factors_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)
    for a, b in zip(jax.tree.leaves(params_p), jax.tree.leaves(params_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)


def test_lora_off_round_records_unchanged():
    """lora off -> round records carry NO adapter keys (and no uplink
    savings keys on an uncompressed run): the default wire plane is
    byte-identical to pre-lora records."""
    recs, _, _ = _run_federation(_config(num_clients=2), 2, rounds=1)
    for rec in recs:
        assert "lora_merged" not in rec
        assert "bytes_saved_uplink" not in rec
        assert "uplink_densify_avoided" not in rec
