"""File-plane uplink error feedback (fed/offline.py): the persisted
compression residual closes the same EF-SGD loop the socket worker runs
in memory — carried only across consecutive rounds, reset (and counted)
on torn/stale/mismatched carries, and refused outright under secure_agg."""

import numpy as np
import pytest

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.fed import compression, offline
from colearn_federated_learning_tpu.utils.serialization import (
    atomic_save_pytree_npz,
    load_pytree_npz,
)

from tests.test_engine import tiny_config


def _resets(reason):
    return telemetry.get_registry().counter(
        "fed.offline_residual_resets_total",
        labels={"reason": reason}).value


@pytest.fixture(scope="module")
def ds():
    return data_registry.get_dataset("mnist_tiny", seed=0)


def _leaves(tree):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def _assert_close(a, b, atol=1e-6):
    for x, y in zip(_leaves(a), _leaves(b)):
        np.testing.assert_allclose(x, y, atol=atol, rtol=0)


def _dense(wire, meta, shapes):
    return compression.decompress_delta(wire, meta, shapes=shapes)


def test_feedback_loop_reconstructs_the_exact_delta(tmp_path, ds):
    """Round 0: wire + residual == the uncompressed delta.  Round 1 (same
    global chain): wire + new residual == delta + carried residual — the
    EF-SGD invariant, with no reset counted across the valid carry."""
    dense_cfg = tiny_config(compress="none")
    fb_cfg = tiny_config(compress="topk", compress_feedback=True,
                         topk_fraction=0.05)
    g0 = str(tmp_path / "global0.npz")
    offline.init_global_model(dense_cfg, g0)
    params, _ = load_pytree_npz(g0)
    res = str(tmp_path / "residual.npz")

    # ---- round 0 ----
    u_dense0 = str(tmp_path / "dense0.npz")
    offline.client_update(dense_cfg, 0, g0, u_dense0, dataset=ds)
    delta0, _ = load_pytree_npz(u_dense0)

    u_fb0 = str(tmp_path / "fb0.npz")
    offline.client_update(fb_cfg, 0, g0, u_fb0, dataset=ds,
                          residual_path=res)
    wire0, m0 = load_pytree_npz(u_fb0)
    residual0, rmeta0 = load_pytree_npz(res)
    assert int(rmeta0["round"]) == 0
    rec0 = _dense(wire0, m0, params)
    _assert_close(
        {"a": [np.add(x, y) for x, y in zip(_leaves(rec0),
                                            _leaves(residual0))]},
        {"a": _leaves(delta0)})

    # ---- round 1, same global for both paths ----
    g1 = str(tmp_path / "global1.npz")
    offline.aggregate_updates(dense_cfg, g0, [u_dense0], g1)
    u_dense1 = str(tmp_path / "dense1.npz")
    offline.client_update(dense_cfg, 0, g1, u_dense1, dataset=ds)
    delta1, _ = load_pytree_npz(u_dense1)

    stale_before = _resets("stale")
    u_fb1 = str(tmp_path / "fb1.npz")
    offline.client_update(fb_cfg, 0, g1, u_fb1, dataset=ds,
                          residual_path=res)
    assert _resets("stale") == stale_before       # consecutive: carried
    wire1, m1 = load_pytree_npz(u_fb1)
    residual1, rmeta1 = load_pytree_npz(res)
    assert int(rmeta1["round"]) == 1
    lhs = [np.add(x, y) for x, y in zip(_leaves(_dense(wire1, m1, params)),
                                        _leaves(residual1))]
    rhs = [np.add(x, y) for x, y in zip(_leaves(delta1),
                                        _leaves(residual0))]
    _assert_close({"a": lhs}, {"a": rhs})


def _fb_round0(cfg, tmp, ds, res_path, tag):
    g = str(tmp / f"g_{tag}.npz")
    offline.init_global_model(cfg, g)
    out = str(tmp / f"u_{tag}.npz")
    offline.client_update(cfg, 0, g, out, dataset=ds, residual_path=res_path)
    return load_pytree_npz(out)


@pytest.mark.parametrize("poison,reason", [
    ("stale", "stale"), ("garbage", "torn"), ("shape", "shape"),
])
def test_invalid_residual_resets_and_counts(tmp_path, ds, poison, reason):
    """A stale (non-consecutive round), torn, or shape-mismatched carry is
    discarded — the update is bitwise the no-carry update — and the reset
    is attributed on ``fed.offline_residual_resets_total``."""
    cfg = tiny_config(compress="topk", compress_feedback=True)
    clean_res = str(tmp_path / "clean_res.npz")
    wire_ref, _ = _fb_round0(cfg, tmp_path, ds, clean_res, "ref")

    bad_res = str(tmp_path / "bad_res.npz")
    if poison == "stale":
        # Valid tree, wrong round: produced 8 rounds ago, not round -1.
        residual0, _ = load_pytree_npz(clean_res)
        atomic_save_pytree_npz(bad_res, residual0, meta={"round": 7})
    elif poison == "garbage":
        with open(bad_res, "wb") as f:
            f.write(b"not an npz archive")
    else:
        atomic_save_pytree_npz(bad_res, {"x": np.zeros(3, np.float32)},
                               meta={"round": -1})

    before = _resets(reason)
    wire_bad, _ = _fb_round0(cfg, tmp_path, ds, bad_res, poison)
    assert _resets(reason) == before + 1
    for x, y in zip(_leaves(wire_ref), _leaves(wire_bad)):
        np.testing.assert_array_equal(x, y)
    # The poisoned carry was replaced by a fresh, valid one.
    _, rmeta = load_pytree_npz(bad_res)
    assert int(rmeta["round"]) == 0


def test_secure_agg_refuses_offline_feedback(tmp_path, ds):
    """Same rejection rule as the wire plane: a masked update leaves no
    plaintext residual to feed back."""
    cfg = tiny_config(compress="topk", compress_feedback=True,
                      secure_agg=True)
    g = str(tmp_path / "g.npz")
    offline.init_global_model(tiny_config(), g)
    with pytest.raises(ValueError, match="secure_agg"):
        offline.client_update(cfg, 0, g, str(tmp_path / "u.npz"),
                              dataset=ds,
                              residual_path=str(tmp_path / "r.npz"))


def test_no_residual_path_keeps_historical_wire(tmp_path, ds):
    """compress_feedback without a residual_path (pre-flag callers) stays
    byte-identical to the plain compressed update."""
    plain = tiny_config(compress="topk")
    fb = tiny_config(compress="topk", compress_feedback=True)
    g = str(tmp_path / "g.npz")
    offline.init_global_model(plain, g)
    a, b = str(tmp_path / "a.npz"), str(tmp_path / "b.npz")
    offline.client_update(plain, 0, g, a, dataset=ds)
    offline.client_update(fb, 0, g, b, dataset=ds)   # no residual_path
    wa, _ = load_pytree_npz(a)
    wb, _ = load_pytree_npz(b)
    for x, y in zip(_leaves(wa), _leaves(wb)):
        np.testing.assert_array_equal(x, y)
