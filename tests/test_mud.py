"""MUD (RFC 8520) device profiles + enrollment gating (comm/mud.py).

CoLearn's defining idea is MUD-identity-gated federated learning
(SURVEY.md §0); these tests cover the profile parser, the coordinator
policy, per-type grouping, and the gate working end to end through a
real broker federation.
"""

import json

import numpy as np
import pytest

from colearn_federated_learning_tpu.comm import mud
from colearn_federated_learning_tpu.comm.broker import MessageBroker
from colearn_federated_learning_tpu.comm.coordinator import (
    FederatedCoordinator,
)
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from tests.test_comm import _config


def _profile(device_type="camera", supported=True, url="https://m.example/p"):
    return json.dumps({"ietf-mud:mud": {
        "mud-version": 1,
        "mud-url": url,
        "is-supported": supported,
        "systeminfo": "test device",
        "mfg-name": "acme",
        "model-name": "cam-3",
        "colearn:device-type": device_type,
        "cache-validity": 24,
    }})


def test_profile_parse_roundtrip_and_errors():
    p = mud.MudProfile.from_json(_profile())
    assert p.device_type == "camera" and p.mfg_name == "acme"
    assert p.is_supported and p.mud_url.startswith("https://")
    p2 = mud.MudProfile.from_json(p.to_json())
    assert p2 == p

    with pytest.raises(mud.MudError, match="JSON"):
        mud.MudProfile.from_json("{not json")
    with pytest.raises(mud.MudError, match="container"):
        mud.MudProfile.from_json(json.dumps({"wrong": {}}))
    with pytest.raises(mud.MudError, match="https"):
        mud.MudProfile.from_json(_profile(url="http://insecure.example"))
    with pytest.raises(mud.MudError, match="mud-version"):
        mud.MudProfile.from_json(json.dumps({"ietf-mud:mud": {
            "mud-version": 2, "mud-url": "https://x.example"}}))


def test_malformed_field_is_mud_error_not_crash():
    # Wrong-typed leaves must raise MudError (the enrollment loop's
    # handler), never a bare ValueError that would crash the coordinator.
    bad = json.dumps({"ietf-mud:mud": {
        "mud-version": 1, "mud-url": "https://m.example/x",
        "cache-validity": "48h"}})
    with pytest.raises(mud.MudError, match="malformed MUD field"):
        mud.MudProfile.from_json(bad)


def test_allowlist_implies_profile_required():
    # Omitting the profile must NOT bypass a type allowlist.
    policy = mud.MudPolicy(allowed_types=("camera",))
    with pytest.raises(mud.MudError, match="requires a MUD"):
        policy.check(None)


def test_policy_gates():
    cam = mud.MudProfile.from_json(_profile("camera"))
    old = mud.MudProfile.from_json(_profile("camera", supported=False))
    bulb = mud.MudProfile.from_json(_profile("bulb"))

    permissive = mud.MudPolicy()
    permissive.check(None)                      # no profile is fine
    permissive.check(cam)
    with pytest.raises(mud.MudError, match="unsupported"):
        permissive.check(old)                   # default require_supported

    strict = mud.MudPolicy(require_profile=True,
                           allowed_types=("camera",))
    strict.check(cam)
    with pytest.raises(mud.MudError, match="requires a MUD"):
        strict.check(None)
    with pytest.raises(mud.MudError, match="not in the allowed"):
        strict.check(bulb)


def test_group_by_device_type():
    infos = [("a", mud.MudProfile.from_json(_profile("camera"))),
             ("b", mud.MudProfile.from_json(_profile("bulb"))),
             ("c", mud.MudProfile.from_json(_profile("camera"))),
             ("d", None)]
    groups = mud.group_by_device_type(infos)
    assert sorted(groups["camera"]) == ["a", "c"]
    assert groups["bulb"] == ["b"] and groups[""] == ["d"]


def test_per_type_federations():
    # The CoLearn topology: 2 cameras + 2 bulbs -> TWO federations over
    # one broker, each training its own global model on exactly its
    # type's devices; a lone thermostat is skipped (below min size).
    import dataclasses

    import jax

    from colearn_federated_learning_tpu.comm.per_type import (
        PerTypeFederation,
    )

    cfg = _config(num_clients=5)
    cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, rounds=2))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port,
                         mud_profile=_profile(t)).start()
            for i, t in ((0, "camera"), (1, "camera"),
                         (2, "bulb"), (3, "bulb"), (4, "thermostat"))
        ]
        try:
            fed = PerTypeFederation(cfg, broker.host, broker.port,
                                    round_timeout=30.0,
                                    min_devices_per_type=2)
            hists = fed.run(min_devices=5, enroll_timeout=20.0)
            assert not fed.errors, fed.errors
            assert set(hists) == {"camera", "bulb"}
            assert fed.skipped == {"thermostat": 1}
            for dtype in ("camera", "bulb"):
                coord = fed.coordinators[dtype]
                ids = {d.device_id for d in coord.trainers}
                want = {"0", "1"} if dtype == "camera" else {"2", "3"}
                assert ids == want, (dtype, ids)
                assert all(r["completed"] == 2 for r in hists[dtype])
            # The two type models genuinely diverged (trained on
            # different cohorts from the same init).
            flat = lambda c: np.concatenate([  # noqa: E731
                np.ravel(np.asarray(a))
                for a in jax.tree.leaves(c.server_state.params)])
            assert not np.allclose(flat(fed.coordinators["camera"]),
                                   flat(fed.coordinators["bulb"]))
        finally:
            fed.close()
            for w in workers:
                w.stop()


def test_enrollment_gate_end_to_end():
    # 2 cameras + 1 bulb + 1 profile-less device announce; a camera-only
    # policy must federate EXACTLY the cameras, record the rejections,
    # and the round must complete with the admitted cohort.
    cfg = _config(num_clients=4)
    policy = mud.MudPolicy(require_profile=True, allowed_types=("camera",))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, 0, broker.host, broker.port,
                         mud_profile=_profile("camera")).start(),
            DeviceWorker(cfg, 1, broker.host, broker.port,
                         mud_profile=_profile("camera")).start(),
            DeviceWorker(cfg, 2, broker.host, broker.port,
                         mud_profile=_profile("bulb")).start(),
            DeviceWorker(cfg, 3, broker.host, broker.port).start(),
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=30.0,
                                         want_evaluator=False,
                                         mud_policy=policy)
            coord.enroll(min_devices=2, timeout=20.0)
            admitted = {d.device_id for d in coord.trainers}
            assert admitted == {"0", "1"}
            rejected = coord._enroll.rejected
            assert "not in the allowed" in rejected["2"]
            assert "requires a MUD" in rejected["3"]
            # Admitted profiles are queryable (per-type topologies).
            assert coord._enroll.profile_of("0").device_type == "camera"
            rec = coord.run_round()
            assert rec["completed"] == 2
            coord.close()
        finally:
            for w in workers:
                w.stop()
