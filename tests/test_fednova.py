"""FedNova normalized averaging (fed/engine.py, strategy="fednova").

Wang et al.'s objective-inconsistency fix, engine-resident: each client
delta is normalized by its effective local-step coefficient a_i and the
mean is rescaled by the weighted-mean coefficient.  The fit with this
framework: straggler step budgets make tau_i genuinely heterogeneous.
"""

import dataclasses

import numpy as np
import pytest
from jax.sharding import Mesh

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cfg(**fed_kw):
    fed = dict(strategy="fednova", rounds=5, cohort_size=0, local_steps=4,
               batch_size=16, lr=0.1, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=64),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="fednova_test"),
    )


def _flat(tree):
    import jax

    return np.concatenate([np.ravel(np.asarray(a))
                           for a in jax.tree.leaves(tree)])


def test_fednova_equals_fedavg_when_steps_homogeneous():
    # Equal tau and equal example counts: a_i identical for every client,
    # so the normalization and the rescale cancel exactly.
    nova = FederatedLearner(_cfg())
    avg = FederatedLearner(_cfg(strategy="fedavg"))
    for _ in range(2):
        r_n = nova.run_round()
        r_a = avg.run_round()
    np.testing.assert_allclose(r_n["train_loss"], r_a["train_loss"],
                               rtol=1e-5)
    np.testing.assert_allclose(_flat(nova.server_state.params),
                               _flat(avg.server_state.params), atol=1e-5)


def test_fednova_differs_and_learns_under_stragglers():
    # Heterogeneous tau (straggler budgets): fednova reweights and must
    # diverge from fedavg while still learning.
    # server_lr=0.5 damps FedNova's variance amplification at this extreme
    # heterogeneity: with momentum 0.9 a tau=1 client's single-batch delta
    # is divided by a_1=1 while tau=4 peers divide by a_4~3.1, so the noisy
    # short-budget gradients dominate the normalized mean (up to ~9x the
    # fedavg weighting) and the raw step oscillates instead of descending.
    nova = FederatedLearner(_cfg(straggler_prob=0.5,
                                 straggler_min_fraction=0.01,
                                 server_lr=0.5))
    avg = FederatedLearner(_cfg(strategy="fedavg", straggler_prob=0.5,
                                straggler_min_fraction=0.01,
                                server_lr=0.5))
    nova.fit(rounds=8)
    avg.fit(rounds=8)
    d = np.abs(_flat(nova.server_state.params)
               - _flat(avg.server_state.params)).max()
    assert d > 1e-4, d
    _, acc = nova.evaluate()
    assert acc > 0.8, acc


def test_fednova_mesh_matches_vmap(cpu_devices):
    cfg = _cfg(straggler_prob=0.3, straggler_min_fraction=0.01)
    ref = FederatedLearner(cfg)
    m = FederatedLearner(cfg, mesh=Mesh(np.array(cpu_devices[:8]),
                                        ("clients",)))
    for _ in range(2):
        r_ref = ref.run_round()
        r_m = m.run_round()
    np.testing.assert_allclose(r_m["train_loss"], r_ref["train_loss"],
                               rtol=1e-5)
    np.testing.assert_allclose(_flat(m.server_state.params),
                               _flat(ref.server_state.params), atol=1e-5)


def test_fednova_rejected_on_stateless_planes():
    from colearn_federated_learning_tpu.fed import setup as setup_lib

    with pytest.raises(NotImplementedError, match="fednova"):
        setup_lib.require_stateless_strategy(_cfg(), "the socket worker")


def test_fednova_momentum_coefficient():
    # a_i for momentum SGD: tau=1 -> 1; tau -> infinity -> tau/(1-m).
    import jax.numpy as jnp

    m = 0.9
    def a(tau):
        tau = jnp.float32(tau)
        return float((tau - m * (1 - m ** tau) / (1 - m)) / (1 - m))
    np.testing.assert_allclose(a(1), 1.0, rtol=1e-5)
    assert abs(a(200) - 200 / (1 - m)) / (200 / (1 - m)) < 0.05


def test_fednova_rejects_adaptive_local_optimizers():
    with pytest.raises(ValueError, match="geometric series"):
        FederatedLearner(_cfg(local_optimizer="adam"))
