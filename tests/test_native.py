"""Native C++ gather + wire codec: build, parity with numpy fallback,
integrity checking."""

import numpy as np
import pytest

from colearn_federated_learning_tpu import native
from colearn_federated_learning_tpu.utils import serialization


def test_native_builds_and_gathers():
    lib = native.load()
    assert lib is not None, "g++ is in this image; native build must work"
    src = np.random.default_rng(0).normal(size=(100, 7, 3)).astype(np.float32)
    idx = np.random.default_rng(1).integers(0, 100, size=500)
    out = native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_native_gather_large_multithreaded():
    # Above the 4 MiB inline threshold so the threaded path runs.
    src = np.arange(2_000_000, dtype=np.float32).reshape(2000, 1000)
    idx = np.random.default_rng(2).integers(0, 2000, size=3000)
    out = native.gather_rows(src, idx)
    np.testing.assert_array_equal(out, src[idx])


def test_native_gather_bounds_checked():
    src = np.zeros((4, 4), np.float32)
    if native.load() is None:
        pytest.skip("no native lib")
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([0, 7]))


def test_gather_rows_numpy_fallback(monkeypatch):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    src = np.arange(24.0).reshape(6, 4)
    out = native.gather_rows(src, np.array([5, 0, 3]))
    np.testing.assert_array_equal(out, src[[5, 0, 3]])


def test_wire_codec_roundtrip_and_autodetect():
    tree = {
        "a": {"w": np.random.default_rng(0).normal(size=(8, 3)).astype(np.float32),
              "b": np.arange(5, dtype=np.int32)},
        "scalar": np.float64(2.5),
    }
    meta = {"round": 7, "weight": 12.0}
    wire = serialization.pytree_to_bytes(tree, meta)
    assert wire[:4] == b"CLW1"
    out, out_meta = serialization.bytes_to_pytree(wire)
    assert out_meta == meta
    np.testing.assert_array_equal(out["a"]["w"], tree["a"]["w"])
    np.testing.assert_array_equal(out["a"]["b"], tree["a"]["b"])
    assert float(out["scalar"]) == 2.5

    # npz bytes still decode through the same entry point
    import io

    buf = io.BytesIO()
    serialization.save_pytree_npz(buf, tree, meta)
    out2, meta2 = serialization.bytes_to_pytree(buf.getvalue())
    assert meta2 == meta
    np.testing.assert_array_equal(out2["a"]["w"], tree["a"]["w"])


def test_wire_codec_detects_corruption():
    wire = bytearray(serialization.pytree_to_bytes({"w": np.ones(64)}))
    wire[-8] ^= 0xFF                      # flip a payload byte
    with pytest.raises(ValueError, match="crc32"):
        serialization.bytes_to_pytree(bytes(wire))


def test_pack_client_shards_native_matches_fallback(monkeypatch):
    from colearn_federated_learning_tpu.data import sharding

    x = np.random.default_rng(0).normal(size=(50, 4, 4, 3)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 10, size=50).astype(np.int32)
    parts = [np.arange(0, 20), np.arange(20, 27), np.arange(27, 50)]
    a = sharding.pack_client_shards(x, y, parts, capacity=25)

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    b = sharding.pack_client_shards(x, y, parts, capacity=25)
    np.testing.assert_array_equal(a.x, b.x)
    np.testing.assert_array_equal(a.y, b.y)
    np.testing.assert_array_equal(a.counts, b.counts)


def test_topk_abs_matches_numpy_selection():
    rng = np.random.default_rng(7)
    for n, k in [(10, 3), (70_000, 3_500), (200_001, 1), (512, 512)]:
        x = rng.normal(size=n).astype(np.float32)
        idx, val = native.topk_abs(x, k)
        assert idx.dtype == np.int32 and len(idx) == k
        assert np.all(np.diff(idx) > 0)            # ascending, unique
        np.testing.assert_array_equal(x[idx], val)
        ref = np.argpartition(np.abs(x), n - k)[-k:]
        # Selection must agree as a SET of magnitudes (tie order may vary).
        np.testing.assert_allclose(np.sort(np.abs(val)),
                                   np.sort(np.abs(x[ref])))


def test_topk_abs_degenerate_distributions():
    # Single-bin histograms (all-equal, all-zero) exercise the boundary
    # nth_element path end-to-end.
    idx, val = native.topk_abs(np.ones(100_000, np.float32), 777)
    assert len(idx) == 777 and np.all(val == 1.0)
    idx, val = native.topk_abs(np.zeros(100_000, np.float32), 777)
    assert len(idx) == 777 and np.all(val == 0.0)


def test_topk_abs_fallback_matches_native(monkeypatch):
    x = np.random.default_rng(3).normal(size=50_001).astype(np.float32)
    a_idx, a_val = native.topk_abs(x, 2_500)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    b_idx, b_val = native.topk_abs(x, 2_500)
    np.testing.assert_allclose(np.sort(np.abs(a_val)),
                               np.sort(np.abs(b_val)))
