"""Tier-1 wiring for scripts/trace_smoke.py: the end-to-end guarantee
`colearn train --trace-dir` makes (trace parses, expected phase spans
present, spans cover the round wall time) holds on 2 synthetic rounds."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import trace_smoke  # noqa: E402


def test_trace_smoke(tmp_path):
    out = trace_smoke.main(str(tmp_path))
    assert out["coverage"] >= 0.95
    assert "client_update" in out["phases"]
    assert os.path.exists(out["trace_file"])
    assert "phase coverage" in out["summary"]
