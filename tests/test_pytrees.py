"""FedAvg math vs numpy (SURVEY.md §7: 'unit ... FedAvg math vs numpy')."""

import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.utils import pytrees


def _stacked_tree(rng, C=5):
    return {
        "w": jnp.asarray(rng.normal(size=(C, 4, 3)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(C, 3)).astype(np.float32)),
    }


def test_tree_weighted_mean_matches_numpy():
    rng = np.random.default_rng(0)
    tree = _stacked_tree(rng)
    w = jnp.asarray([1.0, 2.0, 0.0, 4.0, 3.0])
    out = pytrees.tree_weighted_mean(tree, w)
    expect = np.average(np.asarray(tree["w"]), axis=0, weights=np.asarray(w))
    np.testing.assert_allclose(np.asarray(out["w"]), expect, rtol=1e-5)


def test_tree_weighted_mean_zero_weights_is_zero_not_nan():
    rng = np.random.default_rng(1)
    tree = _stacked_tree(rng)
    out = pytrees.tree_weighted_mean(tree, jnp.zeros(5))
    assert np.isfinite(np.asarray(out["w"])).all()
    np.testing.assert_array_equal(np.asarray(out["b"]), 0.0)


def test_tree_norms_and_arithmetic():
    a = {"x": jnp.asarray([3.0, 0.0]), "y": jnp.asarray([[4.0]])}
    assert float(pytrees.tree_global_norm(a)) == 5.0
    assert pytrees.tree_size(a) == 3
    d = pytrees.tree_sub(a, a)
    assert float(pytrees.tree_global_norm(d)) == 0.0
    s = pytrees.tree_scale(a, 2.0)
    np.testing.assert_array_equal(np.asarray(s["x"]), [6.0, 0.0])
