"""telemetry/health.py: the per-device fleet health ledger — WAL-style
durability (torn tail tolerated, mid-file corruption raises, compaction
via atomic rewrite), EWMA + quantile-sketch latency, cross-source merge,
the transport-retry feed, the `colearn health` renderer, labeled-gauge
export, and the conditional round-record stamps."""

import json
import os

import pytest

from colearn_federated_learning_tpu.telemetry.health import (
    DeviceHealth,
    HealthLedger,
    export_gauges,
    feed_transport_retries,
    health_record_keys,
    load_health,
    render_health,
)
from colearn_federated_learning_tpu.telemetry.registry import MetricsRegistry


# --------------------------------------------------------- durability ----
def test_record_flush_load_roundtrip(tmp_path):
    led = HealthLedger(str(tmp_path), "coordinator")
    led.record("3", round=0, latency_s=0.5)
    led.record("3", round=1, latency_s=0.7, deadline_miss=1)
    led.record("4", round=1, retry=2)
    led.flush()
    led.close()

    devices = load_health(str(tmp_path))
    assert set(devices) == {"3", "4"}
    d3 = devices["3"]
    assert d3.counts["deadline_miss"] == 1
    assert d3.last_round == 1
    assert d3.lat_samples == [0.5, 0.7]
    assert devices["4"].counts["retry"] == 2


def test_unflushed_events_visible_in_memory_not_on_disk(tmp_path):
    led = HealthLedger(str(tmp_path), "coordinator")
    led.record("1", round=0, latency_s=0.1)
    assert "1" in led.devices()            # in-memory immediately
    assert load_health(str(tmp_path)) == {}  # durable only after flush
    led.flush()
    assert set(load_health(str(tmp_path))) == {"1"}


def test_unknown_count_field_raises(tmp_path):
    led = HealthLedger(str(tmp_path), "coordinator")
    with pytest.raises(ValueError, match="unknown health fields"):
        led.record("1", deadline_mises=1)


def test_torn_final_line_tolerated_mid_file_raises(tmp_path):
    led = HealthLedger(str(tmp_path), "aggregator0")
    led.record("1", round=0, latency_s=0.2)
    led.record("2", round=0, latency_s=0.3)
    led.flush()
    led.close()

    # SIGKILL mid-append: the torn FINAL line is the in-flight event —
    # dropped on load, everything before it intact.
    with open(led.path, "a") as f:
        f.write('{"d":"9","round":1,"laten')
    devices = load_health(str(tmp_path))
    assert set(devices) == {"1", "2"}
    # a fresh ledger replays the same file (same leniency)
    led2 = HealthLedger(str(tmp_path), "aggregator0")
    assert set(led2.devices()) == {"1", "2"}

    # torn MID-file is corruption, not a crash artifact: raise.
    lines = open(led.path).read().splitlines()
    lines.insert(1, '{"d":"8","rou')
    with open(led.path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="corrupt health ledger"):
        load_health(str(tmp_path))


def test_compaction_bounds_file_and_preserves_state(tmp_path):
    reg = MetricsRegistry()
    led = HealthLedger(str(tmp_path), "fleetsim", max_lines=8)
    for r in range(20):
        led.record(str(r % 3), round=r, latency_s=0.1 * (r % 3 + 1),
                   retry=1)
        led.flush()
    led.close()

    lines = [ln for ln in open(led.path).read().splitlines() if ln]
    assert len(lines) <= 8 + 1             # bounded, not O(events)
    assert any("snapshot" in json.loads(ln) for ln in lines[:1])

    devices = load_health(str(tmp_path))
    assert set(devices) == {"0", "1", "2"}
    # every event survived the rewrites: 20 retries split 7/7/6
    assert sum(d.counts["retry"] for d in devices.values()) == 20
    # replay into a fresh ledger sees the compacted state too
    led2 = HealthLedger(str(tmp_path), "fleetsim", max_lines=8)
    assert sum(d.counts["retry"]
               for d in led2.devices().values()) == 20


# ------------------------------------------------------ sketch & merge ----
def test_latency_ewma_and_sample_thinning():
    dev = DeviceHealth("7")
    for i in range(1000):
        dev.apply({"latency_s": 1.0 + (i % 10) * 0.01, "round": i})
    assert dev.lat_ewma == pytest.approx(1.045, abs=0.05)
    assert len(dev.lat_samples) < 256      # stride-thinned, bounded
    assert dev.rounds == 1000


def test_merge_sums_counts_and_weights_ewma():
    a = DeviceHealth("5")
    for r in range(3):
        a.apply({"round": r, "latency_s": 1.0, "deadline_miss": 1})
    b = DeviceHealth("5")
    b.apply({"round": 9, "latency_s": 4.0, "retry": 2})

    a.merge(b)
    assert a.counts["deadline_miss"] == 3 and a.counts["retry"] == 2
    assert a.rounds == 4 and a.last_round == 9
    # rounds-weighted: (3*1.0 + 1*4.0) / 4
    assert a.lat_ewma == pytest.approx(1.75)
    assert a.lat_samples == [1.0, 1.0, 1.0, 4.0]


def test_load_health_merges_sources(tmp_path):
    c = HealthLedger(str(tmp_path), "coordinator")
    c.record("2", round=1, deadline_miss=1)
    c.flush()
    a = HealthLedger(str(tmp_path), "aggregator1")
    a.record("2", round=1, latency_s=0.9, agg="1")
    a.flush()

    devices = load_health(str(tmp_path))
    assert set(devices) == {"2"}
    merged = devices["2"]
    assert merged.counts["deadline_miss"] == 1
    assert merged.lat_ewma == pytest.approx(0.9)
    assert merged.agg == "1"


# --------------------------------------------------------------- feeds ----
def test_feed_transport_retries_attributes_deltas_once(tmp_path):
    reg = MetricsRegistry()
    reg.counter("comm.retry_total", labels={"device": "3"}).inc(2)
    reg.counter("comm.retry_total", labels={"device": "agg:0"}).inc(5)

    led = HealthLedger(str(tmp_path), "coordinator")
    seen: dict = {}
    feed_transport_retries(led, seen, registry=reg)
    assert led.devices()["3"].counts["retry"] == 2
    assert "agg:0" not in led.devices()    # non-device peers skipped

    # no new retries -> no double count
    feed_transport_retries(led, seen, registry=reg)
    assert led.devices()["3"].counts["retry"] == 2
    reg.counter("comm.retry_total", labels={"device": "3"}).inc()
    feed_transport_retries(led, seen, registry=reg)
    assert led.devices()["3"].counts["retry"] == 3


# ----------------------------------------------------------- reporting ----
def _fleet():
    devices = {}
    for did, agg, lat in (("0", "0", 0.2), ("1", "0", 0.25),
                          ("2", "1", 1.2), ("3", "1", 1.1)):
        dev = DeviceHealth(did)
        for r in range(4):
            dev.apply({"round": r, "latency_s": lat, "agg": agg})
        devices[did] = dev
    devices["2"].apply({"round": 4, "deadline_miss": 2, "eviction": 1})
    return devices


def test_render_health_sections():
    text = render_health(_fleet())
    assert "devices tracked" in text
    assert "top offenders" in text
    # offender score: 5*1 + 3*2 = 11, ranked first
    first_row = text.splitlines()[6]
    assert first_row.strip().startswith("2") and "11" in first_row
    assert "straggler tail" in text and "p99" in text
    assert "per-aggregator slice skew" in text
    assert "skew (max/min mean)" in text
    assert render_health({}).endswith("no health records found")


def test_export_gauges_labeled_and_bounded():
    reg = MetricsRegistry()
    export_gauges(_fleet(), registry=reg, top=2)
    snap = reg.snapshot()
    assert snap["health.devices_tracked"] == 4
    assert snap["health.device_score{device=2}"] == 11.0
    assert "health.device_latency_ewma_s{device=2}" in snap
    # bounded to the top-2 offenders — no per-device gauge explosion
    assert "health.device_score{device=0}" not in snap


def test_health_record_keys_conditional():
    keys = health_record_keys(_fleet())
    assert keys["health_devices"] == 4
    assert keys["health_lat_p99_s"] == pytest.approx(1.2)
    assert keys["health_worst_device"] == "2"
    assert keys["health_worst_score"] == 11.0
    # a clean fleet stamps no offender keys
    clean = {k: v for k, v in _fleet().items() if k != "2"}
    keys = health_record_keys(clean)
    assert "health_worst_device" not in keys
    assert keys["health_devices"] == 3
