"""PR 19 device fold (ops/fold_kernel.py): the fused batched kernel vs
the host StreamingFolder, which stays the bitwise parity oracle.

- Bitwise parity across every frame type the folder stages — dense
  ("none"), int8, topk, topk8, LoRA-factor trees — under BOTH kernel
  lowerings (``native`` fused C++ and the ``xla`` jitted scan), full and
  partial cohorts, pre-folded partials, the secure-agg correction hook,
  and the tp=2 sharded server.
- Batched-vs-sequential equivalence: folding a block through one
  batched dispatch equals folding it one contribution at a time
  (``lax.scan`` keeps cohort order, add for add).
- Compile-once-per-model: the kernel cache is keyed on the slot-shape
  fingerprint and batch/k extents bucket to powers of two, so a second
  folder of the same model re-uses the compiled kernels — pinned via
  the CompileTracker counters, not asserted prose.
- Staging-time ownership: read-only partial inputs are copied at most
  once, at staging; caller arrays are never mutated by the fold.
"""

import os

import jax
import numpy as np
import pytest

from colearn_federated_learning_tpu.comm.aggregation import StreamingFolder
from colearn_federated_learning_tpu.fed import compression
from colearn_federated_learning_tpu.ops import fold_kernel
from colearn_federated_learning_tpu.parallel import partition

from tests.test_uplink_fastpath import _params, _tree_bytes

BACKENDS = ["native", "xla"]


@pytest.fixture(autouse=True)
def _fresh_kernel_cache():
    fold_kernel.clear_kernel_cache()
    yield
    fold_kernel.clear_kernel_cache()


def _updates(scheme, n=5, shapes=None, fraction=0.1, seed=300):
    shapes = _params() if shapes is None else shapes
    out = []
    for i in range(n):
        rng = np.random.default_rng(seed + i)
        d = jax.tree.map(
            lambda w: rng.standard_normal(w.shape).astype(np.float32),
            shapes)
        wire, cmeta = compression.compress_delta(
            d, scheme, topk_fraction=fraction)
        meta = {"client_id": str(i), "weight": 1.0 + 0.25 * i,
                "mean_loss": 0.5 + 0.1 * i, **cmeta}
        out.append((meta, wire))
    return out


def _run_fold(shapes, updates, *, device=False, backend="native",
              placement=None, batch_max=None, partials=(), correction=None,
              order=None):
    """Build, feed, and finalize one folder; ``backend`` pins the kernel
    lowering via the env override while the fold runs."""
    if order is None:
        order = [m["client_id"] for m, _ in updates]
        order += [key for key, *_ in partials]
    prev = os.environ.get("COLEARN_FOLD_BACKEND")
    os.environ["COLEARN_FOLD_BACKEND"] = backend
    try:
        f = StreamingFolder(shapes, order=order, placement=placement,
                            device_fold=device)
        if batch_max is not None:
            f._fold_batch_max = batch_max
        for meta, wire in updates:
            f.add(dict(meta), jax.tree.map(np.copy, wire))
        for key, tw, tree, ls in partials:
            f.add_partial(key, tw, tree, ls)
        f.finalize()
        if correction is not None:
            f.apply_correction(correction)
        return f
    finally:
        if prev is None:
            os.environ.pop("COLEARN_FOLD_BACKEND", None)
        else:
            os.environ["COLEARN_FOLD_BACKEND"] = prev


def _assert_folds_equal(host, dev):
    assert dev.total_w == host.total_w
    assert dev.loss_sum == host.loss_sum
    assert _tree_bytes(dev.wsum) == _tree_bytes(host.wsum)


# ------------------------------------------------------- frame parity ----
@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("scheme", ["none", "int8", "topk", "topk8"])
def test_device_fold_bitwise_parity(scheme, backend):
    shapes = _params()
    updates = _updates(scheme)
    host = _run_fold(shapes, updates)
    dev = _run_fold(shapes, updates, device=True, backend=backend)
    _assert_folds_equal(host, dev)


@pytest.mark.parametrize("backend", BACKENDS)
def test_lora_factor_frames_parity(backend):
    # Factor trees fold dense (per-leaf scaled numpy) with the rank-wide
    # leaves LoRA ships; the device fold must reproduce them bit for bit.
    shapes = {
        "TransformerBlock_0/attn/query/kernel":
            {"a": np.zeros((4, 16), np.float32),
             "b": np.zeros((16, 4), np.float32)},
        "TransformerBlock_0/Dense_0/kernel":
            {"a": np.zeros((4, 32), np.float32),
             "b": np.zeros((8, 4), np.float32)},
    }
    updates = _updates("none", shapes=shapes, seed=500)
    host = _run_fold(shapes, updates)
    dev = _run_fold(shapes, updates, device=True, backend=backend)
    _assert_folds_equal(host, dev)


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("scheme", ["none", "topk8"])
def test_tp2_sharded_parity(scheme, backend):
    devs = jax.devices("cpu")
    if len(devs) < 2:
        pytest.skip("needs the forced 8-device CPU host")
    pl = partition.make_server_placement(
        _params(), 2, "model", "bert", devices=devs[:2])
    assert pl is not None
    shapes = pl.shapes_tree()
    updates = _updates(scheme, shapes=_params(), seed=700)
    host = _run_fold(shapes, updates, placement=pl)
    dev = _run_fold(shapes, updates, device=True, backend=backend,
                    placement=pl)
    _assert_folds_equal(host, dev)
    # The assembled sharded means agree too (same shard bytes).
    m_host = partition.host_tree(host.mean()[0])
    m_dev = partition.host_tree(dev.mean()[0])
    assert _tree_bytes(m_host) == _tree_bytes(m_dev)


@pytest.mark.parametrize("backend", BACKENDS)
def test_partial_cohort_parity(backend):
    shapes = _params()
    order = [str(i) for i in range(5)]
    updates = _updates("topk8")[:3]          # two cohort slots never reply
    host = _run_fold(shapes, updates, order=order)
    dev = _run_fold(shapes, updates, order=order, device=True,
                    backend=backend)
    assert dev.count == host.count == 3
    _assert_folds_equal(host, dev)


@pytest.mark.parametrize("backend", BACKENDS)
def test_partials_and_correction_parity(backend):
    shapes = _params()
    updates = _updates("topk", seed=900)
    direct, sliced = updates[:3], updates[3:]

    def partial():
        sub = StreamingFolder(shapes,
                              order=[m["client_id"] for m, _ in sliced])
        for meta, wire in sliced:
            sub.add(dict(meta), jax.tree.map(np.copy, wire))
        sub.finalize()
        return [("agg:0", sub.total_w, sub.wsum, sub.loss_sum)]

    rng = np.random.default_rng(17)
    correction = jax.tree.map(
        lambda w: (rng.standard_normal(w.shape) * 1e-3).astype(np.float32),
        shapes)
    host = _run_fold(shapes, direct, partials=partial(),
                     correction=correction)
    dev = _run_fold(shapes, direct, device=True, backend=backend,
                    partials=partial(), correction=correction)
    _assert_folds_equal(host, dev)


@pytest.mark.parametrize("backend", BACKENDS)
def test_batched_vs_sequential_fold_equivalence(backend):
    # Mixed cohort: topk8 / topk (a value-dtype run boundary) / dense,
    # interleaved — one batched dispatch per run vs one contribution at
    # a time must produce identical bits (scan keeps cohort order).
    shapes = _params()
    mixed = []
    for i, scheme in enumerate(["topk8", "topk8", "topk", "none", "topk8"]):
        meta, wire = _updates(scheme, n=1, seed=1100 + i)[0]
        meta["client_id"] = str(i)
        mixed.append((meta, wire))
    host = _run_fold(shapes, mixed)
    batched = _run_fold(shapes, mixed, device=True, backend=backend)
    seq = _run_fold(shapes, mixed, device=True, backend=backend,
                    batch_max=1)
    _assert_folds_equal(host, batched)
    _assert_folds_equal(host, seq)


def test_negative_zero_bits_survive_padding():
    # A staged -0.0 lands in the accumulator by first-densify assignment;
    # bucketing pads rows/k with out-of-range indices (mode='drop'), so
    # no padded add may normalize it to +0.0.  Three updates bucket to
    # B=4 (one padded row); only update 0 touches index 3.
    shapes = {"w": np.zeros((8,), np.float32)}
    upd = []
    for i, (idx, val) in enumerate([(3, -0.0), (1, 1.5), (6, -2.0)]):
        wire = {"w": {"i": np.array([idx], np.int64),
                      "v": np.array([val], np.float32),
                      "n": np.array([8], np.int64)}}
        upd.append(({"client_id": str(i), "weight": 1.0, "mean_loss": 0.0,
                     "compress": "topk"}, wire))
    for backend in BACKENDS:
        fold_kernel.clear_kernel_cache()
        dev = _run_fold(shapes, upd, device=True, backend=backend)
        out = np.asarray(dev.wsum["w"])
        assert out[3] == 0.0 and np.signbit(out[3]), backend
    host = _run_fold(shapes, upd)
    assert _tree_bytes(host.wsum) == _tree_bytes(dev.wsum)


# ------------------------------------------------- compile-once pinning ----
def test_one_compile_per_model_via_tracker():
    shapes = _params()
    updates = _updates("topk8", seed=1300)

    dev1 = _run_fold(shapes, updates, device=True, backend="xla")
    kernel = dev1._kernel
    assert kernel is not None and kernel.backend == "xla"
    compiles_after_first = kernel.compiles
    assert compiles_after_first > 0
    assert kernel.recompiles == 0

    # A second folder of the SAME model (a later round) hits the cache:
    # same kernel object, no new compiles, no retraces — cohort 5 and
    # cohort 6 both bucket to B=8.
    dev2 = _run_fold(shapes, _updates("topk8", n=6, seed=1400),
                     device=True, backend="xla")
    assert dev2._kernel is kernel
    assert kernel.compiles == compiles_after_first
    assert kernel.recompiles == 0


def test_kernel_cache_keys_on_slot_fingerprint():
    prev = os.environ.get("COLEARN_FOLD_BACKEND")
    os.environ["COLEARN_FOLD_BACKEND"] = "native"
    try:
        a = fold_kernel.get_kernel([16, 8])
        assert fold_kernel.get_kernel((16, 8)) is a
        assert fold_kernel.get_kernel([16, 9]) is not a
    finally:
        if prev is None:
            os.environ.pop("COLEARN_FOLD_BACKEND", None)
        else:
            os.environ["COLEARN_FOLD_BACKEND"] = prev


# ------------------------------------------------------------ ownership ----
@pytest.mark.parametrize("device", [False, True])
def test_read_only_partial_is_copied_at_staging(device):
    shapes = _params()
    base = jax.tree.map(lambda w: np.ones(w.shape, np.float32), shapes)
    for leaf in jax.tree.leaves(base):
        leaf.setflags(write=False)
    snapshot = _tree_bytes(base)

    updates = _updates("topk", n=2, seed=1500)
    f = _run_fold(shapes, updates, device=device, backend="native",
                  partials=[("agg:0", 1.0, base, 0.0)])
    # The fold scattered IN PLACE onto the staged partial — but staging
    # owned (copied) the read-only leaves, so the caller's tree is
    # untouched.
    assert _tree_bytes(base) == snapshot
    host = _run_fold(shapes, updates,
                     partials=[("agg:0", 1.0, base, 0.0)])
    _assert_folds_equal(host, f)
