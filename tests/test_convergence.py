"""Convergence observatory (telemetry/convergence.py) and its wiring.

Unit coverage for the observatory's edge cases — first-round cosine
(undefined, NOT NaN), no-op rounds, zero updates, LoRA factor-tree
parity with dense trees, EWMA classification boundaries, non-finite
aggregates — plus the per-device/per-cohort skew attribution, the
``colearn converge`` report, the lr-spike chaos overlay
(fed/strategies.lr_scale_for_round), and the conditional-record-key
contract on all three planes: sync coordinator, async coordinator, and
fleetsim records carry ``conv_*`` keys under ``--learn-observe`` and
stay byte-identical without it.
"""

import json
import math

import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu import fleetsim, telemetry
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.telemetry.convergence import (
    ConvergenceObservatory,
    cohort_skew,
    device_skew,
    render_convergence_report,
    tree_cosine,
    tree_norm,
)
from colearn_federated_learning_tpu.telemetry.registry import MetricsRegistry
from colearn_federated_learning_tpu.utils.config import (
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
    validate_robustness,
)


def _tree(*vals):
    return {"layer": {"w": jnp.asarray(vals, jnp.float32)}}


# ------------------------------------------------------------ tree math --
def test_tree_norm_and_cosine_basics():
    assert tree_norm({}) == 0.0
    assert tree_norm(_tree(3.0, 4.0)) == pytest.approx(5.0)
    assert tree_cosine(_tree(1.0, 0.0), _tree(2.0, 0.0)) == pytest.approx(1.0)
    assert tree_cosine(_tree(1.0, 0.0), _tree(-1.0, 0.0)) == pytest.approx(
        -1.0)
    # Zero norm on either side: undefined -> None, never NaN.
    assert tree_cosine(_tree(0.0, 0.0), _tree(1.0, 1.0)) is None
    assert tree_cosine(_tree(1.0, 1.0), _tree(0.0, 0.0)) is None


# ---------------------------------------------------- observatory edges --
def test_first_round_has_no_cosine_and_classifies_warmup():
    obs = ConvergenceObservatory()
    sig = obs.observe(_tree(1.0, 2.0), lr=0.5)
    assert sig["conv_trend"] == "warmup"
    assert "conv_cos_prev" not in sig          # undefined, not NaN
    assert sig["conv_update_norm"] == pytest.approx(math.sqrt(5.0))
    assert sig["conv_step_size"] == pytest.approx(0.5 * math.sqrt(5.0))
    # Second round: a previous update exists, the cosine appears.
    sig2 = obs.observe(_tree(1.0, 2.0))
    assert sig2["conv_cos_prev"] == pytest.approx(1.0)


def test_none_delta_is_a_noop_round():
    obs = ConvergenceObservatory()
    obs.observe(_tree(1.0, 0.0))
    assert obs.observe(None) is None
    # State untouched: the trend picks up where it left off, and the
    # cosine still compares against the last REAL update.
    assert obs._seen == 1
    sig = obs.observe(_tree(1.0, 0.0))
    assert sig["conv_cos_prev"] == pytest.approx(1.0)


def test_zero_update_round_yields_no_cosine_either_side():
    obs = ConvergenceObservatory()
    sig = obs.observe(_tree(0.0, 0.0))
    assert sig["conv_update_norm"] == 0.0
    assert "conv_cos_prev" not in sig
    # The zero update became prev: next round's cosine is undefined too.
    sig2 = obs.observe(_tree(1.0, 1.0))
    assert "conv_cos_prev" not in sig2
    assert sig2["conv_update_norm"] > 0


def test_lora_factor_tree_parity_with_dense():
    # Same numbers arranged as a dense layer vs a LoRA factor tree:
    # every signal is identical — the observatory folds factor trees
    # natively, no densify, no special-casing.
    dense = ConvergenceObservatory()
    lora = ConvergenceObservatory()
    for step in (1.0, 0.5, 0.25):
        d = {"layer": {"w": jnp.asarray([step, 2 * step], jnp.float32)}}
        f = {"layer": {"lora_a": jnp.asarray([step], jnp.float32),
                       "lora_b": jnp.asarray([2 * step], jnp.float32)}}
        sd, sf = dense.observe(d), lora.observe(f)
        assert sd == sf


def test_ewma_classification_boundaries():
    # warmup_rounds=0 so classification starts immediately after the
    # first EWMA exists; alpha=1 pins the EWMA to the last norm, making
    # every boundary exact.
    obs = ConvergenceObservatory(ewma_alpha=1.0, warmup_rounds=0)
    obs.observe(_tree(1.0, 0.0))                    # ewma = 1.0
    # Exactly at the divergence ratio: NOT divergence (strict >)...
    assert obs.observe(_tree(2.0, 0.0))["conv_trend"] == "progress"
    obs2 = ConvergenceObservatory(ewma_alpha=1.0, warmup_rounds=0)
    obs2.observe(_tree(1.0, 0.0))
    # ...one epsilon above it: divergence.
    assert obs2.observe(_tree(2.001, 0.0))["conv_trend"] == "divergence"
    # Inside the plateau band (|ratio - 1| <= 0.1; the exact edge is
    # not representable in float32, so probe clearly inside it).
    obs3 = ConvergenceObservatory(ewma_alpha=1.0, warmup_rounds=0)
    obs3.observe(_tree(1.0, 0.0))
    assert obs3.observe(_tree(0.95, 0.0))["conv_trend"] == "plateau"
    # Outside the band, below the divergence ratio: progress.
    obs4 = ConvergenceObservatory(ewma_alpha=1.0, warmup_rounds=0)
    obs4.observe(_tree(1.0, 0.0))
    assert obs4.observe(_tree(0.5, 0.0))["conv_trend"] == "progress"
    # Direction flip beats the plateau band: oscillation wins.
    obs5 = ConvergenceObservatory(ewma_alpha=1.0, warmup_rounds=0)
    obs5.observe(_tree(1.0, 0.0))
    sig = obs5.observe(_tree(-1.0, 0.0))
    assert sig["conv_cos_prev"] == pytest.approx(-1.0)
    assert sig["conv_trend"] == "oscillation"
    # Exactly at the oscillation threshold: NOT oscillation (strict <).
    obs6 = ConvergenceObservatory(ewma_alpha=1.0, warmup_rounds=0,
                                  oscillation_cos=-1.0)
    obs6.observe(_tree(1.0, 0.0))
    assert obs6.observe(_tree(-1.0, 0.0))["conv_trend"] == "plateau"


def test_warmup_rounds_suppress_early_classification():
    obs = ConvergenceObservatory(warmup_rounds=2)
    assert obs.observe(_tree(1.0))["conv_trend"] == "warmup"
    assert obs.observe(_tree(100.0))["conv_trend"] == "warmup"
    # Third observation is past warmup: the 100x blowup classifies.
    assert obs.observe(_tree(1000.0))["conv_trend"] == "divergence"


def test_nonfinite_norm_classifies_divergence_and_clears_prev():
    obs = ConvergenceObservatory()
    obs.observe(_tree(1.0, 0.0))
    ewma_before = obs._ewma
    sig = obs.observe(_tree(float("inf"), 0.0))
    assert sig["conv_trend"] == "divergence"
    assert not math.isfinite(sig["conv_update_norm"])
    # The EWMA is NOT poisoned and the prev update is cleared, so the
    # next finite round carries no cosine against garbage.
    assert obs._ewma == ewma_before
    sig2 = obs.observe(_tree(1.0, 0.0))
    assert "conv_cos_prev" not in sig2


def test_export_metrics_uses_catalog_declared_names():
    from colearn_federated_learning_tpu.analysis import metric_catalog

    obs = ConvergenceObservatory()
    reg = MetricsRegistry()
    sig = obs.observe(_tree(1.0, 2.0), lr=0.1)
    sig["conv_cohort_skew"] = 0.25
    obs.export_metrics(reg, sig)
    snap = reg.snapshot()
    assert snap["learn.update_norm"] == pytest.approx(math.sqrt(5.0))
    assert snap["learn.cohort_skew"] == pytest.approx(0.25)
    assert snap["learn.trend_total{trend=warmup}"] == 1
    for name in snap:
        assert metric_catalog.is_known(name), name


# ------------------------------------------------------ skew attribution --
def test_device_skew_median_p90_anomalies():
    out = device_skew([1.0, 1.0, 1.0, 1.0, 10.0])
    assert out["median"] == 1.0
    assert out["anomalies"] == [4]             # 10 > 3 x median
    assert device_skew([]) == {"median": 0.0, "p90": 0.0, "anomalies": []}
    # Uniform norms: nothing anomalous.
    assert device_skew([2.0] * 8)["anomalies"] == []


def test_cohort_skew_separates_aligned_from_opposed():
    agg = _tree(1.0, 0.0)
    # Two cohorts pushing exactly the aggregate's way: zero skew.
    sums = {"layer": {"w": jnp.asarray([[2.0, 0.0], [4.0, 0.0]],
                                       jnp.float32)}}
    w = np.asarray([2.0, 4.0])
    out = cohort_skew(sums, w, agg)
    assert out["conv_cohort_skew"] == pytest.approx(0.0)
    assert out["conv_cohort_cos_min"] == pytest.approx(1.0)
    # One cohort pulling exactly opposite: skew 2 (cos -1).
    sums_op = {"layer": {"w": jnp.asarray([[2.0, 0.0], [-4.0, 0.0]],
                                          jnp.float32)}}
    out_op = cohort_skew(sums_op, w, agg)
    assert out_op["conv_cohort_cos_min"] == pytest.approx(-1.0)
    assert out_op["conv_cohort_skew"] == pytest.approx(2.0)
    # Zero-weight cohorts are skipped, not divided by.
    out_zw = cohort_skew(sums_op, np.asarray([2.0, 0.0]), agg)
    assert out_zw["conv_cohort_skew"] == pytest.approx(0.0)
    # No populated cohorts at all: neutral defaults.
    empty = cohort_skew(sums, np.asarray([0.0, 0.0]), agg)
    assert empty == {"conv_cohort_skew": 0.0, "conv_cohort_cos_min": 1.0}


# ------------------------------------------------------------- reporting --
def test_render_convergence_report_shapes():
    assert render_convergence_report([]).startswith(
        "no learning signals found")
    recs = [
        {"round": 1, "conv_update_norm": 0.5, "conv_step_size": 0.5,
         "conv_norm_ewma": 0.75, "conv_trend": "progress",
         "conv_cos_prev": 0.9, "conv_cohort_skew": 0.3},
        {"round": 0, "conv_update_norm": 1.0, "conv_step_size": 1.0,
         "conv_norm_ewma": 1.0, "conv_trend": "warmup"},
        {"round": 2, "conv_update_norm": 3.0, "conv_step_size": 3.0,
         "conv_norm_ewma": 1.4, "conv_trend": "divergence",
         "conv_cos_prev": 0.1},
        {"round": 3, "unrelated": True},       # filtered out
    ]
    report = render_convergence_report(recs)
    assert "trends: warmup=1  progress=1  divergence=1" in report
    assert "first divergence: round 2" in report
    assert "update_norm: first=1 last=3 max=3" in report
    assert "cohort_skew: mean=0.3000 max=0.3000" in report
    # Rows are round-ordered regardless of input order.
    lines = [ln for ln in report.splitlines() if ln[:5].strip().isdigit()]
    assert [int(ln.split()[0]) for ln in lines] == [0, 1, 2]


def test_cli_converge_report_and_empty_exit_codes(tmp_path, capsys):
    from colearn_federated_learning_tpu.cli import main as cli_main

    p = tmp_path / "results" / "events.jsonl"
    p.parent.mkdir()
    rows = [{"event": "round", "round": r, "conv_update_norm": 1.0 / (r + 1),
             "conv_step_size": 1.0 / (r + 1), "conv_norm_ewma": 1.0,
             "conv_trend": "progress"} for r in range(3)]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert cli_main(["converge", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "trends: progress=3" in out
    # A dir with no learning signals exits 1 (grep-able failure).
    empty = tmp_path / "none"
    empty.mkdir()
    (empty / "x.jsonl").write_text(json.dumps({"round": 0}) + "\n")
    assert cli_main(["converge", str(empty)]) == 1


# -------------------------------------------------- lr-spike chaos knob --
def test_lr_spike_overlay_on_constant_schedule():
    base = FedConfig(strategy="fedavg")
    # Default: the constant schedule compiles the scaling branch away.
    assert strategies.lr_scale_for_round(base, 3) is None
    spiked = FedConfig(strategy="fedavg", lr_spike_round=5,
                       lr_spike_multiplier=10.0)
    assert float(strategies.lr_scale_for_round(spiked, 5)) == 10.0
    assert float(strategies.lr_scale_for_round(spiked, 4)) == 1.0
    assert float(strategies.lr_scale_for_round(spiked, 6)) == 1.0


def test_lr_spike_composes_with_cosine_schedule():
    cfg = FedConfig(strategy="fedavg", rounds=10, lr_schedule="cosine")
    cfg_sp = FedConfig(strategy="fedavg", rounds=10, lr_schedule="cosine",
                       lr_spike_round=4, lr_spike_multiplier=10.0)
    clean = float(strategies.lr_scale_for_round(cfg, 4))
    assert float(strategies.lr_scale_for_round(cfg_sp, 4)) == \
        pytest.approx(10.0 * clean)
    assert float(strategies.lr_scale_for_round(cfg_sp, 5)) == \
        pytest.approx(float(strategies.lr_scale_for_round(cfg, 5)))


def test_validate_robustness_rejects_bad_spike_knobs():
    with pytest.raises(ValueError, match="lr_spike_round"):
        validate_robustness(_fleet_config(False, lr_spike_round=-2))
    with pytest.raises(ValueError, match="lr_spike_multiplier"):
        validate_robustness(_fleet_config(False,
                                          lr_spike_multiplier=0.0))


# --------------------------------------------- trace summary + colearn top
def _trace_doc(with_conv: bool) -> dict:
    args = {"trace_id": "t", "span_id": "a", "parent_id": None}
    if with_conv:
        args.update(conv_update_norm=0.5, conv_trend="progress")
    return {"traceEvents": [
        {"name": "aggregate", "ph": "X", "pid": 1, "tid": 0, "ts": 0,
         "dur": 10_000, "args": args},
    ]}


def test_trace_summary_learning_line_both_shapes():
    with_line = telemetry.summarize_trace(_trace_doc(True))
    assert "learning: 1 observed fold(s)" in with_line
    assert "trend progress=1" in with_line
    without = telemetry.summarize_trace(_trace_doc(False))
    assert "learning:" not in without


def test_render_top_learning_section_both_shapes():
    from colearn_federated_learning_tpu.telemetry import runtime

    snap = {"fed.rounds_total": 4, "learn.update_norm": 0.125,
            "learn.update_norm_ewma": 0.25, "learn.step_size": 0.0625,
            "learn.cos_prev": 0.91, "learn.cohort_skew": 0.4,
            "learn.trend_total{trend=progress}": 3,
            "learn.trend_total{trend=warmup}": 2}
    body = runtime.render_top(snap)
    assert "learning" in body
    assert "update norm" in body and "0.125000" in body
    assert "cos(prev update)" in body and "0.9100" in body
    assert "cohort skew" in body
    assert "progress 3" in body and "warmup 2" in body
    # Default snapshots keep the classic layout: no learning section.
    assert "learning" not in runtime.render_top({"fed.rounds_total": 4})


# ------------------------------------------ fleetsim plane (records+jit) --
def _fleet_config(learn_observe: bool, **fed_kw) -> ExperimentConfig:
    fed = dict(strategy="fedavg", local_steps=2, batch_size=8, lr=0.05,
               momentum=0.0)
    fed.update(fed_kw)
    return ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=1),
        fed=FedConfig(**fed),
        run=RunConfig(name="conv_test", seed=0,
                      learn_observe=learn_observe),
    )


def _make_fleet(learn_observe: bool, num_devices=64, cohort=16, chunk=16,
                **fed_kw):
    spec = fleetsim.PopulationSpec(num_devices=num_devices, feature_dim=16,
                                   shard_capacity=16, min_examples=4,
                                   label_skew=0.9, seed=0)
    population = fleetsim.DevicePopulation(spec)
    traffic = fleetsim.TrafficModel(
        fleetsim.TrafficSpec(base_rate=2000.0, diurnal_amplitude=0.0),
        num_devices)
    return fleetsim.FleetSim.from_population(
        _fleet_config(learn_observe, **fed_kw), population, traffic,
        cohort_size=cohort, chunk_size=chunk)


def test_fleetsim_default_records_have_no_conv_keys():
    fs = _make_fleet(learn_observe=False)
    hist = fs.fit(2)
    for rec in hist:
        assert not any(k.startswith("conv_") for k in rec), sorted(rec)
    # The default jitted trio is untouched: no observatory program.
    assert fs.compile_counts == {"chunk": 1, "finish": 1, "fold": 1}


def test_fleetsim_observed_records_carry_conv_trail():
    fs = _make_fleet(learn_observe=True)
    hist = fs.fit(3)
    for rec in hist:
        assert rec["conv_update_norm"] > 0
        assert rec["conv_trend"] in telemetry.convergence.TRENDS
        # Updates are simulation-local: per-device and per-cohort skew
        # attribution rides along.
        assert rec["conv_norm_median"] > 0
        assert 0.0 <= rec["conv_cohort_skew"] <= 2.0
    assert "conv_cos_prev" not in hist[0]
    assert all("conv_cos_prev" in r for r in hist[1:])
    # The observatory adds its own program; the default trio still
    # compiles once each (the chunked-vmap invariant holds).
    assert fs.compile_counts == {"chunk": 0, "finish": 1, "fold": 1,
                                 "obs_chunk": 1}


def test_fleetsim_async_observed_records_carry_conv_trail():
    fs = _make_fleet(learn_observe=True, num_devices=32, cohort=8, chunk=8)
    hist = fs.fit_async(6, buffer_size=4, max_staleness=8)
    assert all("conv_update_norm" in r for r in hist)
    assert all(r["conv_trend"] in telemetry.convergence.TRENDS
               for r in hist)
    fs2 = _make_fleet(learn_observe=False, num_devices=32, cohort=8,
                      chunk=8)
    hist2 = fs2.fit_async(6, buffer_size=4, max_staleness=8)
    for rec in hist2:
        assert not any(k.startswith("conv_") for k in rec), sorted(rec)


# ------------------------------------------- socket planes (sync, async) --
def _socket_config(learn_observe: bool, num_clients=3) -> ExperimentConfig:
    from colearn_federated_learning_tpu.utils.config import DataConfig

    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=2),
        fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=0,
                      local_steps=2, batch_size=16, lr=0.1),
        run=RunConfig(name="conv_socket_test", backend="cpu",
                      learn_observe=learn_observe),
    )


def test_sync_coordinator_observed_records_carry_conv_trail():
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker

    cfg = _socket_config(learn_observe=True)
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(3)]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=3, timeout=20.0)
            hist = coord.fit(rounds=2)
            coord.close()
        finally:
            for w in workers:
                w.stop()
    assert len(hist) == 2
    for rec in hist:
        assert rec["conv_update_norm"] > 0
        assert rec["conv_trend"] in telemetry.convergence.TRENDS
        assert rec["conv_step_size"] == pytest.approx(
            rec["conv_update_norm"] * cfg.fed.server_lr)
    assert "conv_cos_prev" not in hist[0]
    assert "conv_cos_prev" in hist[1]


def test_async_coordinator_observed_records_carry_conv_trail():
    from colearn_federated_learning_tpu.comm.async_coordinator import (
        AsyncFederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker

    cfg = _socket_config(learn_observe=True)
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(3)]
        try:
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            ) as coord:
                coord.enroll(min_devices=3, timeout=20.0)
                recs = [coord.run_aggregation() for _ in range(2)]
        finally:
            for w in workers:
                w.stop()
    for rec in recs:
        assert rec["conv_update_norm"] > 0
        assert rec["conv_trend"] in telemetry.convergence.TRENDS
    assert "conv_cos_prev" not in recs[0]
    assert "conv_cos_prev" in recs[1]


def test_fleetsim_drift_separates_noniid_from_iid():
    # The committed bench row's acceptance in miniature: matched seeds,
    # only the label skew differs, the cohort-skew signal separates.
    def mean_skew(label_skew: float) -> float:
        spec = fleetsim.PopulationSpec(num_devices=48, feature_dim=16,
                                       shard_capacity=16, min_examples=4,
                                       label_skew=label_skew, seed=0)
        population = fleetsim.DevicePopulation(spec)
        traffic = fleetsim.TrafficModel(
            fleetsim.TrafficSpec(base_rate=2000.0, diurnal_amplitude=0.0),
            48)
        fs = fleetsim.FleetSim.from_population(
            _fleet_config(True), population, traffic, cohort_size=16,
            chunk_size=16)
        hist = fs.fit(4)
        vals = [r["conv_cohort_skew"] for r in hist[1:]]
        return sum(vals) / len(vals)

    assert mean_skew(0.9) > mean_skew(0.0) + 0.2
