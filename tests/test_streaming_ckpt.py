"""ckpt/streaming.py: crash-consistent shard-native checkpoints.

The matrix a kill at ANY byte must leave survivable: per-shard files are
CRC-checked, the generation manifest is the commit marker (fsynced
LAST), and restore falls back a generation — never crashes — on
torn/missing/CRC-bad shards.  Cross-tp legs pin that a tp=2 save
resumes bitwise on tp=1 (and vice versa) through
``partition.host_leaf``-shaped per-shard files and
``make_array_from_single_device_arrays`` placement, against the flat
``RoundCheckpointer`` path.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.ckpt import (
    StreamingCheckpointer,
    load_generation_host,
)
from colearn_federated_learning_tpu.ckpt.streaming import MANIFEST
from colearn_federated_learning_tpu.faults import inject
from colearn_federated_learning_tpu.faults.plan import FaultPlan, FaultSpec
from colearn_federated_learning_tpu.parallel import partition


def _counter(name, **labels):
    from colearn_federated_learning_tpu import telemetry

    reg = telemetry.get_registry()
    if labels:
        return reg.counter(name, labels=labels).value
    return reg.counter(name).value


def _params():
    # CNN-rule names so make_server_placement shards both leaves (kernel
    # on its last dim, bias on dim 0); bf16 kernel exercises the
    # extension-dtype manifest path.
    return {"Dense_0": {
        "kernel": jnp.arange(64, dtype=jnp.bfloat16).reshape(8, 8),
        "bias": jnp.linspace(-1.0, 1.0, 8, dtype=jnp.float32),
    }}


def _sharded(params, tp=2):
    pl = partition.make_server_placement(params, tp, "model", "cnn")
    assert pl is not None, "needs >= 2 XLA host devices (conftest sets 8)"
    return pl.shard(params)


def _state(params):
    # Mirrors the coordinator composite: (server tree, accountant
    # vector, python scalar).
    return (params, np.zeros(1), 7)


def _host(tree):
    return jax.tree.map(lambda a: np.asarray(a), tree)


def _assert_tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x, np.float32), np.asarray(y, np.float32))


# ------------------------------------------------------ save/restore ----
def test_roundtrip_host_template(tmp_path):
    ck = StreamingCheckpointer(str(tmp_path))
    params = _params()
    ck.save(3, _state(params), [{"round": 0}, {"round": 1}])
    got, hist, step = StreamingCheckpointer(str(tmp_path)).restore(
        _state(_host(jax.tree.map(jnp.zeros_like, params))))
    assert step == 3 and [h["round"] for h in hist] == [0, 1]
    _assert_tree_equal(got[0], params)
    np.testing.assert_array_equal(got[1], np.zeros(1))
    assert got[2] == 7 and isinstance(got[2], int)
    assert StreamingCheckpointer(str(tmp_path)).latest_step() == 3


def test_bf16_bitwise_roundtrip(tmp_path):
    ck = StreamingCheckpointer(str(tmp_path))
    params = _params()
    ck.save(1, _state(params), [])
    got, _, _ = StreamingCheckpointer(str(tmp_path)).restore(
        _state(jax.tree.map(jnp.zeros_like, params)))
    k = got[0]["Dense_0"]["kernel"]
    assert np.asarray(k).dtype == jnp.bfloat16
    assert (np.asarray(k).view(np.uint8)
            == np.asarray(params["Dense_0"]["kernel"]).view(np.uint8)).all()


def test_tp2_save_resumes_bitwise_on_tp1(tmp_path):
    params = _params()
    ck = StreamingCheckpointer(str(tmp_path))
    before = _counter("ckpt.resharded_resumes_total")
    ck.save(2, _state(_sharded(params)), [{"round": 0}])
    # Two distinct shard files on disk, never a full-tree artifact.
    gen = os.path.join(str(tmp_path), "gen_00000002")
    shards = [n for n in os.listdir(gen) if n.startswith("shard_")]
    assert len(shards) == 2
    ck2 = StreamingCheckpointer(str(tmp_path))
    got, _, step = ck2.restore(_state(_host(
        jax.tree.map(jnp.zeros_like, params))))
    assert step == 2
    _assert_tree_equal(got[0], params)
    assert _counter("ckpt.resharded_resumes_total") == before + 1
    # The digest is layout-independent: the harness's template-free
    # loader computes the same one from the on-disk generation.
    _, gstep, digest = load_generation_host(str(tmp_path))
    assert gstep == 2 and ck2.last_restore_digest == digest


def test_tp1_save_resumes_bitwise_on_tp2(tmp_path):
    params = _params()
    StreamingCheckpointer(str(tmp_path)).save(1, _state(params), [])
    got, _, _ = StreamingCheckpointer(str(tmp_path)).restore(
        _state(_sharded(jax.tree.map(jnp.zeros_like, params))))
    kernel = got[0]["Dense_0"]["kernel"]
    assert isinstance(kernel, jax.Array)
    assert len({s.device for s in kernel.addressable_shards}) == 2
    _assert_tree_equal(got[0], params)


def test_streaming_matches_flat_round_checkpointer(tmp_path):
    """Pin against the PR-lineage flat path: both checkpointers restore
    the identical state from the same save input."""
    from colearn_federated_learning_tpu.ckpt import RoundCheckpointer

    params = _params()
    history = [{"round": 0, "loss": 1.0}]
    flat = RoundCheckpointer(str(tmp_path / "flat"))
    flat.save(1, (_host(params), np.zeros(1)), history)
    flat.close()
    stream = StreamingCheckpointer(str(tmp_path / "stream"))
    stream.save(1, (_sharded(params), np.zeros(1)), history)

    tmpl = (_host(jax.tree.map(jnp.zeros_like, params)), np.ones(1))
    flat_got, flat_hist, _ = RoundCheckpointer(
        str(tmp_path / "flat")).restore(tmpl)
    stream_got, stream_hist, _ = StreamingCheckpointer(
        str(tmp_path / "stream")).restore(tmpl)
    assert flat_hist == stream_hist == history
    _assert_tree_equal(flat_got[0], stream_got[0])


def test_save_never_gathers(tmp_path):
    before = _counter("comm.gather_bytes_avoided_total")
    StreamingCheckpointer(str(tmp_path)).save(
        1, _state(_sharded(_params())), [])
    assert _counter("comm.gather_bytes_avoided_total") > before


def test_prune_keeps_max_to_keep(tmp_path):
    ck = StreamingCheckpointer(str(tmp_path), max_to_keep=2)
    for step in (1, 2, 3):
        ck.save(step, _state(_params()), [])
    gens = sorted(n for n in os.listdir(str(tmp_path))
                  if n.startswith("gen_"))
    assert gens == ["gen_00000002", "gen_00000003"]


# ------------------------------------------------- recovery matrix ------
def _two_gens(tmp_path):
    ck = StreamingCheckpointer(str(tmp_path))
    ck.save(1, _state(_params()), [{"round": 0}])
    ck.save(2, _state(_params()), [{"round": 0}, {"round": 1}])
    return os.path.join(str(tmp_path), "gen_00000002")


def _restore_falls_back(tmp_path, reason):
    before = _counter("ckpt.generations_discarded_total", reason=reason)
    ck = StreamingCheckpointer(str(tmp_path))
    got, hist, step = ck.restore(
        _state(_host(jax.tree.map(jnp.zeros_like, _params()))))
    assert step == 1 and [h["round"] for h in hist] == [0]
    _assert_tree_equal(got[0], _params())
    assert ck.generations_discarded == {reason: 1}
    assert _counter("ckpt.generations_discarded_total",
                    reason=reason) == before + 1


def test_torn_shard_falls_back_a_generation(tmp_path):
    gen = _two_gens(tmp_path)
    shard = os.path.join(gen, sorted(
        n for n in os.listdir(gen) if n.startswith("shard_"))[0])
    with open(shard, "r+b") as f:
        f.truncate(os.path.getsize(shard) // 2)
    _restore_falls_back(tmp_path, "torn_shard")


def test_crc_mismatch_falls_back_a_generation(tmp_path):
    gen = _two_gens(tmp_path)
    shard = os.path.join(gen, sorted(
        n for n in os.listdir(gen) if n.startswith("shard_"))[0])
    size = os.path.getsize(shard)
    with open(shard, "r+b") as f:     # same size, flipped bytes
        f.seek(size // 2)
        f.write(b"\xff\x00\xff\x00")
    _restore_falls_back(tmp_path, "crc_mismatch")


def test_missing_shard_falls_back_a_generation(tmp_path):
    gen = _two_gens(tmp_path)
    os.unlink(os.path.join(gen, sorted(
        n for n in os.listdir(gen) if n.startswith("shard_"))[0]))
    _restore_falls_back(tmp_path, "missing_shard")


def test_torn_manifest_falls_back_a_generation(tmp_path):
    gen = _two_gens(tmp_path)
    mpath = os.path.join(gen, MANIFEST)
    with open(mpath, "r+b") as f:
        f.truncate(os.path.getsize(mpath) // 2)
    _restore_falls_back(tmp_path, "torn_manifest")


def test_missing_manifest_falls_back_a_generation(tmp_path):
    gen = _two_gens(tmp_path)
    os.unlink(os.path.join(gen, MANIFEST))
    _restore_falls_back(tmp_path, "missing_manifest")


def test_no_restorable_generation_raises(tmp_path):
    gen = _two_gens(tmp_path)
    for name in ("gen_00000001", "gen_00000002"):
        os.unlink(os.path.join(str(tmp_path), name, MANIFEST))
    with pytest.raises(FileNotFoundError):
        StreamingCheckpointer(str(tmp_path)).restore(
            _state(_host(_params())))


def test_shape_mismatch_template_raises(tmp_path):
    StreamingCheckpointer(str(tmp_path)).save(1, _state(_params()), [])
    bad = _params()
    bad["Dense_0"]["bias"] = jnp.zeros((16,), jnp.float32)
    with pytest.raises(ValueError):
        StreamingCheckpointer(str(tmp_path)).restore(_state(_host(bad)))


# ------------------------------------- kill-at-every-phase atomicity ----
def test_stale_manifest_fault_aborts_save_uncommitted(tmp_path):
    """Kill between last shard fsync and manifest replace: the shard
    files land, the commit marker does not, the save counts aborted, and
    restore falls through to the previous generation."""
    ck = StreamingCheckpointer(str(tmp_path))
    ck.save(1, _state(_params()), [{"round": 0}])
    before = _counter("ckpt.save_aborted_total")
    inject.install(FaultPlan([FaultSpec(
        kind="stale_manifest", device_id="*", round=-1,
        op="manifest", hop="manifest")]))
    try:
        ck.save(2, _state(_params()), [{"round": 0}, {"round": 1}])
    finally:
        inject.uninstall()
    assert _counter("ckpt.save_aborted_total") == before + 1
    gen2 = os.path.join(str(tmp_path), "gen_00000002")
    assert not os.path.exists(os.path.join(gen2, MANIFEST))
    assert any(n.startswith("shard_") for n in os.listdir(gen2))
    got, hist, step = StreamingCheckpointer(str(tmp_path)).restore(
        _state(_host(jax.tree.map(jnp.zeros_like, _params()))))
    assert step == 1 and len(hist) == 1
    _assert_tree_equal(got[0], _params())


def test_torn_shard_fault_during_save_discarded_on_restore(tmp_path):
    """Kill mid-shard-write (the fault tears a just-replaced shard
    file): the generation fails its CRC audit and restore falls back."""
    ck = StreamingCheckpointer(str(tmp_path))
    ck.save(1, _state(_params()), [{"round": 0}])
    inject.install(FaultPlan([FaultSpec(
        kind="torn_shard", device_id="0", round=-1,
        op="shard", hop="shard")]))
    try:
        ck.save(2, _state(_params()), [{"round": 0}, {"round": 1}])
    finally:
        inject.uninstall()
    ck2 = StreamingCheckpointer(str(tmp_path))
    _, hist, step = ck2.restore(
        _state(_host(jax.tree.map(jnp.zeros_like, _params()))))
    assert step == 1 and len(hist) == 1
    assert list(ck2.generations_discarded) == ["torn_shard"]


def test_slow_io_fault_stretches_save(tmp_path):
    import time as _time

    inject.install(FaultPlan([FaultSpec(
        kind="slow_io", device_id="*", round=-1, op="shard",
        ms=120, hop="shard")]))
    try:
        t0 = _time.monotonic()
        StreamingCheckpointer(str(tmp_path)).save(1, _state(_params()), [])
        assert _time.monotonic() - t0 >= 0.1
    finally:
        inject.uninstall()


def test_kill_free_save_writes_all_shards_then_manifest(tmp_path):
    before = _counter("ckpt.shards_written_total")
    StreamingCheckpointer(str(tmp_path)).save(
        5, _state(_sharded(_params())), [])
    assert _counter("ckpt.shards_written_total") == before + 2
    gen = os.path.join(str(tmp_path), "gen_00000005")
    assert os.path.exists(os.path.join(gen, MANIFEST))
    assert not any(n.startswith(".tmp-") for n in os.listdir(gen))
