"""PR 10 uplink fast path: sparse-native streaming fold + client-side
error feedback.

- StreamingFolder folds topk contributions from their wire (indices,
  values) — O(k) staged bytes, no per-update densify — BITWISE identical
  to the densify-then-sum fold it replaces: full cohort, partial cohort,
  the secure-agg correction hook, and the tp=2 sharded server
  (per-shard offset-adjusted indices via
  ServerPlacement.partition_flat_indices).
- feedback_compress carries the compression residual across rounds
  (EF-SGD): the residual is exactly what the codec dropped, and feeding
  it back de-biases repeated sparsification.
- DeviceWorker engages feedback only when it is sound (lossy codec, no
  secure_agg), resets the residual on a param-cache miss, and exports
  ``fed.uplink_residual_norm``.
- Config validation rejects the unsound combinations up front.
- Convergence: a topk+feedback federation tracks the dense-uplink
  baseline.
"""

import dataclasses
import random

import jax
import numpy as np
import pytest

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.comm import downlink
from colearn_federated_learning_tpu.comm.aggregation import (
    StreamingFolder,
    _SparseStage,
)
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.fed import compression
from colearn_federated_learning_tpu.fed import setup as setup_lib
from colearn_federated_learning_tpu.parallel import partition
from colearn_federated_learning_tpu.utils.config import validate_robustness

from tests.test_comm import _config, _run_federation


def _params():
    rng = np.random.default_rng(7)
    f = lambda *s: rng.standard_normal(s).astype(np.float32)
    return {
        "params": {
            "Embed_0": {"embedding": f(16, 8)},
            "TransformerBlock_0": {
                "attn": {"query": {"kernel": f(8, 4, 2), "bias": f(4, 2)},
                         "out": {"kernel": f(4, 2, 8)}},
                "Dense_0": {"kernel": f(8, 32), "bias": f(32)},
                "Dense_1": {"kernel": f(32, 8)},
                "LayerNorm_0": {"scale": f(8)},
            },
        }
    }


@pytest.fixture(scope="module")
def placement():
    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("needs the forced 8-device CPU host")
    pl = partition.make_server_placement(
        _params(), 4, "model", "bert", devices=devs[:4])
    assert pl is not None
    return pl


def _tree_bytes(tree):
    return [np.asarray(l).tobytes() for l in jax.tree.leaves(tree)]


def _topk_updates(n, fraction=0.1):
    """n (meta, wire) topk contributions plus the exact dense trees the
    dense reference fold would stage (decompress of the same wire)."""
    shapes = _params()
    out = []
    for i in range(n):
        rng = np.random.default_rng(100 + i)
        d = jax.tree.map(
            lambda w: rng.standard_normal(w.shape).astype(np.float32),
            shapes)
        wire, cmeta = compression.compress_delta(d, "topk",
                                                 topk_fraction=fraction)
        meta = {"client_id": str(i), "weight": 1.0 + 0.25 * i,
                "mean_loss": 0.5 + 0.1 * i, **cmeta}
        dense = compression.decompress_delta(wire, cmeta, shapes=shapes)
        out.append((meta, wire, dense))
    return out


# --------------------------------------------------- sparse fold parity ----
@pytest.mark.parametrize("present", [5, 3])  # full cohort / partial cohort
def test_sparse_fold_bitwise_parity(present):
    shapes = _params()
    order = [str(i) for i in range(5)]
    updates = _topk_updates(5)[:present]
    arrival = list(updates)
    random.Random(13).shuffle(arrival)     # fold must not care

    sparse = StreamingFolder(shapes, order=order)
    dense = StreamingFolder(shapes, order=order)
    for meta, wire, d in arrival:
        sparse.add(dict(meta), jax.tree.map(np.copy, wire))
        # Reference: the exact densify-then-sum path (no compress key →
        # the staged tree is the decompressed delta).
        ref_meta = {k: v for k, v in meta.items() if k != "compress"}
        dense.add(ref_meta, jax.tree.map(np.copy, d))

    m_sp, w_sp, l_sp = sparse.mean()
    m_dn, w_dn, l_dn = dense.mean()
    assert w_sp == w_dn and l_sp == l_dn
    assert _tree_bytes(m_sp) == _tree_bytes(m_dn)
    assert sparse.densify_avoided == present
    assert dense.densify_avoided == 0


def test_sparse_fold_correction_bitwise_parity():
    shapes = _params()
    order = [str(i) for i in range(4)]
    corr = jax.tree.map(
        lambda w: np.full(w.shape, 0.125, np.float32), shapes)

    sparse = StreamingFolder(shapes, order=order)
    dense = StreamingFolder(shapes, order=order)
    for meta, wire, d in _topk_updates(4):
        sparse.add(dict(meta), wire)
        dense.add({k: v for k, v in meta.items() if k != "compress"}, d)
    sparse.finalize(); dense.finalize()
    sparse.apply_correction(corr)
    dense.apply_correction(corr)
    m_sp, _, _ = sparse.mean()
    m_dn, _, _ = dense.mean()
    assert _tree_bytes(m_sp) == _tree_bytes(m_dn)


def test_sparse_fold_sharded_bitwise_parity(placement):
    """tp=2+ sharded sparse fold == replicated sparse fold == dense fold,
    all bitwise (per-shard host reads vs the replicated leaves)."""
    shapes = placement.shapes_tree()
    order = [str(i) for i in range(4)]
    updates = _topk_updates(4)
    arrival = list(updates)
    random.Random(13).shuffle(arrival)

    rep = StreamingFolder(shapes, order=order)
    shd = StreamingFolder(shapes, order=order, placement=placement)
    dns = StreamingFolder(shapes, order=order)
    for meta, wire, d in arrival:
        rep.add(dict(meta), jax.tree.map(np.copy, wire))
        shd.add(dict(meta), jax.tree.map(np.copy, wire))
        dns.add({k: v for k, v in meta.items() if k != "compress"},
                jax.tree.map(np.copy, d))

    m_rep, w_rep, _ = rep.mean()
    m_shd, w_shd, _ = shd.mean()
    m_dns, _, _ = dns.mean()
    assert w_rep == w_shd
    assert shd.densify_avoided == 4
    host = partition.host_tree(m_shd)
    assert _tree_bytes(m_rep) == _tree_bytes(host)
    assert _tree_bytes(m_dns) == _tree_bytes(host)
    for leaf in jax.tree.leaves(m_shd):
        assert isinstance(leaf, jax.Array)


def test_partition_flat_indices_roundtrip(placement):
    """Scattering per-shard (local indices on the shard shape) rebuilds
    exactly the full-leaf scatter, for every leaf of the placement."""
    shapes = placement.shapes_tree()
    refs = jax.tree.leaves(shapes)
    rng = np.random.default_rng(3)
    for pos, ref in enumerate(refs):
        size = int(np.prod(np.shape(ref), dtype=np.int64)) or 1
        k = max(1, size // 7)
        idx = rng.choice(size, size=k, replace=False).astype(np.int64)
        vals = rng.standard_normal(k).astype(np.float32)
        full = np.zeros(size, np.float32)
        full[idx] = vals
        full = full.reshape(np.shape(ref))

        shards = placement.partition_flat_indices(pos, idx, vals)
        # Densify each shard locally, then reassemble via slice order.
        slices = placement._meta[pos][3]
        rebuilt = np.zeros(np.shape(ref), np.float32)
        if len(shards) == 1 and len(slices) == 1:
            flat = np.zeros(size, np.float32)
            flat[shards[0][0]] = shards[0][1]
            rebuilt = flat.reshape(np.shape(ref))
        else:
            total = 0
            for (lidx, lvals, sshape), (_, index) in zip(shards, slices):
                local = np.zeros(
                    int(np.prod(sshape, dtype=np.int64)), np.float32)
                local[lidx] = lvals
                rebuilt[tuple(index)] = local.reshape(sshape)
                total += len(lidx)
            assert total == k          # every entry lands in exactly 1 shard
        np.testing.assert_array_equal(rebuilt, full)


def test_sparse_staging_is_o_k_and_counted():
    """Staged topk contributions hold (indices, values) only — k entries
    per leaf, never a full-shape tensor — and each sparse fold advances
    comm.uplink_densify_avoided_total."""
    shapes = _params()
    frac = 0.05
    ctr = telemetry.get_registry().counter(
        "comm.uplink_densify_avoided_total")
    before = ctr.value
    folder = StreamingFolder(shapes, order=["0", "1"])
    for meta, wire, _ in _topk_updates(2, fraction=frac):
        folder.add(dict(meta), wire)
    assert ctr.value - before == 2
    assert folder.densify_avoided == 2
    for _, contrib, _ in folder._staged.values():
        assert isinstance(contrib, _SparseStage)
        for triples, ref in zip(contrib.leaves, jax.tree.leaves(shapes)):
            k_max = max(1, int(np.ceil(ref.size * frac)))
            staged = sum(len(idx) for idx, _, _ in triples)
            assert staged <= k_max < ref.size


# ----------------------------------------------------- error feedback ------
def test_feedback_compress_residual_roundtrip():
    shapes = _params()
    rng = np.random.default_rng(0)
    d1 = jax.tree.map(
        lambda w: rng.standard_normal(w.shape).astype(np.float32), shapes)
    d2 = jax.tree.map(
        lambda w: rng.standard_normal(w.shape).astype(np.float32), shapes)

    wire, meta, res = compression.feedback_compress(d1, None, "topk")
    recon = compression.decompress_delta(wire, meta, shapes=shapes)
    for r, a, b in zip(jax.tree.leaves(res), jax.tree.leaves(d1),
                       jax.tree.leaves(recon)):
        np.testing.assert_array_equal(r, a - b)

    # Second round: the compensated delta (d2 + residual) is what gets
    # compressed, and the new residual is exactly what its codec dropped.
    wire2, meta2, res2 = compression.feedback_compress(d2, res, "topk")
    comp = jax.tree.map(np.add, d2, res)
    recon2 = compression.decompress_delta(wire2, meta2, shapes=shapes)
    for r, a, b in zip(jax.tree.leaves(res2), jax.tree.leaves(comp),
                       jax.tree.leaves(recon2)):
        np.testing.assert_array_equal(r, a - b)

    # Lossless scheme: nothing dropped, nothing carried.
    wire3, _, res3 = compression.feedback_compress(d2, res2, "none")
    assert res3 is None
    # ... but the pending residual still ships with the dense frame.
    for a, b, r in zip(jax.tree.leaves(wire3), jax.tree.leaves(d2),
                       jax.tree.leaves(res2)):
        np.testing.assert_array_equal(a, b + r)


def test_feedback_debiases_repeated_topk():
    """EF-SGD property: over T rounds of the SAME delta, the summed
    reconstructions with feedback approach T x delta (small entries
    eventually ship via the accumulated residual); without feedback they
    are dropped forever."""
    rng = np.random.default_rng(5)
    d = {"w": rng.standard_normal((64,)).astype(np.float32)}
    T = 24
    acc_fb = np.zeros(64, np.float32)
    acc_raw = np.zeros(64, np.float32)
    res = None
    for _ in range(T):
        wire, meta, res = compression.feedback_compress(
            d, res, "topk", topk_fraction=0.1)
        acc_fb += compression.decompress_delta(wire, meta, shapes=d)["w"]
        wire, meta = compression.compress_delta(d, "topk", topk_fraction=0.1)
        acc_raw += compression.decompress_delta(wire, meta, shapes=d)["w"]
    target = T * d["w"]
    err_fb = np.linalg.norm(acc_fb - target)
    err_raw = np.linalg.norm(acc_raw - target)
    assert err_fb < 0.5 * err_raw


def test_topk_fraction_override_controls_density():
    d = {"w": np.arange(1, 101, dtype=np.float32)}
    for frac, k in ((0.5, 50), (0.02, 2)):
        wire, meta = compression.compress_delta(d, "topk",
                                                topk_fraction=frac)
        idx, vals, size = compression.topk_leaf_arrays(wire["w"])
        assert size == 100 and len(idx) == len(vals) == k
        out = compression.decompress_delta(wire, meta, shapes=d)
        assert np.count_nonzero(out["w"]) == k


# ------------------------------------------------------- worker plane ------
def _worker_cfg(**fed_kw):
    base = dict(compress="topk", compress_feedback=True, rounds=1,
                local_steps=2, momentum=0.0)
    base.update(fed_kw)
    return _config(num_clients=2, **base)


def test_worker_feedback_residual_and_resync_reset():
    cfg = _worker_cfg()
    w = DeviceWorker(cfg, 0)
    try:
        assert w._uplink_residual is None
        params = setup_lib.init_global_params(cfg)
        header, wire = w._train(0, jax.tree.map(np.asarray, params))
        assert header["meta"]["compress"] == "topk"
        assert w._uplink_residual is not None
        norm = telemetry.get_registry().gauge("fed.uplink_residual_norm")
        assert np.isfinite(norm.value) and norm.value > 0.0

        # A delta broadcast this worker has no cached base for must
        # answer "resync" AND drop the residual: it belongs to an update
        # the server never folded against that base.
        header, _ = w._train(
            1, None, meta={downlink.DOWN_KEY: downlink.MODE_DELTA,
                           downlink.DOWN_BASE_KEY: 0})
        assert header["status"] == "resync"
        assert w._uplink_residual is None
    finally:
        w.stop()


def test_worker_without_feedback_keeps_no_residual():
    cfg = _worker_cfg(compress_feedback=False)
    w = DeviceWorker(cfg, 0)
    try:
        params = setup_lib.init_global_params(cfg)
        header, _ = w._train(0, jax.tree.map(np.asarray, params))
        assert header["meta"]["compress"] == "topk"
        assert w._uplink_residual is None
    finally:
        w.stop()


# --------------------------------------------------------- validation ------
def test_worker_rejects_secure_agg_with_feedback():
    cfg = _worker_cfg(compress="none", secure_agg=True,
                      secure_agg_key_exchange="shared_seed")
    with pytest.raises(ValueError, match="error feedback"):
        DeviceWorker(cfg, 0)


def test_validate_robustness_rejects_unsound_uplink_configs():
    base = _config(num_clients=2)
    bad = [
        (dict(compress="gzip9"), "unknown compress"),
        (dict(topk_fraction=0.0), "topk_fraction"),
        (dict(topk_fraction=1.5), "topk_fraction"),
        (dict(secure_agg=True, secure_agg_key_exchange="shared_seed",
              compress_feedback=True), "error feedback"),
    ]
    for kw, match in bad:
        cfg = base.replace(fed=dataclasses.replace(base.fed, **kw))
        with pytest.raises(ValueError, match=match):
            validate_robustness(cfg)
    ok = base.replace(fed=dataclasses.replace(
        base.fed, compress="topk", compress_feedback=True,
        topk_fraction=0.25))
    validate_robustness(ok)           # sound combination passes


# -------------------------------------------------------- convergence ------
def test_topk_feedback_federation_tracks_dense_baseline():
    """Convergence pin: a topk+feedback federation's loss trajectory and
    final params stay close to the dense-uplink run — error feedback
    bounds the sparsification drift the same way the downlink
    reconstruction base bounds quantization drift."""
    reg = telemetry.get_registry()
    cfg = _config(num_clients=3, momentum=0.0, lr=0.05)
    base_recs, base_losses, base_params = _run_federation(cfg, 3, rounds=4)

    cfg_up = cfg.replace(fed=dataclasses.replace(
        cfg.fed, compress="topk", compress_feedback=True,
        topk_fraction=0.25))
    saved = reg.counter("comm.bytes_saved_uplink")
    avoided = reg.counter("comm.uplink_densify_avoided_total")
    saved0, avoided0 = saved.value, avoided.value
    up_recs, up_losses, up_params = _run_federation(cfg_up, 3, rounds=4)

    assert all(r["completed"] == 3 for r in base_recs + up_recs)
    # Every round folds 3 sparse contributions and prices the savings.
    assert avoided.value - avoided0 == 12
    assert saved.value - saved0 > 0
    for r in up_recs:
        assert r["uplink_densify_avoided"] == 3
        assert r["bytes_saved_uplink"] > 0
    for r in base_recs:
        assert "uplink_densify_avoided" not in r
        assert "bytes_saved_uplink" not in r
    # Sparsified rounds drift slightly; trajectories must stay close.
    np.testing.assert_allclose(up_losses, base_losses, rtol=0.2, atol=0.1)
    for a, b in zip(jax.tree.leaves(base_params),
                    jax.tree.leaves(up_params)):
        np.testing.assert_allclose(a, b, atol=0.08)
