"""telemetry/flight.py: the crash flight recorder — atomic dump writes,
bounded rings, heartbeat SIGKILL survivability (a real subprocess, a
real uncatchable signal), watchdog stall detection, the corrupt-dump
contract, and the flight→postmortem round-trip against WAL entries (S4
wire-format tests)."""

import json
import os
import subprocess
import sys
import time

from colearn_federated_learning_tpu.telemetry import flight
from colearn_federated_learning_tpu.telemetry.tracer import Tracer


def make_recorder(tmp_path, **kw) -> flight.FlightRecorder:
    # Direct construction (no install()): no signal handlers, no thread —
    # unit tests drive dump() by hand.
    kw.setdefault("heartbeat_s", 60.0)
    return flight.FlightRecorder(str(tmp_path), role="test", **kw)


# ------------------------------------------------------------- dumping ---
def test_dump_writes_parseable_schema(tmp_path):
    rec = make_recorder(tmp_path)
    rec.record("round", round=2)
    path = rec.dump("install")
    doc = json.loads(open(path).read())
    assert doc["schema"] == "colearn-flight-v1"
    assert doc["pid"] == os.getpid()
    assert doc["role"] == "test"
    assert doc["trigger"] == "install"
    assert doc["events"][-1]["kind"] == "round"
    assert "metrics" in doc and "argv" in doc


def test_dump_rewrites_atomically_and_never_raises(tmp_path):
    rec = make_recorder(tmp_path)
    first = rec.dump("install")
    rec.record("round", round=1)
    second = rec.dump("heartbeat")
    assert first == second             # same path, rewritten in place
    docs = flight.load_flight_dumps(str(tmp_path))
    assert len(docs) == 1             # one black box per pid
    assert docs[0]["trigger"] == "heartbeat"
    # No stray tmp files behind the atomic replace.
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]
    # dump() must not be the second failure: an unwritable dir is eaten.
    rec.path = os.path.join(str(tmp_path), "nope", "deep", "f.json")
    rec.dump("heartbeat")              # no raise


def test_event_ring_is_bounded(tmp_path):
    rec = make_recorder(tmp_path)
    for i in range(2 * flight._EVENT_RING):
        rec.record("round", round=i)
    doc = json.loads(open(rec.dump("heartbeat")).read())
    assert len(doc["events"]) == flight._EVENT_RING
    assert doc["events"][-1]["round"] == 2 * flight._EVENT_RING - 1


def test_attached_tracer_tail_rides_in_dump(tmp_path):
    tracer = Tracer(process="coordinator")
    tracer.enabled = True
    with tracer.span("round", round=1):
        with tracer.span("aggregate"):
            pass
    rec = make_recorder(tmp_path)
    rec.attach_tracer(tracer)
    doc = json.loads(open(rec.dump("heartbeat")).read())
    assert {s["name"] for s in doc["spans"]} == {"round", "aggregate"}


def test_exception_payload_recorded(tmp_path):
    rec = make_recorder(tmp_path)
    rec.dump("fatal_exception", exc="Traceback ...\nValueError: boom")
    doc = flight.load_flight_dumps(str(tmp_path))[0]
    assert doc["trigger"] == "fatal_exception"
    assert "ValueError: boom" in doc["exception"]


def test_watchdog_declares_stall(tmp_path):
    rec = make_recorder(tmp_path, heartbeat_s=0.05, watchdog_s=0.1)
    # The stall dump is overwritten by the next heartbeat ~50ms later, so
    # observe triggers at the dump() boundary rather than racing the file.
    triggers = []
    orig_dump = rec.dump

    def spying_dump(trigger, exc=None):
        triggers.append(trigger)
        return orig_dump(trigger, exc)

    rec.dump = spying_dump
    rec.install()
    try:
        rec.mark_progress()
        deadline = time.monotonic() + 5.0
        while ("watchdog_stall" not in triggers
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert "watchdog_stall" in triggers
        time.sleep(0.2)                # a few more heartbeats pass...
    finally:
        rec.close()
    # ...but the stall is declared once per quiet period, and the final
    # rewrite marks a clean shutdown.
    assert triggers.count("watchdog_stall") == 1
    assert flight.load_flight_dumps(
        str(tmp_path))[0]["trigger"] == "shutdown"


# -------------------------------------------------------- survivability --
def test_sigkill_leaves_parseable_dump(tmp_path):
    """The core contract: SIGKILL is uncatchable, so the last heartbeat
    rewrite IS the black box — at most one heartbeat stale, and it must
    parse."""
    child = (
        "import time\n"
        "from colearn_federated_learning_tpu.telemetry import flight\n"
        f"rec = flight.install_flight_recorder({str(tmp_path)!r},\n"
        "    role='victim', heartbeat_s=0.2)\n"
        "rec.record('round', round=3)\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, "-c", child],
                         stdout=subprocess.PIPE, text=True, env=env)
    try:
        assert p.stdout.readline().strip() == "ready"
        time.sleep(1.0)                # a few heartbeats
        p.kill()
    finally:
        p.wait()
    dumps = [d for d in flight.load_flight_dumps(str(tmp_path))
             if "error" not in d]
    assert [d["pid"] for d in dumps] == [p.pid]
    assert dumps[0]["role"] == "victim"
    assert any(e.get("round") == 3 for e in dumps[0]["events"])


def test_unparseable_dump_is_a_finding_not_a_skip(tmp_path):
    (tmp_path / "flight_123.json").write_text('{"pid": 123, "tru')
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "flight_456.json").write_text(
        json.dumps({"schema": "colearn-flight-v1", "pid": 456, "ts": 1.0}))
    docs = flight.load_flight_dumps(str(tmp_path))
    good = [d for d in docs if "error" not in d]
    bad = [d for d in docs if "error" in d]
    assert [d["pid"] for d in good] == [456]   # recursive walk found it
    assert len(bad) == 1 and bad[0]["_path"].endswith("flight_123.json")


# ----------------------------------------------------------- postmortem --
def _dump_for(tmp_path, pid, rounds, trigger="heartbeat"):
    doc = {"schema": "colearn-flight-v1", "pid": pid, "role": "worker",
           "trigger": trigger, "ts": float(pid), "argv": [],
           "events": [{"ts": 0.0, "kind": "round", "round": r}
                      for r in rounds],
           "metrics": {"comm.retry_total": 2.0}, "spans": []}
    (tmp_path / f"flight_{pid}.json").write_text(json.dumps(doc))


def test_postmortem_splits_committed_vs_in_flight_exactly(tmp_path):
    wal = [{"round": r, "accepted": 2, "completed": 2,
            "total_weight": 10.0} for r in (1, 2, 3, 4)]
    report = flight.postmortem_report([], wal_entries=wal,
                                      checkpoint_step=3)
    assert report["last_committed_round"] == 3
    assert report["committed_rounds"] == 3
    assert report["rounds_in_flight"] == [4]


def test_postmortem_infers_in_flight_from_dumps(tmp_path):
    _dump_for(tmp_path, 100, rounds=[1, 2, 3])
    dumps = flight.load_flight_dumps(str(tmp_path))
    wal = [{"round": 1}, {"round": 2}]
    report = flight.postmortem_report(dumps, wal_entries=wal)
    assert report["last_committed_round"] == 2
    assert report["rounds_in_flight"] == [3]   # seen by a dump, not in WAL
    proc = report["processes"][0]
    assert proc["pid"] == 100
    assert proc["last_round_seen"] == 3
    assert proc["metrics_of_note"] == {"comm.retry_total": 2.0}


def test_postmortem_roundtrip_through_files(tmp_path):
    """S4: recorder dump -> disk -> load_flight_dumps -> report -> JSON
    round-trips without loss of the crash story."""
    rec = flight.FlightRecorder(str(tmp_path), role="coordinator",
                                heartbeat_s=60.0)
    rec.record("round", round=5)
    rec.dump("sigterm")
    _dump_for(tmp_path, 7, rounds=[4], trigger="watchdog_stall")
    dumps = flight.load_flight_dumps(str(tmp_path))
    report = flight.postmortem_report(
        dumps, wal_entries=[{"round": 4}], checkpoint_step=1)
    report2 = json.loads(json.dumps(report))
    assert report2["schema"] == "colearn-postmortem-v1"
    assert report2["process_count"] == 2
    assert sorted(report2["crash_triggers"]) == ["sigterm",
                                                "watchdog_stall"]
    rendered = flight.render_postmortem(report2)
    assert str(os.getpid()) in rendered
    assert "sigterm" in rendered


def test_render_postmortem_reports_unparseable(tmp_path):
    (tmp_path / "flight_9.json").write_text("not json")
    report = flight.postmortem_report(
        flight.load_flight_dumps(str(tmp_path)))
    assert "error" in report["processes"][0]
    assert "[unparseable]" in flight.render_postmortem(report)


def test_install_is_idempotent_per_process(tmp_path):
    """The module singleton: worker + engine may both ask; one recorder."""
    import colearn_federated_learning_tpu.telemetry.flight as fl

    prev = fl._recorder
    fl._recorder = None
    try:
        a = fl.install_flight_recorder(str(tmp_path), role="worker",
                                       heartbeat_s=60.0)
        b = fl.install_flight_recorder(str(tmp_path / "other"))
        assert a is b
        assert fl.get_flight_recorder() is a
        a.close()
    finally:
        fl._recorder = prev
