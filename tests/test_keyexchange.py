"""DH key agreement for wire-plane secure aggregation (comm/keyexchange.py)."""

import numpy as np
import pytest

from colearn_federated_learning_tpu.comm import keyexchange as kx


def test_shared_secret_symmetry():
    priv_a, pub_a = kx.generate_keypair()
    priv_b, pub_b = kx.generate_keypair()
    assert kx.shared_secret(priv_a, pub_b) == kx.shared_secret(priv_b, pub_a)
    # A third party's secret differs.
    priv_c, pub_c = kx.generate_keypair()
    assert kx.shared_secret(priv_c, pub_a) != kx.shared_secret(priv_a, pub_b)


def test_pair_key_symmetric_in_ids_and_distinct_per_pair():
    priv_a, pub_a = kx.generate_keypair()
    priv_b, pub_b = kx.generate_keypair()
    s = kx.shared_secret(priv_a, pub_b)
    np.testing.assert_array_equal(
        np.asarray(kx.pair_prng_key(s, 3, 7)),
        np.asarray(kx.pair_prng_key(s, 7, 3)),
    )
    assert not np.array_equal(
        np.asarray(kx.pair_prng_key(s, 3, 7)),
        np.asarray(kx.pair_prng_key(s, 3, 8)),
    )


@pytest.mark.parametrize("bad", [0, 1, kx.GROUP14_P - 1, kx.GROUP14_P, -5])
def test_degenerate_public_keys_rejected(bad):
    # 0/1/p-1 are the order-1/2 elements of the safe-prime group: accepting
    # them would force the shared secret into a tiny known set.
    with pytest.raises(ValueError, match="public key"):
        kx.validate_public(bad)
    with pytest.raises(ValueError, match="public key"):
        kx.shared_secret(12345, bad)


def test_encode_decode_roundtrip():
    _, pub = kx.generate_keypair()
    assert kx.decode_public(kx.encode_public(pub)) == pub
