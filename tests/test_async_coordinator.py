"""Buffered-asynchronous aggregation (comm/async_coordinator.py).

The reference (and the synchronous coordinator it maps to) is
bulk-synchronous — a slow device stalls every round.  The async
coordinator is the rebuild's FedBuff-style superset: per-device dispatch
pumps, aggregation every ``buffer_size`` updates, staleness-discounted
weights, no round deadline.
"""

import time

import numpy as np

from colearn_federated_learning_tpu.comm.async_coordinator import (
    AsyncFederatedCoordinator,
)
from colearn_federated_learning_tpu.comm.broker import MessageBroker
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _config(num_clients=4, **fed_kw):
    fed = dict(strategy="fedavg", rounds=2, cohort_size=0, local_steps=3,
               batch_size=16, lr=0.1, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="async_comm_test", backend="cpu"),
    )


def test_async_federation_learns_and_tracks_staleness():
    cfg = _config(num_clients=4)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(4)
        ]
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port,
                buffer_size=2, request_timeout=60.0,
            )
            with coord:
                coord.enroll(min_devices=4, timeout=20.0)
                for w in workers:
                    w.await_role(timeout=10.0)
                before = coord.evaluate()
                hist = coord.fit(aggregations=16)
                after = coord.evaluate()
            assert len(hist) == 16
            # Each aggregation folded exactly buffer_size updates and
            # advanced the model version.
            assert hist[-1]["model_version"] == 16
            assert all(len(r["contributors"]) == 2 for r in hist)
            # With 3 continuously-pumping trainers some updates arrive
            # stale (trained on an older version) — and they are bounded.
            assert all(r["staleness_max"] <= coord.max_staleness
                       for r in hist)
            assert np.isfinite(hist[-1]["train_loss"])
            # Learning signal robust to CI load: under heavy contention
            # the pumps starve, staleness rises and its discounts slow
            # convergence — so assert the optimization direction (loss
            # clearly below its start) and sane evals, not an accuracy
            # bar that depends on scheduler timing.  End-to-end accuracy
            # is covered by the deterministic sync-coordinator test and
            # the CLI integration run.
            assert min(r["train_loss"] for r in hist[4:]) < hist[0]["train_loss"]
            assert np.isfinite(before["eval_loss"])
            assert np.isfinite(after["eval_loss"])
        finally:
            for w in workers:
                w.stop()


def test_async_rejects_unsupported_configs():
    import pytest

    # secure_agg needs an agreed per-round cohort the pumps don't have.
    with pytest.raises(NotImplementedError, match="synchronous"):
        AsyncFederatedCoordinator(
            _config(secure_agg=True), "127.0.0.1", 1,
        )
    # adaptive clipping is engine-only cross-round state.
    with pytest.raises(NotImplementedError, match="engine-only"):
        AsyncFederatedCoordinator(
            _config(dp_clip=1.0, dp_noise_multiplier=0.5,
                    dp_adaptive_clip=True),
            "127.0.0.1", 1,
        )


def test_async_charge_privacy_math():
    # Oracle for the per-aggregation effective multiplier:
    # z_eff = (sigma/sqrt(B_cfg)) * sqrt(sum w^2) / max_device(sum w).
    import math
    import types

    import pytest

    from colearn_federated_learning_tpu.privacy.accountant import (
        RdpAccountant,
    )

    cfg = _config(dp_clip=1.0, dp_noise_multiplier=2.0, cohort_size=4)
    self = types.SimpleNamespace(
        config=cfg, accountant=RdpAccountant.from_config(cfg.fed, 1.0))
    charge = AsyncFederatedCoordinator._charge_privacy
    # Two distinct devices, equal weights: sqrt(2 w^2)/w = sqrt(2).
    z = charge(self, [1.0, 1.0], ["a", "b"])
    assert z == pytest.approx((2.0 / 2.0) * math.sqrt(2.0))
    # SAME device twice (two versions in one buffer): its influence is
    # the SUM of its weights -> z halves vs the two-device case.
    z2 = charge(self, [1.0, 1.0], ["a", "a"])
    assert z2 == pytest.approx((2.0 / 2.0) * math.sqrt(2.0) / 2.0)
    # Staleness-discounted second update from another device.
    z3 = charge(self, [1.0, 0.5], ["a", "b"])
    assert z3 == pytest.approx(math.sqrt(1.25))
    assert self.accountant.steps == 3
    assert 0.0 < self.accountant.epsilon() < math.inf


def test_async_dp_federation_reports_epsilon(tmp_path):
    # End to end: buffered-async aggregation WITH clip+noise — every
    # applied aggregation charges the accountant, epsilon grows
    # monotonically, and a restored coordinator replays the exact budget.
    import dataclasses

    cfg = _config(num_clients=3, dp_clip=1.0, dp_noise_multiplier=1.0)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ckpt")))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            ) as coord:
                coord.enroll(min_devices=3, timeout=20.0)
                hist = coord.fit(aggregations=4)
                eps = [r["dp_epsilon"] for r in hist]
                zs = [r["dp_z_eff"] for r in hist]
                final_eps = coord.accountant.epsilon()
                coord.save_checkpoint()
            assert all(np.isfinite(eps)) and all(z > 0 for z in zs)
            assert all(b > a for a, b in zip(eps, eps[1:])), eps

            # Resume: the budget is rebuilt by replaying history.
            coord2 = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            )
            step = coord2.restore_checkpoint()
            assert step == 4
            assert coord2.accountant.epsilon() == final_eps
            coord2.close()
        finally:
            for w in workers:
                w.stop()


def test_async_checkpoint_resume(tmp_path):
    import dataclasses

    cfg = _config(num_clients=3)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ckpt")))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            ) as coord:
                coord.enroll(min_devices=3, timeout=20.0)
                coord.fit(aggregations=3)      # final agg checkpoints
                v_before = coord.version
                params_before = coord.server_state.params

            # "Crashed" coordinator: a fresh instance restores and resumes.
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            ) as coord2:
                step = coord2.restore_checkpoint()
                assert step == v_before == 3
                assert len(coord2.history) == 3
                import jax

                for a, b in zip(jax.tree.leaves(params_before),
                                jax.tree.leaves(coord2.server_state.params)):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))
                coord2.enroll(min_devices=3, timeout=20.0)
                hist = coord2.fit(aggregations=2)
            assert hist[-1]["model_version"] == 5
        finally:
            for w in workers:
                w.stop()


def test_async_escalates_when_no_updates_arrive():
    import pytest

    cfg = _config(num_clients=3)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                request_timeout=1.0, want_evaluator=False,
            )
            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                # Kill every worker: dispatchers retry forever, the
                # aggregator must escalate instead of hanging.
                for w in workers:
                    w.stop()
                with pytest.raises(RuntimeError, match="no update arrived"):
                    coord.run_aggregation()
        finally:
            for w in workers:
                w.stop()


def test_async_elastic_late_join():
    cfg = _config(num_clients=4)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        late = None
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            )
            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                coord.fit(aggregations=2)
                # A new device enrolls mid-run; it must get a pump and
                # eventually contribute.
                late = DeviceWorker(cfg, 3, broker.host,
                                    broker.port).start()
                deadline = time.time() + 30.0
                admitted = []
                while not admitted and time.time() < deadline:
                    admitted = coord.refresh_membership()
                assert admitted == ["3"]
                contributors = set()
                while "3" not in contributors and time.time() < deadline:
                    contributors.update(coord.run_aggregation()["contributors"])
                assert "3" in contributors
        finally:
            for w in workers:
                w.stop()
            if late is not None:
                late.stop()


def test_async_slow_device_does_not_stall():
    cfg = _config(num_clients=3)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            # Make worker 0's trainer artificially slow: the federation
            # must keep aggregating from the fast devices meanwhile.
            real_train = workers[0]._train

            def slow_train(round_idx, params):
                time.sleep(1.5)
                return real_train(round_idx, params)

            workers[0]._train = slow_train
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port,
                buffer_size=1, request_timeout=30.0, want_evaluator=False,
            )
            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                # Warm-up aggregations: the first train request per worker
                # pays its jit compile; timing starts once the pumps are hot.
                coord.fit(aggregations=2)
                t0 = time.perf_counter()
                hist = coord.fit(aggregations=4)
                wall = time.perf_counter() - t0
            # 4 more aggregations of buffer 1: the two fast devices carry
            # them well before the slow device's 1.5 s sleeps could stack
            # up (a synchronous round would pay 1.5 s every round).
            assert len(hist) == 6
            assert wall < 4 * 1.5, wall
        finally:
            for w in workers:
                w.stop()


def test_async_composes_with_topk_compression():
    # Workers compress their deltas (native top-k selector); the async
    # folder must decompress via the shared UpdateFolder plumbing.
    cfg = _config(num_clients=3, compress="topk")
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            )
            # The wire payload really is top-k-compressed (not a silently
            # dropped flag the folder would also accept).
            header, wire = workers[0]._train(0, __import__("jax").tree.map(
                np.asarray,
                __import__(
                    "colearn_federated_learning_tpu.fed.setup",
                    fromlist=["setup"],
                ).init_global_params(cfg),
            ))
            assert header["meta"]["compress"] == "topk"

            def has_kleaf(d):                  # sparse index/value leaves
                if isinstance(d, dict) and set(d) == {"i", "v", "n"}:
                    return True
                return isinstance(d, dict) and any(
                    has_kleaf(v) for v in d.values()
                )

            assert has_kleaf(wire)

            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                hist = coord.fit(aggregations=3)
            assert len(hist) == 3
            assert all(np.isfinite(r["train_loss"]) for r in hist)
        finally:
            for w in workers:
                w.stop()
