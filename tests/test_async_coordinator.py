"""Buffered-asynchronous aggregation (comm/async_coordinator.py).

The reference (and the synchronous coordinator it maps to) is
bulk-synchronous — a slow device stalls every round.  The async
coordinator is the rebuild's FedBuff-style superset: per-device dispatch
pumps, aggregation every ``buffer_size`` updates, staleness-discounted
weights, no round deadline.
"""

import time

import numpy as np
import pytest

from colearn_federated_learning_tpu.comm.async_coordinator import (
    AsyncFederatedCoordinator,
)
from colearn_federated_learning_tpu.comm.broker import MessageBroker
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _config(num_clients=4, **fed_kw):
    fed = dict(strategy="fedavg", rounds=2, cohort_size=0, local_steps=3,
               batch_size=16, lr=0.1, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="async_comm_test", backend="cpu"),
    )


def test_async_federation_learns_and_tracks_staleness():
    cfg = _config(num_clients=4)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(4)
        ]
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port,
                buffer_size=2, request_timeout=60.0,
            )
            with coord:
                coord.enroll(min_devices=4, timeout=20.0)
                for w in workers:
                    w.await_role(timeout=10.0)
                before = coord.evaluate()
                hist = coord.fit(aggregations=16)
                after = coord.evaluate()
            assert len(hist) == 16
            # Each aggregation folded exactly buffer_size updates and
            # advanced the model version.
            assert hist[-1]["model_version"] == 16
            assert all(len(r["contributors"]) == 2 for r in hist)
            # With 3 continuously-pumping trainers some updates arrive
            # stale (trained on an older version) — and they are bounded.
            assert all(r["staleness_max"] <= coord.max_staleness
                       for r in hist)
            assert np.isfinite(hist[-1]["train_loss"])
            # Learning signal robust to CI load: under heavy contention
            # the pumps starve, staleness rises and its discounts slow
            # convergence — so assert the optimization direction (loss
            # clearly below its start) and sane evals, not an accuracy
            # bar that depends on scheduler timing.  End-to-end accuracy
            # is covered by the deterministic sync-coordinator test and
            # the CLI integration run.
            assert min(r["train_loss"] for r in hist[4:]) < hist[0]["train_loss"]
            assert np.isfinite(before["eval_loss"])
            assert np.isfinite(after["eval_loss"])
        finally:
            for w in workers:
                w.stop()


def test_async_rejects_unsupported_configs():
    import pytest

    # secure_agg needs an agreed per-round cohort the pumps don't have.
    with pytest.raises(NotImplementedError, match="synchronous"):
        AsyncFederatedCoordinator(
            _config(secure_agg=True), "127.0.0.1", 1,
        )
    # adaptive clipping is engine-only cross-round state.
    with pytest.raises(NotImplementedError, match="engine-only"):
        AsyncFederatedCoordinator(
            _config(dp_clip=1.0, dp_noise_multiplier=0.5,
                    dp_adaptive_clip=True),
            "127.0.0.1", 1,
        )


def test_async_charge_privacy_math():
    # Oracle for the per-aggregation effective multiplier:
    # z_eff = (sigma/sqrt(B_cfg)) * sqrt(sum w^2) / max_device(sum w).
    import math
    import types

    import pytest

    from colearn_federated_learning_tpu.privacy.accountant import (
        RdpAccountant,
    )

    cfg = _config(dp_clip=1.0, dp_noise_multiplier=2.0, cohort_size=4)
    self = types.SimpleNamespace(
        config=cfg, accountant=RdpAccountant.from_config(cfg.fed, 1.0))
    charge = AsyncFederatedCoordinator._charge_privacy
    # Two distinct devices, equal weights: sqrt(2 w^2)/w = sqrt(2).
    z = charge(self, [1.0, 1.0], ["a", "b"])
    assert z == pytest.approx((2.0 / 2.0) * math.sqrt(2.0))
    # SAME device twice (two versions in one buffer): its influence is
    # the SUM of its weights -> z halves vs the two-device case.
    z2 = charge(self, [1.0, 1.0], ["a", "a"])
    assert z2 == pytest.approx((2.0 / 2.0) * math.sqrt(2.0) / 2.0)
    # Staleness-discounted second update from another device.
    z3 = charge(self, [1.0, 0.5], ["a", "b"])
    assert z3 == pytest.approx(math.sqrt(1.25))
    assert self.accountant.steps == 3
    assert 0.0 < self.accountant.epsilon() < math.inf


def test_async_dp_federation_reports_epsilon(tmp_path):
    # End to end: buffered-async aggregation WITH clip+noise — every
    # applied aggregation charges the accountant, epsilon grows
    # monotonically, and a restored coordinator replays the exact budget.
    import dataclasses

    cfg = _config(num_clients=3, dp_clip=1.0, dp_noise_multiplier=1.0)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ckpt")))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            ) as coord:
                coord.enroll(min_devices=3, timeout=20.0)
                hist = coord.fit(aggregations=4)
                eps = [r["dp_epsilon"] for r in hist]
                zs = [r["dp_z_eff"] for r in hist]
                final_eps = coord.accountant.epsilon()
                coord.save_checkpoint()
            assert all(np.isfinite(eps)) and all(z > 0 for z in zs)
            assert all(b > a for a, b in zip(eps, eps[1:])), eps

            # Resume: the budget is rebuilt by replaying history.
            coord2 = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            )
            step = coord2.restore_checkpoint()
            assert step == 4
            assert coord2.accountant.epsilon() == final_eps
            coord2.close()
        finally:
            for w in workers:
                w.stop()


def test_async_checkpoint_resume(tmp_path):
    import dataclasses

    cfg = _config(num_clients=3)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ckpt")))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            ) as coord:
                coord.enroll(min_devices=3, timeout=20.0)
                coord.fit(aggregations=3)      # final agg checkpoints
                v_before = coord.version
                params_before = coord.server_state.params

            # "Crashed" coordinator: a fresh instance restores and resumes.
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            ) as coord2:
                step = coord2.restore_checkpoint()
                assert step == v_before == 3
                assert len(coord2.history) == 3
                import jax

                for a, b in zip(jax.tree.leaves(params_before),
                                jax.tree.leaves(coord2.server_state.params)):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))
                coord2.enroll(min_devices=3, timeout=20.0)
                hist = coord2.fit(aggregations=2)
            assert hist[-1]["model_version"] == 5
        finally:
            for w in workers:
                w.stop()


def test_async_escalates_when_no_updates_arrive():
    import pytest

    cfg = _config(num_clients=3)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                request_timeout=1.0, want_evaluator=False,
            )
            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                # Kill every worker: dispatchers retry forever, the
                # aggregator must escalate instead of hanging.
                for w in workers:
                    w.stop()
                with pytest.raises(RuntimeError, match="no update arrived"):
                    coord.run_aggregation()
        finally:
            for w in workers:
                w.stop()


def test_async_elastic_late_join():
    cfg = _config(num_clients=4)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        late = None
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            )
            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                coord.fit(aggregations=2)
                # A new device enrolls mid-run; it must get a pump and
                # eventually contribute.
                late = DeviceWorker(cfg, 3, broker.host,
                                    broker.port).start()
                deadline = time.time() + 30.0
                admitted = []
                while not admitted and time.time() < deadline:
                    admitted = coord.refresh_membership()
                assert admitted == ["3"]
                contributors = set()
                while "3" not in contributors and time.time() < deadline:
                    contributors.update(coord.run_aggregation()["contributors"])
                assert "3" in contributors
        finally:
            for w in workers:
                w.stop()
            if late is not None:
                late.stop()


def test_async_slow_device_does_not_stall():
    cfg = _config(num_clients=3)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            # Make worker 0's trainer artificially slow: the federation
            # must keep aggregating from the fast devices meanwhile.
            real_train = workers[0]._train

            def slow_train(round_idx, params):
                time.sleep(1.5)
                return real_train(round_idx, params)

            workers[0]._train = slow_train
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port,
                buffer_size=1, request_timeout=30.0, want_evaluator=False,
            )
            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                # Warm-up aggregations: the first train request per worker
                # pays its jit compile; timing starts once the pumps are hot.
                coord.fit(aggregations=2)
                t0 = time.perf_counter()
                hist = coord.fit(aggregations=4)
                wall = time.perf_counter() - t0
            # 4 more aggregations of buffer 1: the two fast devices carry
            # them well before the slow device's 1.5 s sleeps could stack
            # up (a synchronous round would pay 1.5 s every round).
            assert len(hist) == 6
            assert wall < 4 * 1.5, wall
        finally:
            for w in workers:
                w.stop()


def test_async_composes_with_topk_compression():
    # Workers compress their deltas (native top-k selector); the async
    # folder must decompress via the shared UpdateFolder plumbing.
    cfg = _config(num_clients=3, compress="topk")
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            )
            # The wire payload really is top-k-compressed (not a silently
            # dropped flag the folder would also accept).
            header, wire = workers[0]._train(0, __import__("jax").tree.map(
                np.asarray,
                __import__(
                    "colearn_federated_learning_tpu.fed.setup",
                    fromlist=["setup"],
                ).init_global_params(cfg),
            ))
            assert header["meta"]["compress"] == "topk"

            def has_kleaf(d):                  # sparse index/value leaves
                if isinstance(d, dict) and set(d) == {"i", "v", "n"}:
                    return True
                return isinstance(d, dict) and any(
                    has_kleaf(v) for v in d.values()
                )

            assert has_kleaf(wire)

            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                hist = coord.fit(aggregations=3)
            assert len(hist) == 3
            assert all(np.isfinite(r["train_loss"]) for r in hist)
        finally:
            for w in workers:
                w.stop()


# ===================================================================
# PR 13: streaming-fold parity, straggler pruning, dead-pump eviction,
# resume idempotency, lost-wakeup regression.
# ===================================================================

def _fold_params():
    rng = np.random.default_rng(7)
    f = lambda *s: rng.standard_normal(s).astype(np.float32)
    return {
        "params": {
            "Embed_0": {"embedding": f(16, 8)},
            "Dense_0": {"kernel": f(8, 32), "bias": f(32)},
            "Dense_1": {"kernel": f(32, 8)},
            "LayerNorm_0": {"scale": f(8)},
        }
    }


def _tree_bytes(tree):
    import jax

    return [np.asarray(l).tobytes() for l in jax.tree.leaves(tree)]


def _arrival_stream(n, compress=None):
    """n (device_id, meta, payload, weight) in arrival order, with the
    SAME device appearing twice (a slow device can land updates for two
    model versions in one buffer — the case that forces the async
    staging keys).  Weights are irrational-ish so the float sum is
    order-sensitive and the bitwise compare is meaningful."""
    import jax

    from colearn_federated_learning_tpu.fed import compression

    shapes = _fold_params()
    out = []
    for i in range(n):
        rng = np.random.default_rng(300 + i)
        d = jax.tree.map(
            lambda w: rng.standard_normal(w.shape).astype(np.float32),
            shapes)
        dev = "dup" if i in (0, n - 1) else str(i)
        meta = {"client_id": dev, "mean_loss": 0.3 + 0.05 * i}
        if compress == "topk":
            wire, cmeta = compression.compress_delta(
                d, "topk", topk_fraction=0.2)
            meta.update(cmeta)
            d = wire
        w = (1.0 + i) ** -0.5          # staleness-style discounts
        out.append((dev, meta, d, w))
    return out


def _async_stage(folder, stream):
    """Stage a stream exactly the way run_aggregation does: meta COPY
    with a zero-padded arrival-index key, so the folder's sorted
    finalize (order=None) IS arrival order."""
    for idx, (dev, meta, payload, w) in enumerate(stream):
        fmeta = dict(meta)
        fmeta["client_id"] = f"{idx:08d}@{dev}"
        folder.add(fmeta, payload, weight=w)


@pytest.fixture(scope="module")
def tp_placement():
    import jax

    from colearn_federated_learning_tpu.parallel import partition

    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("needs the forced 8-device CPU host")
    pl = partition.make_server_placement(
        _fold_params(), 4, "model", "bert", devices=devs[:4])
    assert pl is not None
    return pl


def test_async_fold_bitwise_parity_dense():
    # The StreamingFolder staging the async coordinator uses must
    # reproduce the legacy dense UpdateFolder fold BITWISE — same
    # arrival order, duplicate device included.
    from colearn_federated_learning_tpu.comm.aggregation import (
        StreamingFolder,
        UpdateFolder,
    )

    stream = _arrival_stream(5)
    legacy = UpdateFolder(_fold_params())
    for dev, meta, d, w in stream:
        legacy.add(meta, d, weight=w)
    streaming = StreamingFolder(_fold_params())
    _async_stage(streaming, stream)

    m_leg, w_leg, l_leg = legacy.mean()
    m_str, w_str, l_str = streaming.mean()
    assert w_leg == w_str and l_leg == l_str
    assert _tree_bytes(m_leg) == _tree_bytes(m_str)


def test_async_fold_bitwise_parity_topk():
    # Same contract with topk wires: the legacy path densified each
    # update; the async folder stages (indices, values) sparse.
    from colearn_federated_learning_tpu.comm.aggregation import (
        StreamingFolder,
        UpdateFolder,
    )
    import jax

    stream = _arrival_stream(5, compress="topk")
    legacy = UpdateFolder(_fold_params())
    for dev, meta, wire, w in stream:
        legacy.add(dict(meta), jax.tree.map(np.copy, wire), weight=w)
    streaming = StreamingFolder(_fold_params())
    _async_stage(streaming, [
        (dev, meta, jax.tree.map(np.copy, wire), w)
        for dev, meta, wire, w in stream
    ])

    m_leg, w_leg, _ = legacy.mean()
    m_str, w_str, _ = streaming.mean()
    assert w_leg == w_str
    assert streaming.densify_avoided == 5
    assert _tree_bytes(m_leg) == _tree_bytes(m_str)


def test_async_fold_bitwise_parity_tp_sharded(tp_placement):
    # tp-sharded async fold (per-shard sparse scatter) == the legacy
    # replicated dense fold, bitwise on host reads.
    from colearn_federated_learning_tpu.comm.aggregation import (
        StreamingFolder,
        UpdateFolder,
    )
    import jax

    from colearn_federated_learning_tpu.parallel import partition

    stream = _arrival_stream(4, compress="topk")
    legacy = UpdateFolder(_fold_params())
    for dev, meta, wire, w in stream:
        legacy.add(dict(meta), jax.tree.map(np.copy, wire), weight=w)
    sharded = StreamingFolder(tp_placement.shapes_tree(),
                              placement=tp_placement)
    _async_stage(sharded, [
        (dev, meta, jax.tree.map(np.copy, wire), w)
        for dev, meta, wire, w in stream
    ])

    m_leg, w_leg, _ = legacy.mean()
    m_shd, w_shd, _ = sharded.mean()
    assert w_leg == w_shd
    for leaf in __import__("jax").tree.leaves(m_shd):
        assert isinstance(leaf, jax.Array)
    host = partition.host_tree(m_shd)
    assert _tree_bytes(m_leg) == _tree_bytes(host)


def test_async_prune_requires_health_dir():
    with pytest.raises(ValueError, match="health ledger"):
        AsyncFederatedCoordinator(
            _config(), "127.0.0.1", 1, prune_after=3,
        )
    with pytest.raises(ValueError, match="probation"):
        AsyncFederatedCoordinator(
            _config(), "127.0.0.1", 1, probation=0,
        )


def test_async_update_pruning_policy():
    # Unit-level policy oracle (no sockets): streak trigger, score
    # trigger with the latency-EWMA term, the buffer-size floor, and
    # probation re-admission.
    import threading
    import types

    from colearn_federated_learning_tpu import telemetry
    from colearn_federated_learning_tpu.telemetry.health import DeviceHealth

    upd = AsyncFederatedCoordinator._update_pruning
    reg = telemetry.get_registry()
    pruned_stale = reg.counter("async.devices_pruned_total",
                               labels={"reason": "stale"})
    pruned_score = reg.counter("async.devices_pruned_total",
                               labels={"reason": "score"})
    readmit = reg.counter("async.devices_readmitted_total")
    p0s, p0c, r0 = pruned_stale.value, pruned_score.value, readmit.value

    mk = lambda ids: [types.SimpleNamespace(device_id=d) for d in ids]
    ns = types.SimpleNamespace(
        _pruned={}, _stale_streak={"a": 5, "b": 5, "c": 1},
        prune_after=3, prune_score=0.0, probation=2, buffer_size=2,
        _health_lock=threading.Lock(), health=None,
        trainers=mk(["a", "b", "c"]), _state_lock=threading.Lock())
    upd(ns, 0)
    # Both a and b qualify, but pruning both would leave 1 active pump
    # < buffer_size 2: only the worst (tie broken by id) is paused.
    assert ns._pruned == {"a": 2}
    assert pruned_stale.value - p0s == 1

    # Probation end: a is re-admitted with a clean streak.
    ns._stale_streak["a"] = 5
    upd(ns, 2)
    assert "a" not in ns._pruned
    assert "a" not in ns._stale_streak
    assert readmit.value - r0 == 1

    # Score trigger: ledger failure score plus multiples-of-median
    # latency EWMA above 1x.
    slow, fast = DeviceHealth("s"), DeviceHealth("f")
    slow.counts["deadline_miss"] = 4          # score 12
    slow.lat_ewma, fast.lat_ewma = 9.0, 1.0   # median 5 -> +0.8
    attributed = []
    ns2 = types.SimpleNamespace(
        _pruned={}, _stale_streak={},
        prune_after=0, prune_score=12.5, probation=4, buffer_size=1,
        _health_lock=threading.Lock(),
        health=types.SimpleNamespace(
            devices=lambda: {"s": slow, "f": fast},
            record=lambda d, **kw: attributed.append((d, kw))),
        trainers=mk(["s", "f"]), _state_lock=threading.Lock())
    upd(ns2, 0)
    assert ns2._pruned == {"s": 4}
    assert pruned_score.value - p0c == 1
    # The prune is attributed to the device in the health ledger.
    assert attributed == [("s", {"prune": 1})]


def test_async_pruning_pauses_pump_and_readmits(tmp_path):
    import dataclasses

    cfg = _config(num_clients=3)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, health_dir=str(tmp_path / "health")))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=1,
                want_evaluator=False, prune_after=2, probation=3,
            ) as coord:
                coord.enroll(min_devices=3, timeout=20.0)
                rec0 = coord.fit(aggregations=1)[0]
                # Pruning keys are stamped whenever the feature is on;
                # health keys whenever the ledger is attached.
                assert rec0["pruned"] == []
                assert rec0["health_devices"] >= 1
                # Chronic too-stale streak -> the pump is paused.  A
                # FRESH fold from "0" legitimately clears the streak
                # (that's the policy), so re-arm it until an
                # aggregation lands without "0" contributing.
                rec1 = None
                for _ in range(12):
                    coord._stale_streak["0"] = 99
                    rec1 = coord.run_aggregation()
                    if rec1["pruned"] == ["0"]:
                        break
                assert rec1 is not None and rec1["pruned"] == ["0"]
                # While pruned the pump is paused: at most one in-flight
                # pre-prune update from "0" can still fold.
                from_zero = 0
                recs = [coord.run_aggregation() for _ in range(2)]
                for r in recs:
                    from_zero += r["contributors"].count("0")
                assert from_zero <= 1
                assert all(r["pruned"] == ["0"] for r in recs)
                # Probation ended: re-admitted, pump live again.
                rec4 = coord.run_aggregation()
                assert rec4["pruned"] == []
                assert "0" not in coord._stale_streak
        finally:
            for w in workers:
                w.stop()


def test_async_default_records_have_no_feature_keys():
    # Byte-identical default records: no pruning, eviction, or health
    # keys unless those planes are on.
    cfg = _config(num_clients=3)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                want_evaluator=False,
            ) as coord:
                coord.enroll(min_devices=3, timeout=20.0)
                rec = coord.run_aggregation()
            for key in ("pruned", "evicted", "skipped_quorum",
                        "health_devices", "health_worst_device",
                        "mass_folded", "mass_discarded",
                        "arrival_rate_per_s", "staleness_p50",
                        "staleness_p90", "staleness_p99",
                        "conv_update_norm", "conv_trend"):
                assert key not in rec, key
            assert not any(k.startswith("conv_") for k in rec)
        finally:
            for w in workers:
                w.stop()


def test_async_dead_pump_eviction_and_reenroll():
    import dataclasses

    from colearn_federated_learning_tpu import telemetry

    cfg = _config(num_clients=4)
    cfg = cfg.replace(run=dataclasses.replace(cfg.run, evict_after=2))
    evict_ctr = telemetry.get_registry().counter(
        "fed.devices_evicted_total")
    e0 = evict_ctr.value
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        revived = None
        try:
            with AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=1,
                request_timeout=1.0, want_evaluator=False,
            ) as coord:
                coord.enroll(min_devices=3, timeout=20.0)
                # Kill device 0's worker: its pump fails evict_after
                # consecutive dispatches, then stops and revokes the
                # trainer (instead of retrying forever).
                workers[0].stop()
                deadline = time.time() + 60.0
                recs = []
                while "0" not in coord.evicted and time.time() < deadline:
                    recs.append(coord.run_aggregation())
                assert coord.evicted == ["0"]
                assert "0" not in {t.device_id for t in coord.trainers}
                assert evict_ctr.value - e0 == 1
                # Exactly one record carries the eviction key.
                recs += [coord.run_aggregation()]
                tagged = [r for r in recs if "evicted" in r]
                assert len(tagged) == 1 and tagged[0]["evicted"] == ["0"]

                # Elastic re-enrollment restarts the pump under the
                # same device name.
                revived = DeviceWorker(cfg, 0, broker.host,
                                       broker.port).start()
                admitted = []
                while not admitted and time.time() < deadline:
                    admitted = coord.refresh_membership()
                assert admitted == ["0"]
                contributors = set()
                while "0" not in contributors and time.time() < deadline:
                    contributors.update(
                        coord.run_aggregation()["contributors"])
                assert "0" in contributors
        finally:
            for w in workers:
                w.stop()
            if revived is not None:
                revived.stop()


def test_async_restore_is_idempotent(tmp_path):
    # Double restore, and restore on an instance that already charged
    # the accountant, must both land on the checkpoint's exact budget.
    import dataclasses

    cfg = _config(num_clients=3, dp_clip=1.0, dp_noise_multiplier=1.0)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ckpt")))
    with MessageBroker() as broker:
        with AsyncFederatedCoordinator(
            cfg, broker.host, broker.port, buffer_size=2,
            want_evaluator=False,
        ) as coord:
            for i, z in enumerate([1.1, 0.9, 1.4]):
                coord.accountant.step(1, sampling_rate=1.0,
                                      noise_multiplier=z)
                coord.history.append({"aggregation": i, "dp_z_eff": z})
            coord.version = 3
            eps = coord.accountant.epsilon()
            coord.save_checkpoint()

        with AsyncFederatedCoordinator(
            cfg, broker.host, broker.port, buffer_size=2,
            want_evaluator=False,
        ) as c2:
            assert c2.restore_checkpoint() == 3
            assert c2.accountant.epsilon() == eps
            # Retry the restore: replay must not compose on top.
            assert c2.restore_checkpoint() == 3
            assert c2.accountant.epsilon() == eps
            assert c2.accountant.steps == 3
            # Resume AFTER this instance aggregated (its accountant
            # already holds charges): still the checkpoint budget.
            c2.accountant.step(1, sampling_rate=1.0,
                               noise_multiplier=0.8)
            assert c2.restore_checkpoint() == 3
            assert c2.accountant.epsilon() == eps


def test_async_version_cv_poll_not_load_bearing():
    # Regression for the lost-wakeup window: with the cv poll inflated
    # to minutes, progress must come entirely from the aggregator's
    # notify (held across the version increment) and shutdown from
    # close()'s notify — if either were missing, the pumps would sleep
    # out the poll and the aggregation would time out.
    cfg = _config(num_clients=3)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = AsyncFederatedCoordinator(
                cfg, broker.host, broker.port, buffer_size=2,
                request_timeout=30.0, want_evaluator=False,
            )
            coord._cv_poll_s = 300.0
            with coord:
                coord.enroll(min_devices=3, timeout=20.0)
                hist = coord.fit(aggregations=3)
                t_close = time.perf_counter()
            close_s = time.perf_counter() - t_close
            assert len(hist) == 3
            assert hist[-1]["model_version"] == 3
            assert close_s < 10.0, close_s
        finally:
            for w in workers:
                w.stop()
