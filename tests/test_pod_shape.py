"""Pod-shaped multi-chip evidence beyond the 8-device conftest platform
(VERDICT r4 missing #5 / next-round #4).

Three escalations over the existing mesh tests:

1. The FULL 3-D dp x sp x tp(+ep) composition — MoE-BERT with ring
   attention on a (clients, seq, model) mesh — must produce the SAME
   numbers as the single-device vmap reference.  Until now the 3-D
   program was only compile-checked (``__graft_entry__.dryrun_multichip``);
   pieces had equality tests (tests/test_mesh_engine.py 1-D,
   tests/test_tp.py 2-D) but the composition's math was never compared.
2. A cohort-64 round over 16 virtual devices (beyond the conftest's 8):
   stratified sampling, ghost padding, and the psum tree at a
   per-device cohort of 4 x 16 devices.  Subprocess, because the virtual
   device count is fixed at backend init.
3. The driver's own ``dryrun_multichip`` green at n_devices=32 — the
   pod-shaped stretch of the compile-and-run gate (marked slow; also run
   out-of-band by scripts/record_dryrun.py which commits the timing
   artifact to results/).
"""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.parallel.mesh import make_mesh
from colearn_federated_learning_tpu.utils.jax_compat import (
    HAS_NATIVE_SHARD_MAP,
)
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _moe_ring_cfg():
    return ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=4, partition="iid",
                        max_examples_per_client=8),
        model=ModelConfig(name="moe_bert", num_classes=4, width=16, depth=2,
                          num_heads=2, seq_len=64, vocab_size=2000,
                          num_experts=4, attn_impl="ring"),
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=0,
                      local_steps=2, batch_size=4, lr=0.05, momentum=0.9),
        run=RunConfig(name="pod_3d"),
    )


@pytest.mark.skipif(
    not HAS_NATIVE_SHARD_MAP,
    reason="MoE expert-parallel all-to-all aborts the interpreter (C++ "
           "level) under jax<0.6 experimental shard_map on the CPU backend",
)
def test_full_3d_composition_matches_vmap(cpu_devices):
    """One federated round on the full (clients=2, seq=2, model=2) mesh —
    dp x sp(ring) x tp x ep in one jit program — must match the vmap
    engine (which runs the dense-attention twin on unsharded experts):
    same cohort, same per-(client, round) keys, exact attention both ways,
    so losses and the updated global params agree to float32 tolerance."""
    cfg = _moe_ring_cfg()
    mesh = make_mesh(("clients", "seq", "model"), (2, 2, 2),
                     devices=cpu_devices[:8])
    lm = FederatedLearner(cfg, mesh=mesh)
    lv = FederatedLearner(cfg)  # vmap reference (ring -> dense twin)
    rm = lm.run_round()
    rv = lv.run_round()
    assert rm["completed"] == rv["completed"] == 4
    assert rm["total_weight"] == rv["total_weight"]
    np.testing.assert_allclose(rm["train_loss"], rv["train_loss"], rtol=1e-4)
    # fp32 across 2 local steps + a different reduction order (ring
    # collectives + psum vs vmap sum) legitimately drifts a few 1e-4 in
    # isolated small-magnitude elements (observed: 1/32000 at 2.6e-4 abs);
    # a real sharding bug diverges by orders of magnitude.
    for a, b in zip(jax.tree.leaves(lm.server_state.params),
                    jax.tree.leaves(lv.server_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=5e-4)


@pytest.mark.slow
def test_cohort64_over_16_devices():
    """Mesh path at cohort 64 over 16 virtual devices, 128 resident
    clients: every sampled slot must be a real client (interleaved
    placement guarantees each device holds 8 reals >= cohort/D = 4), both
    rounds complete all 64, and training makes progress."""
    child = os.path.join(_REPO, "tests", "pod_child.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    r = subprocess.run(
        [sys.executable, child, "16", "64", "128"],
        capture_output=True, text=True, timeout=600, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("POD ")]
    assert line, r.stdout[-2000:]
    out = json.loads(line[-1][4:])
    assert out["n_devices"] == 16
    assert out["num_clients"] == 128           # no ghost padding needed
    assert out["cohort_per_device"] == 4
    assert out["completed"] == [64, 64]
    assert all(np.isfinite(l) for l in out["train_loss"])
    assert all(w > 0 for w in out["total_weight"])


@pytest.mark.slow
def test_cohort256_over_32_devices():
    """The north-star cohort width at pod-ish device count: 256 sampled
    clients per round over 32 virtual devices (8/device), 512 residents —
    the shape VERDICT r4 missing #5 asked for."""
    child = os.path.join(_REPO, "tests", "pod_child.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    r = subprocess.run(
        [sys.executable, child, "32", "256", "512"],
        capture_output=True, text=True, timeout=1200, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("POD ")]
    assert line, r.stdout[-2000:]
    out = json.loads(line[-1][4:])
    assert out["cohort_per_device"] == 8
    assert out["completed"] == [256, 256]
    assert out["train_loss"][1] < out["train_loss"][0]  # learning


@pytest.mark.slow
def test_dryrun_multichip_32(tmp_path):
    """The driver gate's own entry at pod-ish scale: 32 virtual devices,
    both the 1-D client mesh and the 3-D (8, 2, 2) MoE-BERT mesh."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    r = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(32); print('OK32')"],
        capture_output=True, text=True, timeout=900, env=env, cwd=_REPO,
    )
    assert r.returncode == 0, (r.stdout + r.stderr)[-2000:]
    assert "OK32" in r.stdout
