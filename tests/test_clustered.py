"""Clustered FL (fed/clustered.py): recover concept groups from update
similarity and beat the single global model under concept shift."""

import numpy as np
import jax.numpy as jnp

from colearn_federated_learning_tpu.fed.clustered import (
    ClusteredLearner,
    kmeans_rows,
)
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cfg():
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=64),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=4, cohort_size=0,
                      local_steps=3, batch_size=16, lr=0.1, momentum=0.9),
        run=RunConfig(name="clustered_test"),
    )


def _concept_shift_learner():
    """Clients 4-7 live in a permuted-label concept (y -> 9 - y)."""
    learner = FederatedLearner(_cfg())
    x, y, counts, ids = learner._device_data
    yh = np.array(y)
    shifted = np.isin(np.asarray(learner.client_ids), np.arange(4, 8))
    yh[shifted] = (9 - yh[shifted]) % 10
    learner._device_data = (x, jnp.asarray(yh), counts, ids)
    return learner


def test_kmeans_rows_separates_blobs():
    rng = np.random.default_rng(0)
    X = np.concatenate([rng.normal(0, 0.1, (10, 4)),
                        rng.normal(3, 0.1, (10, 4))])
    labels = kmeans_rows(X, 2)
    assert len(set(labels[:10])) == 1 and len(set(labels[10:])) == 1
    assert labels[0] != labels[10]


def test_clustering_recovers_concepts_and_beats_global():
    clustered = ClusteredLearner(_concept_shift_learner(), num_clusters=2)
    labels = clustered.cluster_and_specialize(warmup_rounds=2)
    # Exact recovery of the latent concept split (clients 0-3 vs 4-7).
    assert len(set(labels[:4])) == 1 and len(set(labels[4:])) == 1
    assert labels[0] != labels[4]

    clustered.fit(rounds=6)
    rep = clustered.evaluate_per_client()
    assert sorted(rep["cluster_sizes"]) == [4, 4]

    # Reference: ONE global model over the conflicting concepts.
    single = _concept_shift_learner()
    single.fit(rounds=8)
    srep = single.evaluate_per_client()

    assert rep["weighted_acc"] > 0.9, rep
    assert rep["weighted_acc"] > srep["weighted_acc"] + 0.1, (
        rep["weighted_acc"], srep["weighted_acc"])


def test_similarity_mesh_matches_vmap(cpu_devices):
    # The shard_map similarity (all_gather of normalized deltas over the
    # client axis) must reproduce the single-device gram matrix: local
    # updates are keyed on ORIGINAL client ids, so placement cannot change
    # the deltas, and the mesh output is re-ordered to id order.
    from jax.sharding import Mesh

    ref = FederatedLearner(_cfg())
    sim_ref = ref.client_update_similarity(steps=2)

    mesh = Mesh(np.array(cpu_devices[:4]), ("clients",))
    m = FederatedLearner(_cfg(), mesh=mesh)
    sim_mesh = m.client_update_similarity(steps=2)

    assert sim_mesh.shape == sim_ref.shape == (8, 8)
    np.testing.assert_allclose(sim_mesh, sim_ref, atol=1e-5)


def test_similarity_mesh_drops_ghost_padding(cpu_devices):
    # 6 clients on a 4-device mesh pad to 8 slots; the similarity matrix
    # must come back (6, 6) in original client-id order.
    import dataclasses

    from jax.sharding import Mesh

    cfg = _cfg()
    cfg = cfg.replace(data=dataclasses.replace(cfg.data, num_clients=6))
    ref = FederatedLearner(cfg)
    sim_ref = ref.client_update_similarity(steps=2)

    mesh = Mesh(np.array(cpu_devices[:4]), ("clients",))
    m = FederatedLearner(cfg, mesh=mesh)
    sim_mesh = m.client_update_similarity(steps=2)

    assert sim_mesh.shape == sim_ref.shape == (6, 6)
    np.testing.assert_allclose(sim_mesh, sim_ref, atol=1e-5)


def test_empty_real_client_rejected_at_packing():
    # The id-based ghost filter in id_order_slots assumes every REAL
    # client owns >= 1 example; the data layer enforces exactly that, so
    # counts==0 can only ever mean ghost padding.  Pin the guard.
    import pytest

    parts = [list(range(i * 40, (i + 1) * 40)) for i in range(5)] + [[]]
    import dataclasses

    cfg = _cfg()
    cfg = cfg.replace(data=dataclasses.replace(cfg.data, num_clients=6))
    with pytest.raises(ValueError, match="zero examples"):
        FederatedLearner(cfg, partitions=parts)


def test_clustered_fl_on_mesh(cpu_devices):
    # Full clustered pipeline over a client mesh: concept recovery from
    # the shard_map similarity, per-cluster training on the same mesh,
    # per-client accuracy at the specialized level.
    from jax.sharding import Mesh

    mesh = Mesh(np.array(cpu_devices[:8]), ("clients",))
    base = FederatedLearner(_cfg(), mesh=mesh)
    x, y, counts, ids = base._device_data
    yh = np.array(y)
    shifted = np.isin(np.asarray(base.client_ids), np.arange(4, 8))
    yh[shifted] = (9 - yh[shifted]) % 10
    base._device_data = (x, jnp.asarray(yh), counts, ids)

    clustered = ClusteredLearner(base, num_clusters=2)
    labels = clustered.cluster_and_specialize(warmup_rounds=2)
    assert len(set(labels[:4])) == 1 and len(set(labels[4:])) == 1
    assert labels[0] != labels[4]
    for learner in clustered.clusters:
        assert learner.mesh is mesh

    clustered.fit(rounds=6)
    rep = clustered.evaluate_per_client()
    assert sorted(rep["cluster_sizes"]) == [4, 4]
    assert rep["weighted_acc"] > 0.9, rep


def test_ifca_refinement_recovers_from_bad_clustering():
    # Adversarial start: the initial labels deliberately mix the concepts
    # (2 clients swapped across clusters).  IFCA reassignment must move
    # them to the cluster whose model fits their shard.
    clustered = ClusteredLearner(_concept_shift_learner(), num_clusters=2)
    clustered.cluster_and_specialize(warmup_rounds=2)
    true = np.array(clustered.labels)
    bad = true.copy()
    bad[0], bad[4] = true[4], true[0]           # swap one client each way
    clustered._build_clusters(
        bad, [c.server_state.params for c in clustered.clusters])
    assert (np.array(clustered.labels) != true).sum() == 2

    labels = clustered.refine(iters=3, rounds_per_iter=2)
    assert (np.array(labels) == true).all(), (labels, true)

    clustered.fit(rounds=4)
    rep = clustered.evaluate_per_client()
    assert rep["weighted_acc"] > 0.9, rep
