"""comm/: broker pub/sub, enrollment roles, tensor transport, and a full
socket-federated run (coordinator + in-process DeviceWorkers) — including
straggler drop and parity with the on-device engine's round math."""

import threading
import time

import numpy as np
import pytest

from colearn_federated_learning_tpu.comm.broker import BrokerClient, MessageBroker
from colearn_federated_learning_tpu.comm.coordinator import FederatedCoordinator
from colearn_federated_learning_tpu.comm.transport import TensorClient, TensorServer
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _config(num_clients=4, **fed_kw):
    fed = dict(strategy="fedavg", rounds=2, cohort_size=0, local_steps=3,
               batch_size=16, lr=0.1, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="comm_test", backend="cpu"),
    )


# ---------------------------------------------------------------- broker ----
def test_broker_pubsub_and_retain():
    with MessageBroker() as broker:
        sub = BrokerClient(broker.host, broker.port)
        sub.subscribe("a/b")
        pub = BrokerClient(broker.host, broker.port)
        pub.publish("a/b", {"x": 1}, body=b"payload")
        header, body = sub.recv(timeout=5.0)
        assert header["topic"] == "a/b" and header["x"] == 1
        assert body == b"payload"

        # retained message reaches a LATE subscriber; wildcard matches
        pub.publish("roles/7", {"role": "trainer"}, retain=True)
        late = BrokerClient(broker.host, broker.port)
        late.subscribe("roles/#")
        header, _ = late.recv(timeout=5.0)
        assert header["topic"] == "roles/7" and header["role"] == "trainer"
        sub.close(); pub.close(); late.close()


# ------------------------------------------------------------- transport ----
def test_tensor_transport_roundtrip():
    def handler(header, tree):
        assert header["op"] == "double"
        out = {k: v * 2 for k, v in tree.items()}
        return {"meta": {"ok": True}}, out

    with TensorServer(handler) as srv:
        cli = TensorClient(srv.host, srv.port)
        tree = {"w": np.arange(6.0).reshape(2, 3), "b": np.ones(3)}
        header, out = cli.request({"op": "double"}, tree, timeout=5.0)
        assert header["status"] == "ok" and header["meta"]["ok"]
        np.testing.assert_array_equal(out["w"], tree["w"] * 2)
        cli.close()


def test_tensor_server_reports_handler_errors():
    def handler(header, tree):
        raise RuntimeError("boom")

    with TensorServer(handler) as srv:
        cli = TensorClient(srv.host, srv.port)
        header, out = cli.request({"op": "x"}, None, timeout=5.0)
        assert header["status"] == "error" and "boom" in header["error"]
        cli.close()


# ------------------------------------------------------ full federation ----
def test_socket_federation_end_to_end():
    cfg = _config(num_clients=4)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(4)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0)
            coord.enroll(min_devices=4, timeout=20.0)
            assert len(coord.trainers) == 3 and coord.evaluator is not None
            roles = [w.await_role(timeout=10.0) for w in workers]
            assert roles.count("evaluator") == 1 and roles.count("trainer") == 3

            before = coord.evaluate()
            hist = coord.fit(rounds=3)
            after = coord.evaluate()
            assert all(r["completed"] == 3 for r in hist)
            assert all(not r["dropped"] for r in hist)
            # default records carry no feature-gated convergence keys
            assert all(not any(k.startswith("conv_") for k in r)
                       for r in hist)
            assert np.isfinite(hist[-1]["train_loss"])
            assert after["eval_acc"] >= before["eval_acc"]
            coord.close()
        finally:
            for w in workers:
                w.stop()


def test_socket_federation_drops_straggler():
    cfg = _config(num_clients=3)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=3, timeout=20.0)
            warm = coord.run_round()        # jit-compiles every worker
            assert warm["completed"] == 3

            # Sabotage worker 1's trainer: hang past the round deadline.
            slow = workers[1]
            orig = slow._train
            done = threading.Event()

            def hang(round_idx, params):
                time.sleep(4.0)
                done.set()
                return orig(round_idx, params)

            slow._train = hang
            coord.round_timeout = 1.5
            rec = coord.run_round()
            assert rec["completed"] == 2
            assert rec["dropped"] == ["1"]
            assert np.isfinite(rec["train_loss"])

            # After the drop the coordinator reconnected; once the device
            # recovers it participates again.
            slow._train = orig
            done.wait(timeout=10.0)
            coord.round_timeout = 60.0
            rec = coord.run_round()
            assert rec["completed"] == 3 and not rec["dropped"]
            coord.close()
        finally:
            for w in workers:
                w.stop()


def test_worker_rejects_bad_client_id():
    with pytest.raises(ValueError, match="out of range"):
        DeviceWorker(_config(num_clients=2), 5)


def test_cli_multiprocess_federation(tmp_path):
    """The reference's deployment shape — broker + N worker processes +
    coordinator, each a separate OS process — driven via the colearn CLI."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    args = ["--config", "mnist_mlp_fedavg", "--dataset", "mnist_tiny",
            "--num-clients", "3", "--local-steps", "2", "--rounds", "2",
            "--backend", "cpu"]
    cli_mod = ["-m", "colearn_federated_learning_tpu.cli"]
    procs = []
    try:
        broker = subprocess.Popen(
            [sys.executable, *cli_mod, "broker"], env=env,
            stdout=subprocess.PIPE, text=True,
        )
        procs.append(broker)
        addr = json.loads(broker.stdout.readline())
        port = str(addr["port"])
        for i in range(3):
            procs.append(subprocess.Popen(
                [sys.executable, *cli_mod, "worker", *args,
                 "--client-id", str(i), "--broker-port", port],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            ))
        out = subprocess.run(
            [sys.executable, *cli_mod, "coordinate", *args,
             "--broker-port", port, "--min-devices", "3",
             "--enroll-timeout", "120", "--round-timeout", "120"],
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        last = json.loads(out.stdout.strip().splitlines()[-1])
        assert last["round"] == 1 and last["completed"] == 2
        assert "eval_acc" in last and 0.0 <= last["eval_acc"] <= 1.0
    finally:
        for p in procs:
            p.kill()


def test_elastic_admission_and_eviction():
    cfg = _config(num_clients=4)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(2)
        ]
        late = None
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=2, timeout=20.0)
            assert len(coord.trainers) == 2
            warm = coord.run_round()
            assert warm["completed"] == 2

            # A third device joins mid-run; refresh admits it.
            late = DeviceWorker(cfg, 2, broker.host, broker.port).start()
            admitted = []
            for _ in range(50):                     # poll until seen
                admitted = coord.refresh_membership(poll=0.1)
                if admitted:
                    break
            assert admitted == ["2"]
            rec = coord.run_round()
            assert rec["completed"] == 3

            # Kill it permanently: after evict_after consecutive failed
            # rounds it is removed from the federation.
            late.stop()
            coord.round_timeout = 1.5
            evicted = []
            for _ in range(coord.evict_after + 1):
                rec = coord.run_round()
                evicted += rec["evicted"]
                if evicted:
                    break
            assert evicted == ["2"]
            assert [t.device_id for t in coord.trainers] == ["0", "1"]
            coord.round_timeout = 60.0
            rec = coord.run_round()
            assert rec["completed"] == 2 and not rec["dropped"]
            coord.close()
        finally:
            for w in workers:
                w.stop()
            if late is not None:
                late.stop()


def test_socket_federation_with_int8_compression():
    import dataclasses

    cfg = _config(num_clients=3)
    cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, compress="int8"))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0)
            coord.enroll(min_devices=3, timeout=20.0)
            before = coord.evaluate()
            coord.fit(rounds=3)
            after = coord.evaluate()
            assert after["eval_acc"] >= before["eval_acc"]
        finally:
            for w in workers:
                w.stop()


def test_coordinator_checkpoint_kill_and_resume(tmp_path):
    """SURVEY.md §5 checkpoint/resume for the SOCKET plane: a coordinator
    that dies mid-run is rebuilt from its checkpoint dir and finishes the
    original round budget with the same server state."""
    import dataclasses

    cfg = _config(num_clients=3, rounds=4)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=1))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=3, timeout=20.0)
            coord.fit(rounds=2)                  # checkpoints each round
            params_at_kill = {
                k: np.array(v) for k, v in
                coord.server_state.params["Dense_0"].items()
            }
            coord.close()                        # "kill" the coordinator

            # Fresh process stand-in: new coordinator, same config/dir.
            coord2 = FederatedCoordinator(cfg, broker.host, broker.port,
                                          round_timeout=60.0,
                                          want_evaluator=False)
            step = coord2.restore_checkpoint()
            assert step == 2 and len(coord2.history) == 2
            for k, v in coord2.server_state.params["Dense_0"].items():
                np.testing.assert_array_equal(np.asarray(v),
                                              params_at_kill[k])
            coord2.enroll(min_devices=3, timeout=20.0)
            hist = coord2.fit()                  # finishes rounds 2..3 only
            assert [r["round"] for r in hist] == [0, 1, 2, 3]
            assert all(r["completed"] == 3 for r in hist[2:])
            coord2.close()
        finally:
            for w in workers:
                w.stop()


# ------------------------------------------- wire secure aggregation ----
def _masked_vs_plain(num_clients: int, neighbors: int):
    """(masked, plain) flattened global params after 2 rounds of full
    participation — shared by the complete-graph and random-ring masking
    tests (the aggregate must match an unmasked federation either way)."""
    import jax

    def run(secure):
        cfg = _config(num_clients=num_clients, secure_agg=secure,
                      secure_agg_neighbors=neighbors if secure else 0)
        with MessageBroker() as broker:
            workers = [
                DeviceWorker(cfg, i, broker.host, broker.port).start()
                for i in range(num_clients)
            ]
            try:
                coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                             round_timeout=60.0,
                                             want_evaluator=False)
                coord.enroll(min_devices=num_clients, timeout=20.0)
                coord.fit(rounds=2)
                return np.concatenate([
                    np.ravel(np.asarray(a))
                    for a in jax.tree.leaves(coord.server_state.params)
                ])
            finally:
                for w in workers:
                    w.stop()

    return run(True), run(False)


def test_socket_secure_agg_masks_cancel():
    # Full participation, complete pairing graph: the coordinator's
    # aggregate over MASKED wire updates must match a parallel unmasked
    # federation (masks cancel in the sum; uniform weighting both sides
    # since secure-agg forces it).
    masked, plain = _masked_vs_plain(num_clients=3, neighbors=0)
    # Cancellation residual is float32-summation noise on ~1e-3 deltas.
    np.testing.assert_allclose(masked, plain, atol=2e-4)


def test_coordinator_view_cannot_unmask_dh():
    # THE secure-aggregation property: with DH key agreement (the wire
    # default), everything the coordinator holds — the experiment seed,
    # every public key, and a single client's masked wire update — is NOT
    # enough to recover that client's delta.  A pair MEMBER (holding a
    # private key) can cancel its own pair's mask; the coordinator's
    # shared-seed derivation (the round-3 attack) recovers nothing.
    import jax
    import jax.numpy as jnp

    from colearn_federated_learning_tpu.comm import keyexchange
    from colearn_federated_learning_tpu.comm.enrollment import (
        fetch_device_info,
    )
    from colearn_federated_learning_tpu.privacy import secure_agg as sa
    from colearn_federated_learning_tpu.utils import prng

    cfg = _config(num_clients=2, secure_agg=True)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(2)
        ]
        try:
            # Plain (unmasked) reference delta for worker 0.
            cfg_plain = _config(num_clients=2, secure_agg=False)
            ref = DeviceWorker(cfg_plain, 0).start()
            try:
                client = TensorClient(ref.host, ref.port)
                params = ref._template_params()
                _, true_delta = client.request(
                    {"op": "train", "round": 0}, params)
                client.close()
            finally:
                ref.stop()

            # Worker 0's MASKED wire update (what the coordinator sees).
            client = TensorClient(workers[0].host, workers[0].port)
            _, masked = client.request(
                {"op": "train", "round": 0, "cohort": [0, 1]}, params)
            client.close()

            flat = lambda t: np.concatenate(  # noqa: E731
                [np.ravel(np.asarray(l)) for l in jax.tree.leaves(t)])
            true_f, masked_f = flat(true_delta), flat(masked)
            # The mask is real: the wire update is nothing like the delta.
            assert np.abs(masked_f - true_f).max() > 0.1

            # ATTACK (coordinator's view): shared experiment seed ->
            # prng.pair_mask_key, the exact derivation the wire plane
            # used before DH.  Must recover nothing.
            key = prng.experiment_key(cfg.run.seed)
            attack_mask = sa.pairwise_mask(
                jax.tree.map(lambda l: jnp.zeros(l.shape, jnp.float32),
                             params),
                key, jnp.asarray(0, jnp.int32),
                jnp.asarray([0, 1], jnp.int32), jnp.asarray(0, jnp.int32),
            )
            attacked = masked_f - flat(attack_mask)
            assert np.abs(attacked - true_f).max() > 0.1

            # PAIR MEMBER's view: worker 1's private key + worker 0's
            # public enrollment record -> the pair key -> exact unmask.
            lookup = BrokerClient(broker.host, broker.port)
            info0 = fetch_device_info(lookup, "0")
            lookup.close()
            secret = keyexchange.shared_secret(
                workers[1]._dh_priv,
                keyexchange.decode_public(info0.pubkey),
            )
            pair_key = keyexchange.pair_prng_key(secret, 0, 1)
            member_mask = sa.pairwise_mask_with_keys(
                jax.tree.map(lambda l: jnp.zeros(l.shape, jnp.float32),
                             params),
                jnp.asarray(pair_key)[None, :],
                jnp.asarray([1.0], jnp.float32),    # sign(1 - 0) from 0's view
                jnp.asarray(0, jnp.int32),
            )
            unmasked = masked_f - flat(member_mask)
            np.testing.assert_allclose(unmasked, true_f, atol=1e-5)
        finally:
            for w in workers:
                w.stop()


def test_dh_ring_masking_cancels():
    # DH pair keys compose with the k-regular random-RING pairing graph
    # (secure_agg_neighbors=2): the ring permutation is public (derived
    # from the shared seed), only the per-pair mask keys are DH secrets.
    # 4 workers, full participation: aggregate must match plain.
    masked, plain = _masked_vs_plain(num_clients=4, neighbors=2)
    np.testing.assert_allclose(masked, plain, atol=2e-4)


def test_dh_peer_restart_refreshes_pubkey():
    # A worker that restarts re-enrolls with a FRESH ephemeral keypair.
    # Peers must pick up the new public key next round (stale cached keys
    # would expand non-cancelling masks and silently corrupt the sum).
    # TWO restarts: leftover queued enrollment records from the first
    # restart must not shadow the second key rotation either.
    import jax

    def restart_same_port(cfg, broker, w):
        # The old listener may linger briefly after stop(); retry bind.
        port = w.port
        w.stop()
        for attempt in range(50):
            try:
                return DeviceWorker(cfg, 1, broker.host, broker.port,
                                    port=port).start()
            except OSError:
                if attempt == 49:
                    raise
                time.sleep(0.1)

    def run(secure):
        import dataclasses

        # Retries OFF: the transport's default retry would heal the
        # restart within the round (reconnect + resend), hiding exactly
        # the drop-then-refresh sequence this test pins down.
        cfg = _config(num_clients=2, secure_agg=secure)
        cfg = cfg.replace(run=dataclasses.replace(cfg.run, comm_retries=0))
        with MessageBroker() as broker:
            w0 = DeviceWorker(cfg, 0, broker.host, broker.port).start()
            w1 = DeviceWorker(cfg, 1, broker.host, broker.port).start()
            try:
                coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                             round_timeout=10.0,
                                             want_evaluator=False)
                coord.enroll(min_devices=2, timeout=20.0)
                coord.run_round()                 # round 0: both healthy
                for _ in range(2):                # two key rotations
                    w1 = restart_same_port(cfg, broker, w1)
                    # Dead socket -> w1 drops, coordinator reconnects...
                    r_drop = coord.run_round()
                    assert "1" in r_drop["dropped"], r_drop
                    # ...and the next round must mask against the FRESH
                    # public key.
                    r_ok = coord.run_round()
                    assert r_ok["completed"] == 2, r_ok
                out = np.concatenate([
                    np.ravel(np.asarray(a))
                    for a in jax.tree.leaves(coord.server_state.params)
                ])
                coord.close()
                return out
            finally:
                w0.stop(); w1.stop()

    masked, plain = run(True), run(False)
    np.testing.assert_allclose(masked, plain, atol=2e-4)


def test_dh_worker_requires_broker():
    with pytest.raises(ValueError, match="broker"):
        DeviceWorker(_config(num_clients=2, secure_agg=True), 0)
    # shared_seed mode explicitly accepts the coordinator-trusted setup.
    w = DeviceWorker(
        _config(num_clients=2, secure_agg=True,
                secure_agg_key_exchange="shared_seed"), 0)
    assert not w._dh_mode


def test_socket_secure_agg_dropout_recovery():
    # One worker dies mid-federation: the unmask round must collect the
    # survivors' orphaned mask halves, leaving a CLEAN aggregate of the
    # survivors (== an unmasked survivors-only run).
    import jax

    cfg = _config(num_clients=3, secure_agg=True)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=8.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=3, timeout=20.0)
            coord.run_round()                 # round 0: everyone healthy
            workers[2].stop()                 # device "2" dies
            rec = coord.run_round()           # round 1: dropout + unmask
            assert "2" in rec["dropped"]
            assert rec["completed"] == 2
            masked = np.concatenate([
                np.ravel(np.asarray(a))
                for a in jax.tree.leaves(coord.server_state.params)
            ])
        finally:
            for w in workers:
                w.stop()

    # Reference: unmasked federation where the same worker NEVER responds
    # in round 1 (survivors-only aggregate).
    cfg_plain = _config(num_clients=3, secure_agg=False)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg_plain, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = FederatedCoordinator(cfg_plain, broker.host, broker.port,
                                         round_timeout=8.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=3, timeout=20.0)
            coord.run_round()
            workers[2].stop()
            coord.run_round()
            plain = np.concatenate([
                np.ravel(np.asarray(a))
                for a in jax.tree.leaves(coord.server_state.params)
            ])
        finally:
            for w in workers:
                w.stop()
    np.testing.assert_allclose(masked, plain, atol=2e-4)


def test_socket_per_client_evaluation():
    # Non-IID partition: the coordinator's wire-plane per-client eval
    # (worker self_eval op) must report a real accuracy spread.
    import dataclasses

    cfg = _config(num_clients=4)
    cfg = cfg.replace(data=dataclasses.replace(
        cfg.data, partition="dirichlet", dirichlet_alpha=0.2))
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(4)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=4, timeout=20.0)
            coord.fit(rounds=3)
            rep = coord.evaluate_per_client()
            assert rep["num_clients_evaluated"] == 4
            assert len(rep["per_client"]) == 4
            assert 0.0 <= rep["acc_p10"] <= rep["acc_p50"] <= rep["acc_p90"] <= 1.0
            assert rep["weighted_acc"] > 0.5       # trained model
        finally:
            for w in workers:
                w.stop()


# ------------------------------------------------------- eviction path ----
def _bare_coordinator(broker, cfg):
    """Coordinator with fabricated membership — unit-tests the failure
    bookkeeping without spinning up workers."""
    from colearn_federated_learning_tpu.comm.enrollment import DeviceInfo

    class _FakeClient:
        closed = False

        def close(self):
            self.closed = True

    coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                 want_evaluator=False)
    devs = [DeviceInfo(device_id=str(i), host="127.0.0.1", port=1)
            for i in range(3)]
    coord.trainers = list(devs)
    coord._clients = {d.device_id: _FakeClient() for d in devs}
    return coord, devs


def test_eviction_counts_accumulate_and_reset_on_success():
    import dataclasses

    cfg = _config(num_clients=3)
    cfg = cfg.replace(run=dataclasses.replace(cfg.run, evict_after=3))
    with MessageBroker() as broker:
        coord, devs = _bare_coordinator(broker, cfg)
        assert coord.evict_after == 3              # from RunConfig
        assert coord._note_round_outcome(devs, ["0", "2"]) == []
        assert coord._fail_counts == {"0": 1, "2": 1}
        assert coord._note_round_outcome(devs, ["0"]) == []
        # Device 2 succeeded: its streak resets; device 0 keeps counting.
        assert coord._fail_counts == {"0": 2}
        assert coord._note_round_outcome(devs, []) == []
        assert coord._fail_counts == {}
        coord.close()


def test_eviction_after_evict_after_consecutive_failures():
    import dataclasses

    cfg = _config(num_clients=3)
    cfg = cfg.replace(run=dataclasses.replace(cfg.run, evict_after=2))
    with MessageBroker() as broker:
        coord, devs = _bare_coordinator(broker, cfg)
        cli0 = coord._clients["0"]
        assert coord._note_round_outcome(devs, ["0"]) == []
        assert coord._note_round_outcome(devs, ["0"]) == ["0"]
        # Evicted: out of the trainer list, connection closed, counter
        # cleared so a re-enrolled device starts a fresh streak.
        assert [t.device_id for t in coord.trainers] == ["1", "2"]
        assert "0" not in coord._clients and cli0.closed
        assert coord._fail_counts == {}
        coord.close()


def test_evict_after_must_be_positive():
    import dataclasses

    cfg = _config(num_clients=3)
    cfg = cfg.replace(run=dataclasses.replace(cfg.run, evict_after=0))
    with MessageBroker() as broker:
        with pytest.raises(ValueError, match="evict_after"):
            FederatedCoordinator(cfg, broker.host, broker.port)


def test_quorum_round_is_noop():
    # All workers stopped mid-run: with min_cohort_fraction the round is
    # an explicit no-op (skipped_quorum, params unchanged), not a
    # zero-survivor aggregate.
    import dataclasses

    import jax

    cfg = _config(num_clients=2, min_cohort_fraction=0.5)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(2)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=2, timeout=20.0)
            rec = coord.run_round()
            assert rec["completed"] == 2 and not rec.get("skipped_quorum")

            for w in workers:
                w.stop()
            coord.round_timeout = 1.5
            before = jax.tree.map(np.asarray, coord.server_state.params)
            rec = coord.run_round()
            assert rec["skipped_quorum"] and rec["completed"] == 0
            assert np.isnan(rec["train_loss"])
            after = jax.tree.map(np.asarray, coord.server_state.params)
            for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
                np.testing.assert_array_equal(a, b)
            coord.close()
        finally:
            for w in workers:
                w.stop()


# ------------------------------------------------------- wire fast path ----
def _run_federation(cfg, n, rounds):
    """Run a fresh broker + n workers + coordinator for ``rounds`` rounds;
    returns (records, final per-round train losses, coordinator params)."""
    import jax

    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(n)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=n, timeout=20.0)
            coord.trainers.sort(key=lambda d: int(d.device_id))
            for w in workers:
                w.await_role(timeout=10.0)
            recs = [coord.run_round() for _ in range(rounds)]
            params = jax.tree.map(np.asarray, coord.server_state.params)
            coord.close()
            return recs, [r["train_loss"] for r in recs], params
        finally:
            for w in workers:
                w.stop()


@pytest.mark.parametrize("cohort", [2, 4])
def test_broadcast_serializes_once_per_round(cohort):
    """Serialize-once: comm.broadcast_encode_total advances by exactly ONE
    per round regardless of cohort size (the replaced path encoded per
    request — ``cohort`` times)."""
    from colearn_federated_learning_tpu import telemetry

    cfg = _config(num_clients=cohort)
    ctr = telemetry.get_registry().counter("comm.broadcast_encode_total")
    before = ctr.value
    recs, _, _ = _run_federation(cfg, cohort, rounds=3)
    assert all(r["completed"] == cohort for r in recs)
    assert ctr.value - before == 3


def test_downlink_int8_tracks_full_params_baseline():
    """A compress_down=int8 federation must land within tolerance of the
    uncompressed run (reconstruction-base error feedback bounds the
    drift), save downlink bytes every post-base round, and never resync
    in a fault-free run."""
    import dataclasses

    from colearn_federated_learning_tpu import telemetry

    reg = telemetry.get_registry()
    cfg = _config(num_clients=3, momentum=0.0, lr=0.05)
    base_recs, base_losses, base_params = _run_federation(cfg, 3, rounds=4)

    cfg_dn = cfg.replace(fed=dataclasses.replace(cfg.fed,
                                                 compress_down="int8"))
    saved = reg.counter("comm.bytes_saved_downlink")
    resync = reg.counter("comm.resync_total")
    saved0, resync0 = saved.value, resync.value
    dn_recs, dn_losses, dn_params = _run_federation(cfg_dn, 3, rounds=4)

    assert all(r["completed"] == 3 for r in base_recs + dn_recs)
    # Round 0 ships the full base; rounds 1-3 each save bytes on all 3
    # sends of the quantized delta frame.
    assert saved.value - saved0 > 0
    assert resync.value - resync0 == 0
    # int8 quantization perturbs each round slightly; the trajectories
    # must stay close, not bitwise equal.
    np.testing.assert_allclose(dn_losses, base_losses, rtol=0.15, atol=0.05)
    import jax

    for a, b in zip(jax.tree.leaves(base_params), jax.tree.leaves(dn_params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=0.25, atol=0.02)


def test_streaming_folder_is_arrival_order_invariant():
    """StreamingFolder staged adds in ANY arrival order finalize to the
    bitwise-identical sums of an UpdateFolder fed in cohort order."""
    import itertools

    import jax

    from colearn_federated_learning_tpu.comm.aggregation import (
        StreamingFolder,
        UpdateFolder,
    )

    rng = np.random.default_rng(0)
    shapes = {"w": np.zeros((5, 3), np.float32), "b": np.zeros((3,),
                                                              np.float32)}
    updates = [
        ({"client_id": str(i), "weight": 1.0 + 0.5 * i,
          "mean_loss": 0.3 * i},
         {"w": rng.normal(size=(5, 3)).astype(np.float32),
          "b": rng.normal(size=(3,)).astype(np.float32)})
        for i in range(3)
    ]
    order = [m["client_id"] for m, _ in updates]

    ref = UpdateFolder(shapes)
    for meta, delta in updates:
        ref.add(meta, delta)
    ref_mean, ref_w, ref_loss = ref.mean()

    for perm in itertools.permutations(updates):
        sf = StreamingFolder(shapes, order=order)
        for meta, delta in perm:
            sf.add(meta, delta)
        sf.finalize()
        assert sf.folded_ids == order
        mean, w, loss = sf.mean()
        assert w == ref_w and loss == ref_loss
        for a, b in zip(jax.tree_util.tree_leaves(mean),
                        jax.tree_util.tree_leaves(ref_mean)):
            np.testing.assert_array_equal(a, b)


def test_streaming_folder_rejects_add_after_finalize():
    from colearn_federated_learning_tpu.comm.aggregation import (
        StreamingFolder,
    )

    sf = StreamingFolder({"w": np.zeros((2,), np.float32)})
    sf.add({"client_id": "0", "weight": 1.0},
           {"w": np.ones((2,), np.float32)})
    sf.finalize()
    sf.finalize()                       # idempotent
    with pytest.raises(RuntimeError):
        sf.add({"client_id": "1"}, {"w": np.ones((2,), np.float32)})


# --------------------------------------------------------- crash resume ----
def test_coordinator_wal_resume_discards_uncommitted_round(tmp_path):
    """The coordinator crash window: a WAL entry whose checkpoint never
    landed marks an uncommitted round — resume discards it (counted),
    restores the last committed state, and re-runs the round."""
    import dataclasses

    from colearn_federated_learning_tpu import telemetry
    from colearn_federated_learning_tpu.ckpt import RoundWal

    cfg = _config(num_clients=2, rounds=4)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1))
    reg = telemetry.get_registry()
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(2)]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=2, timeout=20.0)
            coord.trainers.sort(key=lambda d: int(d.device_id))
            coord.fit(rounds=2)
            coord.close()

            # Simulate the kill landing between WAL append and state
            # save: round 2 is logged but never committed.
            wal = RoundWal(cfg.run.checkpoint_dir)
            wal.append({"round": 2, "accepted": [0, 1], "completed": 2,
                        "total_weight": 0.0})
            wal.close()

            resumed0 = reg.counter("fed.rounds_resumed_total").value
            disc0 = reg.counter(
                "ckpt.wal_uncommitted_discarded_total").value
            coord2 = FederatedCoordinator(cfg, broker.host, broker.port,
                                          round_timeout=60.0,
                                          want_evaluator=False)
            coord2.enroll(min_devices=2, timeout=20.0)
            coord2.trainers.sort(key=lambda d: int(d.device_id))
            step = coord2.restore_checkpoint()
            assert step == 2 and len(coord2.history) == 2
            assert reg.counter("fed.rounds_resumed_total").value \
                == resumed0 + 1
            assert reg.counter(
                "ckpt.wal_uncommitted_discarded_total").value == disc0 + 1

            # The discarded round is RE-RUN, not lost: the log converges
            # back to one committed entry per round.
            coord2.fit(rounds=1)
            entries = RoundWal(cfg.run.checkpoint_dir).load()
            assert [e["round"] for e in entries] == [0, 1, 2]
            assert sorted(entries[-1]["accepted"]) == [0, 1]
            coord2.close()
        finally:
            for w in workers:
                w.stop()
