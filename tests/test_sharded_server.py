"""PR 9 sharded server: the coordinator's fold/update/encode plane lives
sharded on a ``(model,)`` mesh and stays BITWISE identical to the
replicated plane it replaced.

- StreamingFolder with a ServerPlacement: shard-wise staging/summing is
  bitwise equal to the full-leaf fold — full participation, partial
  cohort, and the secure-agg correction path.
- DownlinkEncoder fed a sharded tree emits byte-for-byte the frame the
  gathered tree produces (scheme "none" AND the int8-delta scheme), and
  counts the gather bytes it avoided.
- make_server_placement / from_config degrade observably via labeled
  ``fed.mesh_fallback_total`` counters.
- End-to-end: a tp_size=2 socket federation reproduces the replicated
  federation's final params bit-for-bit.
"""

import random

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from colearn_federated_learning_tpu.comm.aggregation import StreamingFolder
from colearn_federated_learning_tpu.comm.broker import MessageBroker
from colearn_federated_learning_tpu.comm.coordinator import FederatedCoordinator
from colearn_federated_learning_tpu.comm.downlink import DownlinkEncoder
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.parallel import partition
from colearn_federated_learning_tpu.telemetry import registry as telemetry_reg
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _params():
    rng = np.random.default_rng(7)
    f = lambda *s: rng.standard_normal(s).astype(np.float32)
    return {
        "params": {
            "Embed_0": {"embedding": f(16, 8)},
            "TransformerBlock_0": {
                "attn": {"query": {"kernel": f(8, 4, 2), "bias": f(4, 2)},
                         "out": {"kernel": f(4, 2, 8)}},
                "Dense_0": {"kernel": f(8, 32), "bias": f(32)},
                "Dense_1": {"kernel": f(32, 8)},
                "LayerNorm_0": {"scale": f(8)},
            },
        }
    }


@pytest.fixture(scope="module")
def placement():
    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("needs the forced 8-device CPU host")
    pl = partition.make_server_placement(
        _params(), 4, "model", "bert", devices=devs[:4])
    assert pl is not None
    return pl


def _tree_bytes(tree):
    return [np.asarray(l).tobytes() for l in jax.tree.leaves(tree)]


def _deltas(n, scale=1.0):
    out = []
    for i in range(n):
        rng = np.random.default_rng(100 + i)
        d = jax.tree.map(
            lambda w: (rng.standard_normal(w.shape) * scale)
            .astype(np.float32), _params())
        out.append(({"client_id": str(i), "weight": 1.0 + 0.25 * i,
                     "mean_loss": 0.5 + 0.1 * i}, d))
    return out


# ------------------------------------------------------------ fold parity --
@pytest.mark.parametrize("present", [5, 3])  # full cohort / partial cohort
def test_sharded_fold_bitwise_parity(placement, present):
    shapes = placement.shapes_tree()
    order = [str(i) for i in range(5)]
    updates = _deltas(5)[:present]
    arrival = list(updates)
    random.Random(13).shuffle(arrival)     # fold must not care

    rep = StreamingFolder(shapes, order=order)
    shd = StreamingFolder(shapes, order=order, placement=placement)
    for meta, d in arrival:
        rep.add(dict(meta), jax.tree.map(np.copy, d))
        shd.add(dict(meta), jax.tree.map(np.copy, d))

    m_rep, w_rep, l_rep = rep.mean()
    m_shd, w_shd, l_shd = shd.mean()
    assert w_rep == w_shd and l_rep == l_shd
    # The sharded mean is a tree of sharded jax.Arrays; per-shard host
    # reads must reproduce the replicated fold EXACTLY (bitwise).
    assert _tree_bytes(m_rep) == _tree_bytes(partition.host_tree(m_shd))
    for leaf in jax.tree.leaves(m_shd):
        assert isinstance(leaf, jax.Array)


def test_sharded_correction_bitwise_parity(placement):
    shapes = placement.shapes_tree()
    order = [str(i) for i in range(4)]
    corr = jax.tree.map(
        lambda w: np.full(w.shape, 0.125, np.float32), _params())

    rep = StreamingFolder(shapes, order=order)
    shd = StreamingFolder(shapes, order=order, placement=placement)
    for meta, d in _deltas(4):
        rep.add(dict(meta), d)
        shd.add(dict(meta), d)
    rep.finalize(); shd.finalize()
    rep.apply_correction(corr)
    shd.apply_correction(corr)
    m_rep, _, _ = rep.mean()
    m_shd, _, _ = shd.mean()
    assert _tree_bytes(m_rep) == _tree_bytes(partition.host_tree(m_shd))


# ------------------------------------------------------ downlink identity --
def test_sharded_downlink_byte_identity_and_counter(placement):
    params = _params()
    sharded = placement.shard(params)
    avoided = partition.tree_gather_avoided(sharded)
    assert avoided > 0

    body_rep, _, _ = DownlinkEncoder("none").encode_round(2, params)
    reg = telemetry_reg.get_registry()
    before = reg.counter("comm.gather_bytes_avoided_total").value
    body_shd, _, _ = DownlinkEncoder("none").encode_round(2, sharded)
    assert bytes(body_rep) == bytes(body_shd)
    after = reg.counter("comm.gather_bytes_avoided_total").value
    assert after - before == avoided


def test_sharded_downlink_delta_scheme_byte_identity(placement):
    # int8-delta scheme across two rounds: full frame then delta frame,
    # both byte-identical between the gathered and sharded encoders.
    params0, params1 = _params(), jax.tree.map(
        lambda w: w + np.float32(0.01), _params())
    enc_rep, enc_shd = DownlinkEncoder("int8"), DownlinkEncoder("int8")
    for r, p in ((0, params0), (1, params1)):
        body_rep, _, _ = enc_rep.encode_round(r, p)
        body_shd, _, _ = enc_shd.encode_round(r, placement.shard(p))
        assert bytes(body_rep) == bytes(body_shd)


# ---------------------------------------------------- fallback observability --
def test_make_server_placement_fallback_counters():
    reg = telemetry_reg.get_registry()
    devs = jax.devices("cpu")

    assert partition.make_server_placement(_params(), 1, "model",
                                           "bert") is None

    name = "fed.mesh_fallback_total{reason=insufficient_devices}"
    before = reg.snapshot().get(name, 0)
    assert partition.make_server_placement(
        _params(), len(devs) + 1, "model", "bert") is None
    assert reg.snapshot()[name] == before + 1

    # Rules that shard nothing of this tree (odd sizes → replicated):
    name = "fed.mesh_fallback_total{reason=rules_matched_nothing}"
    before = reg.snapshot().get(name, 0)
    assert partition.make_server_placement(
        {"w": np.ones((5,), np.float32)}, 2, "model", "mlp",
        devices=devs[:2]) is None
    assert reg.snapshot()[name] == before + 1


def test_from_config_indivisible_counter():
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner

    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=4,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=2),
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=0,
                      local_steps=1, batch_size=8, lr=0.1, momentum=0.9),
        run=RunConfig(name="indivisible", backend="cpu", tp_size=3),
    )
    name = "fed.mesh_fallback_total{reason=indivisible_devices}"
    reg = telemetry_reg.get_registry()
    before = reg.snapshot().get(name, 0)
    with pytest.warns(UserWarning, match="tp_size=3"):
        learner = FederatedLearner.from_config(cfg)
    assert reg.snapshot()[name] == before + 1
    assert learner.tp_size == 1      # degraded to data parallelism only


# ------------------------------------------------------------- end to end --
def _fed_config(tp_size):
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=4,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=2),
        fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=0,
                      local_steps=2, batch_size=8, lr=0.1, momentum=0.9),
        run=RunConfig(name=f"shard_tp{tp_size}", backend="cpu",
                      tp_size=tp_size),
    )


def _run_federation(tp_size):
    cfg = _fed_config(tp_size)
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(4)]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=4, timeout=20.0)
            hist = coord.fit(rounds=2)
            assert all(r["completed"] == 4 for r in hist)
            host = partition.host_tree(coord.server_state.params)
            sharded = any(
                isinstance(l, jax.Array)
                and len({partition._index_key(s.index)
                         for s in l.addressable_shards}) > 1
                for l in jax.tree.leaves(coord.server_state.params))
            coord.close()
            return host, sharded
        finally:
            for w in workers:
                w.stop()


def test_coordinator_sharded_end_to_end_parity():
    if len(jax.devices("cpu")) < 2:
        pytest.skip("needs the forced 8-device CPU host")
    reg = telemetry_reg.get_registry()
    before = reg.counter("comm.gather_bytes_avoided_total").value
    p_rep, rep_sharded = _run_federation(1)
    assert not rep_sharded
    p_shd, shd_sharded = _run_federation(2)
    assert shd_sharded                 # the global model truly lives sharded
    # Same seed, same workers, byte-identical downlinks, bitwise fold and
    # eager elementwise server update → the two federations agree on
    # every bit of the final global model.
    assert _tree_bytes(p_rep) == _tree_bytes(p_shd)
    # The sharded run's downlink never gathered: counter moved, gauge set.
    assert reg.counter("comm.gather_bytes_avoided_total").value > before
    assert (reg.gauge("comm.server_bytes_per_chip").value or 0) > 0
