"""Numerical parity of the jit-compiled local SGD with torch.optim.SGD
(SURVEY.md §7 hard part #4: "optimizer parity with the reference's PyTorch
SGD").  Same init, same data, same batch schedule, same lr/momentum — the
optax trajectory must track the torch trajectory to float32 round-off."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from colearn_federated_learning_tpu.fed import local as local_lib  # noqa: E402
from colearn_federated_learning_tpu.models import registry as model_registry  # noqa: E402
from colearn_federated_learning_tpu.utils.config import ModelConfig  # noqa: E402

STEPS = 20
BATCH = 16
LR = 0.05
HIDDEN = 32
DEPTH = 2
N = 64  # shard size


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(N, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(N,)).astype(np.int32)
    return x, y


def _batch_schedule(key, count):
    """The EXACT per-step index draw make_local_update performs."""
    idx = []
    for t in range(STEPS):
        k = jax.random.fold_in(key, t)
        idx.append(np.asarray(jax.random.randint(k, (BATCH,), 0, count)))
    return idx


def _torch_mlp_from_flax(params):
    """Torch twin of models/mlp.py with the flax init COPIED in (flax Dense
    kernels are (in, out); torch Linear weights are (out, in))."""
    layers = []
    dims = [28 * 28] + [HIDDEN] * DEPTH + [10]
    for i in range(DEPTH + 1):
        lin = tnn.Linear(dims[i], dims[i + 1])
        p = params[f"Dense_{i}"]
        with torch.no_grad():
            lin.weight.copy_(torch.from_numpy(np.asarray(p["kernel"]).T))
            lin.bias.copy_(torch.from_numpy(np.asarray(p["bias"])))
        layers.append(lin)
        if i < DEPTH:
            layers.append(tnn.ReLU())
    return tnn.Sequential(*layers)


@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_local_sgd_matches_torch(momentum):
    x, y = _data()
    model = model_registry.build_model(
        ModelConfig(name="mlp", num_classes=10, hidden_dim=HIDDEN, depth=DEPTH)
    )
    key = jax.random.PRNGKey(0)
    params = model_registry.init_params(model, jnp.asarray(x[:BATCH]), key)

    # ---- optax path: the real jit-compiled local round ------------------
    opt = local_lib.make_optimizer(LR, momentum)
    update = local_lib.make_local_update(
        model.apply, opt, num_steps=STEPS, batch_size=BATCH,
    )
    data_key = jax.random.PRNGKey(42)
    result = jax.jit(update)(
        params, jnp.asarray(x), jnp.asarray(y),
        jnp.asarray(N, jnp.int32), data_key,
        jnp.asarray(STEPS, jnp.int32),
    )
    ours = jax.tree.map(lambda p, d: np.asarray(p + d), params, result.delta)

    # ---- torch path: identical schedule, torch.optim.SGD ----------------
    tmodel = _torch_mlp_from_flax(params)
    topt = torch.optim.SGD(tmodel.parameters(), lr=LR, momentum=momentum)
    loss_fn = tnn.CrossEntropyLoss()
    losses_t = []
    for idx in _batch_schedule(data_key, N):
        xb = torch.from_numpy(x[idx].reshape(BATCH, -1))
        yb = torch.from_numpy(y[idx].astype(np.int64))
        topt.zero_grad()
        loss = loss_fn(tmodel(xb), yb)
        loss.backward()
        topt.step()
        losses_t.append(loss.item())

    # ---- trajectories agree ---------------------------------------------
    # mean over executed steps matches the torch per-step loss mean
    np.testing.assert_allclose(
        float(result.mean_loss), np.mean(losses_t), rtol=1e-5, atol=1e-6
    )
    lins = [m for m in tmodel if isinstance(m, tnn.Linear)]
    for i, lin in enumerate(lins):
        ref_w = lin.weight.detach().numpy().T
        ref_b = lin.bias.detach().numpy()
        got = ours[f"Dense_{i}"]
        np.testing.assert_allclose(got["kernel"], ref_w, rtol=1e-4, atol=2e-5)
        np.testing.assert_allclose(got["bias"], ref_b, rtol=1e-4, atol=2e-5)
