"""utils/serialization.py: bfloat16 survives both formats, and
``wire_frame_length`` predicts the exact CLW1 frame size.

The bf16 pitfall: ml_dtypes extension dtypes stringify as raw void bytes
(``'<V2'``), so a dtype-``str`` round trip silently reinterprets the
payload.  Both the CLW1 ``"n"`` slot and the npz ``__dtypes__`` sidecar
exist to carry the dtype NAME instead — these tests pin that contract.
"""

import io
import json
import struct

import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.utils import serialization


def _bf16_tree():
    bf16 = jnp.bfloat16
    return {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4).astype(bf16),
        "b": np.array([-1.5, 0.25, 3.0], dtype=bf16),
        "scale": np.array(0.125, dtype=bf16),          # 0-d leaf
        "step": np.array(7, dtype=np.int32),
        "f32": np.linspace(-1, 1, 5, dtype=np.float32),
    }


def _assert_bitwise(a, b):
    a, b = np.asarray(a), np.asarray(b)
    assert a.dtype == b.dtype
    np.testing.assert_array_equal(
        np.ascontiguousarray(a).reshape(-1).view(np.uint8),
        np.ascontiguousarray(b).reshape(-1).view(np.uint8))


def test_wire_roundtrip_preserves_bf16():
    tree = _bf16_tree()
    out, meta = serialization.bytes_to_pytree(
        serialization.pytree_to_bytes(tree, {"round": 3}))
    assert meta == {"round": 3}
    assert out["w"].dtype == jnp.bfloat16
    assert out["w"].shape == (3, 4)
    _assert_bitwise(out["w"], tree["w"])
    _assert_bitwise(out["b"], tree["b"])
    _assert_bitwise(out["step"], tree["step"])
    _assert_bitwise(out["f32"], tree["f32"])
    # Known wire-layout quirk: CLW1 promotes 0-d leaves to (1,) (the
    # encoder's ascontiguousarray).  Value and dtype still round-trip.
    assert out["scale"].shape == (1,)
    assert out["scale"].dtype == jnp.bfloat16
    assert float(out["scale"][0]) == 0.125


def test_wire_header_names_extension_dtypes_only():
    data = serialization.pytree_to_bytes(_bf16_tree())
    (hlen,) = struct.unpack_from(">I", data, 4)
    header = json.loads(bytes(data[8:8 + hlen]).decode())
    by_path = {e["p"]: e for e in header["leaves"]}
    # bf16 leaves carry the dtype-name slot; builtin dtypes must not
    # (the slot exists only because '<V2' is ambiguous).
    assert by_path["w"]["n"] == "bfloat16" and by_path["w"]["d"] == "<V2"
    assert "n" not in by_path["step"]
    assert "n" not in by_path["f32"]


def test_npz_roundtrip_preserves_bf16_and_0d_shape():
    tree = _bf16_tree()
    buf = io.BytesIO()
    serialization.save_pytree_npz(buf, tree, {"tag": "ckpt"})
    buf.seek(0)
    out, meta = serialization.load_pytree_npz(buf)
    assert meta == {"tag": "ckpt"}
    _assert_bitwise(out["w"], tree["w"])
    _assert_bitwise(out["b"], tree["b"])
    # The npz sidecar records the true shape, so 0-d survives exactly.
    assert out["scale"].shape == ()
    assert out["scale"].dtype == jnp.bfloat16
    assert float(out["scale"]) == 0.125


def test_bytes_to_pytree_autodetects_npz_with_bf16():
    buf = io.BytesIO()
    serialization.save_pytree_npz(buf, _bf16_tree())
    out, _ = serialization.bytes_to_pytree(buf.getvalue())
    _assert_bitwise(out["w"], _bf16_tree()["w"])


def test_wire_frame_length_matches_encoder():
    for tree, meta in [
        (_bf16_tree(), None),
        (_bf16_tree(), {"round": 12, "down": "full"}),
        ({"a": np.zeros((8, 8), np.float32)}, {"round": 0}),
        ({"empty": np.zeros((0,), np.float32),
          "zero_d": np.float64(2.5)}, None),
    ]:
        predicted = serialization.wire_frame_length(tree, meta)
        actual = len(serialization.pytree_to_bytes(tree, meta))
        assert predicted == actual
