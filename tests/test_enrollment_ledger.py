"""Durable enrollment (ckpt/wal.EnrollmentLedger) and challenge-on-resume
(coordinator.verify_resumed_devices): the WAL-backed admission record a
resumed coordinator trusts instead of replayable broker announcements,
and the nonce-echo proof of key possession that gates readmission."""

import json
import os

import pytest

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.ckpt import EnrollmentLedger
from colearn_federated_learning_tpu.comm import enrollment, keyexchange
from colearn_federated_learning_tpu.comm.broker import (
    BrokerClient,
    MessageBroker,
)
from colearn_federated_learning_tpu.comm.coordinator import FederatedCoordinator
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


class _Dev:
    def __init__(self, device_id, host="127.0.0.1", port=1, pubkey=""):
        self.device_id, self.host, self.port = device_id, host, port
        self.pubkey = pubkey


def _rejections(reason):
    return telemetry.get_registry().counter(
        "comm.enroll_challenge_rejected_total",
        labels={"reason": reason}).value


# ------------------------------------------------------------- ledger ----
def test_ledger_appends_durably_and_latest_wins(tmp_path):
    led = EnrollmentLedger(str(tmp_path))
    led.admit(_Dev("0", port=7001, pubkey="aa"))
    led.admit(_Dev("1", port=7002, pubkey="bb"))
    led.admit(_Dev("0", port=7009, pubkey="cc"))    # key rotation
    led.close()

    fresh = EnrollmentLedger(str(tmp_path))         # reopen: survives
    devs = fresh.devices()
    assert set(devs) == {"0", "1"}
    assert devs["0"]["port"] == 7009 and devs["0"]["pubkey"] == "cc"
    assert devs["1"]["pubkey"] == "bb"


def test_ledger_tolerates_torn_tail(tmp_path):
    led = EnrollmentLedger(str(tmp_path))
    led.admit(_Dev("0", pubkey="aa"))
    led.close()
    with open(led.path, "a", encoding="utf-8") as f:
        f.write('{"device_id": "1", "pubk')       # append died mid-line
    devs = EnrollmentLedger(str(tmp_path)).devices()
    assert set(devs) == {"0"}


# -------------------------------------------------- challenge-on-resume ----
def _config(num_clients, ckpt_dir):
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=2, local_steps=2,
                      batch_size=16, lr=0.1),
        run=RunConfig(name="ledger_test", backend="cpu",
                      checkpoint_dir=ckpt_dir),
    )


def _enroll_coordinator(cfg, broker, n):
    coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                 round_timeout=20.0)
    coord.enroll(min_devices=n, timeout=20.0)
    return coord


def test_resume_readmits_only_ledger_verified_devices(tmp_path):
    """First enrollment writes the ledger; a resumed coordinator readmits
    the recorded devices after they answer the nonce challenge — and
    rejects a device whose announcement replayed (or was forged) but was
    never admitted to the ledger."""
    cfg = _config(3, str(tmp_path))
    with MessageBroker() as broker:
        first = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                 for i in range(2)]
        late = None
        try:
            coord = _enroll_coordinator(cfg, broker, 2)
            coord.close()
            assert set(EnrollmentLedger(str(tmp_path)).devices()) == \
                {"0", "1"}

            # A third device announces AFTER the crash: its retained
            # record replays into the resumed coordinator's enrollment,
            # but no ledger line vouches for it.
            late = DeviceWorker(cfg, 2, broker.host, broker.port).start()
            base = _rejections("not_in_ledger")
            resumed = _enroll_coordinator(cfg, broker, 3)
            out = resumed.verify_resumed_devices()
            assert sorted(out["verified"]) == ["0", "1"]
            assert out["rejected"] == ["2"]
            assert _rejections("not_in_ledger") == base + 1
            survivors = {t.device_id for t in resumed.trainers} | (
                {resumed.evaluator.device_id} if resumed.evaluator else set())
            assert "2" not in survivors
            resumed.close()
            # The rejection is durable: the replay-recorded admission was
            # revoked, so the impostor cannot pass a FUTURE resume on it.
            assert "2" not in EnrollmentLedger(str(tmp_path)).devices()
        finally:
            for w in first + ([late] if late else []):
                w.stop()


def test_resume_rejects_forged_and_undecodable_ledger_keys(tmp_path):
    """A device that cannot echo the nonce under the LEDGER's pubkey is an
    impostor (bad_tag); an undecodable recorded key rejects too
    (bad_ledger_key).  Neither is readmitted."""
    cfg = _config(2, str(tmp_path))
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(2)]
        try:
            coord = _enroll_coordinator(cfg, broker, 2)
            coord.close()

            # Tamper the ledger: bind device 0 to a key it does not hold,
            # and device 1 to garbage.
            led = EnrollmentLedger(str(tmp_path))
            devs = led.devices()
            _, wrong_pub = keyexchange.generate_keypair()
            e0 = dict(devs["0"], pubkey=keyexchange.encode_public(wrong_pub))
            e1 = dict(devs["1"], pubkey="not-hex-not-a-key")
            with open(led.path, "w", encoding="utf-8") as f:
                f.write(json.dumps(e0) + "\n" + json.dumps(e1) + "\n")

            base_tag = _rejections("bad_tag")
            base_key = _rejections("bad_ledger_key")
            resumed = _enroll_coordinator(cfg, broker, 2)
            out = resumed.verify_resumed_devices()
            assert out["verified"] == []
            assert sorted(out["rejected"]) == ["0", "1"]
            assert _rejections("bad_tag") == base_tag + 1
            assert _rejections("bad_ledger_key") == base_key + 1
            assert resumed.trainers == [] and resumed.evaluator is None
            resumed.close()
        finally:
            for w in workers:
                w.stop()


def test_preledger_entry_admits_on_presence_alone(tmp_path):
    """Documented trust step-down: a ledger line without a pubkey (written
    by a pre-identity build) readmits on ledger presence, no challenge."""
    cfg = _config(2, str(tmp_path))
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(2)]
        try:
            coord = _enroll_coordinator(cfg, broker, 2)
            coord.close()
            led = EnrollmentLedger(str(tmp_path))
            entries = [dict(e, pubkey="") for e in led.devices().values()]
            with open(led.path, "w", encoding="utf-8") as f:
                for e in entries:
                    f.write(json.dumps(e) + "\n")

            resumed = _enroll_coordinator(cfg, broker, 2)
            out = resumed.verify_resumed_devices()
            assert sorted(out["verified"]) == ["0", "1"]
            assert out["rejected"] == []
            resumed.close()
        finally:
            for w in workers:
                w.stop()


# ------------------------------------------------- announce supersession ----
def test_reannounce_supersedes_stale_retained_record(tmp_path):
    """A stale retained announcement (dead address, left over from before
    a device restart) is superseded by the live re-announce — enrollment
    connects to the CURRENT address, latest record wins."""
    cfg = _config(1, str(tmp_path))
    with MessageBroker() as broker:
        stale = BrokerClient(broker.host, broker.port)
        enrollment.announce(stale, enrollment.DeviceInfo(
            device_id="0", host="127.0.0.1", port=9))   # nothing listens
        stale.close()

        worker = DeviceWorker(cfg, 0, broker.host, broker.port).start()
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=20.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=1, timeout=20.0)
            assert [t.port for t in coord.trainers] == [worker.port]
            # And the ledger recorded the live binding, not the stale one.
            assert EnrollmentLedger(
                str(tmp_path)).devices()["0"]["port"] == worker.port
            coord.close()
        finally:
            worker.stop()
