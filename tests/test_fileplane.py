"""File/hierarchical-plane faults (faults/fileplane.py): hop-keyed
specs, the atomic exchange writes they prey on, the offline aggregator's
skip-and-log quorum semantics, and drop_silo coverage for HierFAVG."""

import dataclasses
import glob
import os

import numpy as np
import pytest

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.faults import (
    FaultPlan,
    FaultSpec,
    fileplane,
    inject,
)
from tests.test_engine import tiny_config


def _counter(name):
    return telemetry.get_registry().counter(name).value


@pytest.fixture
def clean_interposer():
    yield
    inject.uninstall()


# ------------------------------------------------------------- keying ----
def test_spec_hop_keys_one_exchange_leg():
    spec = FaultSpec(kind="drop_silo", device_id="g1", round=2, hop="sync")
    assert spec.matches("g1", 2, "sync", hop="sync")
    assert not spec.matches("g1", 2, "seed", hop="seed")
    assert not spec.matches("g0", 2, "sync", hop="sync")
    # No hop on the spec → any hop matches (comm-plane specs unchanged).
    wild = FaultSpec(kind="drop_silo", device_id="g1", round=2)
    assert wild.matches("g1", 2, "sync", hop="sync")
    assert wild.matches("g1", 2, "seed", hop="seed")


def test_hop_plan_json_roundtrip_and_determinism():
    def plan():
        return FaultPlan([FaultSpec(kind="truncate_file", device_id="s0",
                                    hop="update", probability=0.5,
                                    count=0)], seed=3)
    p = FaultPlan.from_json(plan().to_json())
    assert p.faults[0].hop == "update"
    fires = [
        tuple(bool(q.match("s0", r, "update", kinds=("truncate_file",),
                           hop="update")) for r in range(16))
        for q in (plan(), plan())
    ]
    assert fires[0] == fires[1]              # seeded gate, not a dice roll
    assert any(fires[0]) and not all(fires[0])


def test_hopless_match_key_is_preserved():
    # The probability hash key only grows the hop segment when a hop is
    # given — a pre-hop comm-plane schedule replays bit-identically.
    def fires(hop_kw):
        p = FaultPlan([FaultSpec(kind="drop_request", probability=0.5,
                                 count=0)], seed=5)
        return tuple(bool(p.match(str(d), r, "train", **hop_kw))
                     for d in range(4) for r in range(8))
    assert fires({}) == fires({"hop": fileplane.ANY})


# ---------------------------------------------------------- hooks ----
def test_hooks_are_noops_without_a_plan(tmp_path):
    inject.uninstall()
    meta = {"round": 3}
    assert not fileplane.should_drop("0", 1)
    assert fileplane.stale_meta(meta, "0", 1) is meta
    assert not fileplane.maybe_truncate(str(tmp_path / "missing.npz"),
                                        "0", 1)


def test_hooks_fire_and_count_by_device_and_kind(tmp_path, clean_interposer):
    inject.install(FaultPlan([
        FaultSpec(kind="drop_silo", device_id="2", round=1, hop="update"),
        FaultSpec(kind="stale_round", device_id="2", round=1, hop="update"),
        FaultSpec(kind="truncate_file", device_id="2", round=1,
                  hop="update"),
    ], seed=0))
    before = _counter("fault.injected_total{device=2,kind=drop_silo}")

    assert not fileplane.should_drop("2", 0)           # wrong round
    assert fileplane.should_drop("2", 1)
    assert not fileplane.should_drop("2", 1)           # budget spent
    assert _counter("fault.injected_total{device=2,kind=drop_silo}") \
        == before + 1

    stamped = fileplane.stale_meta({"round": 1, "weight": 2.0}, "2", 1)
    assert stamped["round"] == 0 and stamped["weight"] == 2.0

    p = tmp_path / "u.npz"
    p.write_bytes(b"x" * 100)
    assert fileplane.maybe_truncate(str(p), "2", 1)
    assert p.stat().st_size == 50


# --------------------------------------------------- offline plane ----
def test_client_update_drop_silo_publishes_nothing(tmp_path,
                                                   clean_interposer):
    from colearn_federated_learning_tpu.fed import offline

    cfg = tiny_config()
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)
    inject.install(FaultPlan([
        FaultSpec(kind="drop_silo", device_id="0", round=0, hop="update"),
    ]))
    out = str(tmp_path / "u0.npz")
    stats = offline.client_update(cfg, 0, g0, out)
    assert stats["dropped"] and stats["weight"] == 0.0
    assert not os.path.exists(out)


def test_offline_round_survives_torn_and_stale_updates(tmp_path,
                                                       clean_interposer):
    """The acceptance soak: one silo's file is torn mid-write, another
    replays an old round stamp — the aggregator skips both (counted, with
    reasons), commits on the surviving quorum, and the output model is a
    readable, scoreable npz.  Zero torn-file crashes."""
    from colearn_federated_learning_tpu.fed import offline

    cfg = tiny_config(min_cohort_fraction=0.5)
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)

    inject.install(FaultPlan([
        FaultSpec(kind="truncate_file", device_id="1", round=0,
                  hop="update"),
        FaultSpec(kind="stale_round", device_id="2", round=0, hop="update"),
    ], seed=0))
    updates = []
    for cid in range(4):
        out = str(tmp_path / f"u{cid}.npz")
        offline.client_update(cfg, cid, g0, out)
        updates.append(out)
    inject.uninstall()

    torn0 = _counter("fed.offline_updates_rejected_total{reason=torn}")
    stale0 = _counter("fed.offline_updates_rejected_total{reason=stale}")
    g1 = str(tmp_path / "g1.npz")
    agg = offline.aggregate_updates(cfg, g0, updates, g1)
    assert agg["num_updates"] == 2 and agg["num_rejected"] == 2
    assert len(agg["rejected"]) == 2
    assert any("stale update" in r for r in agg["rejected"])
    assert _counter(
        "fed.offline_updates_rejected_total{reason=torn}") == torn0 + 1
    assert _counter(
        "fed.offline_updates_rejected_total{reason=stale}") == stale0 + 1
    # The committed model is whole: evaluable, and no temp files leaked.
    rec = offline.evaluate_global(cfg, g1)
    assert rec["round"] == 1 and np.isfinite(rec["eval_loss"])
    assert glob.glob(str(tmp_path / ".tmp-*")) == []


def test_aggregate_raises_below_quorum_with_reasons(tmp_path,
                                                    clean_interposer):
    from colearn_federated_learning_tpu.fed import offline

    cfg = tiny_config(min_cohort_fraction=1.0)
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)
    inject.install(FaultPlan([
        FaultSpec(kind="truncate_file", device_id="0", round=0,
                  hop="update"),
    ]))
    u0, u1 = str(tmp_path / "u0.npz"), str(tmp_path / "u1.npz")
    offline.client_update(cfg, 0, g0, u0)
    offline.client_update(cfg, 1, g0, u1)
    inject.uninstall()
    with pytest.raises(ValueError, match=r"1/2 updates usable \(quorum 2\)"):
        offline.aggregate_updates(cfg, g0, [u0, u1],
                                  str(tmp_path / "g1.npz"))


def test_atomic_write_never_leaves_partials_on_error(tmp_path):
    from colearn_federated_learning_tpu.utils.serialization import (
        atomic_save_pytree_npz,
    )

    path = str(tmp_path / "m.npz")
    with pytest.raises(TypeError):
        atomic_save_pytree_npz(path, {"layers": [np.zeros(3)]})
    assert os.listdir(tmp_path) == []      # neither target nor temp file


# ----------------------------------------------- hierarchical plane ----
def _hier(**kw):
    from tests.test_hierarchical import _cfg

    from colearn_federated_learning_tpu.fed.hierarchical import (
        HierarchicalLearner,
    )

    return HierarchicalLearner(_cfg(), num_groups=2, sync_period=2, **kw)


def _flat(tree):
    import jax

    return np.concatenate([np.ravel(np.asarray(a))
                           for a in jax.tree.leaves(tree)])


def test_hier_drop_silo_on_sync_renormalizes(clean_interposer):
    h = _hier()
    inject.install(FaultPlan([
        FaultSpec(kind="drop_silo", device_id="g1", round=1, hop="sync"),
    ]))
    before = _counter("fed.hier_groups_dropped_total{group=g1}")
    hist = h.fit(rounds=2)
    assert hist[0].get("groups_dropped") is None
    assert hist[1]["groups_dropped"] == ["g1"]
    assert _counter("fed.hier_groups_dropped_total{group=g1}") == before + 1
    # Sole survivor: the cloud model IS group 0's model, and the re-seed
    # pushed it back into both groups.
    a = _flat(h.groups[0].server_state.params)
    np.testing.assert_array_equal(a, _flat(h.global_params))
    np.testing.assert_array_equal(a, _flat(h.groups[1].server_state.params))


def test_hier_drop_silo_on_seed_leaves_group_stale(clean_interposer):
    h = _hier()
    inject.install(FaultPlan([
        FaultSpec(kind="drop_silo", device_id="g0", round=1, hop="seed"),
    ]))
    hist = h.fit(rounds=2)
    # The sync itself succeeded — no uplink was dropped...
    assert "groups_dropped" not in hist[1]
    # ...but g0 never received the cloud model back, while g1 did.
    cloud = _flat(h.global_params)
    np.testing.assert_array_equal(cloud,
                                  _flat(h.groups[1].server_state.params))
    assert np.abs(cloud - _flat(h.groups[0].server_state.params)).max() > 0


def test_hier_round_records_unchanged_without_plan():
    inject.uninstall()
    h = _hier()
    hist = h.fit(rounds=2)
    assert all("groups_dropped" not in r for r in hist)
