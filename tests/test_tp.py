"""Tensor parallelism (parallel/tp.py): partition rules + engine integration.

The reference has no model parallelism (SURVEY.md §2: TP/PP/SP/EP absent);
these tests cover the rebuild's TP superset: GSPMD-auto ``model`` axis
composed with the manual ``clients`` shard_map axis, numerically equivalent
to the single-device vmap path.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.parallel import tp as tp_lib
from colearn_federated_learning_tpu.parallel.mesh import make_mesh
from colearn_federated_learning_tpu.utils.jax_compat import (
    HAS_NATIVE_SHARD_MAP,
)
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


# Running a round with a GSPMD-auto ``model`` axis (auto != {}) aborts the
# interpreter at the C++ level under jax<0.6 experimental shard_map on the
# CPU backend; spec/build-only tests are unaffected.
requires_native_shard_map = pytest.mark.skipif(
    not HAS_NATIVE_SHARD_MAP,
    reason="partial-manual shard_map (auto model axis) aborts under jax<0.6",
)


def _bert_cfg(**fed_kw):
    fed = dict(strategy="fedavg", rounds=1, cohort_size=0, local_steps=2,
               batch_size=4, lr=0.05, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=8),
        model=ModelConfig(name="bert", num_classes=4, width=32, depth=1,
                          num_heads=4, seq_len=64, vocab_size=2000),
        fed=FedConfig(**fed),
        run=RunConfig(name="tp_test"),
    )


def _tiny_params(name, **kw):
    import jax.numpy as jnp

    cfg = ModelConfig(name=name, num_classes=4, width=32, depth=1,
                      num_heads=4, seq_len=64, vocab_size=2000, **kw)
    model = model_registry.build_model(cfg)
    x = (jnp.zeros((2, 64), jnp.int32) if name == "bert"
         else jnp.zeros((2, 28, 28, 1), jnp.float32))
    return model_registry.init_params(model, x, jax.random.PRNGKey(0))


def test_param_specs_bert_rules():
    params = _tiny_params("bert")
    specs = tp_lib.param_specs(params, "model", 2)
    blk = specs["TransformerBlock_0"]
    attn = blk["MultiHeadAttention_0"]
    # (D, H, hd) q/k/v kernels: heads dim sharded; (H, hd) bias: dim 0.
    assert attn["query"]["kernel"] == P(None, "model", None)
    assert attn["query"]["bias"] == P("model", None)
    # (H, hd, D) out projection: row parallel, bias replicated.
    assert attn["out"]["kernel"] == P("model", None, None)
    assert attn["out"]["bias"] == P()
    # Block MLP: up column-parallel, down row-parallel.
    assert blk["Dense_0"]["kernel"] == P(None, "model")
    assert blk["Dense_0"]["bias"] == P("model")
    assert blk["Dense_1"]["kernel"] == P("model", None)
    assert blk["Dense_1"]["bias"] == P()
    # Token embedding table vocab-sharded; norms replicated.
    assert specs["Embed_0"]["embedding"] == P("model", None)
    assert specs["LayerNorm_0"]["scale"] == P()
    assert tp_lib.sharded_fraction(params, "model", 2) > 0.5


def test_param_specs_vit_rules():
    params = _tiny_params("vit_b16", patch_size=4)
    specs = tp_lib.param_specs(params, "model", 2)
    blk = specs["ViTBlock_0"]
    assert blk["MultiHeadAttention_0"]["query"]["kernel"] == P(None, "model", None)
    assert blk["Dense_0"]["kernel"] == P(None, "model")
    assert specs["Conv_0"]["kernel"] == P()


def test_indivisible_dims_replicate():
    params = _tiny_params("bert")
    # 4 heads / 3-way axis does not divide: every spec must be replicated
    # rather than letting GSPMD pad.
    specs = tp_lib.param_specs(params, "model", 3)
    q = specs["TransformerBlock_0"]["MultiHeadAttention_0"]["query"]["kernel"]
    assert q == P()
    # MLP hidden 128 divides by 3? no → replicated too.
    assert specs["TransformerBlock_0"]["Dense_0"]["kernel"] == P()


@requires_native_shard_map
def test_tp_round_matches_vmap(cpu_devices):
    cfg = _bert_cfg()
    mesh = make_mesh(("clients", "model"), (4, 2), devices=cpu_devices[:8])
    tp_learner = FederatedLearner(cfg, mesh=mesh)
    assert tp_learner.tp_size == 2
    ref = FederatedLearner(cfg)

    for _ in range(2):
        m_tp = tp_learner.run_round()
        m_ref = ref.run_round()
    assert m_tp["completed"] == m_ref["completed"] == 8
    np.testing.assert_allclose(m_tp["train_loss"], m_ref["train_loss"],
                               rtol=1e-4)

    # Params: TP-sharded leaves are genuinely distributed ...
    q = tp_learner.server_state.params["TransformerBlock_0"][
        "MultiHeadAttention_0"]["query"]["kernel"]
    assert "model" in jax.tree.leaves(tuple(q.sharding.spec))
    shard_shape = q.addressable_shards[0].data.shape
    assert shard_shape[1] == q.shape[1] // 2
    # ... and the trained model matches the single-device trajectory.
    p_tp = np.concatenate(
        [np.ravel(np.asarray(a))
         for a in jax.tree.leaves(tp_learner.server_state.params)]
    )
    p_ref = np.concatenate(
        [np.ravel(np.asarray(a))
         for a in jax.tree.leaves(ref.server_state.params)]
    )
    np.testing.assert_allclose(p_tp, p_ref, atol=2e-6)

    # Eval runs with TP-sharded params and agrees too.
    lt, at = tp_learner.evaluate()
    lr_, ar_ = ref.evaluate()
    assert abs(lt - lr_) < 1e-4 and abs(at - ar_) < 1e-6


@requires_native_shard_map
def test_tp_composes_with_privacy(cpu_devices):
    # DP clip+noise and secure-agg masks run per-client INSIDE the manual
    # clients axis while params stay TP-sharded — the composition the
    # flagship (cross-silo ViT + DP) config needs.
    cfg = _bert_cfg(dp_clip=1.0, dp_noise_multiplier=0.1, secure_agg=True)
    mesh = make_mesh(("clients", "model"), (4, 2), devices=cpu_devices[:8])
    learner = FederatedLearner(cfg, mesh=mesh)
    m = learner.run_round()
    assert m["completed"] == 8
    assert np.isfinite(m["train_loss"])


@requires_native_shard_map
def test_dp_sp_tp_composition(cpu_devices):
    # The full 3-D mesh: manual clients (FedAvg psum) x manual seq (ring
    # attention) x auto model (TP) — one jit program, same trajectory as
    # the single-device vmap path.
    model = ModelConfig(name="bert", num_classes=4, width=16, depth=1,
                        num_heads=2, seq_len=64, vocab_size=2000)
    base = ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=4, partition="iid",
                        max_examples_per_client=8),
        model=model,
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=0,
                      local_steps=1, batch_size=4, lr=0.05, momentum=0.9),
        run=RunConfig(name="dp_sp_tp"),
    )
    cfg3d = base.replace(
        model=ModelConfig(**{**model.__dict__, "attn_impl": "ring"})
    )
    mesh = make_mesh(("clients", "seq", "model"), (2, 2, 2),
                     devices=cpu_devices[:8])
    learner = FederatedLearner(cfg3d, mesh=mesh)
    assert learner.sp and learner.tp_size == 2
    m = learner.run_round()
    ref = FederatedLearner(base)
    m_ref = ref.run_round()
    np.testing.assert_allclose(m["train_loss"], m_ref["train_loss"], rtol=1e-5)
    p1 = np.concatenate([np.ravel(np.asarray(a))
                         for a in jax.tree.leaves(learner.server_state.params)])
    p2 = np.concatenate([np.ravel(np.asarray(a))
                         for a in jax.tree.leaves(ref.server_state.params)])
    np.testing.assert_allclose(p1, p2, atol=2e-6)


def test_from_config_builds_tp_mesh(cpu_devices):
    cfg = _bert_cfg()
    cfg = cfg.replace(run=RunConfig(name="tp_auto", tp_size=2))
    learner = FederatedLearner.from_config(cfg)
    assert learner.mesh is not None
    assert learner.mesh.shape["model"] == 2
    assert learner.mesh.shape["clients"] == len(jax.devices()) // 2


@requires_native_shard_map
def test_tp_checkpoint_roundtrip(cpu_devices, tmp_path):
    # Checkpoint/resume with TP-sharded server state: the restore targets
    # the LIVE sharded arrays, so shardings must survive the roundtrip.
    import dataclasses

    cfg = _bert_cfg()
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, name="tp_ckpt", checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=1))
    mesh = make_mesh(("clients", "model"), (4, 2), devices=cpu_devices[:8])
    a = FederatedLearner(cfg, mesh=mesh)
    a.fit(rounds=2)
    p_before = np.concatenate([np.ravel(np.asarray(x))
                               for x in jax.tree.leaves(a.server_state.params)])

    b = FederatedLearner(cfg, mesh=mesh)
    step = b.restore_checkpoint()
    assert step == 2
    q = b.server_state.params["TransformerBlock_0"][
        "MultiHeadAttention_0"]["query"]["kernel"]
    assert q.addressable_shards[0].data.shape[1] == q.shape[1] // 2
    p_after = np.concatenate([np.ravel(np.asarray(x))
                              for x in jax.tree.leaves(b.server_state.params)])
    np.testing.assert_array_equal(p_before, p_after)


def test_scaffold_rejects_tp(cpu_devices):
    cfg = _bert_cfg(strategy="scaffold", momentum=0.0)
    mesh = make_mesh(("clients", "model"), (4, 2), devices=cpu_devices[:8])
    with pytest.raises(ValueError, match="scaffold"):
        FederatedLearner(cfg, mesh=mesh)
