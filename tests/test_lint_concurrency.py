"""Concurrency lint family (CL017–CL021) + the machinery that rode in
with it: one positive / negative / suppression fixture per rule, the
lock-order-cycle construction on a raw ClassLockIndex, the CL022
reason-required contract on suppressions, and the SARIF reporter
round-trip."""

import ast
import json
import textwrap

from colearn_federated_learning_tpu.analysis import lock_regions, reporters
from colearn_federated_learning_tpu.analysis.engine import (
    LintConfig,
    LintEngine,
    write_baseline,
)
from colearn_federated_learning_tpu.cli import main as cli_main


def run_lint(tmp_path, source, relpath="pkg/comm/mod.py", rules=None,
             baseline=""):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    eng = LintEngine(config=LintConfig(enable=rules), root=str(tmp_path))
    return eng.run([str(path)], baseline_path=baseline)


def rule_ids(result):
    return sorted({f.rule for f in result.findings})


# ------------------------------------------------------------- CL017 ----
_CL017_RACY = """
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = {}

        def start(self):
            threading.Thread(target=self._loop).start()

        def put(self, k, v):
            with self._lock:
                self._state[k] = v

        def size(self):
            with self._lock:
                return len(self._state)

        def _loop(self):
            return self._state.get("x")%s
"""


def test_cl017_flags_bare_access_on_thread_reachable_path(tmp_path):
    res = run_lint(tmp_path, _CL017_RACY % "", rules=["CL017"])
    assert rule_ids(res) == ["CL017"]
    (f,) = res.findings
    assert "_state" in f.message and "_lock" in f.message
    assert "_loop" in f.message


def test_cl017_suppression(tmp_path):
    res = run_lint(tmp_path,
                   _CL017_RACY % "  # colearn: noqa(CL017): test fixture",
                   rules=["CL017"])
    assert res.findings == [] and res.suppressed == 1


def test_cl017_quiet_when_access_is_locked(tmp_path):
    res = run_lint(tmp_path, """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}

            def start(self):
                threading.Thread(target=self._loop).start()

            def put(self, k, v):
                with self._lock:
                    self._state[k] = v

            def size(self):
                with self._lock:
                    return len(self._state)

            def _loop(self):
                with self._lock:
                    return self._state.get("x")
    """, rules=["CL017"])
    assert res.findings == []


def test_cl017_quiet_off_thread(tmp_path):
    # Same bare access, but nothing ever hands a method to another
    # thread: single-threaded classes are not in scope.
    res = run_lint(tmp_path, """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}

            def put(self, k, v):
                with self._lock:
                    self._state[k] = v

            def size(self):
                with self._lock:
                    return len(self._state)

            def peek(self):
                return self._state.get("x")
    """, rules=["CL017"])
    assert res.findings == []


def test_cl017_guarded_by_annotation_overrides_counting(tmp_path):
    # One locked access is below the >=2 inference threshold; the
    # explicit guarded-by annotation pins the contract anyway.
    res = run_lint(tmp_path, """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}  # colearn: guarded-by(_lock)

            def start(self):
                threading.Thread(target=self._loop).start()

            def put(self, k, v):
                with self._lock:
                    self._state[k] = v

            def _loop(self):
                return self._state.get("x")
    """, rules=["CL017"])
    assert rule_ids(res) == ["CL017"]


def test_cl017_scoped_to_threaded_dirs(tmp_path):
    res = run_lint(tmp_path, _CL017_RACY % "", relpath="pkg/fed/mod.py",
                   rules=["CL017"])
    assert res.findings == []


# ------------------------------------------------------------- CL018 ----
def test_cl018_flags_opposite_nesting_order(tmp_path):
    res = run_lint(tmp_path, """
        import threading

        class Duo:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def fwd(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def rev(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
    """, rules=["CL018"])
    assert rule_ids(res) == ["CL018"]
    assert "_a_lock -> _b_lock -> _a_lock" in res.findings[0].message


def test_cl018_quiet_on_consistent_order(tmp_path):
    res = run_lint(tmp_path, """
        import threading

        class Duo:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def fwd(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def also_fwd(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
    """, rules=["CL018"])
    assert res.findings == []


def test_lock_order_cycle_construction():
    # The graph machinery directly: three locks in a rotating order
    # build the 3-ring, reported once in canonical rotation.
    tree = ast.parse(textwrap.dedent("""
        class Tri:
            def __init__(self):
                import threading
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self._c_lock = threading.Lock()

            def ab(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def bc(self):
                with self._b_lock:
                    with self._c_lock:
                        pass

            def ca(self):
                with self._c_lock:
                    with self._a_lock:
                        pass
    """))
    classdef = tree.body[0]
    idx = lock_regions.ClassLockIndex(classdef, comments={})
    assert idx.locks == {"_a_lock", "_b_lock", "_c_lock"}
    assert ("_a_lock", "_b_lock") in idx.edges
    assert idx.cycles() == [["_a_lock", "_b_lock", "_c_lock"]]


# ------------------------------------------------------------- CL019 ----
def test_cl019_flags_sleep_and_broker_rpc_under_lock(tmp_path):
    res = run_lint(tmp_path, """
        import time
        import threading

        from pkg.broker import BrokerClient

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def spin(self):
                with self._lock:
                    time.sleep(0.1)

            def refresh(self):
                with self._lock:
                    return BrokerClient("h", 1, timeout=5.0)
    """, rules=["CL019"])
    assert rule_ids(res) == ["CL019"]
    assert len(res.findings) == 2


def test_cl019_quiet_outside_lock_and_for_own_cv_wait(tmp_path):
    res = run_lint(tmp_path, """
        import time
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()
                self._ready = False

            def spin(self):
                time.sleep(0.1)
                with self._lock:
                    pass

            def wait_ready(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait(1.0)
    """, rules=["CL019"])
    assert res.findings == []


def test_cl019_suppression(tmp_path):
    res = run_lint(tmp_path, """
        import time
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def spin(self):
                with self._lock:
                    time.sleep(0.1)  # colearn: noqa(CL019): test fixture
    """, rules=["CL019"])
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL020 ----
def test_cl020_flags_wait_outside_predicate_loop(tmp_path):
    res = run_lint(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition()
                self._ready = False

            def wait_ready(self):
                with self._cv:
                    self._cv.wait(1.0)
    """, rules=["CL020"])
    assert rule_ids(res) == ["CL020"]


def test_cl020_quiet_in_while_loop_and_wait_for(tmp_path):
    res = run_lint(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition()
                self._ready = False

            def wait_ready(self):
                with self._cv:
                    while not self._ready:
                        self._cv.wait(1.0)

            def wait_pred(self):
                with self._cv:
                    self._cv.wait_for(lambda: self._ready, 1.0)
    """, rules=["CL020"])
    assert res.findings == []


# ------------------------------------------------------------- CL021 ----
_CL021_FANOUT = """
    import threading

    class T:
        def __init__(self):
            self._lock = threading.Lock()
            self._subs = {}

        def add(self, k, v):
            with self._lock:
                self._subs[k] = v

        def drop(self, k):
            with self._lock:
                self._subs.pop(k, None)

        def fanout(self):
            for s in %s:
                s()
"""


def test_cl021_flags_unlocked_iteration(tmp_path):
    res = run_lint(tmp_path, _CL021_FANOUT % "self._subs.values()",
                   rules=["CL021"])
    assert rule_ids(res) == ["CL021"]
    assert "_subs" in res.findings[0].message


def test_cl021_quiet_under_lock_and_for_snapshots(tmp_path):
    locked = run_lint(tmp_path, """
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()
                self._subs = {}

            def add(self, k, v):
                with self._lock:
                    self._subs[k] = v

            def drop(self, k):
                with self._lock:
                    self._subs.pop(k, None)

            def fanout(self):
                with self._lock:
                    for s in self._subs.values():
                        s()
    """, rules=["CL021"])
    assert locked.findings == []
    snapshot = run_lint(tmp_path,
                        _CL021_FANOUT % "list(self._subs.values())",
                        relpath="pkg/comm/snap.py", rules=["CL021"])
    assert snapshot.findings == []


def test_cl021_comprehension_iteration_is_flagged(tmp_path):
    res = run_lint(tmp_path, """
        import threading

        class T:
            def __init__(self):
                self._lock = threading.Lock()
                self._subs = {}

            def add(self, k, v):
                with self._lock:
                    self._subs[k] = v

            def drop(self, k):
                with self._lock:
                    self._subs.pop(k, None)

            def names(self):
                return [k for k in self._subs]
    """, rules=["CL021"])
    assert rule_ids(res) == ["CL021"]


# ------------------------------------------------------------- CL022 ----
_JIT_PRINT = """
    import jax

    @jax.jit
    def step(x):
        print("trace")%s
        return x
"""


def test_cl022_flags_bare_live_suppression(tmp_path):
    res = run_lint(tmp_path, _JIT_PRINT % "  # colearn: noqa(CL001)",
                   relpath="pkg/fed/mod.py", rules=["CL001"])
    assert rule_ids(res) == ["CL022"]
    assert res.suppressed == 1


def test_cl022_quiet_with_reason(tmp_path):
    res = run_lint(tmp_path,
                   _JIT_PRINT % "  # colearn: noqa(CL001): test fixture",
                   relpath="pkg/fed/mod.py", rules=["CL001"])
    assert res.findings == [] and res.suppressed == 1


def test_cl022_blanket_noqa_is_exempt(tmp_path):
    res = run_lint(tmp_path, _JIT_PRINT % "  # colearn: noqa",
                   relpath="pkg/fed/mod.py", rules=["CL001"])
    assert res.findings == [] and res.suppressed == 1


def test_cl022_dead_bare_noqa_gets_cl000_only(tmp_path):
    res = run_lint(tmp_path, """
        def quiet():
            return 1  # colearn: noqa(CL001)
    """, relpath="pkg/fed/mod.py", rules=["CL001"])
    assert rule_ids(res) == ["CL000"]


# -------------------------------------------------------------- SARIF ----
def test_sarif_round_trip(tmp_path):
    res = run_lint(tmp_path, _JIT_PRINT % "", relpath="pkg/fed/mod.py",
                   rules=["CL001"])
    assert len(res.findings) == 1
    doc = json.loads(reporters.render_sarif(res))
    assert doc["version"] == "2.1.0"
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "colearn-lint"
    (result,) = run["results"]
    (finding,) = res.findings
    assert result["ruleId"] == finding.rule == "CL001"
    rules_table = run["tool"]["driver"]["rules"]
    assert rules_table[result["ruleIndex"]]["id"] == "CL001"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == finding.path
    assert loc["region"]["startLine"] == finding.line
    assert (result["partialFingerprints"]["colearnFingerprint/v1"]
            == finding.fingerprint())


def test_sarif_clean_run_has_empty_results(tmp_path):
    res = run_lint(tmp_path, "x = 1\n", relpath="pkg/fed/mod.py",
                   rules=["CL001"])
    doc = json.loads(reporters.render_sarif(res))
    assert doc["runs"][0]["results"] == []


def test_cli_format_sarif(tmp_path, capsys):
    path = tmp_path / "pkg" / "fed" / "mod.py"
    path.parent.mkdir(parents=True)
    path.write_text(textwrap.dedent(_JIT_PRINT % ""))
    rc = cli_main(["lint", str(path), "--root", str(tmp_path),
                   "--rules", "CL001", "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["runs"][0]["results"][0]["ruleId"] == "CL001"


# --------------------------------------------------------------- gate ----
def test_gate_fails_on_nonempty_baseline(tmp_path, capsys):
    path = tmp_path / "pkg" / "fed" / "mod.py"
    path.parent.mkdir(parents=True)
    path.write_text(textwrap.dedent(_JIT_PRINT % ""))
    eng = LintEngine(config=LintConfig(enable=["CL001"]),
                     root=str(tmp_path))
    res = eng.run([str(path)], baseline_path="")
    write_baseline(str(tmp_path / "lint_baseline.json"), res.findings)

    rc = cli_main(["lint", str(path), "--root", str(tmp_path),
                   "--rules", "CL001", "--gate"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "baseline" in err and "1 fingerprint(s)" in err


def test_gate_passes_on_empty_baseline(tmp_path, capsys):
    path = tmp_path / "pkg" / "fed" / "mod.py"
    path.parent.mkdir(parents=True)
    path.write_text("x = 1\n")
    rc = cli_main(["lint", str(path), "--root", str(tmp_path),
                   "--rules", "CL001", "--gate"])
    capsys.readouterr()
    assert rc == 0
