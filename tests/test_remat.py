"""Rematerialization (jax.checkpoint) for the transformer families.

``ModelConfig.remat=True`` wraps every block in ``nn.remat``: activation
memory under autodiff goes from ∝ depth to ∝ 1 block at the cost of one
extra forward per block — the standard trade that fits deep local
training on a chip.  Numerics must be EXACT: same param pytree, same
loss, same gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.fed import losses
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _grads(cfg: ModelConfig, x, y):
    model = model_registry.build_model(cfg)
    params = model_registry.init_params(model, x, jax.random.PRNGKey(0))

    def loss(p):
        return losses.softmax_cross_entropy(
            model.apply({"params": p}, x, train=True), y
        )

    value, grads = jax.jit(jax.value_and_grad(loss))(params)
    return params, value, grads


def test_remat_is_numerically_identical():
    for name, x in [
        ("bert", jax.random.randint(jax.random.PRNGKey(1), (4, 64), 1, 2000)),
        ("vit_b16",
         jax.random.normal(jax.random.PRNGKey(1), (4, 28, 28, 1))),
    ]:
        y = jax.random.randint(jax.random.PRNGKey(2), (4,), 0, 4)
        base = ModelConfig(name=name, num_classes=4, width=32, depth=2,
                           num_heads=4, seq_len=64, vocab_size=2000,
                           patch_size=4)
        import dataclasses

        p0, v0, g0 = _grads(base, x, y)
        p1, v1, g1 = _grads(dataclasses.replace(base, remat=True), x, y)
        # Identical param pytree (checkpoints/wire payloads compatible).
        assert jax.tree.structure(p0) == jax.tree.structure(p1)
        np.testing.assert_allclose(float(v0), float(v1), rtol=1e-6)
        # Tight allclose, not bitwise: jax.checkpoint replays each
        # block's forward inside the backward pass, and XLA:CPU fuses /
        # reorders the recomputed reductions differently from the stored
        # activations (observed max |diff| ~3e-6 on these widths).  The
        # math is the same; the summation order is not.
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)


def test_remat_trains_in_engine():
    # Remat must not CHANGE training — so the pin is trajectory parity
    # against the non-remat engine, not a loss-goes-down heuristic (two
    # rounds of this tiny config land wherever the lr schedule takes
    # them, remat or not; both arms see the identical trajectory).
    def run(remat):
        cfg = ExperimentConfig(
            data=DataConfig(dataset="agnews_tiny", num_clients=4,
                            partition="iid", max_examples_per_client=16),
            model=ModelConfig(name="bert", num_classes=4, width=32, depth=2,
                              num_heads=4, seq_len=64, vocab_size=2000,
                              remat=remat),
            fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=0,
                          local_steps=2, batch_size=4, lr=0.05, momentum=0.9),
            run=RunConfig(name="remat_test"),
        )
        return FederatedLearner(cfg).fit(rounds=2)

    hist_remat = run(True)
    hist_plain = run(False)
    assert len(hist_remat) == len(hist_plain)
    for r_rm, r_pl in zip(hist_remat, hist_plain):
        assert np.isfinite(r_rm["train_loss"])
        # Tight allclose, not exact: XLA:CPU reorders the recomputed
        # reductions under jax.checkpoint (see test above), and the ulp
        # drift compounds over local steps.
        np.testing.assert_allclose(r_rm["train_loss"], r_pl["train_loss"],
                                   rtol=1e-4)
