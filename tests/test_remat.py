"""Rematerialization (jax.checkpoint) for the transformer families.

``ModelConfig.remat=True`` wraps every block in ``nn.remat``: activation
memory under autodiff goes from ∝ depth to ∝ 1 block at the cost of one
extra forward per block — the standard trade that fits deep local
training on a chip.  Numerics must be EXACT: same param pytree, same
loss, same gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.fed import losses
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _grads(cfg: ModelConfig, x, y):
    model = model_registry.build_model(cfg)
    params = model_registry.init_params(model, x, jax.random.PRNGKey(0))

    def loss(p):
        return losses.softmax_cross_entropy(
            model.apply({"params": p}, x, train=True), y
        )

    value, grads = jax.jit(jax.value_and_grad(loss))(params)
    return params, value, grads


def test_remat_is_numerically_identical():
    for name, x in [
        ("bert", jax.random.randint(jax.random.PRNGKey(1), (4, 64), 1, 2000)),
        ("vit_b16",
         jax.random.normal(jax.random.PRNGKey(1), (4, 28, 28, 1))),
    ]:
        y = jax.random.randint(jax.random.PRNGKey(2), (4,), 0, 4)
        base = ModelConfig(name=name, num_classes=4, width=32, depth=2,
                           num_heads=4, seq_len=64, vocab_size=2000,
                           patch_size=4)
        import dataclasses

        p0, v0, g0 = _grads(base, x, y)
        p1, v1, g1 = _grads(dataclasses.replace(base, remat=True), x, y)
        # Identical param pytree (checkpoints/wire payloads compatible).
        assert jax.tree.structure(p0) == jax.tree.structure(p1)
        np.testing.assert_allclose(float(v0), float(v1), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)


def test_remat_trains_in_engine():
    cfg = ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=4, partition="iid",
                        max_examples_per_client=16),
        model=ModelConfig(name="bert", num_classes=4, width=32, depth=2,
                          num_heads=4, seq_len=64, vocab_size=2000,
                          remat=True),
        fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=0,
                      local_steps=2, batch_size=4, lr=0.05, momentum=0.9),
        run=RunConfig(name="remat_test"),
    )
    learner = FederatedLearner(cfg)
    hist = learner.fit(rounds=2)
    assert np.isfinite(hist[-1]["train_loss"])
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
