"""Ulysses (all-to-all) sequence parallelism (parallel/ulysses.py).

The second long-context layout next to ring attention: one all-to-all to
head-sharding, local dense attention over the full sequence, one
all-to-all back.  Must match the dense oracle exactly and train through
the federated 2-D (clients, seq) mesh like the ring path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.parallel.mesh import make_mesh
from colearn_federated_learning_tpu.parallel.ring import dense_attention
from colearn_federated_learning_tpu.parallel.ulysses import ulysses_attention
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)
from colearn_federated_learning_tpu.utils.jax_compat import shard_map


def _run_sharded(fn, mesh, args, specs, out_spec):
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=specs,
                             out_specs=out_spec, check_vma=False))(*args)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense_oracle(cpu_devices, causal):
    mesh = Mesh(np.array(cpu_devices[:4]), ("seq",))
    B, L, H, D = 2, 32, 4, 8
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, L, H, D), jnp.float32) for kk in ks)
    mask = jax.random.bernoulli(jax.random.PRNGKey(3), 0.8, (B, L))

    ref = dense_attention(q, k, v, mask, causal=causal)
    out = _run_sharded(
        lambda q_, k_, v_, m_: ulysses_attention(
            q_, k_, v_, m_, axis_name="seq", causal=causal
        ),
        mesh, (q, k, v, mask),
        (P(None, "seq"), P(None, "seq"), P(None, "seq"), P(None, "seq")),
        P(None, "seq"),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_rejects_indivisible_heads(cpu_devices):
    mesh = Mesh(np.array(cpu_devices[:4]), ("seq",))
    q = jnp.zeros((1, 16, 3, 8))         # 3 heads / 4-way axis
    with pytest.raises(ValueError, match="divisible"):
        _run_sharded(
            lambda x: ulysses_attention(x, x, x, axis_name="seq"),
            mesh, (q,), (P(None, "seq"),), P(None, "seq"),
        )


def test_federated_ulysses_matches_single_device(cpu_devices):
    model = dict(name="bert", num_classes=4, width=16, depth=1, num_heads=4,
                 seq_len=64, vocab_size=2000)
    base = ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=4, partition="iid",
                        max_examples_per_client=8),
        model=ModelConfig(**model),
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=0,
                      local_steps=2, batch_size=4, lr=0.05, momentum=0.9),
        run=RunConfig(name="ulysses_fed"),
    )
    cfg = base.replace(model=ModelConfig(**{**model, "attn_impl": "ulysses"}))
    mesh = make_mesh(("clients", "seq"), (4, 2), devices=cpu_devices[:8])
    sp = FederatedLearner(cfg, mesh=mesh)
    assert sp.sp
    ref = FederatedLearner(base)
    for _ in range(2):
        r_sp = sp.run_round()
        r_ref = ref.run_round()
    np.testing.assert_allclose(r_sp["train_loss"], r_ref["train_loss"],
                               rtol=1e-5)
    p1 = np.concatenate([np.ravel(np.asarray(a))
                         for a in jax.tree.leaves(sp.server_state.params)])
    p2 = np.concatenate([np.ravel(np.asarray(a))
                         for a in jax.tree.leaves(ref.server_state.params)])
    np.testing.assert_allclose(p1, p2, atol=2e-6)
