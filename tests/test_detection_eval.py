"""Detection-oriented evaluation (fed/evaluation.py confusion matrix +
detection_report; engine.evaluate_detection).

The reference's deployment task is IoT network-anomaly detection, where
plain accuracy hides an always-benign classifier — the metrics that
matter are per-class recall and the alarm detection/false-alarm rates.
"""

import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.evaluation import (
    detection_report,
    make_confusion_eval_fn,
)


def test_detection_report_oracle():
    # 3 classes, benign = 0.  Rows = true, cols = predicted.
    conf = np.array([
        [80, 15, 5],     # benign: 20 false alarms
        [10, 35, 5],     # attack A: 10 missed
        [0, 10, 40],     # attack B: 0 missed (10 misattributed to A,
    ], np.float64)       #           still alarms)
    rep = detection_report(conf, benign_class=0)
    assert rep["accuracy"] == pytest.approx(155 / 200)
    # Alarm view: any non-benign prediction is an alarm.
    assert rep["false_alarm_rate"] == pytest.approx(20 / 100)
    assert rep["detection_rate"] == pytest.approx(90 / 100)
    # Per-class recall oracle.
    np.testing.assert_allclose(rep["per_class_recall"],
                               [0.8, 0.7, 0.8])
    # Precision for class 1: 35 / (15+35+10).
    assert rep["per_class_precision"][1] == pytest.approx(35 / 60)
    f1_1 = 2 * (35 / 60) * 0.7 / ((35 / 60) + 0.7)
    assert rep["per_class_f1"][1] == pytest.approx(f1_1)
    assert 0.0 < rep["macro_f1"] < 1.0

    # Degenerate: always-benign classifier — accuracy can look fine while
    # detection_rate exposes it.
    lazy = np.array([[100, 0], [50, 0]], np.float64)
    rep2 = detection_report(lazy, benign_class=0)
    assert rep2["accuracy"] == pytest.approx(100 / 150)
    assert rep2["detection_rate"] == 0.0
    assert rep2["false_alarm_rate"] == 0.0


def test_confusion_eval_fn_counts_every_example():
    import flax.linen as nn
    import jax

    class Const(nn.Module):
        # Predict argmax of a fixed per-class bias: deterministic preds.
        @nn.compact
        def __call__(self, x, train=False):
            b = self.param("b", nn.initializers.zeros, (3,))
            return jnp.broadcast_to(b, (x.shape[0], 3)) + x.sum() * 0.0

    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    x = rng.normal(size=(37, 4)).astype(np.float32)   # non-multiple of batch
    y = rng.integers(0, 3, 37)
    model = Const()
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(x[:1]))["params"]
    params = {"b": jnp.asarray([0.0, 1.0, 0.0])}      # always predicts 1
    fn = make_confusion_eval_fn(model.apply, x, y, batch=8, num_classes=3)
    conf = np.asarray(fn(params))
    assert conf.sum() == 37                            # padding not counted
    np.testing.assert_array_equal(conf[:, 1],
                                  np.bincount(y, minlength=3))
    assert conf[:, [0, 2]].sum() == 0


def test_offline_eval_detection_report(tmp_path):
    # File-plane parity: `colearn eval --detection-eval` on a global-model
    # file reports the same detection view the engine produces.
    import dataclasses

    from colearn_federated_learning_tpu.fed import offline
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    cfg = ExperimentConfig(
        data=DataConfig(dataset="iot_traffic_tiny", num_clients=4,
                        partition="iid", max_examples_per_client=32),
        model=ModelConfig(name="tcn", num_classes=8, width=16, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=0,
                      local_steps=2, batch_size=16, lr=0.05, momentum=0.9),
        run=RunConfig(name="offline_detect"),
    )
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)
    rec = offline.evaluate_global(cfg, g0, detection=True)
    assert {"detection_rate", "false_alarm_rate", "macro_f1",
            "per_class_recall"} <= set(rec)
    assert len(rec["per_class_recall"]) == 8
    assert sum(rec["support"]) == 400           # iot_traffic_tiny n_test


def test_engine_detection_eval_on_iot_config():
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    cfg = ExperimentConfig(
        data=DataConfig(dataset="iot_traffic_tiny", num_clients=8,
                        partition="iid", max_examples_per_client=64),
        model=ModelConfig(name="tcn", num_classes=8, width=16, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=6, cohort_size=0,
                      local_steps=4, batch_size=16, lr=0.05, momentum=0.9),
        run=RunConfig(name="detection_test"),
    )
    learner = FederatedLearner(cfg)
    learner.fit(rounds=6)
    rep = learner.evaluate_detection()
    assert rep["support"].sum() == len(learner.dataset.y_test)
    # The synthetic traffic classes are learnable: the trained model must
    # both detect attacks and keep false alarms low.
    assert rep["detection_rate"] > 0.8, rep["detection_rate"]
    assert rep["false_alarm_rate"] < 0.2, rep["false_alarm_rate"]
    assert rep["macro_f1"] > 0.6, rep["macro_f1"]
