"""ops/attention.py flash kernel vs the dense oracle (interpret mode on CPU),
plus the pluggable MultiHeadAttention module: identical params across cores,
matching outputs, usable gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.models.attention import MultiHeadAttention
from colearn_federated_learning_tpu.ops.attention import flash_attention
from colearn_federated_learning_tpu.parallel.ring import dense_attention


def _rand(key, B, L, H, D, frac_pad=0.25):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (B, L, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, L, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, L, H, D), jnp.float32)
    mask = jax.random.uniform(ks[3], (B, L)) > frac_pad
    return q, k, v, mask


@pytest.mark.parametrize("L,block", [(32, 16), (48, 16), (40, 128)])
def test_flash_matches_dense(L, block):
    q, k, v, mask = _rand(jax.random.PRNGKey(0), B=2, L=L, H=2, D=8)
    out = flash_attention(q, k, v, mask, block_q=block, block_k=block)
    ref = dense_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_causal_and_nomask():
    q, k, v, _ = _rand(jax.random.PRNGKey(1), B=1, L=32, H=2, D=8)
    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_fully_masked_rows_zero():
    q, k, v, _ = _rand(jax.random.PRNGKey(2), B=2, L=16, H=1, D=4)
    mask = jnp.zeros((2, 16), bool).at[1].set(True)
    out = flash_attention(q, k, v, mask, block_q=8, block_k=8)
    assert np.allclose(np.asarray(out)[0], 0.0)
    ref = dense_attention(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_grads_match_dense():
    q, k, v, mask = _rand(jax.random.PRNGKey(3), B=2, L=16, H=2, D=4)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, mask, block_q=8, block_k=8) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, mask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_mha_module_cores_agree():
    B, L, D, H = 2, 24, 16, 4
    x = jax.random.normal(jax.random.PRNGKey(4), (B, L, D))
    mask = jax.random.uniform(jax.random.PRNGKey(5), (B, L)) > 0.2
    dense_m = MultiHeadAttention(num_heads=H, impl="dense")
    flash_m = MultiHeadAttention(num_heads=H, impl="flash")
    params = dense_m.init(jax.random.PRNGKey(6), x, mask)
    # Same param pytree regardless of core.
    chex_tree = jax.tree.structure(params)
    assert jax.tree.structure(flash_m.init(jax.random.PRNGKey(6), x, mask)) == chex_tree
    yd = dense_m.apply(params, x, mask)
    yf = flash_m.apply(params, x, mask)
    np.testing.assert_allclose(np.asarray(yd), np.asarray(yf),
                               rtol=1e-5, atol=1e-5)


def test_mha_module_bad_impl():
    x = jnp.zeros((1, 8, 8))
    with pytest.raises(ValueError, match="unknown attn impl"):
        MultiHeadAttention(num_heads=2, impl="nope").init(
            jax.random.PRNGKey(0), x
        )


def test_bert_model_flash_matches_dense():
    import dataclasses

    from colearn_federated_learning_tpu.models import registry
    from colearn_federated_learning_tpu.utils.config import ModelConfig

    cfg = ModelConfig(name="bert", num_classes=4, width=32, depth=2,
                      num_heads=4, seq_len=16, vocab_size=100)
    ids = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 100)
    dense = registry.build_model(cfg)
    flash = registry.build_model(dataclasses.replace(cfg, attn_impl="flash"))
    params = registry.init_params(dense, ids, jax.random.PRNGKey(1))
    yd = dense.apply({"params": params}, ids, train=False)
    yf = flash.apply({"params": params}, ids, train=False)
    np.testing.assert_allclose(np.asarray(yd), np.asarray(yf),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("L,block", [(40, 16), (32, 128)])
def test_flash_backward_padded_blocks_match_dense(L, block):
    """The Pallas backward must handle block padding exactly: odd L forces
    padded q/k rows through both bwd kernels."""
    q, k, v, mask = _rand(jax.random.PRNGKey(7), B=2, L=L, H=2, D=8)

    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, mask, block_q=block, block_k=block) ** 2
        ), argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda q, k, v: jnp.sum(dense_attention(q, k, v, mask) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_flash_backward_causal_matches_dense():
    q, k, v, _ = _rand(jax.random.PRNGKey(8), B=1, L=32, H=2, D=8)

    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=8, block_k=8) ** 2
        ), argnums=(0, 1, 2),
    )(q, k, v)
    gd = jax.grad(
        lambda q, k, v: jnp.sum(dense_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_flash_backward_fully_masked_rows_zero_grad():
    """Batch 0 has every key masked: its dq must be exactly zero and dk/dv
    must receive no contribution from it."""
    q, k, v, _ = _rand(jax.random.PRNGKey(9), B=2, L=16, H=1, D=4)
    mask = jnp.zeros((2, 16), bool).at[1].set(True)

    gf = jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, mask, block_q=8, block_k=8) ** 2
        ), argnums=(0, 1, 2),
    )(q, k, v)
    assert np.allclose(np.asarray(gf[0])[0], 0.0)
    assert np.allclose(np.asarray(gf[1])[0], 0.0)
    assert np.allclose(np.asarray(gf[2])[0], 0.0)
    gd = jax.grad(
        lambda q, k, v: jnp.sum(dense_attention(q, k, v, mask) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)
