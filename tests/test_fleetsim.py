"""fleetsim/: the chunked-vmap fleet simulator is the ENGINE at scale,
not a parallel-but-different implementation — single-chunk rounds match
`FederatedLearner.run_round` bit-for-bit (same PRNG keys, same FedAvg
weighting, same server update), multi-chunk rounds to float tolerance,
and a FaultPlan dropping k devices yields exactly the aggregate an
independent per-client re-derivation produces without them (ISSUE 6
acceptance).  Plus: population determinism/chunk-independence, traffic
determinism/diurnal swing, wire-byte estimates, CLI + bench schema."""

import dataclasses
import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu import fleetsim, telemetry
from colearn_federated_learning_tpu.analysis import metric_catalog
from colearn_federated_learning_tpu.faults.plan import FaultPlan, FaultSpec
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils import prng
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def tiny_config(**fed_kw) -> ExperimentConfig:
    fed = dict(strategy="fedavg", rounds=2, local_epochs=1, batch_size=32,
               lr=0.05, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=10,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="test", seed=0),
    )


def fleet_config(**fed_kw) -> ExperimentConfig:
    fed = dict(strategy="fedavg", local_steps=2, batch_size=8, lr=0.05,
               momentum=0.0)
    fed.update(fed_kw)
    return ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=1),
        fed=FedConfig(**fed),
        run=RunConfig(name="test", seed=0),
    )


def make_fleet(num_devices=256, cohort=64, chunk=32, **kw):
    spec = fleetsim.PopulationSpec(num_devices=num_devices, feature_dim=16,
                                   shard_capacity=16, min_examples=4)
    population = fleetsim.DevicePopulation(spec)
    traffic = fleetsim.TrafficModel(
        fleetsim.TrafficSpec(base_rate=2000.0, diurnal_amplitude=0.0),
        num_devices)
    return fleetsim.FleetSim.from_population(
        fleet_config(), population, traffic, cohort_size=cohort,
        chunk_size=chunk, **kw)


def max_param_diff(a, b) -> float:
    la = jax.tree.leaves(jax.device_get(a))
    lb = jax.tree.leaves(jax.device_get(b))
    return max(float(np.max(np.abs(x - y))) for x, y in zip(la, lb))


# ------------------------------------------------------------ population --
def test_population_is_deterministic_and_chunking_independent():
    pop = fleetsim.DevicePopulation(fleetsim.PopulationSpec(
        num_devices=1000, feature_dim=8, shard_capacity=8, min_examples=2))
    ids = np.array([3, 500, 999])
    x1, y1, c1 = pop.materialize(ids)
    # Same devices asked for one at a time, in another order: identical.
    for k, i in enumerate([999, 3, 500]):
        xi, yi, ci = pop.materialize(np.array([i]))
        j = int(np.where(ids == i)[0][0])
        np.testing.assert_array_equal(x1[j], xi[0])
        np.testing.assert_array_equal(y1[j], yi[0])
        assert c1[j] == ci[0]
    # A fresh population with the same spec regenerates the same fleet.
    pop2 = fleetsim.DevicePopulation(pop.spec)
    x2, y2, c2 = pop2.materialize(ids)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)


def test_population_counts_labels_and_padding():
    spec = fleetsim.PopulationSpec(num_devices=500, feature_dim=8,
                                   shard_capacity=8, min_examples=3,
                                   label_skew=0.9)
    pop = fleetsim.DevicePopulation(spec)
    ids = np.arange(500)
    x, y, counts = pop.materialize(ids)
    assert x.shape == (500, 8, 8) and y.shape == (500, 8)
    assert counts.min() >= 3 and counts.max() <= 8
    # Non-IID: with 90% skew the home class dominates each valid shard.
    home = pop.home_classes(ids)
    valid = np.arange(8)[None, :] < counts[:, None]
    match = ((y == home[:, None]) & valid).sum()
    assert match / valid.sum() > 0.75
    # Padding rows are zeroed so vmapped batches never read garbage.
    assert np.all(x[~valid] == 0.0)


def test_speed_classes_map_to_step_budgets():
    spec = fleetsim.PopulationSpec(num_devices=10_000)
    pop = fleetsim.DevicePopulation(spec)
    ids = np.arange(10_000)
    idx = pop.speed_class_index(ids)
    fracs = np.bincount(idx, minlength=3) / ids.size
    for k, cls in enumerate(spec.speed_classes):
        assert abs(fracs[k] - cls.fraction) < 0.03
    budgets = pop.step_budgets(ids, num_steps=8)
    assert set(np.unique(budgets)) == {2, 4, 8}
    np.testing.assert_array_equal(
        budgets == 8, idx == 0)  # fast class runs the full budget


# --------------------------------------------------------------- traffic --
def test_traffic_is_deterministic_and_diurnal():
    tm = fleetsim.TrafficModel(
        fleetsim.TrafficSpec(base_rate=2.0, diurnal_amplitude=1.0,
                             round_minutes=60.0), 5000)
    m0 = tm.available_mask(3)
    np.testing.assert_array_equal(m0, tm.available_mask(3))
    # Amplitude 1.0 over a 24h cycle: availability must visibly swing.
    fracs = [tm.expected_available(r) for r in range(24)]
    assert max(fracs) > 1.5 * min(fracs)
    # Different rounds draw different cohorts (fresh arrival draws).
    assert not np.array_equal(tm.available_mask(3), tm.available_mask(4))


def test_traffic_cohort_sampling_is_a_subset_without_replacement():
    tm = fleetsim.TrafficModel(fleetsim.TrafficSpec(base_rate=20.0), 2000)
    cohort = tm.sample_cohort(0, 64)
    assert cohort.size == 64 and np.unique(cohort).size == 64
    mask = tm.available_mask(0)
    assert mask[cohort].all()
    np.testing.assert_array_equal(cohort, tm.sample_cohort(0, 64))


# ---------------------------------------------------------- engine parity --
def test_single_chunk_round_matches_engine_exactly():
    ln = FederatedLearner(tiny_config(cohort_size=4))
    fs = fleetsim.FleetSim.from_learner(
        FederatedLearner(tiny_config(cohort_size=4)), chunk_size=8)
    h_e = ln.fit(rounds=2)
    h_f = fs.fit(2)
    assert max_param_diff(ln.server_state.params,
                          fs.server_state.params) <= 1e-7
    for k in ("train_loss", "completed", "total_weight"):
        assert h_f[-1][k] == pytest.approx(h_e[-1][k], abs=1e-6), k


def test_multi_chunk_round_matches_engine_allclose():
    ln = FederatedLearner(tiny_config())          # full 10-client cohort
    fs = fleetsim.FleetSim.from_learner(
        FederatedLearner(tiny_config()), chunk_size=3)  # 4 padded chunks
    h_e = ln.fit(rounds=2)
    h_f = fs.fit(2)
    # Chunked folding reorders float sums; identical semantics otherwise.
    assert max_param_diff(ln.server_state.params,
                          fs.server_state.params) <= 1e-5
    assert h_f[-1]["total_weight"] == pytest.approx(h_e[-1]["total_weight"])
    assert h_f[-1]["completed"] == pytest.approx(h_e[-1]["completed"])


def test_engine_straggler_budgets_replicated():
    kw = dict(straggler_prob=0.5, straggler_min_fraction=0.5, rounds=1)
    h_e = FederatedLearner(tiny_config(**kw)).fit(rounds=1)
    fs = fleetsim.FleetSim.from_learner(
        FederatedLearner(tiny_config(**kw)), chunk_size=4)
    h_f = fs.fit(1)
    assert h_f[0]["completed"] == pytest.approx(h_e[0]["completed"])
    assert h_f[0]["total_weight"] == pytest.approx(h_e[0]["total_weight"])


# ----------------------------------------------------------- fault parity --
def manual_engine_round(ln, exclude=frozenset()):
    """Independent per-client re-derivation of round 0 (no vmap, no
    chunking): engine keys, engine weighting, engine server update,
    minus the excluded devices — the acceptance-criterion reference."""
    params = ln.server_state.params
    r = jnp.asarray(0, jnp.int32)
    budget = jnp.asarray(ln.num_steps, jnp.int32)
    wsum = None
    total_w = 0.0
    for cid in range(ln.num_clients):
        key = prng.client_round_key(ln.base_key,
                                    jnp.asarray(cid, jnp.int32), r)
        res = ln.local_update(params, jnp.asarray(ln.shards.x[cid]),
                              jnp.asarray(ln.shards.y[cid]),
                              jnp.asarray(ln.shards.counts[cid]),
                              key, budget, None)
        res = jax.device_get(res)
        w = float(res.num_examples) * float(
            bool(res.completed) and res.num_examples > 0
            and cid not in exclude)
        delta = jax.tree.map(lambda l: np.asarray(l, np.float64), res.delta)
        scaled = jax.tree.map(lambda l: w * l, delta)
        wsum = scaled if wsum is None else jax.tree.map(
            np.add, wsum, scaled)
        total_w += w
    mean_delta = jax.tree.map(lambda l: l / total_w, wsum)
    return jax.tree.map(
        lambda p, d: np.asarray(p, np.float64)
        + ln.config.fed.server_lr * d,
        jax.device_get(params), mean_delta), total_w


def test_fault_plan_drop_matches_engine_excluding_devices():
    # ISSUE 6 acceptance: dropping k simulated devices via the FaultPlan
    # == the engine aggregate without those devices.
    dropped = {2, 5, 7}
    plan = FaultPlan([FaultSpec(kind="drop_request", device_id=str(d),
                                round=0, op="train") for d in dropped])
    ref_ln = FederatedLearner(tiny_config())
    want_params, want_w = manual_engine_round(ref_ln, exclude=dropped)

    fs = fleetsim.FleetSim.from_learner(
        FederatedLearner(tiny_config()), chunk_size=4, fault_plan=plan)
    rec = fs.run_round()
    got = jax.device_get(fs.server_state.params)
    diff = max(float(np.max(np.abs(np.asarray(a, np.float64) - b)))
               for a, b in zip(jax.tree.leaves(got),
                               jax.tree.leaves(want_params)))
    assert diff <= 1e-5
    assert rec["dropped"] == len(dropped)
    assert rec["completed"] == ref_ln.num_clients - len(dropped)
    assert rec["total_weight"] == pytest.approx(want_w)
    assert plan.total_fired() == len(dropped)


def test_fault_corrupt_discards_update_but_spends_uplink():
    plan = FaultPlan([FaultSpec(kind="corrupt_payload", device_id="4",
                                round=0, op="train")])
    base = fleetsim.FleetSim.from_learner(
        FederatedLearner(tiny_config()), chunk_size=8)
    rec0 = base.run_round()
    fs = fleetsim.FleetSim.from_learner(
        FederatedLearner(tiny_config()), chunk_size=8, fault_plan=plan)
    rec1 = fs.run_round()
    assert rec1["corrupted"] == 1
    assert rec1["completed"] == rec0["completed"] - 1
    # The corrupted device still uploaded (CRC-reject happens AFTER the
    # bytes are spent); a dropped device would not have.
    assert rec1["bytes_up_est"] == rec0["bytes_up_est"]
    assert rec1["clients_trained"] == rec0["clients_trained"]


def test_fault_delay_cuts_step_budget_to_incomplete():
    # Losing the whole round deadline -> zero budget -> straggler that
    # never completes; it reports (uplink spent) but carries no weight.
    plan = FaultPlan([FaultSpec(kind="delay", device_id="1", round=0,
                                op="train", ms=1000.0)])
    base = fleetsim.FleetSim.from_learner(
        FederatedLearner(tiny_config()), chunk_size=8)
    rec0 = base.run_round()
    fs = fleetsim.FleetSim.from_learner(
        FederatedLearner(tiny_config()), chunk_size=8, fault_plan=plan,
        round_deadline_ms=1000.0)
    rec1 = fs.run_round()
    assert rec1["straggled"] == 1
    assert rec1["completed"] == rec0["completed"] - 1
    assert rec1["bytes_up_est"] == rec0["bytes_up_est"]


# ------------------------------------------------- population-mode rounds --
def test_population_mode_trains_and_counts_bytes():
    reg = telemetry.get_registry()
    before_rounds = reg.counter("fleetsim.rounds_total").value
    before_clients = reg.counter("fleetsim.clients_trained_total").value
    fs = make_fleet(num_devices=256, cohort=64, chunk=32)
    hist = fs.fit(4)
    assert len(hist) == 4
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    for rec in hist:
        assert rec["cohort"] == 64
        assert rec["bytes_down_est"] == 64 * fs.down_frame_bytes
        assert rec["bytes_up_est"] == 64 * fs.up_frame_bytes
        assert 0.0 < rec["available_fraction"] <= 1.0
    assert reg.counter("fleetsim.rounds_total").value == before_rounds + 4
    assert (reg.counter("fleetsim.clients_trained_total").value
            == before_clients + 4 * 64)


def test_chunk_size_does_not_change_population_mode_result():
    a = make_fleet(num_devices=128, cohort=48, chunk=48)
    b = make_fleet(num_devices=128, cohort=48, chunk=7)
    a.fit(2)
    b.fit(2)
    assert max_param_diff(a.server_state.params,
                          b.server_state.params) <= 1e-5


def test_compressed_schemes_shrink_byte_estimates():
    spec = fleetsim.PopulationSpec(num_devices=64, feature_dim=16,
                                   shard_capacity=16, min_examples=4)
    pop = fleetsim.DevicePopulation(spec)
    tm = fleetsim.TrafficModel(
        fleetsim.TrafficSpec(base_rate=2000.0, diurnal_amplitude=0.0), 64)
    plain = fleetsim.FleetSim.from_population(
        fleet_config(), pop, tm, cohort_size=16, chunk_size=16)
    packed = fleetsim.FleetSim.from_population(
        fleet_config(compress="int8", compress_down="topk"), pop, tm,
        cohort_size=16, chunk_size=16)
    assert packed.up_frame_bytes < plain.up_frame_bytes
    assert packed.down_frame_bytes < plain.down_frame_bytes
    assert plain.down_frame_bytes == plain.down_full_bytes


def test_fleetsim_rejects_engine_only_configs():
    fs_args = dict(num_devices=32, cohort=8, chunk=8)
    for bad in (dict(strategy="scaffold"), dict(aggregator="median"),
                dict(dp_clip=1.0), dict(secure_agg=True)):
        spec = fleetsim.PopulationSpec(num_devices=32, feature_dim=8,
                                       shard_capacity=8, min_examples=2)
        with pytest.raises(NotImplementedError):
            fleetsim.FleetSim.from_population(
                fleet_config(**bad), fleetsim.DevicePopulation(spec),
                fleetsim.TrafficModel(fleetsim.TrafficSpec(), 32),
                cohort_size=fs_args["cohort"], chunk_size=fs_args["chunk"])


def test_all_fleetsim_metrics_are_cataloged():
    for name in ("fleetsim.rounds_total", "fleetsim.clients_trained_total",
                 "fleetsim.bytes_up_est_total",
                 "fleetsim.bytes_down_est_total", "fleetsim.devices",
                 "fleetsim.chunk_size", "fleetsim.available_fraction",
                 "fleetsim.round_time_s"):
        assert metric_catalog.is_known(name), name


# --------------------------------------------------------- CLI and bench --
def test_cli_fleetsim_smoke(capsys):
    from colearn_federated_learning_tpu.cli import main as cli_main

    rc = cli_main(["fleetsim", "--devices", "128", "--cohort", "32",
                   "--rounds", "2", "--chunk", "16", "--feature-dim", "8",
                   "--capacity", "8", "--hidden-dim", "16", "--depth", "1",
                   "--local-steps", "2", "--batch-size", "4"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["rounds"] == 2
    assert summary["clients_trained"] == 64
    assert summary["clients_per_sec"] > 0
    assert summary["bytes_up_per_round"] > 0


def test_bench_fleet_writes_schema_valid_jsonl(tmp_path):
    out = tmp_path / "fleet_bench.jsonl"
    proc = subprocess.run(
        [sys.executable, "scripts/bench_fleet.py", "--cohorts", "32",
         "--rounds", "1", "--chunk", "16", "--check-schema",
         "--out", str(out)],
        capture_output=True, text=True, timeout=240,
        cwd=str(__import__("pathlib").Path(__file__).resolve().parents[1]),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    assert rows and rows[0]["cohort"] == 32
    assert rows[0]["clients_per_sec"] > 0
    assert rows[0]["bytes_up_per_round"] > 0


# -------------------------------------------------- compile invariant --
def test_fleetsim_one_compile_per_sweep():
    """The pad-to-fixed-width contract, machine-checked: a multi-round,
    multi-chunk sweep (ragged tail chunks AND availability-varying
    cohorts included) holds exactly ONE compiled signature per jitted
    executable.  A second chunk signature means the zero-padding broke
    and every ragged cohort would pay a recompile at fleet scale."""
    fs = make_fleet(num_devices=128, cohort=48, chunk=16)
    fs.fit(2)
    assert fs.compile_counts == {"chunk": 1, "finish": 1, "fold": 1}
    assert fs._chunk_fn.recompiles == 0


def test_cli_fleetsim_reports_compile_counts(capsys):
    from colearn_federated_learning_tpu.cli import main as cli_main

    rc = cli_main(["fleetsim", "--devices", "64", "--cohort", "24",
                   "--rounds", "2", "--chunk", "8", "--feature-dim", "8",
                   "--capacity", "8", "--hidden-dim", "16", "--depth", "1",
                   "--local-steps", "2", "--batch-size", "4"])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["compiles"]["chunk"] == 1
    assert summary["compiles"]["finish"] == 1


# --------------------------------------------------------- buffered async --
def test_fit_async_converges_with_one_compile_per_shape():
    reg = telemetry.get_registry()
    before_aggs = reg.counter("fleetsim.async_aggregations_total").value
    fs = make_fleet(num_devices=32, cohort=8, chunk=8)
    hist = fs.fit_async(10, buffer_size=8, max_staleness=8)
    assert len(hist) == 10
    # Versions advance by exactly one per aggregation — the WAL/monotone
    # invariant the chaos gate checks on the socket plane.
    assert [r["model_version"] for r in hist] == list(range(1, 11))
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    for rec in hist:
        assert rec["contributors"] == 8 == rec["buffer_size"]
        assert 0 <= rec["staleness_mean"] <= rec["staleness_max"] <= 8
        assert rec["sim_time_min"] > 0
        # Pruning is off: the feature-gated keys must be ABSENT so the
        # default record schema is byte-identical.
        assert "pruned" not in rec and "pruned_total" not in rec
    # Pad-to-chunk keeps the jitted trio at one compile each.
    assert fs.compile_counts == {"chunk": 1, "finish": 1, "fold": 1}
    assert (reg.counter("fleetsim.async_aggregations_total").value
            == before_aggs + 10)


def test_fit_async_staleness_discard_and_pruning_cut_waste():
    # Same seeded fleet twice: the 5% chronic stragglers (20x service
    # time) blow past max_staleness every flight, so the unpruned run
    # keeps folding money into discarded updates; pruning pauses them
    # after the first discard and probation keeps them out.
    runs = {}
    for label, prune_after in (("unpruned", 0), ("pruned", 1)):
        fs = make_fleet(num_devices=32, cohort=8, chunk=8)
        hist = fs.fit_async(30, buffer_size=8, max_staleness=6,
                            prune_after=prune_after, probation=30,
                            straggler_fraction=0.25,
                            straggler_multiplier=4.0)
        runs[label] = hist
    wasted_un = runs["unpruned"][-1]["wasted_updates_total"]
    wasted_pr = runs["pruned"][-1]["wasted_updates_total"]
    assert wasted_un > 0, "straggler population produced no discards"
    assert wasted_pr < wasted_un
    assert runs["pruned"][-1]["pruned_total"] >= 1
    # Pruned-run records carry the feature-gated keys.
    assert all("pruned" in r and "pruned_total" in r for r in runs["pruned"])
    # Equal-quality gate (loose tier-1 flavor of the bench sentinel).
    import math
    for hist in runs.values():
        assert math.isfinite(hist[-1]["train_loss"])


def test_fit_async_validates_inputs():
    fs = make_fleet(num_devices=16, cohort=8, chunk=8)
    with pytest.raises(ValueError, match="buffer"):
        fs.fit_async(2, buffer_size=0)
    with pytest.raises(ValueError, match="buffer"):
        fs.fit_async(2, buffer_size=17)   # > num_devices
    learner = FederatedLearner(tiny_config())
    from_learner = fleetsim.FleetSim.from_learner(learner, chunk_size=4)
    with pytest.raises(NotImplementedError, match="traffic"):
        from_learner.fit_async(2, buffer_size=2)
    # The version-grouped fold pads each group to one chunk dispatch, so
    # a buffer wider than the chunk can never fold in one program.
    narrow = make_fleet(num_devices=32, cohort=8, chunk=4)
    with pytest.raises(ValueError, match="buffer"):
        narrow.fit_async(2, buffer_size=8)
    with pytest.raises(ValueError, match="auto"):
        fs.fit_async(2, buffer_size="adaptive")


def test_fit_async_observe_stamps_observatory_keys():
    fs = make_fleet(num_devices=32, cohort=8, chunk=8)
    hist = fs.fit_async(6, buffer_size=8, max_staleness=8, observe=True)
    for rec in hist:
        # Staleness tail + contribution mass + EWMA arrival rate ride
        # along only when the observatory is armed.
        assert rec["mass_folded"] > 0.0
        assert rec["mass_discarded"] >= 0.0
        assert rec["arrival_rate_ewma_per_min"] >= 0.0
        assert (rec["staleness_p50"] <= rec["staleness_p90"]
                <= rec["staleness_p99"])
    # Compile-once must survive the extra bookkeeping.
    assert fs.compile_counts == {"chunk": 1, "finish": 1, "fold": 1}


def test_fit_async_auto_buffer_sizes_from_arrival_rate():
    reg = telemetry.get_registry()
    fs = make_fleet(num_devices=32, cohort=8, chunk=8)
    hist = fs.fit_async(10, buffer_size="auto", max_staleness=8,
                        auto_interval_min=2.0)
    assert len(hist) == 10
    for rec in hist:
        # Auto-K stays inside the only legal band: at least 1, never
        # wider than the compiled chunk.
        assert 1 <= rec["buffer_size"] <= 8
        # auto implies observe: the records carry the measurements that
        # drove the sizing.
        assert "arrival_rate_ewma_per_min" in rec
    # The controller actually resized at least once off the warm-start
    # K=8 (2-minute target x observed rate lands away from 8).
    assert len({rec["buffer_size"] for rec in hist}) > 1
    assert reg.gauge("fleetsim.async_buffer_size").value == \
        hist[-1]["buffer_size"]
    # One compile per shape still holds across resizes: the fold pads
    # every group to chunk_size regardless of K.
    assert fs.compile_counts == {"chunk": 1, "finish": 1, "fold": 1}
