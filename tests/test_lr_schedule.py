"""Round-level client-lr schedules (fed/strategies.lr_scale_for_round +
the lr_scale operand threaded through fed/local.py).

Round 3's text configs ran constant lr and were cut off mid-climb; the
schedule gives warmup (transformer-client stability) and cosine decay
(plateau) without retracing — the factor is computed in-graph from the
round operand.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.fed import local as local_lib
from colearn_federated_learning_tpu.fed import strategies
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _fed(**kw):
    base = dict(strategy="fedavg", rounds=20, cohort_size=0, local_steps=4,
                batch_size=16, lr=0.1, momentum=0.9)
    base.update(kw)
    return FedConfig(**base)


def test_schedule_math_oracle():
    cfg = _fed(lr_schedule="cosine", rounds=10, lr_min_fraction=0.1)
    # Round 0 starts at 1; the far end sits at the floor.
    assert float(strategies.lr_scale_for_round(cfg, 0)) == pytest.approx(1.0)
    assert float(strategies.lr_scale_for_round(cfg, 10)) == pytest.approx(0.1)
    assert float(strategies.lr_scale_for_round(cfg, 999)) == pytest.approx(0.1)
    # Midpoint of the half-cosine: floor + (1-floor)/2.
    assert float(strategies.lr_scale_for_round(cfg, 5)) == pytest.approx(0.55)

    w = _fed(lr_schedule="warmup_cosine", rounds=12, warmup_rounds=4)
    # Linear ramp (r+1)/warmup — round 0 trains at 1/4, never 0.
    got = [float(strategies.lr_scale_for_round(w, r)) for r in range(4)]
    np.testing.assert_allclose(got, [0.25, 0.5, 0.75, 1.0])
    # Cosine leg spans the remaining 8 rounds down to 0.
    assert float(strategies.lr_scale_for_round(w, 12)) == pytest.approx(0.0)
    assert float(strategies.lr_scale_for_round(w, 8)) == pytest.approx(0.5)

    # Constant returns None so the scaling branch compiles away.
    assert strategies.lr_scale_for_round(_fed(), 7) is None

    with pytest.raises(ValueError, match="lr_schedule"):
        strategies.lr_scale_for_round(_fed(lr_schedule="linear"), 0)


@pytest.mark.parametrize("opt,momentum", [("sgd", 0.9), ("sgd", 0.0),
                                          ("adam", 0.0)])
def test_lr_scale_equals_scaled_lr(opt, momentum):
    # The scheduled path (lr, scale=s) must reproduce the direct path
    # (lr*s, no scale) EXACTLY: for SGD the momentum buffer is
    # lr-independent, for Adam the update is proportional to lr.
    import flax.linen as nn
    import jax

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(3)(x.reshape((x.shape[0], -1)))

    model = Tiny()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, 5)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 3, 32))
    params = model.init(jax.random.PRNGKey(0), x[:1])["params"]
    key = jax.random.PRNGKey(7)
    s = 0.37

    def run(lr, scale):
        fn = local_lib.make_local_update(
            model.apply, local_lib.make_optimizer(lr, momentum, opt),
            num_steps=6, batch_size=8,
        )
        return fn(params, x, y, jnp.asarray(32), key,
                  jnp.asarray(6, jnp.int32),
                  None if scale is None else jnp.float32(scale))

    a = run(0.1, s)
    b = run(0.1 * s, None)
    for la, lb in zip(jax.tree.leaves(a.delta), jax.tree.leaves(b.delta)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   atol=1e-6)


def _cfg(**fed_kw):
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=64),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=_fed(**fed_kw),
        run=RunConfig(name="sched_test"),
    )


def test_engine_trains_with_schedule_and_decays():
    learner = FederatedLearner(_cfg(lr_schedule="warmup_cosine",
                                    warmup_rounds=2, rounds=8))
    learner.fit(rounds=8)
    _, acc = learner.evaluate()
    assert acc > 0.9, acc

    # The factor must actually shrink late-round updates: by round 7 of
    # an 8-round cosine the scale is ~0.04, so the per-round update-norm
    # telemetry must sit far below the constant-lr run's.
    const = FederatedLearner(_cfg())
    sched = FederatedLearner(_cfg(lr_schedule="cosine", rounds=8,
                                  lr_min_fraction=0.0))
    for _ in range(8):
        rec_c = const.run_round()
        rec_s = sched.run_round()
    assert rec_s["delta_norm_mean"] < 0.3 * rec_c["delta_norm_mean"], (
        rec_s["delta_norm_mean"], rec_c["delta_norm_mean"])


def test_scaffold_schedule_round_runs():
    cfg = _cfg(strategy="scaffold", momentum=0.0, lr_schedule="warmup_cosine",
               warmup_rounds=2, rounds=6)
    learner = FederatedLearner(cfg)
    learner.fit(rounds=6)
    _, acc = learner.evaluate()
    assert acc > 0.8, acc
