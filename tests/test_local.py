"""Local trainer: loss decreases, straggler budgets mask updates, FedProx pulls."""

import jax
import jax.numpy as jnp
import numpy as np

from colearn_federated_learning_tpu.fed import local as local_lib
from colearn_federated_learning_tpu.models.mlp import MLP
from colearn_federated_learning_tpu.utils import prng, pytrees


def _toy_problem(seed=0, n=128, d=8, k=3):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(d, k))
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.normal(size=(n, k)), axis=1).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def _setup(num_steps=20, prox_mu=0.0, lr=0.1):
    model = MLP(num_classes=3, hidden_dim=16, depth=1)
    x, y = _toy_problem()
    params = model.init(jax.random.PRNGKey(0), x[:4])["params"]
    opt = local_lib.make_optimizer(lr, 0.9)
    update = local_lib.make_local_update(
        model.apply, opt, num_steps=num_steps, batch_size=16, prox_mu=prox_mu
    )
    return model, params, x, y, update


def test_local_update_learns():
    model, params, x, y, update = _setup()
    key = prng.client_round_key(prng.experiment_key(0), 0, 0)
    res = update(params, x, y, jnp.asarray(len(x)), key, jnp.asarray(20))
    assert bool(res.completed)
    assert int(res.num_examples) == 128
    # Moved away from init, and the last steps beat the first steps.
    assert float(pytrees.tree_global_norm(res.delta)) > 0.0

    logits0 = model.apply({"params": params}, x)
    p1 = jax.tree.map(lambda a, b: a + b, params, res.delta)
    logits1 = model.apply({"params": p1}, x)
    acc0 = float((jnp.argmax(logits0, -1) == y).mean())
    acc1 = float((jnp.argmax(logits1, -1) == y).mean())
    assert acc1 > acc0


def test_zero_budget_is_noop_and_incomplete():
    _, params, x, y, update = _setup()
    key = prng.experiment_key(1)
    res = update(params, x, y, jnp.asarray(len(x)), key, jnp.asarray(0))
    assert float(pytrees.tree_global_norm(res.delta)) == 0.0
    assert not bool(res.completed)


def test_partial_budget_partial_progress():
    _, params, x, y, update = _setup(num_steps=20)
    key = prng.experiment_key(2)
    res_full = update(params, x, y, jnp.asarray(len(x)), key, jnp.asarray(20))
    res_half = update(params, x, y, jnp.asarray(len(x)), key, jnp.asarray(10))
    n_full = float(pytrees.tree_global_norm(res_full.delta))
    n_half = float(pytrees.tree_global_norm(res_half.delta))
    assert 0.0 < n_half < n_full
    assert bool(res_half.completed)  # 10 >= 25% of 20


def test_fedprox_term_shrinks_delta():
    _, params, x, y, update0 = _setup(prox_mu=0.0)
    _, _, _, _, update_prox = _setup(prox_mu=10.0)
    key = prng.experiment_key(3)
    d0 = update0(params, x, y, jnp.asarray(len(x)), key, jnp.asarray(20)).delta
    dp = update_prox(params, x, y, jnp.asarray(len(x)), key, jnp.asarray(20)).delta
    assert float(pytrees.tree_global_norm(dp)) < float(pytrees.tree_global_norm(d0))


def test_vmap_over_clients_matches_single():
    _, params, x, y, update = _setup()
    key0 = prng.client_round_key(prng.experiment_key(0), 0, 0)
    key1 = prng.client_round_key(prng.experiment_key(0), 1, 0)
    xs = jnp.stack([x, x * 0.5])
    ys = jnp.stack([y, y])
    counts = jnp.asarray([128, 128])
    keys = jnp.stack([key0, key1])
    budgets = jnp.asarray([20, 20])
    batched = jax.vmap(update, in_axes=(None, 0, 0, 0, 0, 0))(
        params, xs, ys, counts, keys, budgets
    )
    single = update(params, x, y, jnp.asarray(128), key0, jnp.asarray(20))
    for a, b in zip(jax.tree.leaves(batched.delta), jax.tree.leaves(single.delta)):
        np.testing.assert_allclose(np.asarray(a[0]), np.asarray(b), rtol=2e-4, atol=1e-5)
