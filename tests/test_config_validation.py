"""Config-validation guards (utils/config.validate_experiment).

Pins VERDICT r4 weak #5: ``attn_impl="flash"`` below the measured
dense/flash crossover (~L=1k, PERF.md §1b) is a user footgun — dense is
faster there — so construction warns.  The warning must fire exactly for
the below-crossover case and stay silent for dense and for long sequences,
and it must be a WARNING, not an error: the combination executes correctly
(a kernel benchmark needs to be able to run it).
"""

import dataclasses
import warnings

import pytest

from colearn_federated_learning_tpu.utils.config import (
    FLASH_SEQ_CROSSOVER,
    ModelConfig,
    get_config,
    validate_experiment,
)


def _bert_cfg(**model_kw):
    cfg = get_config("agnews_bert_fedavg")
    return cfg.replace(model=dataclasses.replace(cfg.model, **model_kw))


def test_flash_below_crossover_warns():
    cfg = _bert_cfg(attn_impl="flash", seq_len=128)
    with pytest.warns(UserWarning, match="dense attention is measured FASTER"):
        validate_experiment(cfg)


def test_flash_at_or_above_crossover_silent():
    cfg = _bert_cfg(attn_impl="flash", seq_len=FLASH_SEQ_CROSSOVER)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        validate_experiment(cfg)


def test_dense_short_seq_silent():
    cfg = _bert_cfg(attn_impl="dense", seq_len=128)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        validate_experiment(cfg)


def test_engine_init_routes_through_validation():
    # The guard must fire on the real construction path, not only when
    # called directly — a tiny MLP run with a flash-flagged model config.
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner

    cfg = get_config("mnist_mlp_fedavg")
    cfg = cfg.replace(
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=8, depth=1,
                          attn_impl="flash", seq_len=128),
        data=dataclasses.replace(cfg.data, num_clients=2,
                                 max_examples_per_client=16),
    )
    with pytest.warns(UserWarning, match="dense attention is measured FASTER"):
        FederatedLearner.from_config(cfg)
