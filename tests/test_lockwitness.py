"""faults/lockwitness: off-mode zero-cost passthrough, seeded
lock-order inversion detection, unguarded-access witnesses on guarded
structures, Condition held-stack truthfulness across wait(), the
per-pid JSON dump, and the procsoak report collectors."""

import json
import threading

import pytest

from colearn_federated_learning_tpu.faults import lockwitness, procsoak


@pytest.fixture
def witness_on(monkeypatch):
    monkeypatch.setenv("COLEARN_LOCK_WITNESS", "1")
    monkeypatch.delenv("COLEARN_LOCK_WITNESS_DIR", raising=False)
    lockwitness.reset()
    yield
    lockwitness.reset()


# ----------------------------------------------------------------- off --
def test_off_mode_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv("COLEARN_LOCK_WITNESS", raising=False)
    assert not isinstance(lockwitness.lock("x"), lockwitness.WitnessLock)
    obj = {"a": 1}
    assert lockwitness.guarded(obj, "t", lockwitness.lock("x")) is obj
    assert lockwitness.report() == {"enabled": False}


# ----------------------------------------------------------- inversion --
def test_seeded_inversion_is_witnessed(witness_on):
    a = lockwitness.lock("A")
    b = lockwitness.lock("B")
    with a:
        with b:
            pass                       # establishes A -> B
    with b:
        with a:                        # B -> A closes the ring
            pass
    rep = lockwitness.report()
    assert rep["edges"] == ["A->B", "B->A"]
    assert len(rep["inversions"]) == 1
    assert rep["inversions"][0]["edge"] == ["B", "A"]


def test_inversion_witnessed_even_when_acquire_times_out(witness_on):
    # The deadlock-shaped case: the second acquire BLOCKS (and here
    # times out) — the attempt alone must record the inversion, since a
    # real deadlock never reaches on_acquired.
    a = lockwitness.lock("A")
    b = lockwitness.lock("B")
    with a:
        with b:
            pass
    holder = threading.Event()
    release = threading.Event()

    def hold_a():
        with a:
            holder.set()
            release.wait(5.0)

    t = threading.Thread(target=hold_a)
    t.start()
    assert holder.wait(5.0)
    with b:
        got = a.acquire(timeout=0.05)
        assert not got
    release.set()
    t.join(5.0)
    rep = lockwitness.report()
    assert len(rep["inversions"]) == 1


def test_consistent_order_records_no_inversion(witness_on):
    a = lockwitness.lock("A")
    b = lockwitness.lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    rep = lockwitness.report()
    assert rep["edges"] == ["A->B"]
    assert rep["inversions"] == []
    assert rep["acquires"] == 6


# ------------------------------------------------------------- guarded --
def test_guarded_dict_stamps_unguarded_access(witness_on):
    lk = lockwitness.lock("G")
    d = lockwitness.guarded({}, "t._d", lk)
    with lk:
        d["a"] = 1                     # guarded: clean
    assert lockwitness.report()["unguarded"] == []
    d["b"] = 2                         # bare: witnessed
    for _ in d:
        pass
    rep = lockwitness.report()
    ops = [u["op"] for u in rep["unguarded"]]
    assert ops == ["setitem", "iter"]
    assert all(u["structure"] == "t._d" for u in rep["unguarded"])
    # the stamp names the CALLER site, not the wrapper internals
    assert "test_lockwitness.py" in rep["unguarded"][0]["site"]


def test_guarded_set_and_list(witness_on):
    lk = lockwitness.lock("G")
    s = lockwitness.guarded(set(), "t._s", lk)
    xs = lockwitness.guarded([], "t._l", lk)
    with lk:
        s.add(1)
        xs.append(2)
    assert lockwitness.report()["unguarded"] == []
    s.add(3)
    xs.append(4)
    assert len(lockwitness.report()["unguarded"]) == 2


def test_condition_wait_keeps_held_stack_truthful(witness_on):
    cv = lockwitness.condition("CV")
    other = lockwitness.lock("L")
    fired = []

    def notifier():
        with cv:
            fired.append(True)
            cv.notify()

    with cv:
        t = threading.Timer(0.05, notifier)
        t.start()
        # wait() releases the witnessed lock through _release_save: if
        # the held stack went stale the notifier's acquire would record
        # a bogus CV -> CV edge or deadlock; it must just succeed.
        assert cv.wait_for(lambda: fired, timeout=5.0)
        with other:                    # edge CV -> L, no inversion
            pass
    rep = lockwitness.report()
    assert rep["inversions"] == []
    assert "CV->L" in rep["edges"]


# ---------------------------------------------------------------- dump --
def test_atexit_dump_writes_per_pid_json(witness_on, monkeypatch, tmp_path):
    out = tmp_path / "lw"
    monkeypatch.setenv("COLEARN_LOCK_WITNESS_DIR", str(out))
    lockwitness.reset()
    a = lockwitness.lock("A")
    with a:
        pass
    lockwitness._WITNESS._dump()
    (path,) = sorted(out.glob("lockwitness-*.json"))
    doc = json.loads(path.read_text())
    assert doc["acquires"] == 1 and doc["inversions"] == []


# ------------------------------------------------- procsoak collectors --
def _fake_report(**over):
    doc = {"enabled": True, "pid": 1, "acquires": 10, "guarded_ops": 5,
           "edges": [], "inversions": [], "unguarded": []}
    doc.update(over)
    return doc


def test_collect_lockwitness_aggregates_and_skips_garbage(tmp_path):
    d = tmp_path / "lockwitness"
    d.mkdir()
    (d / "lockwitness-1.json").write_text(json.dumps(_fake_report()))
    (d / "lockwitness-2.json").write_text(json.dumps(_fake_report(
        pid=2, inversions=[{"edge": ["B", "A"]}],
        unguarded=[{"structure": "x", "op": "iter"}])))
    (d / "lockwitness-3.json").write_text("{not json")
    (d / "flight-9.json").write_text("{}")      # foreign file: ignored
    lw = procsoak._collect_lockwitness(str(d))
    assert lw["enabled"] and lw["reports"] == 2
    assert lw["reports_unparseable"] == 1
    assert lw["acquires"] == 20 and lw["guarded_ops"] == 10
    assert lw["inversions"] == 1 and lw["unguarded"] == 1
    assert lw["inversion_records"] == [{"edge": ["B", "A"]}]


def test_collect_lockwitness_missing_dir(tmp_path):
    lw = procsoak._collect_lockwitness(str(tmp_path / "nope"))
    assert lw["enabled"] and lw["reports"] == 0 and lw["inversions"] == 0


def test_merge_lockwitness():
    off = procsoak._merge_lockwitness({"enabled": False},
                                      {"enabled": False})
    assert off == {"enabled": False}
    merged = procsoak._merge_lockwitness(
        {"enabled": True, "reports": 4, "acquires": 100, "guarded_ops": 7,
         "inversions": 1, "unguarded": 0,
         "inversion_records": [{"edge": ["B", "A"]}],
         "unguarded_records": []},
        {"enabled": False},
        {"enabled": True, "reports": 4, "acquires": 50, "guarded_ops": 3,
         "inversions": 0, "unguarded": 2, "inversion_records": [],
         "unguarded_records": [{"op": "iter"}, {"op": "pop"}]})
    assert merged["reports"] == 8 and merged["acquires"] == 150
    assert merged["inversions"] == 1 and merged["unguarded"] == 2
    assert len(merged["unguarded_records"]) == 2
