"""analysis/sentinel.py: declarative SLO rules over results JSONL —
rule validation, where-filters, aggregation bounds, missing-data
semantics, reorder stability (S4 wire-format contract), the torn-tail
JSONL loader, and the `colearn sentinel` CLI gate exiting non-zero on an
injected rounds/sec regression."""

import json

import pytest

from colearn_federated_learning_tpu.analysis import sentinel
from colearn_federated_learning_tpu.cli import main as cli_main


def write_rows(path, rows):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))


def write_rules(root, rules_toml):
    (root / "pyproject.toml").write_text(
        "[tool.colearn.slo]\n" + rules_toml)


FLEET_ROWS = [
    {"bench": "fleet_round", "devices": 1000, "rounds_per_sec": 27.7},
    {"bench": "fleet_round", "devices": 1000000, "rounds_per_sec": 0.022},
    {"bench": "fleet_round", "devices": 1000000, "rounds_per_sec": 0.031},
]


# ----------------------------------------------------------- rule shape --
def test_rule_validation_rejects_bad_tables():
    with pytest.raises(ValueError, match="order-independent"):
        sentinel.SloRule(id="r", file="f", field="x", agg="last", min=0)
    with pytest.raises(ValueError, match="min and/or max"):
        sentinel.SloRule(id="r", file="f", field="x", agg="min")
    with pytest.raises(ValueError, match="needs a field"):
        sentinel.SloRule(id="r", file="f", agg="mean", min=0)
    with pytest.raises(ValueError, match="unknown keys"):
        sentinel.SloRule.from_table(
            {"id": "r", "file": "f", "field": "x", "min": 0,
             "threshold": 1})


def test_duplicate_rule_ids_rejected(tmp_path):
    write_rules(tmp_path, """
[[tool.colearn.slo.rules]]
id = "dup"
file = "results/a.jsonl"
field = "x"
min = 0

[[tool.colearn.slo.rules]]
id = "dup"
file = "results/b.jsonl"
field = "x"
min = 0
""")
    with pytest.raises(ValueError, match="duplicate"):
        sentinel.load_rules(str(tmp_path))


# ----------------------------------------------------------- evaluation --
def test_where_filter_and_min_bound(tmp_path):
    write_rows(tmp_path / "results" / "fleet.jsonl", FLEET_ROWS)
    rule = sentinel.SloRule(
        id="r", file="results/fleet.jsonl", field="rounds_per_sec",
        agg="min", where={"devices": 1000000}, min=0.01)
    res = rule.evaluate(str(tmp_path))
    assert res["ok"] and res["rows"] == 2 and res["value"] == 0.022


def test_violation_reports_reason(tmp_path):
    write_rows(tmp_path / "results" / "fleet.jsonl", FLEET_ROWS)
    rule = sentinel.SloRule(
        id="r", file="results/fleet.jsonl", field="rounds_per_sec",
        agg="min", where={"devices": 1000000}, min=5.0)
    res = rule.evaluate(str(tmp_path))
    assert not res["ok"]
    assert res["reason"].startswith("below_min:")


def test_max_bound_and_count_agg(tmp_path):
    write_rows(tmp_path / "results" / "fleet.jsonl", FLEET_ROWS)
    over = sentinel.SloRule(
        id="hi", file="results/fleet.jsonl", field="rounds_per_sec",
        agg="max", max=10.0)
    assert not over.evaluate(str(tmp_path))["ok"]     # 27.7 > 10
    count = sentinel.SloRule(
        id="n", file="results/fleet.jsonl", agg="count",
        where={"devices": 1000000}, min=2)
    assert count.evaluate(str(tmp_path))["ok"]


def test_missing_file_and_rows_are_violations_unless_allowed(tmp_path):
    rule = sentinel.SloRule(
        id="r", file="results/nope.jsonl", field="x", min=0)
    res = rule.evaluate(str(tmp_path))
    assert not res["ok"] and res["reason"] == "file_missing"
    allowed = sentinel.SloRule(
        id="r", file="results/nope.jsonl", field="x", min=0,
        allow_missing=True)
    assert allowed.evaluate(str(tmp_path))["ok"]
    write_rows(tmp_path / "results" / "fleet.jsonl", FLEET_ROWS)
    nomatch = sentinel.SloRule(
        id="r", file="results/fleet.jsonl", field="rounds_per_sec",
        where={"devices": 7}, min=0)
    assert nomatch.evaluate(str(tmp_path))["reason"] == "no_matching_rows"


def test_empty_rule_set_is_not_a_green_verdict(tmp_path):
    verdict = sentinel.evaluate_slo(str(tmp_path))
    assert verdict["rules"] == 0
    assert not verdict["ok"]          # fake green forbidden


def test_verdict_is_stable_under_row_reordering(tmp_path):
    """S4: every offered aggregation is order-independent, so merging
    shards or appending re-runs in any order must produce byte-identical
    rule results."""
    rules = [
        sentinel.SloRule(id="lo", file="results/f.jsonl",
                         field="rounds_per_sec", agg="min",
                         where={"devices": 1000000}, min=0.01),
        sentinel.SloRule(id="mean", file="results/f.jsonl",
                         field="rounds_per_sec", agg="mean", max=50.0),
        sentinel.SloRule(id="n", file="results/f.jsonl", agg="count",
                         min=3),
    ]
    write_rows(tmp_path / "results" / "f.jsonl", FLEET_ROWS)
    forward = sentinel.evaluate_slo(str(tmp_path), rules=rules)
    write_rows(tmp_path / "results" / "f.jsonl", FLEET_ROWS[::-1])
    backward = sentinel.evaluate_slo(str(tmp_path), rules=rules)
    assert forward["results"] == backward["results"]
    assert forward["ok"] and backward["ok"]


# -------------------------------------------------------------- loading --
def test_jsonl_loader_tolerates_torn_tail_only(tmp_path):
    p = tmp_path / "rows.jsonl"
    p.write_text('{"a": 1}\n{"a": 2}\n{"a": 3, "tru')
    assert [r["a"] for r in sentinel.load_jsonl_rows(str(p))] == [1, 2]
    p.write_text('{"a": 1}\n{"a": 2, "tru\n{"a": 3}\n')
    with pytest.raises(ValueError, match="corrupt JSONL"):
        sentinel.load_jsonl_rows(str(p))


def test_load_rules_from_pyproject(tmp_path):
    write_rules(tmp_path, """
[[tool.colearn.slo.rules]]
id = "fleet"
file = "results/f.jsonl"
where = { devices = 1000000 }
field = "rounds_per_sec"
agg = "min"
min = 0.01
""")
    rules = sentinel.load_rules(str(tmp_path))
    assert len(rules) == 1
    assert rules[0].where == {"devices": 1000000}


# ------------------------------------------------------------ CLI gate --
def test_cli_sentinel_fails_on_injected_regression(tmp_path, capsys):
    """The acceptance fixture: a committed rounds/sec that regressed
    below the SLO floor must exit non-zero (and say why)."""
    write_rules(tmp_path, """
[[tool.colearn.slo.rules]]
id = "fleet-1m-round-rate"
file = "results/fleet_bench.jsonl"
where = { devices = 1000000 }
field = "rounds_per_sec"
agg = "min"
min = 0.01
""")
    write_rows(tmp_path / "results" / "fleet_bench.jsonl",
               [{"devices": 1000000, "rounds_per_sec": 0.002}])
    rc = cli_main(["sentinel", "--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "below_min" in out and "VIOLATION" in out

    # Fix the regression: same rules, healthy number, exit 0.
    write_rows(tmp_path / "results" / "fleet_bench.jsonl",
               [{"devices": 1000000, "rounds_per_sec": 0.03}])
    assert cli_main(["sentinel", "--root", str(tmp_path)]) == 0


def test_cli_sentinel_json_verdict(tmp_path, capsys):
    write_rules(tmp_path, """
[[tool.colearn.slo.rules]]
id = "n"
file = "results/f.jsonl"
agg = "count"
min = 1
""")
    write_rows(tmp_path / "results" / "f.jsonl", [{"x": 1}])
    rc = cli_main(["sentinel", "--root", str(tmp_path),
                   "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["schema"] == "colearn-slo-verdict-v1"
    assert doc["ok"] and doc["rules"] == 1


def test_repo_slo_rules_hold_against_committed_results():
    """The CI gate itself: the repo's own [tool.colearn.slo] rules must
    pass against the committed results/*.jsonl."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rules = sentinel.load_rules(root)
    if not rules:
        pytest.skip("no tomllib/tomli available")
    verdict = sentinel.evaluate_slo(root, rules=rules)
    assert verdict["ok"], verdict["results"]


# ------------------------------------------------------- window rules --
def _round_rows(values):
    return [{"round": r, "phase_agg_fold_s": v}
            for r, v in enumerate(values)]


def test_window_rule_fires_on_injected_latency_regression(tmp_path,
                                                          capsys):
    """The tentpole acceptance fixture: 20 healthy rounds of fold
    latency, then 5 rounds at 2.5x — the trailing-p99 / baseline-median
    ratio must trip the 1.5x band, in the API and through the CLI."""
    write_rows(tmp_path / "results" / "rounds.jsonl",
               _round_rows([0.10] * 20 + [0.25] * 5))
    rule = sentinel.WindowRule(
        id="fold-p99", file="results/rounds.jsonl",
        field="phase_agg_fold_s", window=5, baseline=20,
        agg="p99", baseline_agg="median", max_ratio=1.5)
    res = rule.evaluate(str(tmp_path))
    assert not res["ok"]
    assert res["value"] == pytest.approx(2.5)
    assert res["reason"].startswith("above_max_ratio:2.5")
    assert res["window_value"] == pytest.approx(0.25)
    assert res["baseline_value"] == pytest.approx(0.10)

    write_rules(tmp_path, """
[[tool.colearn.slo.rules]]
id = "fold-p99"
file = "results/rounds.jsonl"
field = "phase_agg_fold_s"
window = 5
baseline = 20
max_ratio = 1.5
""")
    rc = cli_main(["sentinel", "--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "above_max_ratio" in out and "VIOLATION" in out


def test_window_rule_steady_state_stays_green(tmp_path):
    jitter = [0.10 + (r % 4) * 0.002 for r in range(25)]
    write_rows(tmp_path / "results" / "rounds.jsonl",
               _round_rows(jitter))
    rule = sentinel.WindowRule(
        id="fold-p99", file="results/rounds.jsonl",
        field="phase_agg_fold_s", window=5, baseline=20,
        agg="p99", baseline_agg="median", max_ratio=1.5)
    res = rule.evaluate(str(tmp_path))
    assert res["ok"] and res["reason"] is None
    assert res["value"] == pytest.approx(0.106 / 0.104)


def test_window_rule_is_stable_under_row_reordering(tmp_path):
    """Rows sort by ``order_by`` before windowing: merged shards or
    re-appended history cannot move rounds between window and baseline."""
    rows = _round_rows([0.10] * 20 + [0.25] * 5)
    rule = sentinel.WindowRule(
        id="fold-p99", file="results/rounds.jsonl",
        field="phase_agg_fold_s", window=5, baseline=20,
        agg="p99", max_ratio=1.5)
    write_rows(tmp_path / "results" / "rounds.jsonl", rows)
    forward = rule.evaluate(str(tmp_path))
    shuffled = rows[1::2] + rows[0::2][::-1]
    write_rows(tmp_path / "results" / "rounds.jsonl", shuffled)
    assert rule.evaluate(str(tmp_path)) == forward


def test_window_rule_insufficient_history(tmp_path):
    write_rows(tmp_path / "results" / "rounds.jsonl",
               _round_rows([0.1] * 10))
    rule = sentinel.WindowRule(
        id="r", file="results/rounds.jsonl", field="phase_agg_fold_s",
        window=5, baseline=20, max_ratio=1.5)
    res = rule.evaluate(str(tmp_path))
    assert not res["ok"]
    assert res["reason"] == "insufficient_rows:10<25"
    # a short clean run can opt out of strictness
    rule.allow_missing = True
    assert rule.evaluate(str(tmp_path))["ok"]


def test_window_rule_validation_and_dispatch():
    table = {"id": "w", "file": "f", "field": "x", "window": 5,
             "max_ratio": 1.5}
    assert isinstance(sentinel.rule_from_table(table),
                      sentinel.WindowRule)
    assert isinstance(
        sentinel.rule_from_table({"id": "s", "file": "f", "field": "x",
                                  "agg": "mean", "min": 0}),
        sentinel.SloRule)
    with pytest.raises(ValueError, match="max_ratio and/or min_ratio"):
        sentinel.WindowRule(id="w", file="f", field="x", window=5,
                            baseline=20)
    with pytest.raises(ValueError, match="not in"):
        sentinel.WindowRule(id="w", file="f", field="x", window=5,
                            baseline=20, agg="last", max_ratio=1.5)
    with pytest.raises(ValueError, match="unknown keys"):
        sentinel.WindowRule.from_table(
            {"id": "w", "file": "f", "field": "x", "window": 5,
             "max_ratio": 1.5, "threshold": 2})
