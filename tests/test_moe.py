"""Mixture-of-Experts family (models/moe.py) + expert parallelism.

EP is absent in the reference (SURVEY.md §2); this is the rebuild's
distributed superset: capacity-based static-shape routing, Switch aux loss
via sow, expert banks sharded over the ``model`` axis (parallel/tp.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.models.moe import MoEFfn
from colearn_federated_learning_tpu.parallel import tp as tp_lib
from colearn_federated_learning_tpu.parallel.mesh import make_mesh
from colearn_federated_learning_tpu.utils.jax_compat import (
    HAS_NATIVE_SHARD_MAP,
)
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _moe_cfg(**model_kw):
    model = dict(name="moe_bert", num_classes=4, width=32, depth=1,
                 num_heads=4, seq_len=64, vocab_size=2000, num_experts=4)
    model.update(model_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=16),
        model=ModelConfig(**model),
        fed=FedConfig(strategy="fedavg", rounds=3, cohort_size=0,
                      local_steps=2, batch_size=4, lr=0.05, momentum=0.9),
        run=RunConfig(name="moe_test"),
    )


def test_moe_forward_shape_and_aux():
    cfg = _moe_cfg()
    model = model_registry.build_model(cfg.model)
    x = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 1, 2000)
    params = model_registry.init_params(model, x, jax.random.PRNGKey(0))
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (4, 4)
    assert bool(jnp.isfinite(logits).all())

    # Training-mode apply sows one Switch aux value per MoE layer; at init
    # the router is near-uniform so the aux sits near its optimum 1.0.
    _, upd = model.apply({"params": params}, x, train=True,
                         mutable=["intermediates"])
    leaves = [
        v for p, v in jax.tree_util.tree_leaves_with_path(upd["intermediates"])
        if any(getattr(q, "key", None) == "moe_aux" for q in p)
    ]
    # GShard interleaving: depth//2 MoE blocks, except depth==1 -> 1.
    d = cfg.model.depth
    assert len(leaves) == (1 if d == 1 else d // 2)
    assert 0.9 < float(leaves[0]) < 1.5


def test_moe_capacity_limits_tokens():
    # With a tiny capacity factor most tokens are dropped (block output
    # shrinks toward zero); ample capacity routes everything.
    D, E = 16, 4
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 32, D))
    tight = MoEFfn(embed_dim=D, num_experts=E, capacity_factor=0.05)
    ample = MoEFfn(embed_dim=D, num_experts=E, capacity_factor=4.0)
    pt = tight.init(jax.random.PRNGKey(1), x)["params"]
    out_t = tight.apply({"params": pt}, x)
    out_a = ample.apply({"params": pt}, x)
    assert bool(jnp.isfinite(out_t).all()) and bool(jnp.isfinite(out_a).all())
    # Tight capacity must carry strictly less routed mass.
    assert float(jnp.abs(out_t).sum()) < 0.5 * float(jnp.abs(out_a).sum())


def test_moe_padding_tokens_excluded():
    # Padding tokens must claim no expert capacity: with exactly enough
    # capacity for the real tokens, every real token still routes (nonzero
    # output) and every pad position contributes zero.
    D, E = 16, 2
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 32, D))
    mask = jnp.arange(32)[None, :] < 16                  # half the row real
    layer = MoEFfn(embed_dim=D, num_experts=E, top_k=1, capacity_factor=1.0)
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    out = layer.apply({"params": params}, x, token_mask=mask)
    pad_out = out[0, 16:]
    assert float(jnp.abs(pad_out).max()) == 0.0
    # capacity C = N/E = 16 per expert >= 16 real tokens: none dropped even
    # if the router sends every real token to one expert.
    real_rows = jnp.abs(out[0, :16]).max(axis=-1)
    assert float(real_rows.min()) > 0.0
    # Aux statistics ignore pads: a uniform-ish router over real tokens
    # keeps the Switch loss near 1.
    _, upd = layer.apply({"params": params}, x, token_mask=mask,
                         mutable=["intermediates"])
    (aux,) = [
        v for p, v in jax.tree_util.tree_leaves_with_path(upd["intermediates"])
        if any(getattr(q, "key", None) == "moe_aux" for q in p)
    ]
    assert 0.5 < float(aux) < 2.0


def test_moe_trains_and_balances():
    learner = FederatedLearner(_moe_cfg())
    hist = learner.fit(rounds=3)
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    assert np.isfinite(learner.evaluate()[0])


@pytest.mark.skipif(
    not HAS_NATIVE_SHARD_MAP,
    reason="expert-parallel all-to-all aborts the interpreter (C++ level) "
           "under jax<0.6 experimental shard_map on the CPU backend",
)
def test_moe_expert_parallel_matches_single_device(cpu_devices):
    cfg = _moe_cfg()
    ref = FederatedLearner(cfg)
    for _ in range(2):
        ref.run_round()

    mesh = make_mesh(("clients", "model"), (4, 2), devices=cpu_devices[:8])
    ep = FederatedLearner(cfg, mesh=mesh)
    assert tp_lib.sharded_fraction(ep.params, "model", 2) > 0.8
    # Expert banks are genuinely distributed over the model axis.
    bank = ep.params["TransformerBlock_0"]["MoEFfn_0"]["experts_up"]
    assert bank.addressable_shards[0].data.shape[0] == bank.shape[0] // 2
    for _ in range(2):
        m = ep.run_round()
    assert np.isfinite(m["train_loss"])

    p1 = np.concatenate([np.ravel(np.asarray(a))
                         for a in jax.tree.leaves(ep.server_state.params)])
    p2 = np.concatenate([np.ravel(np.asarray(a))
                         for a in jax.tree.leaves(ref.server_state.params)])
    np.testing.assert_allclose(p1, p2, atol=2e-6)
