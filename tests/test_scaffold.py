"""SCAFFOLD: control-variate algebra, engine integration (vmap + mesh),
and the drift-correction property it exists for."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from colearn_federated_learning_tpu.fed import local as local_lib
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils import pytrees
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cfg(strategy="scaffold", num_clients=8, cohort=4, alpha=0.05, seed=0):
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition="dirichlet", dirichlet_alpha=alpha),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(strategy=strategy, rounds=10, cohort_size=cohort,
                      local_steps=5, batch_size=16, lr=0.05, momentum=0.0),
        run=RunConfig(name=f"scaffold_{strategy}", backend="cpu", seed=seed),
    )


def test_scaffold_local_update_algebra():
    """With zero variates the correction is a no-op (matches plain SGD) and
    option II reproduces c' = -delta/(K*lr)."""
    import optax

    def apply_fn(vars_, x, train=False):
        return x @ vars_["params"]["w"]

    w = {"w": jnp.eye(4)}
    lr = 0.1
    opt = optax.sgd(lr)
    plain = local_lib.make_local_update(apply_fn, opt, num_steps=4,
                                        batch_size=8)
    scaf = local_lib.make_local_update(apply_fn, opt, num_steps=4,
                                       batch_size=8, scaffold=True, lr=lr)
    x = jax.random.normal(jax.random.PRNGKey(0), (32, 4))
    y = jax.random.randint(jax.random.PRNGKey(1), (32,), 0, 4)
    count = jnp.asarray(32)
    key = jax.random.PRNGKey(2)
    budget = jnp.asarray(4, jnp.int32)

    zeros = pytrees.tree_zeros_like(w)
    r_plain = plain(w, x, y, count, key, budget)
    sr = scaf(w, x, y, count, key, budget, zeros, zeros)
    np.testing.assert_allclose(np.asarray(sr.result.delta["w"]),
                               np.asarray(r_plain.delta["w"]), rtol=1e-6)
    expected_c = -np.asarray(sr.result.delta["w"]) / (4 * lr)
    np.testing.assert_allclose(np.asarray(sr.c_new["w"]), expected_c,
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(sr.delta_c["w"]), expected_c,
                               rtol=1e-5)

    # A nonzero shared correction (c - c_i) shifts every SGD step by
    # -lr*(c - c_i) per step relative to plain SGD when gradients are
    # unaffected... verify the correction enters: different c => different delta.
    ones = jax.tree.map(jnp.ones_like, w)
    sr2 = scaf(w, x, y, count, key, budget, zeros, ones)
    assert not np.allclose(np.asarray(sr2.result.delta["w"]),
                           np.asarray(sr.result.delta["w"]))


def test_scaffold_requires_lr():
    import optax

    with pytest.raises(ValueError, match="lr"):
        local_lib.make_local_update(lambda *a, **k: None, optax.sgd(0.1),
                                    num_steps=1, batch_size=1, scaffold=True)


def test_scaffold_engine_converges_and_beats_fedavg_under_drift():
    """Strong non-IID partition + partial participation: SCAFFOLD's whole
    point.  It must converge, keep finite state, and not lose to FedAvg."""
    scaf = FederatedLearner(_cfg("scaffold"))
    fed = FederatedLearner(_cfg("fedavg"))
    for _ in range(10):
        scaf.run_round()
        fed.run_round()
    loss_s, acc_s = scaf.evaluate()
    loss_f, acc_f = fed.evaluate()
    assert np.isfinite(loss_s)
    c_norm = float(pytrees.tree_global_norm(scaf.client_c))
    assert np.isfinite(c_norm) and c_norm > 0  # variates actually moved
    assert acc_s >= acc_f - 0.05  # parity-or-better under drift


def test_scaffold_mesh_matches_vmap(cpu_devices):
    cfg = _cfg(cohort=0)                       # full participation
    mesh = Mesh(np.array(cpu_devices[:4]), ("clients",))
    a = FederatedLearner(cfg)
    b = FederatedLearner(cfg, mesh=mesh)
    for _ in range(3):
        ra = a.run_round()
        rb = b.run_round()
    np.testing.assert_allclose(ra["train_loss"], rb["train_loss"], rtol=1e-4)
    # global control variates agree across placements
    ca = np.asarray(a.server_state.control["Dense_0"]["kernel"])
    cb = np.asarray(b.server_state.control["Dense_0"]["kernel"])
    np.testing.assert_allclose(ca, cb, rtol=1e-4, atol=1e-6)
    la, aa = a.evaluate()
    lb, ab = b.evaluate()
    np.testing.assert_allclose(la, lb, rtol=1e-3)


def test_scaffold_rejected_by_stateless_paths(tmp_path):
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker
    from colearn_federated_learning_tpu.fed import offline

    cfg = _cfg()
    g0 = str(tmp_path / "g.npz")
    offline.init_global_model(cfg, g0)        # init itself is fine
    with pytest.raises(NotImplementedError, match="scaffold"):
        offline.client_update(cfg, 0, g0, str(tmp_path / "u.npz"))
    with pytest.raises(NotImplementedError, match="scaffold"):
        DeviceWorker(cfg, 0)


def test_scaffold_rejects_privacy_hooks():
    cfg = _cfg()
    cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, secure_agg=True))
    with pytest.raises(ValueError, match="incompatible"):
        FederatedLearner(cfg)


def test_scaffold_checkpoint_roundtrip(tmp_path):
    cfg = _cfg()
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ckpt")))
    a = FederatedLearner(cfg)
    a.run_round(); a.run_round()
    a.save_checkpoint()

    b = FederatedLearner(cfg)
    step = b.restore_checkpoint()
    assert step == 2
    np.testing.assert_allclose(
        np.asarray(a.server_state.control["Dense_0"]["kernel"]),
        np.asarray(b.server_state.control["Dense_0"]["kernel"]),
    )
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(a.client_c)[0]),
        np.asarray(jax.tree.leaves(b.client_c)[0]),
    )
    b.run_round()                              # resumes cleanly


def test_scaffold_variates_are_cohort_resident(mesh8):
    """Flagship regime: many clients, small cohort.  The full variate store
    must live on HOST (numpy) and the round program must only ever see the
    cohort block — num_clients=512 x model on-device would not fit the
    flagship configs."""
    cfg = _cfg(num_clients=512, cohort=16)
    cfg = cfg.replace(data=dataclasses.replace(cfg.data, partition="iid"))
    learner = FederatedLearner(cfg, mesh=mesh8)
    # host-resident store, full size
    leaves = jax.tree.leaves(learner.client_c)
    assert all(isinstance(l, np.ndarray) for l in leaves)
    assert all(l.shape[0] == learner.num_clients for l in leaves)

    before = jax.tree.map(np.array, learner.client_c)
    rec = learner.run_round()
    assert rec["completed"] >= 1

    # exactly the sampled cohort's rows changed
    _, rows = learner._host_sample_cohort(0)
    changed = set()
    for b, a in zip(jax.tree.leaves(before), jax.tree.leaves(learner.client_c)):
        diff = np.abs(a - b).reshape(a.shape[0], -1).sum(axis=1)
        changed |= set(np.nonzero(diff)[0].tolist())
    assert changed, "no variates moved"
    assert changed <= set(rows.tolist())
    assert len(changed) <= learner.cohort_size

    # and the jitted round program's variate operand is cohort-sized
    sel, rows = learner._host_sample_cohort(1)
    assert sel.shape[0] == learner.cohort_size == 16
