"""analysis/: one positive + one suppression fixture per rule
(CL001–CL016 and CL023; CL017–CL021 live in test_lint_concurrency.py),
the noqa/baseline machinery (CL000 dead suppressions,
line-shift-stable fingerprints), the `colearn lint` CLI exit codes, the
labeled-counter roll-up the registry grew for per-device attribution,
and the tier-1 self-check that the installed package is lint-clean."""

import json
import os
import textwrap

import pytest

from colearn_federated_learning_tpu.analysis.engine import (
    LintConfig,
    LintEngine,
    write_baseline,
)
from colearn_federated_learning_tpu.cli import main as cli_main
from colearn_federated_learning_tpu.telemetry import registry as telemetry_registry
from colearn_federated_learning_tpu.telemetry.registry import MetricsRegistry


def run_lint(tmp_path, source, relpath="pkg/comm/mod.py", rules=None,
             baseline=""):
    """Lint one fixture file placed at ``relpath`` under a scratch root
    (the directory names drive the scoped rules: comm/, faults/)."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    eng = LintEngine(config=LintConfig(enable=rules), root=str(tmp_path))
    return eng.run([str(path)], baseline_path=baseline)


def rule_ids(result):
    return sorted({f.rule for f in result.findings})


# ------------------------------------------------------------- CL001 ----
def test_cl001_flags_print_in_jit_decorated_function(tmp_path):
    res = run_lint(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            print("tracing", x)
            return x
    """, relpath="pkg/fed/mod.py", rules=["CL001"])
    assert rule_ids(res) == ["CL001"]
    assert res.exit_code == 1


def test_cl001_flags_time_call_in_jit_call_site_target(tmp_path):
    res = run_lint(tmp_path, """
        import time
        from jax import jit

        def train(x):
            t0 = time.perf_counter()
            return x + t0

        train_fast = jit(train)
    """, relpath="pkg/fed/mod.py")
    assert rule_ids(res) == ["CL001"]


def test_cl001_suppression(tmp_path):
    res = run_lint(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            print("trace marker")  # colearn: noqa(CL001): test fixture
            return x
    """, relpath="pkg/fed/mod.py", rules=["CL001"])
    assert res.findings == [] and res.suppressed == 1


def test_cl001_ignores_untraced_functions(tmp_path):
    # Scoped to CL001: a host-side stdout print is fine by THIS rule
    # (CL010 has its own opinion about library stdout).
    res = run_lint(tmp_path, """
        def host_side(x):
            print(x)
            return x
    """, relpath="pkg/fed/mod.py", rules=["CL001"])
    assert res.findings == []


# ------------------------------------------------------------- CL002 ----
def test_cl002_flags_untimed_client_and_recv_in_comm(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.broker import BrokerClient

        def attach(host, port):
            return BrokerClient(host, port)

        def drain(sock):
            return sock.recv(4)
    """)
    assert rule_ids(res) == ["CL002"]
    assert len(res.findings) == 2


def test_cl002_passes_timeout_kwarg_and_timeout_bearing_function(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.broker import BrokerClient

        def attach(host, port):
            return BrokerClient(host, port, timeout=5.0)

        def drain(sock, timeout):
            sock.settimeout(timeout)
            return sock.recv(4)
    """)
    assert res.findings == []


def test_cl002_only_applies_under_comm(tmp_path):
    res = run_lint(tmp_path, """
        def drain(sock):
            return sock.recv(4)
    """, relpath="pkg/fed/mod.py")
    assert res.findings == []


def test_cl002_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def accept_forever(srv):
            return srv.accept()  # colearn: noqa(CL002): test fixture
    """)
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL003 ----
def test_cl003_flags_bare_except_and_swallowed_handler(tmp_path):
    res = run_lint(tmp_path, """
        def teardown(sock):
            try:
                sock.close()
            except OSError:
                pass
            try:
                sock.detach()
            except:
                return None
    """)
    assert rule_ids(res) == ["CL003"]
    assert len(res.findings) == 2


def test_cl003_allows_handlers_with_real_bodies(tmp_path):
    res = run_lint(tmp_path, """
        def teardown(sock, counter):
            try:
                sock.close()
            except OSError:
                counter.inc()
    """)
    assert res.findings == []


def test_cl003_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def teardown(sock):
            try:
                sock.close()
            except OSError:  # colearn: noqa(CL003): test fixture
                pass
    """)
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL004 ----
def test_cl004_flags_wall_clock_and_unseeded_rng_in_faults(tmp_path):
    res = run_lint(tmp_path, """
        import random
        import time

        def jitter():
            return random.random() + time.time()
    """, relpath="pkg/faults/mod.py")
    assert rule_ids(res) == ["CL004"]
    assert len(res.findings) == 2


def test_cl004_allows_seeded_rng_and_monotonic(tmp_path):
    res = run_lint(tmp_path, """
        import random
        import time

        def jitter(seed):
            rng = random.Random(seed)
            return rng.uniform(0, 1), time.monotonic()
    """, relpath="pkg/faults/mod.py")
    assert res.findings == []


def test_cl004_suppression(tmp_path):
    res = run_lint(tmp_path, """
        import time

        def stamp():
            return time.time()  # colearn: noqa(CL004): test fixture
    """, relpath="pkg/faults/mod.py")
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL005 ----
def test_cl005_flags_typoed_counter_name(tmp_path):
    res = run_lint(tmp_path, """
        def bump(registry):
            registry.counter("comm.retry_totl").inc()
    """, relpath="pkg/fed/mod.py")
    assert rule_ids(res) == ["CL005"]


def test_cl005_passes_catalog_names_and_wildcard_fstrings(tmp_path):
    res = run_lint(tmp_path, """
        def bump(registry, kind):
            registry.counter("comm.retry_total").inc()
            registry.counter(f"fault.injected.{kind}").inc()
            registry.histogram("fed.round_time_s").observe(1.0)
    """, relpath="pkg/fed/mod.py")
    assert res.findings == []


def test_cl005_flags_fstring_with_unknown_prefix(tmp_path):
    res = run_lint(tmp_path, """
        def bump(registry, kind):
            registry.counter(f"surprise.{kind}").inc()
    """, relpath="pkg/fed/mod.py")
    assert rule_ids(res) == ["CL005"]


def test_cl005_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def bump(registry):
            registry.counter("scratch.local_only").inc()  # colearn: noqa(CL005): test fixture
    """, relpath="pkg/fed/mod.py")
    assert res.findings == [] and res.suppressed == 1


def test_cl005_flags_non_literal_metric_name(tmp_path):
    # A plain variable slips past catalog validation entirely — the
    # hardened rule reports it instead of silently passing.
    res = run_lint(tmp_path, """
        def bump(registry, name):
            registry.counter(name).inc()
    """, relpath="pkg/fed/mod.py")
    assert rule_ids(res) == ["CL005"]
    assert "non-literal" in res.findings[0].message


def test_cl005_non_literal_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def snapshot(registry, names):
            return {n: registry.counter(n).value  # colearn: noqa(CL005): test fixture
                    for n in names}
    """, relpath="pkg/fed/mod.py")
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL006 ----
def test_cl006_flags_host_sync_in_traced_function(tmp_path):
    res = run_lint(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return float(x)
    """, relpath="pkg/fed/mod.py")
    assert rule_ids(res) == ["CL006"]


def test_cl006_flags_block_until_ready_in_hot_loop(tmp_path):
    res = run_lint(tmp_path, """
        def fit(batches):
            for b in batches:  # colearn: hot
                b.result.block_until_ready()
    """, relpath="pkg/fed/mod.py")
    assert rule_ids(res) == ["CL006"]


def test_cl006_suppression(tmp_path):
    res = run_lint(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return float(x)  # colearn: noqa(CL006): test fixture
    """, relpath="pkg/fed/mod.py")
    assert res.findings == [] and res.suppressed == 1


def test_cl006_allows_host_sync_outside_hot_paths(tmp_path):
    res = run_lint(tmp_path, """
        def summarize(x):
            return float(x)
    """, relpath="pkg/fed/mod.py")
    assert res.findings == []


# ------------------------------------------------------------- CL007 ----
def test_cl007_flags_per_request_encode_in_hot_fanout_loop(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.utils.serialization import pytree_to_bytes

        def broadcast(devs, params):
            for d in devs:  # colearn: hot
                d.send(pytree_to_bytes(params))
    """)
    assert rule_ids(res) == ["CL007"]


def test_cl007_allows_encode_hoisted_before_the_loop(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.utils.serialization import pytree_to_bytes

        def broadcast(devs, params):
            body = pytree_to_bytes(params)
            for d in devs:  # colearn: hot
                d.send(body)
    """)
    assert res.findings == []


def test_cl007_ignores_loops_not_marked_hot(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.utils.serialization import pytree_to_bytes

        def snapshot_all(trees):
            for t in trees:
                yield pytree_to_bytes(t)
    """)
    assert res.findings == []


def test_cl007_suppression(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.utils.serialization import save_pytree_npz

        def dump(devs, params):
            for d in devs:  # colearn: hot
                save_pytree_npz(d.path, params)  # colearn: noqa(CL007): test fixture
    """)
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL008 ----
def test_cl008_flags_in_place_exchange_writes_in_fed(tmp_path):
    res = run_lint(tmp_path, """
        import numpy as np
        from pkg.utils.serialization import save_pytree_npz

        def publish(path, tree):
            save_pytree_npz(path, tree)

        def manifest(path, names):
            with open(path, "w") as f:
                f.write("\\n".join(names))

        def raw(path, arrs):
            np.savez(path, **arrs)
    """, relpath="pkg/fed/exchange.py")
    assert rule_ids(res) == ["CL008"]
    assert len(res.findings) == 3


def test_cl008_allows_temp_plus_replace_in_same_function(tmp_path):
    res = run_lint(tmp_path, """
        import os
        from pkg.utils.serialization import save_pytree_npz

        def publish(path, tree):
            tmp = path + ".tmp"
            save_pytree_npz(tmp, tree)
            os.replace(tmp, path)
    """, relpath="pkg/fed/exchange.py")
    assert res.findings == []


def test_cl008_ignores_writes_outside_fed(tmp_path):
    res = run_lint(tmp_path, """
        def snapshot(path, blob):
            with open(path, "wb") as f:
                f.write(blob)
    """, relpath="pkg/telemetry/dump.py")
    assert res.findings == []


def test_cl008_ignores_reads_and_appends(tmp_path):
    res = run_lint(tmp_path, """
        def load(path):
            with open(path, "rb") as f:
                return f.read()

        def journal(path, line):
            with open(path, "a") as f:
                f.write(line)
    """, relpath="pkg/fed/offline.py")
    assert res.findings == []


def test_cl008_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def scratch(path, blob):
            with open(path, "wb") as f:  # colearn: noqa(CL008): test fixture
                f.write(blob)
    """, relpath="pkg/fed/exchange.py")
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL009 ----
def test_cl009_flags_per_device_loop_in_hot_path(tmp_path):
    res = run_lint(tmp_path, """
        def run_round(cohort_ids, train_one):
            out = []
            for device_id in cohort_ids:  # colearn: hot
                out.append(train_one(device_id))
            return out
    """, relpath="pkg/fleetsim/mod.py")
    assert rule_ids(res) == ["CL009"]
    assert res.exit_code == 1


def test_cl009_flags_local_update_call_per_iteration(tmp_path):
    res = run_lint(tmp_path, """
        def run_round(chunks, local_update, params):
            acc = None
            for chunk in chunks:  # colearn: hot
                acc = local_update(params, chunk)
            return acc
    """, relpath="pkg/fleetsim/sim.py")
    assert rule_ids(res) == ["CL009"]


def test_cl009_allows_chunk_loop(tmp_path):
    # The blessed shape: loop over CHUNK OFFSETS, one jitted vmapped
    # dispatch per chunk (fleetsim/sim.FleetSim.run_round).
    res = run_lint(tmp_path, """
        def run_round(n, chunk, chunk_fn, fold, acc):
            for lo in range(0, n, chunk):  # colearn: hot
                acc = fold(acc, chunk_fn(lo))
            return acc
    """, relpath="pkg/fleetsim/sim.py")
    assert res.findings == []


def test_cl009_ignores_unmarked_and_non_fleetsim_loops(tmp_path):
    src = """
        def setup(device_ids, probe):
            for device_id in device_ids:
                probe(device_id)

        def elsewhere(client_ids, send):
            for client_id in client_ids:  # colearn: hot
                send(client_id)
    """
    # Unmarked fleetsim loop: cold paths may iterate per device.
    res = run_lint(tmp_path, src.split("def elsewhere")[0],
                   relpath="pkg/fleetsim/population.py")
    assert res.findings == []
    # Marked per-client loop OUTSIDE fleetsim/: not CL009's business
    # (the comm fan-out has its own rules).
    res = run_lint(tmp_path, "def elsewhere" + src.split("def elsewhere")[1],
                   relpath="pkg/comm/mod.py")
    assert res.findings == []


def test_cl009_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def debug_round(cohort_ids, train_one):
            for device_id in cohort_ids:  # colearn: hot  # colearn: noqa(CL009): test fixture
                train_one(device_id)
    """, relpath="pkg/fleetsim/mod.py")
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL010 ----
def test_cl010_flags_print_to_stdout_in_library_code(tmp_path):
    res = run_lint(tmp_path, """
        def announce(port):
            print({"port": port})
    """, relpath="pkg/comm/mod.py")
    assert rule_ids(res) == ["CL010"]
    assert res.exit_code == 1


def test_cl010_flags_explicit_sys_stdout(tmp_path):
    res = run_lint(tmp_path, """
        import sys

        def announce(port):
            print(port, file=sys.stdout)
    """, relpath="pkg/fed/mod.py")
    assert rule_ids(res) == ["CL010"]


def test_cl010_allows_stderr_and_file_objects(tmp_path):
    res = run_lint(tmp_path, """
        import sys

        def announce(port, log):
            print(port, file=sys.stderr)
            print(port, file=log)
    """, relpath="pkg/comm/mod.py")
    assert res.findings == []


def test_cl010_exempts_cli_scripts_and_main_guards(tmp_path):
    src = """
        def report(x):
            print(x)
    """
    # cli.py and bench.py ARE the stdout contract (machine-readable
    # summary lines); scripts/ is operator tooling.
    assert run_lint(tmp_path, src, relpath="pkg/cli.py").findings == []
    assert run_lint(tmp_path, src, relpath="pkg/bench.py").findings == []
    assert run_lint(tmp_path, src,
                    relpath="pkg/scripts/tool.py").findings == []
    # __main__ guard: the module is being run AS a script.
    res = run_lint(tmp_path, """
        def build():
            return "x"

        if __name__ == "__main__":
            print(build())
    """, relpath="pkg/native/build.py")
    assert res.findings == []


def test_cl010_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def report(x):
            print(x)  # colearn: noqa(CL010): test fixture
    """, relpath="pkg/fed/mod.py")
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL011 ----
def test_cl011_flags_expander_call_per_pair(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.privacy.secure_agg import pairwise_mask

        def mask_all(update, key, me, partners, rnd):
            for p in partners:  # colearn: hot
                update = update + pairwise_mask(update, key, me, p, rnd)
            return update
    """, relpath="pkg/privacy/mod.py")
    assert rule_ids(res) == ["CL011"]
    assert res.exit_code == 1


def test_cl011_flags_per_pair_head_in_comm(tmp_path):
    res = run_lint(tmp_path, """
        def fold_masks(pair_rows, expand, acc):
            for pair in pair_rows:  # colearn: hot
                acc = acc + expand(pair)
            return acc
    """, relpath="pkg/comm/mod.py")
    assert rule_ids(res) == ["CL011"]


def test_cl011_allows_key_derivation_loop(tmp_path):
    # The sanctioned per-pair loop: deriving the KEY TABLE (one scalar
    # modexp per pair), which then feeds ONE *_with_keys dispatch.
    res = run_lint(tmp_path, """
        from pkg.comm.keyexchange import pair_prng_key, shared_secret
        from pkg.privacy.secure_agg import mask_update_with_keys

        def mask(update, priv, me, peers, pubs, signs, rnd):
            keys = []
            for p in peers:  # colearn: hot
                keys.append(pair_prng_key(shared_secret(priv, pubs[p]),
                                          me, p))
            return mask_update_with_keys(update, keys, signs, rnd)
    """, relpath="pkg/comm/worker.py")
    assert res.findings == []


def test_cl011_ignores_unmarked_and_out_of_scope_loops(tmp_path):
    src = """
        from pkg.privacy.secure_agg import pairwise_mask

        def cold(update, key, me, partners, rnd):
            for p in partners:
                update = update + pairwise_mask(update, key, me, p, rnd)
            return update
    """
    # Unmarked loop in privacy/: cold paths may iterate per pair.
    res = run_lint(tmp_path, src, relpath="pkg/privacy/mod.py")
    assert res.findings == []
    # Marked per-pair loop OUTSIDE privacy//comm/: not CL011's business.
    res = run_lint(tmp_path, """
        def sweep(pair_counts, probe):
            for pairs in pair_counts:  # colearn: hot
                probe(pairs)
    """, relpath="pkg/fleetsim/mod.py")
    assert res.findings == []


def test_cl011_suppression(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.privacy.secure_agg import mask_scalar

        def debug_mask(xs, key, me, partners, rnd):
            for p in partners:  # colearn: hot  # colearn: noqa(CL011): test fixture
                xs = mask_scalar(xs, key, me, p, rnd)
            return xs
    """, relpath="pkg/privacy/mod.py")
    assert res.findings == [] and res.suppressed == 1


def test_cl012_flags_device_get_in_hot_wire_path(tmp_path):
    res = run_lint(tmp_path, """
        import jax

        def encode_round(rnd, params, codec):
            with codec.span("serialize"):  # colearn: hot
                host = jax.device_get(params)
            return codec.pack(rnd, host)
    """, relpath="pkg/comm/downlink.py")
    assert rule_ids(res) == ["CL012"]
    assert res.exit_code == 1


def test_cl012_flags_tree_map_asarray_gather(tmp_path):
    # The full-tree gather idiom spelled via tree.map(np.asarray, ...):
    # every leaf is pulled whole to one host buffer.
    res = run_lint(tmp_path, """
        import jax
        import numpy as np

        def serialize(params, wire):  # colearn: hot
            host = jax.tree.map(np.asarray, params)
            return wire.pack(host)
    """, relpath="pkg/comm/coordinator.py")
    assert rule_ids(res) == ["CL012"]


def test_cl012_allows_per_shard_reads_and_cold_paths(tmp_path):
    # Per-shard host reads (the sanctioned replacement) don't trip it.
    res = run_lint(tmp_path, """
        import numpy as np

        def host_read(a):
            out = np.empty(a.shape, a.dtype)
            for sh in a.addressable_shards:  # colearn: hot
                out[sh.index] = np.asarray(sh.data)
            return out
    """, relpath="pkg/comm/downlink.py", rules=["CL012"])
    assert res.findings == []
    # Unmarked (cold) gather in comm/: eval paths may gather whole trees.
    res = run_lint(tmp_path, """
        import jax

        def evaluate(params, batch):
            return score(jax.device_get(params), batch)
    """, relpath="pkg/comm/coordinator.py")
    assert res.findings == []
    # Hot gather OUTSIDE comm/: not CL012's business.
    res = run_lint(tmp_path, """
        import jax

        def snapshot(params):  # colearn: hot
            return jax.device_get(params)
    """, relpath="pkg/ckpt/mod.py")
    assert res.findings == []


def test_cl012_suppression(tmp_path):
    res = run_lint(tmp_path, """
        import jax
        import numpy as np

        def stage(delta, w):  # colearn: hot
            host = jax.tree.map(np.asarray, delta)  # colearn: noqa(CL012): test fixture
            return scale(host, w)
    """, relpath="pkg/comm/aggregation.py")
    assert res.findings == [] and res.suppressed == 1


def test_cl012_device_fold_region_stays_gather_free(tmp_path):
    # The PR-19 device-fold block (`_fold_block_device`, `# colearn: hot`)
    # retired aggregation.py's last CL012 noqa: staging owns each leaf
    # with a PER-LEAF asarray loop, and the fold itself runs on slots.
    # Pin both directions so the region cannot quietly regress into the
    # full-tree-gather idiom the noqa used to excuse.
    res = run_lint(tmp_path, """
        import jax
        import numpy as np

        def _fold_block_device(self, ids):  # colearn: hot
            leaves, treedef = jax.tree.flatten(self.acc)
            owned = [np.asarray(leaf) * self.w for leaf in leaves]
            return jax.tree.unflatten(treedef, owned)
    """, relpath="pkg/comm/aggregation.py", rules=["CL012"])
    assert res.findings == []
    res = run_lint(tmp_path, """
        import jax
        import numpy as np

        def _fold_block_device(self, ids):  # colearn: hot
            host = jax.tree.map(np.asarray, self.acc)
            return self.kernel.fold(host)
    """, relpath="pkg/comm/aggregation.py", rules=["CL012"])
    assert rule_ids(res) == ["CL012"]


def test_cl013_flags_decompress_in_hot_aggregation_path(tmp_path):
    res = run_lint(tmp_path, """
        from pkg.fed import compression

        def add(self, meta, delta):  # colearn: hot
            dense = compression.decompress_delta(delta, meta,
                                                 shapes=self.shapes)
            return self.stage(dense)
    """, relpath="pkg/comm/aggregation.py", rules=["CL013"])
    assert rule_ids(res) == ["CL013"]
    assert res.exit_code == 1


def test_cl013_flags_full_shape_alloc_in_hot_loop(tmp_path):
    res = run_lint(tmp_path, """
        import numpy as np

        def fold(folder, updates):
            for meta, idx, vals in updates:  # colearn: hot
                buf = np.zeros(folder.model_shape, np.float32)
                buf.reshape(-1)[idx] = vals
                folder.accumulate(buf)
    """, relpath="pkg/comm/aggregation.py", rules=["CL013"])
    assert rule_ids(res) == ["CL013"]


def test_cl013_allows_cold_paths_and_other_dirs(tmp_path):
    # The once-per-round accumulator densify at finalize is NOT hot.
    res = run_lint(tmp_path, """
        import numpy as np

        def finalize(self, staged):
            acc = np.zeros(self.model_shape, np.float32)
            for idx, vals in staged:
                acc.reshape(-1)[idx] += vals
            return acc
    """, relpath="pkg/comm/aggregation.py", rules=["CL013"])
    assert res.findings == []
    # Hot full-shape alloc OUTSIDE comm/: not CL013's business.
    res = run_lint(tmp_path, """
        import numpy as np

        def estimate(shape):  # colearn: hot
            return np.zeros(shape, np.float32)
    """, relpath="pkg/fleetsim/mod.py", rules=["CL013"])
    assert res.findings == []


def test_cl013_suppression(tmp_path):
    # int8 dequantize is inherently dense — the sanctioned noqa shape.
    res = run_lint(tmp_path, """
        from pkg.fed import compression

        def add(self, meta, delta):  # colearn: hot
            dense = compression.decompress_delta(  # colearn: noqa(CL013): test fixture
                delta, meta, shapes=self.shapes)
            return self.stage(dense)
    """, relpath="pkg/comm/aggregation.py", rules=["CL013"])
    assert res.findings == [] and res.suppressed == 1


def test_cl014_flags_raw_clock_delta_in_hot_wire_path(tmp_path):
    res = run_lint(tmp_path, """
        import time

        def collect(self, devs):  # colearn: hot
            t0 = time.perf_counter()
            out = [self.ask(d) for d in devs]
            dt = time.perf_counter() - t0
            print("collected in", dt)
            return out
    """, relpath="pkg/comm/coordinator.py", rules=["CL014"])
    assert rule_ids(res) == ["CL014"]
    assert res.exit_code == 1


def test_cl014_allows_attributed_deltas_and_deadline_math(tmp_path):
    # Accumulation into a named stat (the StreamingFolder.fold_s idiom)
    # is attributed — the delta lands in round meta.
    res = run_lint(tmp_path, """
        import time

        def add(self, meta, delta):  # colearn: hot
            t0 = time.perf_counter()
            self.stage(meta, delta)
            self.fold_s += time.perf_counter() - t0
    """, relpath="pkg/comm/aggregation.py", rules=["CL014"])
    assert res.findings == []
    # A delta fed straight to a registry histogram is attributed.
    res = run_lint(tmp_path, """
        import time

        def fold(self, reg, parts):  # colearn: hot
            t0 = time.monotonic()
            for p in parts:
                self.merge(p)
            reg.histogram("fed.phase_time_s").observe(
                time.monotonic() - t0)
    """, relpath="pkg/comm/aggregator.py", rules=["CL014"])
    assert res.findings == []
    # Deadline arithmetic keeps the clock on the RIGHT — budget
    # bookkeeping, not an unattributed duration.
    res = run_lint(tmp_path, """
        import time

        def wait(self, fut, deadline):  # colearn: hot
            return fut.result(timeout=deadline - time.monotonic())
    """, relpath="pkg/comm/transport.py", rules=["CL014"])
    assert res.findings == []
    # Cold comm path: eval/debug timing is not CL014's business.
    res = run_lint(tmp_path, """
        import time

        def profile(self, devs):
            t0 = time.time()
            self.ping(devs)
            return time.time() - t0
    """, relpath="pkg/comm/coordinator.py", rules=["CL014"])
    assert res.findings == []
    # Hot raw delta OUTSIDE comm/: other planes keep their own idioms.
    res = run_lint(tmp_path, """
        import time

        def step(batch):  # colearn: hot
            t0 = time.perf_counter()
            run(batch)
            return time.perf_counter() - t0
    """, relpath="pkg/fed/mod.py", rules=["CL014"])
    assert res.findings == []


def test_cl014_suppression(tmp_path):
    res = run_lint(tmp_path, """
        import time

        def drain(self, q):  # colearn: hot
            t0 = time.monotonic()
            q.drain()
            lag = time.monotonic() - t0  # colearn: noqa(CL014): test fixture
            return lag
    """, relpath="pkg/comm/worker.py", rules=["CL014"])
    assert res.findings == [] and res.suppressed == 1


def test_cl015_flags_bare_sleep_in_retry_loop(tmp_path):
    res = run_lint(tmp_path, """
        import time

        def request(self, header, retry):
            for attempt in range(retry.max_retries):
                try:
                    return self.ask(header)
                except OSError:
                    time.sleep(retry.delay(attempt))
    """, relpath="pkg/comm/transport.py", rules=["CL015"])
    assert rule_ids(res) == ["CL015"]
    assert res.exit_code == 1


def test_cl015_allows_event_wait_and_one_shot_sleep(tmp_path):
    # The sanctioned idiom: backoff waits on the owner's stop event.
    res = run_lint(tmp_path, """
        def pump(self):
            while not self._stop.is_set():
                if not self.dispatch():
                    self._stop.wait(0.2)
    """, relpath="pkg/comm/worker.py", rules=["CL015"])
    assert res.findings == []
    # A one-shot sleep outside any loop (startup grace) is not a
    # backoff — CL015 only polices loops.
    res = run_lint(tmp_path, """
        import time

        def start(self):
            self.spawn()
            time.sleep(0.1)
    """, relpath="pkg/comm/broker.py", rules=["CL015"])
    assert res.findings == []
    # Sleeps in loops OUTSIDE comm/: other planes (bench scripts,
    # fleetsim clocks) keep their own idioms.
    res = run_lint(tmp_path, """
        import time

        def poll(path):
            while not path.exists():
                time.sleep(0.5)
    """, relpath="pkg/faults/watch.py", rules=["CL015"])
    assert res.findings == []


def test_cl015_suppression(tmp_path):
    res = run_lint(tmp_path, """
        import time

        def settle(self):
            for _ in range(3):
                time.sleep(0.01)  # colearn: noqa(CL015): test fixture
    """, relpath="pkg/comm/transport.py", rules=["CL015"])
    assert res.findings == [] and res.suppressed == 1


def test_cl016_flags_uncataloged_record_key(tmp_path):
    res = run_lint(tmp_path, """
        def _round(self, r):
            rec = {"round": r, "completed": 3}
            rec["train_los"] = 1.0
            return rec
    """, relpath="pkg/comm/coordinator.py", rules=["CL016"])
    assert rule_ids(res) == ["CL016"]
    assert res.exit_code == 1
    assert "train_los" in res.findings[0].message


def test_cl016_flags_typo_in_dict_literal_and_update(tmp_path):
    # Dict-literal assignment to a record name and .update kwargs are
    # both validated against the catalog.
    res = run_lint(tmp_path, """
        def _round(self, r):
            rec = {"round": r, "cohrt": 4}
            rec.update(train_loss=0.5, stalenes_mean=1.0)
            return rec
    """, relpath="pkg/fleetsim/sim.py", rules=["CL016"])
    assert rule_ids(res) == ["CL016"]
    assert len(res.findings) == 2
    flagged = {f.message.split("'")[1] for f in res.findings}
    assert flagged == {"cohrt", "stalenes_mean"}


def test_cl016_allows_cataloged_keys_and_dynamic_updates(tmp_path):
    # Cataloged keys pass; **splat and computed updates are out of
    # scope (their keys are cataloged at the call sites that build them).
    res = run_lint(tmp_path, """
        def _round(self, r, extras):
            rec = {"round": r, "completed": 3, "train_loss": 0.1}
            rec["conv_update_norm"] = 0.5
            rec.update(**extras)
            rec.update({"staleness_mean": 1.0})
            return rec
    """, relpath="pkg/comm/async_coordinator.py", rules=["CL016"])
    assert res.findings == []
    # Wire-header dicts in other comm/ files keep their own vocabulary.
    res = run_lint(tmp_path, """
        def reply(self):
            out = {"op": "subscribe_ack", "status": "ok"}
            return out
    """, relpath="pkg/comm/broker.py", rules=["CL016"])
    assert res.findings == []


def test_cl016_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def _round(self, r):
            rec = {"round": r}
            rec["experimental_key"] = 1  # colearn: noqa(CL016): test fixture
            return rec
    """, relpath="pkg/comm/coordinator.py", rules=["CL016"])
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------------------------- CL023 ----
def test_cl023_flags_replace_without_fsync_in_ckpt(tmp_path):
    # os.replace alone satisfies CL008's torn-reader contract but not
    # CL023's power-loss one: the rename can land before the data blocks.
    res = run_lint(tmp_path, """
        import os

        def commit(path, body):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(body)
            os.replace(tmp, path)
    """, relpath="pkg/ckpt/gen.py", rules=["CL023"])
    assert rule_ids(res) == ["CL023"]


def test_cl023_flags_in_place_npz_in_offline(tmp_path):
    res = run_lint(tmp_path, """
        import numpy as np

        def export(path, arrays):
            np.savez(path, **arrays)
    """, relpath="pkg/fed/offline.py", rules=["CL023"])
    assert rule_ids(res) == ["CL023"]


def test_cl023_passes_fsync_before_replace_and_atomic_helper(tmp_path):
    res = run_lint(tmp_path, """
        import os
        import numpy as np
        from pkg.utils.serialization import atomic_save_pytree_npz

        def commit(path, body):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

        def shard_write(path, buffers):
            _atomic_write(path, lambda f: np.savez(f, **buffers))

        def export(path, tree):
            atomic_save_pytree_npz(path, tree)
    """, relpath="pkg/ckpt/streaming.py", rules=["CL023"])
    assert res.findings == []


def test_cl023_only_applies_to_durable_paths(tmp_path):
    # The same in-place write outside ckpt/ and fed/offline.py is CL008's
    # (or nobody's) business, not CL023's.
    res = run_lint(tmp_path, """
        def scratch(path, body):
            with open(path, "w") as f:
                f.write(body)
    """, relpath="pkg/comm/mod.py", rules=["CL023"])
    assert res.findings == []


def test_cl023_suppression(tmp_path):
    res = run_lint(tmp_path, """
        def scratch(path, body):
            with open(path, "w") as f:  # colearn: noqa(CL023): test fixture
                f.write(body)
    """, relpath="pkg/ckpt/tmp.py", rules=["CL023"])
    assert res.findings == [] and res.suppressed == 1


# ------------------------------------------- engine machinery ----------
def test_cl000_dead_suppression_is_reported(tmp_path):
    res = run_lint(tmp_path, """
        X = 1  # colearn: noqa(CL002)
    """)
    assert rule_ids(res) == ["CL000"]


def test_blanket_noqa_suppresses_every_rule_on_the_line(tmp_path):
    res = run_lint(tmp_path, """
        import time

        def jitter():
            return time.time()  # colearn: noqa
    """, relpath="pkg/faults/mod.py")
    assert res.findings == [] and res.suppressed == 1


def test_syntax_error_becomes_cl999_finding(tmp_path):
    res = run_lint(tmp_path, "def broken(:\n")
    assert rule_ids(res) == ["CL999"]
    assert res.exit_code == 1


def test_docstring_mentioning_noqa_does_not_suppress(tmp_path):
    res = run_lint(tmp_path, '''
        def teardown(sock):
            """Mentions # colearn: noqa(CL003) in prose only."""
            try:
                sock.close()
            except OSError:
                pass
    ''')
    assert rule_ids(res) == ["CL003"]


def test_baseline_absorbs_findings_and_survives_line_shifts(tmp_path):
    src = """
        def teardown(sock):
            try:
                sock.close()
            except OSError:
                pass
    """
    res = run_lint(tmp_path, src)
    assert len(res.findings) == 1
    bl = tmp_path / "baseline.json"
    write_baseline(str(bl), res.findings)

    # Same finding, two lines lower: the fingerprint hashes source text,
    # not line numbers, so the baseline still covers it.
    shifted = "\n# shifted\n# shifted\n" + textwrap.dedent(src)
    res2 = run_lint(tmp_path, shifted, baseline=str(bl))
    assert res2.findings == [] and res2.baselined == 1


def test_unknown_rule_id_raises():
    with pytest.raises(ValueError, match="unknown lint rule"):
        LintEngine(config=LintConfig(enable=["CL404"]))


def test_config_disable_skips_rule(tmp_path):
    res = run_lint(tmp_path, """
        def drain(sock):
            return sock.recv(4)
    """, rules=None, baseline="")
    assert rule_ids(res) == ["CL002"]
    path = tmp_path / "pkg/comm/mod.py"
    eng = LintEngine(config=LintConfig(disable=("CL002",)),
                     root=str(tmp_path))
    assert eng.run([str(path)], baseline_path="").findings == []


# ------------------------------------------------------------- CLI ------
def _write_fixture(tmp_path, source, relpath="pkg/comm/mod.py"):
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return path


def test_cli_lint_exits_nonzero_and_emits_json(tmp_path, capsys):
    bad = _write_fixture(tmp_path, """
        def drain(sock):
            return sock.recv(4)
    """)
    rc = cli_main(["lint", str(bad), "--root", str(tmp_path),
                   "--format", "json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["counts"] == {"CL002": 1}
    assert doc["findings"][0]["rule"] == "CL002"
    assert doc["findings"][0]["line"] == 3


def test_cli_lint_exits_zero_on_clean_tree(tmp_path, capsys):
    clean = _write_fixture(tmp_path, "X = 1\n")
    rc = cli_main(["lint", str(clean), "--root", str(tmp_path)])
    assert rc == 0
    assert "clean" in capsys.readouterr().out


def test_cli_lint_unknown_rule_is_usage_error(tmp_path, capsys):
    clean = _write_fixture(tmp_path, "X = 1\n")
    rc = cli_main(["lint", str(clean), "--root", str(tmp_path),
                   "--rules", "CL404"])
    capsys.readouterr()
    assert rc == 2


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    _write_fixture(tmp_path, """
        def drain(sock):
            return sock.recv(4)
    """)
    target = str(tmp_path / "pkg")
    rc = cli_main(["lint", target, "--root", str(tmp_path),
                   "--write-baseline"])
    capsys.readouterr()
    assert rc == 0
    assert (tmp_path / "lint_baseline.json").exists()
    rc = cli_main(["lint", target, "--root", str(tmp_path)])
    capsys.readouterr()
    assert rc == 0


# ------------------------------------------- labeled counters ----------
def test_counter_labels_roll_up_into_aggregate():
    reg = MetricsRegistry()
    reg.counter("comm.retry_total", labels={"device": "3"}).inc(2)
    reg.counter("comm.retry_total", labels={"device": "5"}).inc()
    snap = reg.snapshot()
    assert snap["comm.retry_total"] == 3.0
    assert snap["comm.retry_total{device=3}"] == 2.0
    assert snap["comm.retry_total{device=5}"] == 1.0


def test_counter_labels_same_set_returns_same_child():
    reg = MetricsRegistry()
    a = reg.counter("comm.retry_total", labels={"device": "3"})
    b = reg.counter("comm.retry_total", labels={"device": "3"})
    assert a is b
    # The unlabeled aggregate is the parent, untouched until a child incs.
    assert reg.counter("comm.retry_total").value == 0.0


def test_strict_mode_rejects_uncataloged_names(monkeypatch):
    monkeypatch.setattr(telemetry_registry, "_STRICT", True)
    reg = MetricsRegistry()
    reg.counter("comm.retry_total").inc()           # cataloged: fine
    reg.counter("fault.injected.delay")             # wildcard family: fine
    with pytest.raises(ValueError, match="metric_catalog"):
        reg.counter("comm.retry_totl")


# ------------------------------------------- tier-1 self-check ----------
def test_installed_package_is_lint_clean():
    import colearn_federated_learning_tpu as pkg

    pkg_dir = os.path.dirname(os.path.abspath(pkg.__file__))
    root = os.path.dirname(pkg_dir)
    eng = LintEngine(config=LintConfig.from_pyproject(root), root=root)
    res = eng.run([pkg_dir])
    assert res.findings == [], "\n".join(f.render() for f in res.findings)
    assert res.files > 50
