"""Sequence parallelism: SP BERT (ring attention, sharded positions,
psum pooling) must match its dense-attention twin — forward, grads, and a
full federated round on a 2-D (clients, seq) mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.fed.losses import softmax_cross_entropy
from colearn_federated_learning_tpu.models import registry as model_registry
from colearn_federated_learning_tpu.parallel.mesh import make_mesh
from colearn_federated_learning_tpu.parallel.sp import (
    make_sp_apply,
    make_sp_loss_grad,
)
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

# Small on purpose: these tests pay 8-device shard_map compiles on one
# CPU core; depth 2 keeps inter-block coverage, width/heads are minimal.
BERT_CFG = ModelConfig(name="bert", num_classes=4, width=16, depth=2,
                       num_heads=2, seq_len=32, vocab_size=200)


@pytest.fixture(scope="module")
def models_and_params():
    # Module-scoped: model build + init compile once for both oracle tests.
    dense = model_registry.build_model(BERT_CFG)
    sp = model_registry.build_model(
        dataclasses.replace(BERT_CFG, attn_impl="ring"), seq_axis_name="seq"
    )
    ids = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0, 200)
    params = model_registry.init_params(dense, ids, jax.random.PRNGKey(1))
    return dense, sp, ids, params


def test_sp_forward_matches_dense(cpu_devices, models_and_params):
    mesh = make_mesh(("seq",), (4,), devices=cpu_devices[:4])
    dense, sp, ids, params = models_and_params
    y_ref = dense.apply({"params": params}, ids, train=False)
    y_sp = make_sp_apply(sp, mesh)(params, ids)
    np.testing.assert_allclose(np.asarray(y_sp), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-4)


def test_sp_grads_match_dense(cpu_devices, models_and_params):
    mesh = make_mesh(("seq",), (4,), devices=cpu_devices[:4])
    dense, sp, ids, params = models_and_params
    labels = jnp.array([0, 1, 2, 3])

    def dense_loss(p):
        return softmax_cross_entropy(
            dense.apply({"params": p}, ids, train=True), labels
        )

    l_ref, g_ref = jax.value_and_grad(dense_loss)(params)
    l_sp, g_sp = make_sp_loss_grad(sp, softmax_cross_entropy, mesh)(
        params, ids, labels
    )
    np.testing.assert_allclose(float(l_sp), float(l_ref), rtol=1e-5)
    flat_ref = jax.tree.leaves(g_ref)
    flat_sp = jax.tree.leaves(g_sp)
    for a, b in zip(flat_sp, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


def _sp_exp_config(attn_impl="ring"):
    return ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=16),
        model=dataclasses.replace(
            BERT_CFG, seq_len=64, vocab_size=2000, attn_impl=attn_impl),
        # Full participation (cohort = all clients): mesh and single-device
        # paths then train the SAME cohort, so results must agree.
        fed=FedConfig(strategy="fedavg", rounds=2, cohort_size=0,
                      local_steps=1, batch_size=4, lr=0.1, momentum=0.9),
        run=RunConfig(name="sp_test", backend="cpu"),
    )


def test_federated_round_on_2d_mesh_matches_single_device(cpu_devices):
    mesh = make_mesh(("clients", "seq"), (4, 2), devices=cpu_devices[:8])
    sp_learner = FederatedLearner(_sp_exp_config(), mesh=mesh)
    assert sp_learner.sp and sp_learner.seq_size == 2
    ref_learner = FederatedLearner(_sp_exp_config(attn_impl="dense"))

    m_sp = sp_learner.run_round()
    m_ref = ref_learner.run_round()
    assert m_sp["completed"] == m_ref["completed"] == 8
    np.testing.assert_allclose(m_sp["train_loss"], m_ref["train_loss"],
                               rtol=5e-3)
    # eval runs the dense twin on the full sequence
    loss_sp, acc_sp = sp_learner.evaluate()
    loss_ref, acc_ref = ref_learner.evaluate()
    np.testing.assert_allclose(loss_sp, loss_ref, rtol=5e-3)
    assert abs(acc_sp - acc_ref) < 0.05


def test_sp_requires_divisible_seq(cpu_devices):
    # The engine must refuse a seq axis that does not divide the example
    # length: 30-token examples over a 4-way "seq" axis.
    mesh = make_mesh(("clients", "seq"), (2, 4), devices=cpu_devices[:8])
    cfg = _sp_exp_config()
    import numpy as onp

    from colearn_federated_learning_tpu.data.registry import Dataset, DatasetSpec

    spec = DatasetSpec("odd_text", "text", (30,), 4, 64, 16, vocab_size=2000)
    ds = Dataset(
        spec=spec,
        x_train=onp.ones((64, 30), onp.int32), y_train=onp.zeros(64, onp.int32),
        x_test=onp.ones((16, 30), onp.int32), y_test=onp.zeros(16, onp.int32),
        source="synthetic",
    )
    with pytest.raises(ValueError, match="not divisible"):
        FederatedLearner(cfg, dataset=ds, mesh=mesh)


def test_ring_config_single_device_falls_back_to_dense():
    learner = FederatedLearner(_sp_exp_config())  # no mesh
    assert not learner.sp
    learner.run_round()
    assert np.isfinite(learner.history[-1]["train_loss"])


def test_offline_entrypoints_accept_ring_configs(tmp_path):
    # File/socket federation participants are single processes with no
    # shard_map mesh; SP (ring) configs must fall back to the dense core
    # (identical params) instead of crashing at model build.
    from colearn_federated_learning_tpu.fed import offline

    cfg = _sp_exp_config()                       # attn_impl="ring"
    g0 = str(tmp_path / "g.npz")
    offline.init_global_model(cfg, g0)
    stats = offline.client_update(cfg, 0, g0, str(tmp_path / "u.npz"))
    assert np.isfinite(stats["mean_loss"])
    rec = offline.evaluate_global(cfg, g0)
    assert 0.0 <= rec["eval_acc"] <= 1.0
