"""telemetry/: span nesting + timing, Chrome-trace round-trip, metrics
registry, trace-id propagation through a loopback socket federation, and
the MetricsLogger phase-field / stream-ownership fixes."""

import io
import json
import time

import pytest

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.metrics import MetricsLogger
from colearn_federated_learning_tpu.telemetry.registry import (
    Histogram,
    MetricsRegistry,
)
from colearn_federated_learning_tpu.telemetry.tracer import Tracer
from colearn_federated_learning_tpu.utils.profiling import RoundProfiler


# ------------------------------------------------------------- tracer ----
def test_span_nesting_and_parent_ids():
    tr = Tracer(process="t")
    with tr.span("round", round=0) as outer:
        with tr.span("aggregate") as inner:
            assert tr.current_context() == inner.context
        assert tr.current_context() == outer.context
    assert tr.current_context() is None
    spans = {s.name: s for s in tr.snapshot()}
    assert spans["aggregate"].parent_id == spans["round"].span_id
    assert spans["aggregate"].trace_id == spans["round"].trace_id
    assert spans["round"].parent_id is None
    assert spans["round"].attrs == {"round": 0}


def test_span_timing_monotonic_and_contained():
    tr = Tracer(process="t")
    with tr.span("outer"):
        with tr.span("inner"):
            time.sleep(0.01)
    inner, outer = (
        {s.name: s for s in tr.snapshot()}[k] for k in ("inner", "outer")
    )
    assert inner.ended and outer.ended
    assert inner.duration_s >= 0.01
    assert outer.duration_s >= inner.duration_s


def test_disabled_tracer_still_times_but_records_nothing():
    tr = Tracer(process="t", enabled=False)
    with tr.span("x") as sp:
        time.sleep(0.005)
    assert sp.duration_s >= 0.005
    assert tr.snapshot() == []


def test_span_buffer_bounded_counts_drops():
    tr = Tracer(process="t", max_spans=2)
    for _ in range(4):
        with tr.span("s"):
            pass
    assert len(tr.snapshot()) == 2 and tr.dropped == 2


def test_remote_parent_and_adopt_roundtrip():
    coord, worker = Tracer(process="coord"), Tracer(process="worker-0")
    with coord.span("round") as round_sp:
        ctx = coord.current_context()
        with worker.capture() as captured:
            with worker.span("worker.train", parent=ctx):
                pass
        wire = [s.to_dict() for s in captured]
        coord.adopt(json.loads(json.dumps(wire)))   # through JSON, as on the wire
    spans = {s.name: s for s in coord.snapshot()}
    assert spans["worker.train"].trace_id == round_sp.trace_id
    assert spans["worker.train"].parent_id == round_sp.span_id
    assert spans["worker.train"].process == "worker-0"
    # malformed entries are skipped, not fatal
    assert coord.adopt([{"nonsense": 1}, None]) == 0


# ----------------------------------------------------------- registry ----
def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(2.5)
    for v in range(100):
        reg.histogram("h").observe(float(v))
    snap = reg.snapshot()
    assert snap["c"] == 5.0
    assert snap["g"] == 2.5
    h = snap["h"]
    assert h["count"] == 100 and h["min"] == 0.0 and h["max"] == 99.0
    assert 40.0 <= h["p50"] <= 60.0
    with pytest.raises(TypeError):
        reg.gauge("c")                   # kind mismatch on an existing name
    with pytest.raises(ValueError):
        reg.counter("c").inc(-1)
    reg.reset()
    assert reg.snapshot() == {}


def test_histogram_thinning_keeps_exact_count_sum():
    h = Histogram("h", max_samples=64)
    n = 10_000
    for v in range(n):
        h.observe(float(v))
    s = h.summary()
    assert s["count"] == n
    assert s["sum"] == float(n * (n - 1) // 2)
    assert s["min"] == 0.0 and s["max"] == float(n - 1)
    # the deterministic thinning keeps quantiles roughly in place
    assert 0.3 * n <= s["p50"] <= 0.7 * n


# ------------------------------------------------- chrome-trace export ----
def test_chrome_trace_schema_roundtrip(tmp_path):
    tr = Tracer(process="engine")
    with tr.span("round", round=0):
        with tr.span("client_update"):
            pass
    path = telemetry.write_trace(
        str(tmp_path / "t_trace.json"), tr.snapshot(), metrics={"m": 1.0}
    )
    doc = telemetry.load_trace(path)
    events = doc["traceEvents"]
    x = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert {e["name"] for e in x} == {"round", "client_update"}
    for e in x:                          # Chrome-trace complete events
        assert {"name", "ph", "ts", "dur", "pid", "tid", "args"} <= set(e)
        assert e["dur"] >= 0
    assert any(e["name"] == "process_name" for e in meta)
    assert doc["otherData"]["metrics"] == {"m": 1.0}
    # inverse: spans survive the round-trip with ids intact
    back = {s.name: s for s in telemetry.trace_spans(doc)}
    orig = {s.name: s for s in tr.snapshot()}
    assert back["client_update"].parent_id == orig["round"].span_id
    with pytest.raises(ValueError):
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        telemetry.load_trace(str(bad))


def test_summarize_trace_reports_phases_and_coverage():
    tr = Tracer(process="engine")
    with tr.span("round"):
        with tr.span("client_update"):
            time.sleep(0.01)
    text = telemetry.summarize_trace(
        {"traceEvents": telemetry.spans_to_chrome(tr.snapshot())}
    )
    assert "client_update" in text and "phase coverage" in text


# ------------------------------------- propagation through the sockets ----
def test_trace_propagation_loopback_federation():
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig,
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=3, partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=0,
                      local_steps=2, batch_size=8, lr=0.1),
        run=RunConfig(name="trace_test", backend="cpu"),
    )
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(3)]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0)
            coord.enroll(min_devices=3, timeout=20.0)
            rec = coord.run_round()
            coord.close()
        finally:
            for w in workers:
                w.stop()

    spans = coord.tracer.snapshot()
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    round_sp = by_name["round"][0]
    # the worker's train spans were shipped back and stitched into the
    # coordinator's trace under the SAME trace id
    trains = by_name["worker.train"]
    assert len(trains) == rec["completed"]
    for s in trains:
        assert s.trace_id == round_sp.trace_id
        assert s.process.startswith("worker-")
        assert s.duration_s > 0
    # worker child spans rode along too
    assert any(s.name == "local_train" for s in spans)
    # and none of it leaked into the round record (JSONL purity)
    assert "trace_spans" not in json.dumps(rec)
    assert rec["phase_broadcast_collect_s"] > 0
    assert rec["phase_aggregate_s"] > 0


# -------------------------------------------------------- MetricsLogger ----
def test_metrics_logger_never_closes_external_stream():
    buf = io.StringIO()
    with MetricsLogger(stream=buf, name="t") as m:
        m.log({"round": 0, "x": 1.0})
    assert not buf.closed                 # caller still owns the stream
    rec = json.loads(buf.getvalue().splitlines()[0])
    assert rec["round"] == 0 and rec["name"] == "t"


def test_metrics_logger_rejects_path_plus_stream(tmp_path):
    with pytest.raises(ValueError):
        MetricsLogger(path=str(tmp_path / "m.jsonl"), stream=io.StringIO())


def test_metrics_logger_closes_tensorboard():
    closed = {"flush": 0, "close": 0}

    class FakeTB:
        def scalar(self, *a, **kw):
            pass

        def flush(self):
            closed["flush"] += 1

        def close(self):
            closed["close"] += 1

    m = MetricsLogger(name="t")
    m._tb = FakeTB()
    m.log({"round": 0, "acc": 0.5})
    m.close()
    assert closed["flush"] >= 1 and closed["close"] == 1
    assert m._tb is None


def test_metrics_logger_jsonl_has_phase_fields(tmp_path):
    """engine.fit's per-round records — and therefore the JSONL — carry
    the span-timed phase durations."""
    import dataclasses

    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import get_config

    cfg = get_config("mnist_mlp_fedavg")
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, dataset="mnist_tiny",
                                 num_clients=4),
        fed=dataclasses.replace(cfg.fed, rounds=1, local_steps=1,
                                batch_size=8, cohort_size=4),
        run=dataclasses.replace(cfg.run, backend="cpu", eval_every=1,
                                name="phase_test"),
    )
    path = str(tmp_path / "m.jsonl")
    learner = FederatedLearner.from_config(cfg)
    with MetricsLogger(path=path, name="phase_test") as m:
        learner.fit(log_fn=m.log)
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["phase_update_s"] > 0
    assert "phase_sync_s" in rec and "phase_eval_s" in rec
    assert rec["round_time_s"] >= rec["phase_update_s"]


# ------------------------------------------------- profiler satellite ----
def test_round_profiler_active_is_public():
    p = RoundProfiler(None)               # disabled: no profile dir
    assert p.active is False
    p.before_round(0)
    assert p.active is False              # still disabled
    p.close()
