"""Checkpoint/resume (ckpt/) and metrics (metrics.py) round-trips."""

import json

import numpy as np

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.metrics import MetricsLogger
from tests.test_engine import tiny_config


def test_metrics_jsonl_and_summary(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path=path, name="t") as m:
        m.log({"round": 0, "round_time_s": 0.5, "eval_acc": 0.4})
        m.log({"round": 1, "round_time_s": 0.5, "eval_acc": 0.9})
        s = m.summary(samples_per_round=100, n_chips=2)
    assert s["rounds"] == 2
    np.testing.assert_allclose(s["rounds_per_sec"], 2.0)
    np.testing.assert_allclose(s["client_samples_per_sec_per_chip"], 100.0)
    assert s["final_acc"] == 0.9 and s["best_acc"] == 0.9
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2 and lines[0]["name"] == "t"


def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Train 4 rounds straight vs 2 + checkpoint + restore + 2: identical."""
    import dataclasses
    import jax

    base_cfg = tiny_config(rounds=4)
    cfg = base_cfg.replace(run=dataclasses.replace(
        base_cfg.run, checkpoint_dir=str(tmp_path / "ck")))

    straight = FederatedLearner(base_cfg)  # no checkpointing
    straight.fit(rounds=4)

    first = FederatedLearner(cfg)
    first.fit(rounds=2)
    first.save_checkpoint()

    resumed = FederatedLearner(cfg)
    step = resumed.restore_checkpoint()
    assert step == 2
    resumed.fit(rounds=2)

    assert resumed.evaluate() == straight.evaluate()
    for a, b in zip(jax.tree.leaves(straight.server_state.params),
                    jax.tree.leaves(resumed.server_state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fit_auto_checkpoints(tmp_path):
    import dataclasses

    cfg = tiny_config(rounds=3)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2))
    learner = FederatedLearner(cfg)
    learner.fit(rounds=3)
    fresh = FederatedLearner(cfg)
    step = fresh.restore_checkpoint()
    assert step == 3  # final round always checkpoints
    assert len(fresh.history) == 3
    # fit() default = REMAINING rounds to the configured total (0 here).
    fresh.fit()
    assert len(fresh.history) == 3


def test_checkpoint_dir_without_cadence_saves_final_round(tmp_path):
    import dataclasses

    cfg = tiny_config(rounds=2)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ck")))  # checkpoint_every=0
    learner = FederatedLearner(cfg)
    learner.fit()
    fresh = FederatedLearner(cfg)
    assert fresh.restore_checkpoint() == 2
    # resume default runs only the remaining rounds (none)
    fresh.fit()
    assert len(fresh.history) == 2
