"""Checkpoint/resume (ckpt/) and metrics (metrics.py) round-trips."""

import json

import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.metrics import MetricsLogger
from tests.test_engine import tiny_config


def test_metrics_jsonl_and_summary(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with MetricsLogger(path=path, name="t") as m:
        m.log({"round": 0, "round_time_s": 0.5, "eval_acc": 0.4})
        m.log({"round": 1, "round_time_s": 0.5, "eval_acc": 0.9})
        s = m.summary(samples_per_round=100, n_chips=2)
    assert s["rounds"] == 2
    np.testing.assert_allclose(s["rounds_per_sec"], 2.0)
    np.testing.assert_allclose(s["client_samples_per_sec_per_chip"], 100.0)
    assert s["final_acc"] == 0.9 and s["best_acc"] == 0.9
    lines = [json.loads(l) for l in open(path)]
    assert len(lines) == 2 and lines[0]["name"] == "t"


def test_checkpoint_resume_matches_uninterrupted(tmp_path):
    """Train 4 rounds straight vs 2 + checkpoint + restore + 2: identical."""
    import dataclasses
    import jax

    base_cfg = tiny_config(rounds=4)
    cfg = base_cfg.replace(run=dataclasses.replace(
        base_cfg.run, checkpoint_dir=str(tmp_path / "ck")))

    straight = FederatedLearner(base_cfg)  # no checkpointing
    straight.fit(rounds=4)

    first = FederatedLearner(cfg)
    first.fit(rounds=2)
    first.save_checkpoint()

    resumed = FederatedLearner(cfg)
    step = resumed.restore_checkpoint()
    assert step == 2
    resumed.fit(rounds=2)

    assert resumed.evaluate() == straight.evaluate()
    for a, b in zip(jax.tree.leaves(straight.server_state.params),
                    jax.tree.leaves(resumed.server_state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fit_auto_checkpoints(tmp_path):
    import dataclasses

    cfg = tiny_config(rounds=3)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2))
    learner = FederatedLearner(cfg)
    learner.fit(rounds=3)
    fresh = FederatedLearner(cfg)
    step = fresh.restore_checkpoint()
    assert step == 3  # final round always checkpoints
    assert len(fresh.history) == 3
    # fit() default = REMAINING rounds to the configured total (0 here).
    fresh.fit()
    assert len(fresh.history) == 3


def test_checkpoint_dir_without_cadence_saves_final_round(tmp_path):
    import dataclasses

    cfg = tiny_config(rounds=2)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ck")))  # checkpoint_every=0
    learner = FederatedLearner(cfg)
    learner.fit()
    fresh = FederatedLearner(cfg)
    assert fresh.restore_checkpoint() == 2
    # resume default runs only the remaining rounds (none)
    fresh.fit()
    assert len(fresh.history) == 2


# ------------------------------------------------------------- round WAL ----
def _counter(name):
    from colearn_federated_learning_tpu import telemetry

    return telemetry.get_registry().counter(name).value


def test_round_wal_append_load_rewind(tmp_path):
    from colearn_federated_learning_tpu.ckpt import RoundWal

    wal = RoundWal(str(tmp_path))
    assert wal.committed_rounds() is None        # no log yet
    for r in range(3):
        wal.append({"round": r, "accepted": [0, 1]})
    assert wal.committed_rounds() == 3
    assert [e["round"] for e in wal.load()] == [0, 1, 2]
    wal.rewind(1)
    assert [e["round"] for e in wal.load()] == [0]
    wal.append({"round": 1, "accepted": []})     # appendable after rewind
    assert wal.committed_rounds() == 2
    wal.close()


def test_round_wal_torn_tail_is_dropped_and_counted(tmp_path):
    from colearn_federated_learning_tpu.ckpt import RoundWal

    wal = RoundWal(str(tmp_path))
    wal.append({"round": 0})
    wal.close()
    # The append that was in flight when the process died.
    with open(wal.path, "a") as f:
        f.write('{"round": 1, "acc')
    before = _counter("ckpt.wal_torn_tail_total")
    assert [e["round"] for e in wal.load()] == [0]
    assert _counter("ckpt.wal_torn_tail_total") == before + 1


def test_round_wal_mid_file_corruption_raises(tmp_path):
    from colearn_federated_learning_tpu.ckpt import RoundWal

    wal = RoundWal(str(tmp_path))
    with open(wal.path, "w") as f:
        f.write('{"round": 0}\n{"torn\n{"round": 2}\n')
    with pytest.raises(ValueError, match="corrupt WAL entry"):
        wal.load()


def test_engine_interrupted_midrun_resumes_bitwise(tmp_path):
    """SIGKILL-shaped interrupt: fit() dies after round 1's record is out
    but before its own checkpoint cadence finishes the run; a fresh
    learner restores and the FINAL params are bitwise-identical to an
    uninterrupted run's."""
    import dataclasses
    import jax

    base_cfg = tiny_config(rounds=4)
    cfg = base_cfg.replace(run=dataclasses.replace(
        base_cfg.run, checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=1))

    straight = FederatedLearner(base_cfg)
    straight.fit(rounds=4)

    class Killed(Exception):
        pass

    def die_at_round_1(rec):
        if rec["round"] == 1:
            raise Killed

    first = FederatedLearner(cfg)
    with pytest.raises(Killed):
        first.fit(log_fn=die_at_round_1)

    resumed = FederatedLearner(cfg)
    step = resumed.restore_checkpoint()
    assert step == 1             # round 1's checkpoint never committed
    resumed.fit()                # default: the REMAINING 3 rounds
    assert len(resumed.history) == 4
    assert resumed.evaluate() == straight.evaluate()
    for a, b in zip(jax.tree.leaves(straight.server_state.params),
                    jax.tree.leaves(resumed.server_state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
