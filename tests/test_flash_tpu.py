"""Real-TPU (non-interpret) execution of the Pallas flash kernels.

The main suite runs on a virtual CPU mesh (conftest forces the platform),
where Pallas runs in interpret mode — these tests only execute when the
process actually sits on a TPU, i.e. when run OUTSIDE the suite:

    JAX_PLATFORMS='' python -m pytest tests/test_flash_tpu.py -p no:cacheprovider --noconftest

They validate that the (8, 128)-tiled kernels compile and match the dense
oracle forward AND backward on hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu",
    reason="needs a real TPU (interpret-mode coverage lives in "
           "test_ops_attention.py)",
)


def _rand(key, B, L, H, D, frac_pad=0.25):
    ks = jax.random.split(key, 4)
    q = jax.random.normal(ks[0], (B, L, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, L, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, L, H, D), jnp.float32)
    mask = jax.random.uniform(ks[3], (B, L)) > frac_pad
    return q, k, v, mask


def test_flash_forward_backward_on_tpu():
    from colearn_federated_learning_tpu.ops.attention import flash_attention
    from colearn_federated_learning_tpu.parallel.ring import dense_attention

    q, k, v, mask = _rand(jax.random.PRNGKey(0), B=2, L=256, H=4, D=128)

    out = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, mask, interpret=False)
    )(q, k, v)
    ref = dense_attention(q, k, v, mask)
    # The MXU computes f32 matmuls at DEFAULT precision (bf16 passes), so
    # kernel-vs-oracle agreement on hardware is bf16-rounding-limited.
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-3, atol=5e-3)

    gf = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, mask, interpret=False) ** 2),
        argnums=(0, 1, 2),
    ))(q, k, v)
    gd = jax.grad(
        lambda q, k, v: jnp.sum(dense_attention(q, k, v, mask) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=1e-2)


def test_flash_causal_bf16_on_tpu():
    from colearn_federated_learning_tpu.ops.attention import flash_attention
    from colearn_federated_learning_tpu.parallel.ring import dense_attention

    q, k, v, _ = _rand(jax.random.PRNGKey(1), B=1, L=512, H=2, D=64)
    q, k, v = (a.astype(jnp.bfloat16) for a in (q, k, v))
    out = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=True,
                                        interpret=False)
    )(q, k, v)
    ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)
