"""Byzantine-robust aggregation (fed/robust.py + engine wiring).

The reference's mean aggregator lets one malicious IoT device steer the
global model arbitrarily; the rebuild adds coordinate-wise median and
trimmed mean.  Tests: statistics vs numpy oracles (with masking), a
label-flip poisoning attack the median survives and the mean does not,
and mesh/vmap equivalence.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.fed.robust import robust_aggregate
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def test_robust_statistics_match_numpy():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(9, 4, 3)).astype(np.float32)
    mask = np.array([1, 1, 1, 0, 1, 1, 0, 1, 1], bool)   # 7 contributors
    tree = {"a": jnp.asarray(x), "b": jnp.asarray(x[:, 0])}

    med = robust_aggregate(tree, jnp.asarray(mask), "median")
    np.testing.assert_allclose(np.asarray(med["a"]),
                               np.median(x[mask], axis=0), atol=1e-6)

    tm = robust_aggregate(tree, jnp.asarray(mask), "trimmed_mean",
                          trim_fraction=0.2)
    k = int(np.floor(0.2 * mask.sum()))                  # 1 per side
    ref = np.sort(x[mask], axis=0)[k:mask.sum() - k].mean(axis=0)
    np.testing.assert_allclose(np.asarray(tm["a"]), ref, atol=1e-6)

    # No contributors -> zeros, not NaN.
    zed = robust_aggregate(tree, jnp.zeros(9, bool), "median")
    assert float(np.abs(np.asarray(zed["a"])).max()) == 0.0


def test_krum_excludes_outliers():
    rng = np.random.default_rng(1)
    # 7 honest updates clustered at +1, 2 attackers far away.
    x = (1.0 + 0.01 * rng.normal(size=(9, 16))).astype(np.float32)
    x[0] = 50.0
    x[4] = -50.0
    tree = {"w": jnp.asarray(x)}
    out = robust_aggregate(tree, jnp.ones(9, bool), "krum",
                           trim_fraction=0.25)      # f = floor(.25*9) = 2
    got = np.asarray(out["w"])
    honest = np.delete(x, [0, 4], axis=0)
    # Multi-Krum selects n-f = 7 best-scored: exactly the honest cluster.
    np.testing.assert_allclose(got, honest.mean(axis=0), atol=1e-4)

    # Masked rows never participate (attacker hidden behind the mask).
    mask = np.ones(9, bool); mask[0] = False
    out = robust_aggregate(tree, jnp.asarray(mask), "krum",
                           trim_fraction=0.2)
    assert np.abs(np.asarray(out["w"])).max() < 10.0

    # Float32-overflow attacker: sum(x*x) = inf must yield a WORSE score,
    # not a zero one (distance clamping, not zeroing).
    x2 = (1.0 + 0.01 * rng.normal(size=(6, 16))).astype(np.float32)
    x2[2] = 1e25                      # sq overflows float32
    out = robust_aggregate({"w": jnp.asarray(x2)}, jnp.ones(6, bool),
                           "krum", trim_fraction=0.2)
    got = np.asarray(out["w"])
    assert np.isfinite(got).all() and np.abs(got).max() < 10.0


def _cfg(aggregator="mean", num_clients=8):
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition="iid", max_examples_per_client=64),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=5, cohort_size=0,
                      local_steps=3, batch_size=16, lr=0.1, momentum=0.9,
                      aggregator=aggregator),
        run=RunConfig(name=f"robust_{aggregator}"),
    )


class _LabelFlipLearner(FederatedLearner):
    """Flip the labels of the first ``n_bad`` clients AFTER partitioning —
    a classic data-poisoning attacker inside the simulation."""

    def __init__(self, config, n_bad: int, **kw):
        self._n_bad = n_bad
        super().__init__(config, **kw)
        x, y, counts, ids = self._device_data
        yh = np.array(y)                              # writable copy
        bad = np.isin(np.asarray(self.client_ids), np.arange(n_bad))
        yh[bad] = (9 - yh[bad]) % 10                  # deterministic flip
        y = jnp.asarray(yh)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            y = jax.device_put(
                y, NamedSharding(self.mesh, P(self.client_axis))
            )
        self._device_data = (x, y, counts, ids)


def test_median_survives_label_flip_poisoning():
    # 3 of 8 clients flip every label.  The mean aggregator degrades badly;
    # the coordinate-wise median keeps learning.  (Measured on this seed:
    # mean 0.665, median 0.857 after 8 rounds.)
    mean_l = _LabelFlipLearner(_cfg("mean"), n_bad=3)
    mean_l.fit(rounds=8)
    _, acc_mean = mean_l.evaluate()

    med_l = _LabelFlipLearner(_cfg("median"), n_bad=3)
    med_l.fit(rounds=8)
    _, acc_med = med_l.evaluate()

    assert acc_med > 0.8, acc_med
    assert acc_med > acc_mean + 0.1, (acc_med, acc_mean)

    # Trimmed mean needs trim >= attacker fraction to help: with 3/8
    # attackers, trim 0.4 trims 3 per side; 0.1 trims none (k = 0).
    tm_cfg = _cfg("trimmed_mean")
    tm_cfg = tm_cfg.replace(
        fed=dataclasses.replace(tm_cfg.fed, trim_fraction=0.4))
    tm_l = _LabelFlipLearner(tm_cfg, n_bad=3)
    tm_l.fit(rounds=8)
    _, acc_tm = tm_l.evaluate()
    assert acc_tm > acc_mean + 0.1, (acc_tm, acc_mean)


def test_krum_survives_label_flip_in_engine():
    # Krum with f = floor(0.4*8) = 3 against 3 label-flippers.
    cfg = _cfg("krum")
    cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, trim_fraction=0.4))
    k_l = _LabelFlipLearner(cfg, n_bad=3)
    k_l.fit(rounds=8)
    _, acc = k_l.evaluate()
    assert acc > 0.8, acc


def test_trimmed_mean_learns_clean():
    cfg = _cfg("trimmed_mean")
    cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, trim_fraction=0.2))
    learner = FederatedLearner(cfg)
    learner.fit(rounds=8)
    _, acc = learner.evaluate()
    assert acc > 0.85, acc


def test_robust_mesh_matches_vmap(cpu_devices):
    from jax.sharding import Mesh

    cfg = _cfg("median")
    ref = FederatedLearner(cfg)
    mesh = Mesh(np.array(cpu_devices[:8]), ("clients",))
    m = FederatedLearner(cfg, mesh=mesh)
    for _ in range(2):
        r_ref = ref.run_round()
        r_m = m.run_round()
    np.testing.assert_allclose(r_m["train_loss"], r_ref["train_loss"],
                               rtol=1e-5)
    p1 = np.concatenate([np.ravel(np.asarray(a))
                         for a in jax.tree.leaves(m.server_state.params)])
    p2 = np.concatenate([np.ravel(np.asarray(a))
                         for a in jax.tree.leaves(ref.server_state.params)])
    np.testing.assert_allclose(p1, p2, atol=1e-6)


def test_robust_guards():
    # A trim that rounds to zero clients is a silent plain mean: loud error.
    with pytest.raises(ValueError, match="trims zero"):
        FederatedLearner(_cfg("trimmed_mean"))   # floor(0.1 * 8) == 0
    with pytest.raises(ValueError, match="secure-agg"):
        FederatedLearner(_cfg("median").replace(
            fed=dataclasses.replace(_cfg("median").fed, secure_agg=True)))
    with pytest.raises(ValueError, match="Gaussian"):
        FederatedLearner(_cfg("median").replace(
            fed=dataclasses.replace(_cfg("median").fed, dp_clip=1.0,
                                    dp_noise_multiplier=0.5)))



def test_trim_clamps_under_runtime_dropouts():
    # Construction-time validation only sees the STATIC cohort size; at
    # runtime stragglers can shrink n_valid so floor(trim * n_valid) hits
    # 0 — e.g. trim 0.2 with 4 survivors.  The statistic must still trim
    # one row per side rather than silently degrade to a plain mean.
    rng = np.random.default_rng(3)
    x = rng.normal(size=(8, 5)).astype(np.float32)
    x[1] = 1e4                        # outlier a real trim removes
    mask = np.zeros(8, bool); mask[:4] = True
    out = robust_aggregate({"w": jnp.asarray(x)}, jnp.asarray(mask),
                           "trimmed_mean", trim_fraction=0.2)
    got = np.asarray(out["w"])
    ref = np.sort(x[:4], axis=0)[1:3].mean(axis=0)    # k clamped to 1
    np.testing.assert_allclose(got, ref, atol=1e-6)
    # trim_fraction == 0 is an explicit "no trimming" request: no clamp.
    out0 = robust_aggregate({"w": jnp.asarray(x)}, jnp.asarray(mask),
                            "trimmed_mean", trim_fraction=0.0)
    np.testing.assert_allclose(np.asarray(out0["w"]), x[:4].mean(axis=0),
                               rtol=1e-5)


def test_krum_clamps_f_under_runtime_dropouts():
    # Same hazard for Krum: floor(0.2 * 4) = 0 would select ALL survivors
    # (plain mean, attacker included); the clamp assumes >= 1 attacker.
    rng = np.random.default_rng(7)
    x = (1.0 + 0.01 * rng.normal(size=(8, 6))).astype(np.float32)
    x[2] = 100.0                      # attacker among the 4 survivors
    mask = np.zeros(8, bool); mask[:4] = True
    out = robust_aggregate({"w": jnp.asarray(x)}, jnp.asarray(mask),
                           "krum", trim_fraction=0.2)
    got = np.asarray(out["w"])
    np.testing.assert_allclose(got.mean(), 1.0, atol=0.05)


def test_krum_survives_nan_rows():
    # A masked row (dropped straggler) full of NaN must not poison the
    # selection matmul (0 * NaN = NaN without sanitization).
    rng = np.random.default_rng(5)
    x = (1.0 + 0.01 * rng.normal(size=(6, 8))).astype(np.float32)
    x[3] = np.nan
    mask = np.ones(6, bool); mask[3] = False
    out = robust_aggregate({"w": jnp.asarray(x)}, jnp.asarray(mask),
                           "krum", trim_fraction=0.25)
    got = np.asarray(out["w"])
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got.mean(), 1.0, atol=0.1)


def test_krum_excludes_valid_nonfinite_attacker():
    # An UNMASKED attacker submitting inf/NaN must be excluded by score,
    # not sanitized into an innocent-looking zero row that gets selected.
    rng = np.random.default_rng(9)
    x = (1.0 + 0.01 * rng.normal(size=(6, 8))).astype(np.float32)
    x[1] = np.inf
    out = robust_aggregate({"w": jnp.asarray(x)}, jnp.ones(6, bool),
                           "krum", trim_fraction=0.2)
    got = np.asarray(out["w"])
    assert np.isfinite(got).all()
    # Aggregate stays at the honest cluster (~1.0), NOT diluted toward 0
    # by a zeroed attacker row.
    np.testing.assert_allclose(got.mean(), 1.0, atol=0.05)
