"""Literature-anchored validation (SURVEY.md hard-part #5, VERDICT r4 #6).

Two layers:

1. The McMahan pathological-non-IID partitioner is a pure function over
   labels — its structural properties (2 digits per client, equal sizes,
   exact cover) are pinned here with synthetic labels, no data needed.
2. The accuracy anchors run ONLY when real MNIST is staged on disk
   (scripts/fetch_data.py -> $COLEARN_DATA_DIR/mnist.npz): a shortened
   version of scripts/validate_literature.py's protocol — the paper's 2NN
   at C=0.1, B=10, E=1 must clear 90% test accuracy within 30 IID rounds
   (the paper's Figure 2 curve is well above that by then), and the
   pathological split must trail the IID split at equal rounds.  The full
   rounds-to-97% protocol (Table 1: ~87 IID / ~664 non-IID) lives in the
   script; this is the CI-sized slice.
"""

import os

import numpy as np
import pytest

from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.data.partition import (
    label_distribution,
    pathological_partition,
)


def _labels(n=6000, n_classes=10, seed=0):
    return np.random.default_rng(seed).integers(0, n_classes, n)


def test_pathological_partition_structure():
    labels = _labels()
    parts = pathological_partition(labels, num_clients=100, seed=0)
    # Exact cover: every index appears exactly once across clients.
    all_idx = np.concatenate(parts)
    assert len(all_idx) == len(labels)
    assert len(np.unique(all_idx)) == len(labels)
    # Equal shard deal: sizes match the 2-shard allotment within rounding.
    sizes = np.array([len(p) for p in parts])
    assert sizes.min() >= len(labels) // 100 - 2
    assert sizes.max() <= len(labels) // 100 + 2
    # Pathological skew: almost every client sees at most ~2-3 distinct
    # labels (a shard can straddle one label boundary).
    dist = label_distribution(labels, parts, 10)
    classes_per_client = (dist > 0).sum(axis=1)
    assert np.median(classes_per_client) <= 3
    assert classes_per_client.max() <= 4


def test_pathological_partition_deterministic():
    labels = _labels()
    a = pathological_partition(labels, 50, seed=7)
    b = pathological_partition(labels, 50, seed=7)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    c = pathological_partition(labels, 50, seed=8)
    assert any(len(x) != len(y) or (x != y).any() for x, y in zip(a, c))


def test_pathological_partition_too_few_examples():
    with pytest.raises(ValueError, match="need >="):
        pathological_partition(_labels(n=50), num_clients=100)


def _real_mnist():
    ds = data_registry.get_dataset("mnist", seed=0)
    return ds if ds.source == "disk" else None


needs_mnist = pytest.mark.skipif(
    not os.path.exists(os.path.join(
        os.environ.get("COLEARN_DATA_DIR", "/nonexistent"), "mnist.npz")),
    reason="real MNIST not staged (scripts/fetch_data.py + COLEARN_DATA_DIR)",
)


@needs_mnist
@pytest.mark.slow
def test_mcmahan_2nn_iid_anchor():
    from scripts.validate_literature import mcmahan_2nn_config
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner

    ds = _real_mnist()
    assert ds is not None
    cfg = mcmahan_2nn_config("iid", rounds=30, lr=0.1)
    learner = FederatedLearner.from_config(cfg, dataset=ds)
    learner.fit(rounds=30)
    _, acc = learner.evaluate()
    assert float(acc) >= 0.90, f"IID 2NN at round 30: acc={float(acc):.4f}"


@needs_mnist
@pytest.mark.slow
def test_mcmahan_2nn_noniid_trails_iid():
    from scripts.validate_literature import mcmahan_2nn_config
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner

    ds = _real_mnist()
    assert ds is not None
    accs = {}
    for part in ("iid", "pathological"):
        cfg = mcmahan_2nn_config(part, rounds=20, lr=0.1)
        learner = FederatedLearner.from_config(cfg, dataset=ds)
        learner.fit(rounds=20)
        _, acc = learner.evaluate()
        accs[part] = float(acc)
    assert accs["pathological"] < accs["iid"], accs
