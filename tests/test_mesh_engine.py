"""Scale-sim (SURVEY.md §7): the multi-chip shard_map engine on a faked
8-device CPU mesh — psum aggregation must match the single-device vmap
engine's math."""

import dataclasses

import numpy as np

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from tests.test_engine import tiny_config


def test_sharded_engine_learns(mesh8):
    learner = FederatedLearner(tiny_config(rounds=4), mesh=mesh8)
    # 10 clients pad to 16 (2 per device), ghosts carry zero weight.
    assert learner.num_clients == 16
    hist = learner.fit(rounds=4)
    # Ghosts contribute exactly nothing: the aggregate weight is the sum of
    # REAL clients' example counts.
    assert hist[0]["total_weight"] == float(learner.shards.counts.sum())
    _, acc = learner.evaluate()
    assert acc > 0.5


def test_sharded_full_participation_matches_vmap(mesh8):
    """With full participation and no stragglers, the mesh engine computes
    the same weighted average as the vmap engine (same clients, same keys),
    so round-1 training losses must agree to float tolerance."""
    cfg = tiny_config(rounds=1)
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(cfg.data, num_clients=8)
    )
    lv = FederatedLearner(cfg)
    lm = FederatedLearner(cfg, mesh=mesh8)
    rv = lv.run_round()
    rm = lm.run_round()
    assert rm["total_weight"] == rv["total_weight"]
    np.testing.assert_allclose(rm["train_loss"], rv["train_loss"], rtol=1e-4)
    # And the resulting global params agree.
    import jax

    for a, b in zip(
        jax.tree.leaves(lv.server_state.params), jax.tree.leaves(lm.server_state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_sharded_privacy_path_runs(mesh8):
    cfg = tiny_config(rounds=2, dp_clip=1.0, dp_noise_multiplier=0.1,
                      secure_agg=True)
    learner = FederatedLearner(cfg, mesh=mesh8)
    hist = learner.fit(rounds=2)
    assert np.isfinite(hist[-1]["train_loss"])
    # Ghost clients (counts==0) must be excluded from uniform weighting.
    assert hist[-1]["total_weight"] <= 10


def test_sharded_partial_cohort_is_device_stratified(mesh8):
    """Partial cohorts on a mesh are sampled PER DEVICE (stratified): each
    device contributes exactly cohort/D of its own resident clients, and —
    because real clients are interleaved across devices — every sampled
    slot is a real client whenever each device holds >= cohort/D reals.
    This is a deliberate semantic difference from the vmap engine's global
    without-replacement sample (no cross-device data movement); this test
    pins the contract."""
    cfg = tiny_config(rounds=3, cohort_size=8)
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(cfg.data, num_clients=24)
    )
    learner = FederatedLearner(cfg, mesh=mesh8)
    assert learner.cohort_per_device == 1    # 8 slots over 8 devices
    hist = learner.fit(rounds=3)
    for rec in hist:
        # all 8 sampled slots are real clients -> all complete, and the
        # total weight is the sum of exactly 8 real shard counts
        assert rec["completed"] == 8
        assert rec["total_weight"] > 0
    _, acc = learner.evaluate()
    assert np.isfinite(acc)
