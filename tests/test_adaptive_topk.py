"""Adaptive per-round topk density (comm/worker._adapt_topk): the
effective fraction is steered off the error-feedback residual-norm trend
within the configured band, validated up front, and a topk_adaptive
federation tracks the fixed-density baseline."""

import jax
import numpy as np
import pytest

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.comm.broker import MessageBroker
from colearn_federated_learning_tpu.comm.coordinator import FederatedCoordinator
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.utils.config import validate_robustness

from tests.test_comm import _config


def _adaptive_cfg(**kw):
    fed = dict(compress="topk", compress_feedback=True, topk_adaptive=True,
               topk_fraction=0.05, topk_min_fraction=0.02,
               topk_max_fraction=0.1)
    fed.update(kw)
    return _config(num_clients=3, **fed)


# --------------------------------------------------------------- policy ----
def test_adapt_widens_on_rising_norm_and_tightens_on_falling():
    w = DeviceWorker(_adaptive_cfg(), 0)
    assert w._topk_fraction == pytest.approx(0.05)
    w._adapt_topk(1.0)                   # first norm: no trend yet
    assert w._topk_fraction == pytest.approx(0.05)
    w._adapt_topk(2.0)                   # rising: codec is dropping signal
    assert w._topk_fraction == pytest.approx(0.05 * 1.25)
    w._adapt_topk(1.5)                   # falling: density has slack
    assert w._topk_fraction == pytest.approx(0.05 * 1.25 * 0.9)


def test_adapt_clips_to_configured_band():
    w = DeviceWorker(_adaptive_cfg(), 0)
    norms = iter(range(1, 40))
    w._adapt_topk(next(norms))
    for n in norms:                      # monotone rising: grow to the cap
        w._adapt_topk(n)
    assert w._topk_fraction == pytest.approx(0.1)
    for n in range(40, 1, -1):           # monotone falling: shrink to floor
        w._adapt_topk(n)
    assert w._topk_fraction == pytest.approx(0.02)
    assert telemetry.get_registry().gauge(
        "fed.topk_fraction_effective").value == pytest.approx(0.02)


def test_adaptive_disabled_never_moves():
    w = DeviceWorker(_config(num_clients=3, compress="topk",
                             compress_feedback=True), 0)
    for n in (1.0, 5.0, 25.0):
        w._adapt_topk(n)
    assert w._topk_fraction == pytest.approx(0.05)


# ----------------------------------------------------------- validation ----
def test_validation_rejects_unsound_adaptive_configs():
    with pytest.raises(ValueError, match="topk_adaptive"):
        validate_robustness(_config(num_clients=3, topk_adaptive=True))
    with pytest.raises(ValueError, match="topk_adaptive"):
        validate_robustness(_config(num_clients=3, compress="topk",
                                    topk_adaptive=True))
    with pytest.raises(ValueError, match="topk_min_fraction"):
        validate_robustness(_adaptive_cfg(topk_min_fraction=0.3,
                                          topk_max_fraction=0.1))
    validate_robustness(_adaptive_cfg())     # sound config passes


# ---------------------------------------------------------- convergence ----
def _run(cfg, rounds=4):
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(3)]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0)
            coord.enroll(min_devices=3, timeout=20.0)
            hist = coord.fit(rounds=rounds)
            acc = coord.evaluate()["eval_acc"]
            coord.close()
            return hist, acc
        finally:
            for w in workers:
                w.stop()


def test_adaptive_federation_tracks_fixed_density():
    fixed = _config(num_clients=3, compress="topk", compress_feedback=True,
                    topk_fraction=0.05)
    h_fix, acc_fix = _run(fixed, rounds=6)
    # Band floor at the fixed baseline's density: the comparison isolates
    # the STEERING (can it widen/settle without hurting convergence),
    # not a thinner wire budget.
    h_ad, acc_ad = _run(_adaptive_cfg(topk_min_fraction=0.05,
                                      topk_max_fraction=0.2), rounds=6)
    assert all(r["completed"] == 2 for r in h_ad)
    assert np.isfinite(h_ad[-1]["train_loss"])
    # Density steering must not cost convergence on the smoke problem.
    assert acc_ad >= acc_fix - 0.1, (acc_ad, acc_fix)
    eff = telemetry.get_registry().gauge("fed.topk_fraction_effective").value
    assert 0.05 <= eff <= 0.2
