"""Test harness: force an 8-device virtual CPU platform BEFORE jax imports.

This is the scale-sim strategy from SURVEY.md §7: multi-chip sharding is
validated on a faked 8-device CPU mesh (``xla_force_host_platform_device_count``)
because the sandbox has a single real TPU chip.
"""

import os

# The sandbox boot (sitecustomize) pins JAX_PLATFORMS=axon and may touch the
# backend before conftest runs, so setting the env var is not enough; the
# jax.config update below is what actually forces CPU.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is dominated by XLA:CPU compiles
# of the jit round programs, and most tests re-request programs an earlier
# run (or another xdist worker) already built.  Keyed by host CPU features
# like __graft_entry__'s cache — XLA:CPU AOT results can SIGILL on a
# different host.
# One shared implementation (utils/compile_cache.py); export_env=True so
# the multi-process tests (CLI federation, DCN children) spawn fresh
# interpreters that share the cache instead of recompiling every program
# from scratch — the single biggest suite cost.
from colearn_federated_learning_tpu.utils.compile_cache import (  # noqa: E402
    enable_host_keyed_cache,
)

enable_host_keyed_cache(os.path.dirname(os.path.abspath(__file__)),
                        dirname=".jax_test_cache", export_env=True)

import sys

# Repo root on sys.path regardless of how pytest was launched: test modules
# import both `tests.*` helpers and `scripts.*` protocol builders, and
# pytest's prepend import mode only adds tests/ itself.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 virtual CPU devices, got {devices}"
    return devices


@pytest.fixture(scope="session")
def mesh8(cpu_devices):
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(cpu_devices[:8]), ("clients",))
