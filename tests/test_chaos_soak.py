"""Chaos soak regression gate (tier 1, CPU, deterministic).

Runs the canned fault plan (drops + delays + one corrupt frame + one
mid-run crash) against a fault-free baseline with the SAME config and
seeds, then asserts the acceptance criteria via the same ``check_soak``
the operator script (scripts/chaos_soak.py) uses.  A separate small pair
of runs pins the zero-cost contract: an installed-but-empty fault plan
leaves round records byte-identical to no fault layer at all."""

import importlib.util
import json
import pathlib

import pytest

from colearn_federated_learning_tpu import faults
from colearn_federated_learning_tpu.faults import soak as soak_lib

ROUNDS = 10


def _load_script():
    path = (pathlib.Path(__file__).resolve().parent.parent
            / "scripts" / "chaos_soak.py")
    spec = importlib.util.spec_from_file_location("chaos_soak_script", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def soak_pair():
    base = faults.run_soak(rounds=ROUNDS)
    faulted = faults.run_soak(rounds=ROUNDS, plan=faults.canned_plan())
    return base, faulted


def test_canned_plan_meets_acceptance(soak_pair):
    base, faulted = soak_pair
    problems = _load_script().check_soak(base, faulted, ROUNDS, tol=0.1)
    assert problems == []


def test_no_round_records_lost(soak_pair):
    base, faulted = soak_pair
    for s in (base, faulted):
        assert [r["round"] for r in s["records"]] == list(range(ROUNDS))


def test_faulted_run_recovers_and_counts(soak_pair):
    _, faulted = soak_pair
    # Each scheduled spec fired its full budget — determinism, not luck.
    plan = faults.canned_plan()
    assert set(faulted["faults_fired"]) == set(range(len(plan.faults)))
    assert faulted["counters"]["fault.injected_total"] == sum(
        faulted["faults_fired"].values()
    )
    assert faulted["counters"]["comm.retry_total"] > 0
    assert faulted["counters"]["comm.corrupt_frames_total"] == 1
    assert faulted["counters"]["fed.rounds_skipped_quorum"] == 1
    # The quorum no-op round released no aggregate...
    skipped = [r for r in faulted["records"] if r.get("skipped_quorum")]
    assert [r["round"] for r in skipped] == [2]
    # ...and every non-skipped post-warmup round completed with a quorum.
    for r in faulted["records"]:
        if not r.get("skipped_quorum"):
            assert r["completed"] >= max(1, r["cohort"] // 2)
    # The crashed worker was evicted, the flaky ones were not.
    assert faulted["evicted"] == ["3"]


def test_fault_layer_is_zero_cost_when_disabled():
    """Installed-but-empty plan vs no plan at all: byte-identical round
    records (minus wall-clock fields), zero injections."""
    kw = dict(rounds=3, n_workers=2, round_timeout=60.0)
    plain = faults.run_soak(**kw)
    empty = faults.run_soak(plan=faults.FaultPlan([]), **kw)
    assert empty["counters"]["fault.injected_total"] == 0
    a = json.dumps([soak_lib.strip_timing(r) for r in plain["records"]],
                   sort_keys=True)
    b = json.dumps([soak_lib.strip_timing(r) for r in empty["records"]],
                   sort_keys=True)
    assert a == b
