"""Real-dataset disk path (data/registry.py `_load_disk`).

All committed accuracy curves run on synthetic stand-ins because the
sandbox has no network; these tests prove the DISK branch — the one a
user with real data actually hits — works end to end: registry
resolution order, keras-layout normalization, shape validation, and a
full engine round training on disk-staged data.
"""

import numpy as np
import pytest

from colearn_federated_learning_tpu.data import registry
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _write_npz(path, x_train, y_train, x_test, y_test):
    np.savez(path, x_train=x_train, y_train=y_train,
             x_test=x_test, y_test=y_test)


def _stage_mnist_tiny(tmp_path, n_train=256, n_test=64, dtype=np.float32):
    """A separable two-class-per-pixel-block dataset in the mnist_tiny
    shape, written keras-style; labels 0..9."""
    rng = np.random.default_rng(0)
    y_tr = rng.integers(0, 10, n_train)
    y_te = rng.integers(0, 10, n_test)

    def make_x(y):
        x = 0.1 * rng.standard_normal((len(y), 28, 28, 1))
        for i, yi in enumerate(y):        # class-dependent bright block
            x[i, 2 * yi: 2 * yi + 3, :5, 0] += 2.0
        return x.astype(np.float32)

    x_tr, x_te = make_x(y_tr), make_x(y_te)
    if dtype == np.uint8:
        x_tr = (np.clip(x_tr, 0, 1) * 255).astype(np.uint8)
        x_te = (np.clip(x_te, 0, 1) * 255).astype(np.uint8)
    _write_npz(tmp_path / "mnist_tiny.npz", x_tr, y_tr, x_te, y_te)
    return x_tr, y_tr


def test_registry_prefers_disk(tmp_path, monkeypatch):
    x_tr, y_tr = _stage_mnist_tiny(tmp_path)
    monkeypatch.setenv("COLEARN_DATA_DIR", str(tmp_path))
    ds = registry.get_dataset("mnist_tiny", seed=0)
    assert ds.source == "disk"
    np.testing.assert_array_equal(ds.y_train, y_tr.astype(np.int32))
    np.testing.assert_allclose(ds.x_train, x_tr, atol=1e-6)
    # Other names still fall back to synthetic.
    assert registry.get_dataset("cifar10_tiny").source == "synthetic"
    # Without the env var the same name is synthetic again.
    monkeypatch.delenv("COLEARN_DATA_DIR")
    assert registry.get_dataset("mnist_tiny").source == "synthetic"


def test_disk_normalizes_keras_raw_bytes(tmp_path, monkeypatch):
    # uint8 0..255 images (the layout keras/fetch scripts produce) must be
    # scaled to [0, 1] float32; (N, 28, 28) grayscale gets its channel dim.
    rng = np.random.default_rng(1)
    x = rng.integers(0, 256, (32, 28, 28), dtype=np.uint8)
    y = rng.integers(0, 10, 32)
    _write_npz(tmp_path / "mnist_tiny.npz", x, y, x[:8], y[:8])
    monkeypatch.setenv("COLEARN_DATA_DIR", str(tmp_path))
    ds = registry.get_dataset("mnist_tiny")
    assert ds.source == "disk"
    assert ds.x_train.dtype == np.float32
    assert ds.x_train.shape == (32, 28, 28, 1)
    assert 0.0 <= ds.x_train.min() and ds.x_train.max() <= 1.0


@pytest.mark.parametrize("corruption",
                         ["missing_key", "bad_shape", "bad_labels",
                          "wrapping_labels"])
def test_disk_malformed_raises(tmp_path, monkeypatch, corruption):
    # A staged-but-broken file must raise loudly, never silently fall back
    # to synthetic (the user believes they are training on real data).
    rng = np.random.default_rng(2)
    x = rng.standard_normal((16, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, 16)
    if corruption == "missing_key":
        np.savez(tmp_path / "mnist_tiny.npz", x_train=x, y_train=y, x_test=x)
    elif corruption == "bad_shape":
        _write_npz(tmp_path / "mnist_tiny.npz", x[:, :14], y, x, y)
    elif corruption == "wrapping_labels":
        # int64 values that would WRAP into range under an int32 cast;
        # the range check must run on the original width.
        yw = y.astype(np.int64)
        yw[0] = 2**32 + 3
        _write_npz(tmp_path / "mnist_tiny.npz", x, yw, x, y)
    else:
        _write_npz(tmp_path / "mnist_tiny.npz", x, y + 100, x, y)
    monkeypatch.setenv("COLEARN_DATA_DIR", str(tmp_path))
    with pytest.raises(ValueError, match="mnist_tiny.npz"):
        registry.get_dataset("mnist_tiny")


def test_engine_trains_on_disk_data(tmp_path, monkeypatch):
    # End to end: registry -> partitioner -> engine round on disk-staged
    # data.  The staged dataset is separable, so accuracy must climb well
    # above chance within a few rounds.
    _stage_mnist_tiny(tmp_path, n_train=512, n_test=128)
    monkeypatch.setenv("COLEARN_DATA_DIR", str(tmp_path))
    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=64),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=6, cohort_size=0,
                      local_steps=4, batch_size=16, lr=0.1, momentum=0.9),
        run=RunConfig(name="disk_e2e"),
    )
    learner = FederatedLearner(cfg)
    assert learner.dataset.source == "disk"
    learner.fit(rounds=6)
    _, acc = learner.evaluate()
    assert acc > 0.5, acc
