"""Update compression: int8 quantization roundtrip, file-flow integration,
and socket federation with compressed updates."""

import dataclasses

import jax
import numpy as np
import pytest

from colearn_federated_learning_tpu.fed import compression
from colearn_federated_learning_tpu.utils import serialization


def _delta():
    rng = np.random.default_rng(0)
    return {
        "layer": {"w": rng.normal(scale=0.02, size=(64, 32)).astype(np.float32),
                  "b": rng.normal(scale=0.01, size=(32,)).astype(np.float32)},
        "head": {"w": np.zeros((32, 10), np.float32)},
    }


def test_int8_roundtrip_error_bounded():
    d = _delta()
    wire, meta = compression.compress_delta(d, "int8")
    assert meta["compress"] == "int8"
    out = compression.decompress_delta(wire, meta)
    for path in (("layer", "w"), ("layer", "b"), ("head", "w")):
        a = d[path[0]][path[1]]
        b = out[path[0]][path[1]]
        scale = np.abs(a).max() / 127.0
        assert np.abs(a - b).max() <= scale / 2 + 1e-9


def test_int8_shrinks_wire_payload():
    d = _delta()
    plain = serialization.pytree_to_bytes(d)
    wire, meta = compression.compress_delta(d, "int8")
    packed = serialization.pytree_to_bytes(wire, meta)
    assert len(packed) < len(plain) * 0.5
    tree, m = serialization.bytes_to_pytree(bytes(packed))
    out = compression.decompress_delta(tree, m)
    assert out["layer"]["w"].shape == (64, 32)


def test_none_passthrough_and_unknown():
    d = _delta()
    wire, meta = compression.compress_delta(d, "none")
    assert wire is d and compression.decompress_delta(wire, meta) is d
    with pytest.raises(ValueError, match="unknown compression"):
        compression.compress_delta(d, "gzip9")


def test_offline_flow_with_int8(tmp_path):
    from colearn_federated_learning_tpu.fed import offline
    from tests.test_engine import tiny_config

    cfg = tiny_config()
    cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, compress="int8"))
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)
    ups = []
    for i in range(3):
        u = str(tmp_path / f"u{i}.npz")
        offline.client_update(cfg, i, g0, u)
        ups.append(u)
    g1 = str(tmp_path / "g1.npz")
    agg = offline.aggregate_updates(cfg, g0, ups, g1)
    assert agg["num_updates"] == 3
    rec = offline.evaluate_global(cfg, g1)
    assert np.isfinite(rec["eval_loss"])

    # int8 aggregation lands close to the uncompressed result
    cfg0 = tiny_config()
    g0b = str(tmp_path / "g0b.npz")
    offline.init_global_model(cfg0, g0b)
    ups0 = []
    for i in range(3):
        u = str(tmp_path / f"v{i}.npz")
        offline.client_update(cfg0, i, g0b, u)
        ups0.append(u)
    g1b = str(tmp_path / "g1b.npz")
    offline.aggregate_updates(cfg0, g0b, ups0, g1b)
    a, _ = serialization.load_pytree_npz(g1)
    b, _ = serialization.load_pytree_npz(g1b)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(la, lb, atol=2e-3)


def test_topk_roundtrip_keeps_largest_entries():
    d = _delta()
    wire, meta = compression.compress_delta(d, "topk")
    assert meta["compress"] == "topk"
    out = compression.decompress_delta(wire, meta, shapes=d)
    for path in (("layer", "w"), ("layer", "b"), ("head", "w")):
        a = d[path[0]][path[1]]
        b = out[path[0]][path[1]]
        assert b.shape == a.shape
        k = max(1, int(np.ceil(a.size * compression.TOPK_FRACTION)))
        kept = np.flatnonzero(b.ravel())
        assert len(kept) <= k
        # every kept value is exact, and they are the top magnitudes
        np.testing.assert_array_equal(b.ravel()[kept], a.ravel()[kept])
        thresh = np.sort(np.abs(a.ravel()))[-k] if a.any() else 0.0
        assert (np.abs(a.ravel()[kept]) >= thresh - 1e-12).all()


def test_topk_shrinks_wire_payload_and_needs_shapes():
    d = _delta()
    plain = serialization.pytree_to_bytes(d)
    wire, meta = compression.compress_delta(d, "topk")
    packed = serialization.pytree_to_bytes(wire, meta)
    assert len(packed) < len(plain) * 0.2        # ~5% density + indices
    with pytest.raises(ValueError, match="shapes"):
        compression.decompress_delta(wire, meta)


def test_offline_flow_with_topk(tmp_path):
    """File federation end-to-end with sparse updates: init -> 2 client
    updates -> aggregate -> eval stays finite and the model moves."""
    from colearn_federated_learning_tpu.fed import offline
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, RunConfig,
    )

    cfg = ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=2, partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=1),
        fed=FedConfig(strategy="fedavg", rounds=1, local_steps=2,
                      batch_size=16, lr=0.1, momentum=0.9, compress="topk"),
        run=RunConfig(name="topk_flow", backend="cpu"),
    )
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)
    ups = []
    for cid in range(2):
        up = str(tmp_path / f"u{cid}.npz")
        offline.client_update(cfg, cid, g0, up)
        ups.append(up)
    g1 = str(tmp_path / "g1.npz")
    stats = offline.aggregate_updates(cfg, g0, ups, g1)
    assert stats["round"] == 1
    rep = offline.evaluate_global(cfg, g1)
    assert np.isfinite(rep["eval_loss"])


def test_topk_roundtrips_list_containers():
    d = {"layers": [np.arange(12, dtype=np.float32).reshape(3, 4),
                    np.ones(5, np.float32)]}
    wire, meta = compression.compress_delta(d, "topk")
    out = compression.decompress_delta(wire, meta, shapes=d)
    assert isinstance(out["layers"], list)
    assert out["layers"][0].shape == (3, 4)
    assert out["layers"][1].shape == (5,)
