"""Update compression: int8 quantization roundtrip, file-flow integration,
and socket federation with compressed updates."""

import dataclasses

import jax
import numpy as np
import pytest

from colearn_federated_learning_tpu.fed import compression
from colearn_federated_learning_tpu.utils import serialization


def _delta():
    rng = np.random.default_rng(0)
    return {
        "layer": {"w": rng.normal(scale=0.02, size=(64, 32)).astype(np.float32),
                  "b": rng.normal(scale=0.01, size=(32,)).astype(np.float32)},
        "head": {"w": np.zeros((32, 10), np.float32)},
    }


def test_int8_roundtrip_error_bounded():
    d = _delta()
    wire, meta = compression.compress_delta(d, "int8")
    assert meta["compress"] == "int8"
    out = compression.decompress_delta(wire, meta)
    for path in (("layer", "w"), ("layer", "b"), ("head", "w")):
        a = d[path[0]][path[1]]
        b = out[path[0]][path[1]]
        scale = np.abs(a).max() / 127.0
        assert np.abs(a - b).max() <= scale / 2 + 1e-9


def test_int8_shrinks_wire_payload():
    d = _delta()
    plain = serialization.pytree_to_bytes(d)
    wire, meta = compression.compress_delta(d, "int8")
    packed = serialization.pytree_to_bytes(wire, meta)
    assert len(packed) < len(plain) * 0.5
    tree, m = serialization.bytes_to_pytree(bytes(packed))
    out = compression.decompress_delta(tree, m)
    assert out["layer"]["w"].shape == (64, 32)


def test_none_passthrough_and_unknown():
    d = _delta()
    wire, meta = compression.compress_delta(d, "none")
    assert wire is d and compression.decompress_delta(wire, meta) is d
    with pytest.raises(ValueError, match="unknown compression"):
        compression.compress_delta(d, "topk")


def test_offline_flow_with_int8(tmp_path):
    from colearn_federated_learning_tpu.fed import offline
    from tests.test_engine import tiny_config

    cfg = tiny_config()
    cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, compress="int8"))
    g0 = str(tmp_path / "g0.npz")
    offline.init_global_model(cfg, g0)
    ups = []
    for i in range(3):
        u = str(tmp_path / f"u{i}.npz")
        offline.client_update(cfg, i, g0, u)
        ups.append(u)
    g1 = str(tmp_path / "g1.npz")
    agg = offline.aggregate_updates(cfg, g0, ups, g1)
    assert agg["num_updates"] == 3
    rec = offline.evaluate_global(cfg, g1)
    assert np.isfinite(rec["eval_loss"])

    # int8 aggregation lands close to the uncompressed result
    cfg0 = tiny_config()
    g0b = str(tmp_path / "g0b.npz")
    offline.init_global_model(cfg0, g0b)
    ups0 = []
    for i in range(3):
        u = str(tmp_path / f"v{i}.npz")
        offline.client_update(cfg0, i, g0b, u)
        ups0.append(u)
    g1b = str(tmp_path / "g1b.npz")
    offline.aggregate_updates(cfg0, g0b, ups0, g1b)
    a, _ = serialization.load_pytree_npz(g1)
    b, _ = serialization.load_pytree_npz(g1b)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(la, lb, atol=2e-3)
