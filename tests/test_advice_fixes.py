"""Regressions for the round-2 ADVICE.md findings: scaffold momentum bias,
profiler leak on early fit() exit, prime-count mesh factoring, shared round
deadline in the socket coordinator, versioned native library filename."""

import threading
import time

import numpy as np
import pytest

from colearn_federated_learning_tpu.comm.broker import MessageBroker
from colearn_federated_learning_tpu.comm.coordinator import FederatedCoordinator
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.parallel import factor_devices, make_mesh
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


def _cfg(**fed_kw):
    fed = dict(strategy="fedavg", rounds=4, cohort_size=0, local_steps=2,
               batch_size=16, lr=0.05, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=4, partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=1),
        fed=FedConfig(**fed),
        run=RunConfig(name="advice", backend="cpu"),
    )


# ---- 1. scaffold momentum guard -------------------------------------------
def test_scaffold_rejects_momentum():
    """Option-II variate refresh is only the mean corrected gradient under
    vanilla SGD; the default momentum=0.9 must be rejected, not silently
    biased."""
    with pytest.raises(ValueError, match="momentum"):
        FederatedLearner(_cfg(strategy="scaffold", momentum=0.9))
    # momentum=0.0 still builds
    FederatedLearner(_cfg(strategy="scaffold", momentum=0.0))


# ---- 2. profiler closed on early exit from fit() --------------------------
def test_profiler_closed_on_fit_exception(tmp_path):
    import dataclasses

    cfg = _cfg()
    cfg = dataclasses.replace(
        cfg, run=dataclasses.replace(cfg.run, profile_dir=str(tmp_path)),
    )
    learner = FederatedLearner(cfg)

    def explode(rec):
        # Round 1 is INSIDE the default trace window (rounds 1..2): the
        # profiler is active when this raises.
        if rec["round"] == 1:
            raise RuntimeError("mid-window failure")

    with pytest.raises(RuntimeError, match="mid-window"):
        learner.fit(rounds=3, log_fn=explode)
    # If fit() leaked the active trace, the next window's start_trace would
    # raise "profiler already started".
    learner.fit(rounds=2)


# ---- 3. mesh factoring for 2 / prime device counts ------------------------
def test_factor_devices_small_and_prime():
    # The trailing (seq) axis takes the whole remainder when it is prime —
    # (1, n) still gives ring attention a real ring; (n, 1) broke it.
    assert factor_devices(2, 2) == (1, 2)
    assert factor_devices(3, 2) == (1, 3)
    assert factor_devices(7, 2) == (1, 7)
    assert factor_devices(8, 2) == (4, 2)
    assert factor_devices(1, 2) == (1, 1)


def test_make_mesh_two_devices_ring_axis(cpu_devices):
    m = make_mesh(("clients", "seq"), devices=cpu_devices[:2])
    assert m.shape == {"clients": 1, "seq": 2}


# ---- 4. shared round deadline ---------------------------------------------
def test_round_timeout_is_shared_not_per_future():
    """Three of four workers hang: the round must cost ~round_timeout, not
    3 x round_timeout (the old sequential per-future collection)."""
    cfg = _cfg(local_steps=1)
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(4)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=4, timeout=20.0)
            warm = coord.run_round()                 # compile everywhere
            assert warm["completed"] == 4

            release = threading.Event()
            originals = {}
            for w in workers[1:]:
                orig = w._train
                originals[w] = orig

                def hang(round_idx, params, _orig=orig):
                    release.wait(timeout=30.0)
                    return _orig(round_idx, params)

                w._train = hang
            coord.round_timeout = 1.5
            t0 = time.perf_counter()
            rec = coord.run_round()
            elapsed = time.perf_counter() - t0
            release.set()
            assert rec["completed"] == 1
            assert sorted(rec["dropped"]) == ["1", "2", "3"]
            assert np.isfinite(rec["train_loss"])
            # one shared deadline: well under 3 sequential timeouts (4.5s)
            assert elapsed < 3.5, f"round took {elapsed:.1f}s"
            coord.close()
        finally:
            for w in workers:
                w.stop()


# ---- 5. versioned native library filename ---------------------------------
def test_native_lib_filename_carries_abi_version():
    from colearn_federated_learning_tpu.native import build as build_mod

    assert f"v{build_mod.ABI_VERSION}" in build_mod.LIB.name
