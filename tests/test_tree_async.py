"""Buffered-async aggregator tree: health-driven slice assignment,
bitwise parity of the per-aggregator partial fold against the flat
async fold (dense, topk8 and LoRA-factor uplinks), re-home dedup
(double-fold-free by construction), the tree-gated record keys, the
per-buffer secure-agg mask cohorts, and the two-tier fleetsim path —
including the pin that aggregators=0 records stay byte-identical."""

import jax
import numpy as np
import pytest

from colearn_federated_learning_tpu.analysis import metric_catalog
from colearn_federated_learning_tpu.comm.aggregation import StreamingFolder
from colearn_federated_learning_tpu.comm.aggregator import (
    assign_slices,
    slice_cohort,
)
from colearn_federated_learning_tpu.fed import compression, hierarchical, lora
from colearn_federated_learning_tpu.utils import pytrees

from tests.test_fleetsim import make_fleet
from tests.test_uplink_fastpath import _params, _tree_bytes


# ------------------------------------------- health-driven assignment ----
def test_assign_slices_default_degrades_to_divmod():
    cohort = [str(i) for i in range(11)]
    for n in (1, 2, 3, 5):
        assert assign_slices(cohort, n) == slice_cohort(cohort, n)
        # All-equal scores are indistinguishable from no ledger at all:
        # the stable sort preserves cohort order exactly.
        uniform = {c: 0.25 for c in cohort}
        assert assign_slices(cohort, n, uniform) == slice_cohort(cohort, n)


def test_assign_slices_concentrates_stragglers_in_last_slice():
    cohort = [str(i) for i in range(12)]
    scores = {c: 0.0 for c in cohort}
    stragglers = {"1", "4", "9"}
    for s in stragglers:
        scores[s] = 5.0
    layout = assign_slices(cohort, 4, scores)
    # Same slice sizes as the contiguous layout, same cohort multiset.
    assert [len(sl) for sl in layout] == [3, 3, 3, 3]
    assert sorted(c for sl in layout for c in sl) == sorted(cohort)
    # Every straggler lands in the LAST slice; the healthy slices are
    # straggler-free, so their buffers keep their fold cadence.
    assert set(layout[-1]) == stragglers
    for sl in layout[:-1]:
        assert not (set(sl) & stragglers)
    # Device tuples (sync-plane cohort entries) key by their id field.
    tuples = [(i, "h", 9000 + i) for i in range(6)]
    tl = assign_slices(tuples, 2, {"2": 9.0, "5": 9.0})
    assert {d[0] for d in tl[-1]} >= {2, 5}


# ---------------------------------------------- partial-fold parity ------
def _async_updates(scheme, n=6):
    """n (meta, wire) contributions for one async dispatch version."""
    shapes = _params()
    out = []
    for i in range(n):
        rng = np.random.default_rng(300 + i)
        d = jax.tree.map(
            lambda w: rng.standard_normal(w.shape).astype(np.float32),
            shapes)
        meta = {"client_id": str(i), "weight": 1.0 + 0.125 * i,
                "mean_loss": 0.4 + 0.05 * i}
        if scheme == "dense":
            wire = d
        else:
            wire, cmeta = compression.compress_delta(d, scheme,
                                                     topk_fraction=0.1)
            meta.update(cmeta)
        out.append((meta, wire))
    return shapes, out


def _tree_vs_flat(shapes, updates, n_agg):
    """Fold ``updates`` once flat (slice-blocked) and once through the
    tree (per-aggregator StreamingFolder partials combined at the root
    via add_partial) and return both root folders."""
    order = [m["client_id"] for m, _ in updates]
    layout = slice_cohort(order, n_agg)

    flat = StreamingFolder(shapes, order=order, slices=layout)
    for meta, wire in updates:
        flat.add(dict(meta), jax.tree.map(np.copy, wire))
    flat.finalize()

    staged = {m["client_id"]: (m, w) for m, w in updates}
    root = StreamingFolder(
        shapes, order=[f"agg:{i}" for i in range(n_agg)])
    for i, sl in enumerate(layout):
        leaf = StreamingFolder(shapes, order=list(sl))
        for cid in sl:
            meta, wire = staged[cid]
            leaf.add(dict(meta), jax.tree.map(np.copy, wire))
        leaf.finalize()
        root.add_partial(f"agg:{i}", leaf.total_w, leaf.wsum,
                         leaf.loss_sum, count=leaf.count)
    root.finalize()
    return flat, root


@pytest.mark.parametrize("n_agg", [2, 3])
@pytest.mark.parametrize("scheme", ["dense", "topk", "topk8"])
def test_partial_fold_at_aggregator_bitwise_vs_flat(scheme, n_agg):
    shapes, updates = _async_updates(scheme)
    flat, root = _tree_vs_flat(shapes, updates, n_agg)
    assert root.total_w == flat.total_w
    assert root.loss_sum == flat.loss_sum
    assert _tree_bytes(root.wsum) == _tree_bytes(flat.wsum)
    # tau = 0 at the root: (1 + 0)^-0.5 == 1.0 exactly, and the IEEE
    # multiply by 1.0 is the identity — so a fresh partial's staleness
    # discount cannot perturb the parity above.
    scaled = pytrees.tree_scale(root.wsum, (1.0 + 0) ** -0.5)
    assert _tree_bytes(scaled) == _tree_bytes(root.wsum)


def test_partial_fold_lora_factor_trees_bitwise():
    """The rank-r uplink folds factor trees, not model trees — the tree
    combine must be bitwise on those too (same shapes through the
    aggregator tier and the root)."""
    template = lora.init_factors(_params(), 4, model_name="bert")
    assert jax.tree.leaves(template), "factor template matched no leaves"
    shapes = jax.tree.map(np.asarray, template)
    updates = []
    for i in range(5):
        rng = np.random.default_rng(70 + i)
        f = jax.tree.map(
            lambda w: rng.standard_normal(w.shape).astype(np.float32),
            shapes)
        updates.append(({"client_id": str(i), "weight": 1.0 + 0.5 * i,
                         "mean_loss": 0.3}, f))
    flat, root = _tree_vs_flat(shapes, updates, 2)
    assert root.total_w == flat.total_w
    assert _tree_bytes(root.wsum) == _tree_bytes(flat.wsum)


# -------------------------------------------------- re-home dedup --------
def test_rehome_dedup_folds_once():
    """A contribution re-homed to a sibling arrives under the same dedup
    key ``version@device``; the buffer discards the staged copy before
    re-staging, so the fold stays single-copy — count, weight and bytes
    all match a folder that saw the update exactly once."""
    shapes, updates = _async_updates("dense", n=3)
    meta, wire = updates[0]
    key = f"{7:08d}@{meta['client_id']}"

    once = StreamingFolder(shapes)
    once.add({**meta, "client_id": key}, jax.tree.map(np.copy, wire))

    twice = StreamingFolder(shapes)
    twice.add({**meta, "client_id": key}, jax.tree.map(np.copy, wire))
    assert twice.has(key)
    assert twice.discard(key) is True        # the re-home dedup path
    assert not twice.has(key)
    assert twice.discard(key) is False       # nothing left to drop
    twice.add({**meta, "client_id": key}, jax.tree.map(np.copy, wire))

    once.finalize()
    twice.finalize()
    assert twice.count == once.count == 1
    assert twice.total_w == once.total_w
    assert _tree_bytes(twice.wsum) == _tree_bytes(once.wsum)
    # Post-finalize discard must refuse: the sum already includes it.
    with pytest.raises(RuntimeError):
        twice.discard(key)


# ----------------------------------------------- record-key registry -----
TREE_KEYS = ("agg_id", "agg_buffer_k", "agg_buffer_staged",
             "agg_buffer_rate_per_s", "oldest_version", "folded_keys",
             "rehomed_devices", "rehomed_total", "agg_fold_tracking_min",
             "aggregators")


def test_tree_gated_record_keys_registered():
    assert set(TREE_KEYS) <= set(metric_catalog.RECORD_KEYS)


# --------------------------------------------- per-buffer mask cohorts ---
def test_buffer_mask_cohorts_partition_and_predicted_dropouts():
    assignment = {str(i): i % 3 for i in range(9)}
    cohorts = hierarchical.buffer_mask_cohorts(assignment)
    assert sorted(cohorts) == [0, 1, 2]
    # A mask pair never spans two buffers: the cohorts partition the
    # assignment exactly, each sorted for deterministic pair order.
    assert sorted(d for devs in cohorts.values() for d in devs) \
        == sorted(assignment)
    for devs in cohorts.values():
        assert devs == sorted(devs, key=str)
    # Pruned devices are predicted dropouts — excluded BEFORE mask
    # commitment, so they never appear in any pairing cohort.
    pruned = hierarchical.buffer_mask_cohorts(assignment, pruned=["4", "7"])
    assert "4" not in pruned[1] and "7" not in pruned[1]
    assert sum(len(d) for d in pruned.values()) == 7


def test_async_mask_cost_predicted_dropout_is_free():
    assignment = {str(i): (0 if i < 6 else 1) for i in range(10)}
    bill = hierarchical.async_mask_cost(assignment, param_count=1000,
                                        pruned=["2", "3"])
    assert bill["predicted_dropouts"] == 2
    # The headline: a pruned client never masked, so its departure costs
    # zero share recoveries — unlike a reactive mid-buffer death, which
    # costs its full degree.
    assert bill["predicted_recovery_shares"] == 0
    assert bill["active_devices"] == 8
    b0 = bill["buffers"][0]
    assert b0["devices"] == 4                 # 6 assigned, 2 pruned
    assert b0["pairs_per_device"] == 3        # masks span the buffer only
    assert b0["reactive_recovery_shares"] == b0["pairs_per_device"]
    assert bill["buffers"][1]["pairs_per_device"] == 3
    assert bill["pairs_total"] == (4 * 3 + 4 * 3) // 2


# ------------------------------------------------- two-tier fleetsim -----
@pytest.mark.slow
def test_fleetsim_tree_async_two_tier_smoke():
    fs = make_fleet(num_devices=32, cohort=8, chunk=8)
    hist = fs.fit_async(8, buffer_size="auto", aggregators=2,
                        max_staleness=20, auto_interval_min=2.0)
    assert len(hist) == 8
    assert [r["model_version"] for r in hist] == list(range(1, 9))
    for rec in hist:
        assert rec["aggregators"] == 2
        assert rec["agg_id"] in (0, 1)
        assert 1 <= rec["agg_buffer_k"] <= 8
        assert 0.0 <= rec["agg_fold_tracking_min"] <= 1.0
        assert np.isfinite(rec["train_loss"])
        assert set(rec) <= set(metric_catalog.RECORD_KEYS)
    # Both slices actually fold: per-slice buffers, not one hot slice.
    assert {r["agg_id"] for r in hist} == {0, 1}


def test_fleetsim_default_async_records_carry_no_tree_keys():
    """aggregators=0 (the default) must keep the flat async record
    schema byte-identical — none of the tree-gated keys may leak."""
    fs = make_fleet(num_devices=32, cohort=8, chunk=8)
    hist = fs.fit_async(4, buffer_size=8, max_staleness=8)
    for rec in hist:
        assert not (set(rec) & set(TREE_KEYS))
    with pytest.raises(ValueError, match="aggregator"):
        fs.fit_async(2, buffer_size=8, aggregators=1)
