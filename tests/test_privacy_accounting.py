"""RDP accountant: closed-form Gaussian point, subsampling amplification,
composition monotonicity, and the engine's per-round (ε, δ) reporting."""

import dataclasses
import math

import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.privacy.accountant import (
    RdpAccountant,
    rdp_to_eps_delta,
    subsampled_gaussian_rdp,
)
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)


# ---- closed forms ----------------------------------------------------------
def test_full_batch_rdp_is_exact_gaussian():
    """q=1 collapses to the exact Gaussian RDP α/(2σ²)."""
    for sigma in (0.5, 1.0, 2.0, 4.0):
        for order in (2, 3, 8, 64):
            got = subsampled_gaussian_rdp(1.0, sigma, order)
            assert got == pytest.approx(order / (2 * sigma**2), rel=1e-12)


def test_gaussian_eps_matches_analytic_minimum():
    """For q=1, ε(δ) = min_α [Tα/2σ² + log(1/δ)/(α-1)] has the closed form
    at α* = 1 + sqrt(2σ²·log(1/δ)/T); the integer-order grid must land
    within a few percent above it."""
    sigma, delta, T = 2.0, 1e-5, 10
    acc = RdpAccountant(sigma, 1.0, delta=delta)
    acc.step(T)
    a_star = 1.0 + math.sqrt(2 * sigma**2 * math.log(1 / delta) / T)
    eps_star = T * a_star / (2 * sigma**2) + math.log(1 / delta) / (a_star - 1)
    eps = acc.epsilon()
    assert eps >= eps_star - 1e-9          # discrete grid can't beat analytic
    assert eps <= eps_star * 1.05


def test_small_q_quadratic_amplification():
    """At order 2 the series is exact: log(1 + q²(e-1) + O(q³)) ≈ q²(e-1)."""
    q, sigma = 0.01, 1.0
    got = subsampled_gaussian_rdp(q, sigma, 2)
    expect = math.log(
        (1 - q) ** 2 + 2 * q * (1 - q) + q**2 * math.exp(1.0 / sigma**2)
    )
    assert got == pytest.approx(expect, rel=1e-12)
    assert got < 2 / (2 * sigma**2) * 0.01  # amplification is dramatic


def test_accountant_monotonicity_and_edges():
    base = RdpAccountant(1.0, 0.25)
    base.step(10)
    more_rounds = RdpAccountant(1.0, 0.25)
    more_rounds.step(50)
    quieter = RdpAccountant(2.0, 0.25)
    quieter.step(10)
    bigger_cohort = RdpAccountant(1.0, 0.5)
    bigger_cohort.step(10)
    assert base.epsilon() < more_rounds.epsilon()
    assert quieter.epsilon() < base.epsilon()
    assert base.epsilon() < bigger_cohort.epsilon()

    assert RdpAccountant(1.0, 0.25).epsilon() == 0.0       # no rounds yet
    zero_noise = RdpAccountant(0.0, 0.25)
    zero_noise.step()
    assert math.isinf(zero_noise.epsilon())
    with pytest.raises(ValueError):
        rdp_to_eps_delta(np.ones(3), np.arange(2, 5, dtype=float), 0.0)
    with pytest.raises(ValueError):
        subsampled_gaussian_rdp(1.2, 1.0, 2)


# ---- engine integration ----------------------------------------------------
def _cfg(**fed_kw):
    fed = dict(strategy="fedavg", rounds=3, cohort_size=4, local_steps=2,
               batch_size=8, lr=0.05, momentum=0.9)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=16, partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=16, depth=1),
        fed=FedConfig(**fed),
        run=RunConfig(name="dp_acct", backend="cpu"),
    )


def test_engine_reports_cumulative_epsilon():
    learner = FederatedLearner(
        _cfg(dp_clip=1.0, dp_noise_multiplier=1.0, dp_delta=1e-5)
    )
    eps = []
    for _ in range(3):
        rec = learner.run_round()
        assert rec["dp_delta"] == 1e-5
        eps.append(rec["dp_epsilon"])
    assert all(np.isfinite(e) and e > 0 for e in eps)
    assert eps[0] < eps[1] < eps[2]        # budget strictly accumulates
    # matches a freshly composed accountant for the same mechanism
    ref = RdpAccountant(1.0, learner.dp_cohort / learner.real_num_clients,
                        delta=1e-5)
    ref.step(3)
    assert eps[-1] == pytest.approx(ref.epsilon(), rel=1e-12)


def test_engine_omits_epsilon_without_dp():
    learner = FederatedLearner(_cfg())
    rec = learner.run_round()
    assert "dp_epsilon" not in rec and learner.accountant is None


def test_epsilon_survives_checkpoint_resume(tmp_path):
    cfg = _cfg(dp_clip=1.0, dp_noise_multiplier=1.0)
    cfg = cfg.replace(run=dataclasses.replace(
        cfg.run, checkpoint_dir=str(tmp_path / "ckpt")))
    a = FederatedLearner(cfg)
    a.run_round()
    a.run_round()
    a.save_checkpoint()
    eps_2 = a.history[-1]["dp_epsilon"]

    b = FederatedLearner(cfg)
    assert b.restore_checkpoint() == 2
    rec = b.run_round()                    # round 2 overall
    assert rec["dp_epsilon"] > eps_2       # continues, doesn't restart at 0


def test_coordinator_reports_and_checkpoints_epsilon(tmp_path):
    """Socket plane: per-round ε with the ACTUAL cohort fraction, and the
    accumulated RDP state survives kill-and-resume."""
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker

    cfg = _cfg(dp_clip=1.0, dp_noise_multiplier=1.0, cohort_size=2, rounds=3)
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, num_clients=3),
        run=dataclasses.replace(cfg.run,
                                checkpoint_dir=str(tmp_path / "ckpt"),
                                checkpoint_every=1),
    )
    with MessageBroker() as broker:
        workers = [
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(3)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=3, timeout=20.0)
            r0 = coord.run_round()
            r1 = coord.run_round()
            assert 0 < r0["dp_epsilon"] < r1["dp_epsilon"]
            coord.save_checkpoint()
            eps_at_kill = r1["dp_epsilon"]
            coord.close()

            coord2 = FederatedCoordinator(cfg, broker.host, broker.port,
                                          round_timeout=60.0,
                                          want_evaluator=False)
            assert coord2.restore_checkpoint() == 2
            assert coord2.accountant.epsilon() == pytest.approx(eps_at_kill)
            coord2.enroll(min_devices=3, timeout=20.0)
            rec = coord2.run_round()
            assert rec["dp_epsilon"] > eps_at_kill
            coord2.close()
        finally:
            for w in workers:
                w.stop()


def test_coordinator_charges_realized_not_nominal_noise():
    """Workers calibrate noise to the NOMINAL cohort; when fewer enroll the
    realized central noise is smaller and ε must be charged accordingly
    (higher), not at the nominal σ."""
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker

    cfg = _cfg(dp_clip=1.0, dp_noise_multiplier=1.0, cohort_size=0)
    cfg = cfg.replace(data=dataclasses.replace(cfg.data, num_clients=3))
    with MessageBroker() as broker:
        workers = [  # nominal cohort is 3 (all clients); only 2 enroll
            DeviceWorker(cfg, i, broker.host, broker.port).start()
            for i in range(2)
        ]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0,
                                         want_evaluator=False)
            coord.enroll(min_devices=2, timeout=20.0)
            rec = coord.run_round()
            assert rec["completed"] == 2

            sigma_eff = 1.0 * math.sqrt(2.0 / 3.0)
            ref_eff = RdpAccountant(1.0, 1.0, delta=cfg.fed.dp_delta)
            ref_eff.step(sampling_rate=1.0, noise_multiplier=sigma_eff)
            assert rec["dp_epsilon"] == pytest.approx(ref_eff.epsilon())

            ref_nominal = RdpAccountant(1.0, 1.0, delta=cfg.fed.dp_delta)
            ref_nominal.step()
            assert rec["dp_epsilon"] > ref_nominal.epsilon()
            coord.close()
        finally:
            for w in workers:
                w.stop()
