"""Aggregator tree (comm/aggregator.py): slice layout, the partial
combine's bitwise parity against the slice-blocked flat fold, the
analytic ingest bill, and full tree federations — dense and topk parity
with the flat coordinator, failover re-home on a killed aggregator, and
secure-agg composition over slice-local mask groups."""

import random

import jax
import numpy as np
import pytest

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.comm.aggregation import StreamingFolder
from colearn_federated_learning_tpu.comm.aggregator import (
    AggregatorServer,
    combine_partial_weights,
    expected_ingest,
    slice_cohort,
)
from colearn_federated_learning_tpu.comm.broker import MessageBroker
from colearn_federated_learning_tpu.comm.coordinator import FederatedCoordinator
from colearn_federated_learning_tpu.comm.worker import DeviceWorker
from colearn_federated_learning_tpu.parallel import partition
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

from tests.test_uplink_fastpath import _params, _topk_updates, _tree_bytes


# ------------------------------------------------------------- slicing ----
def test_slice_cohort_contiguous_and_balanced():
    cohort = [str(i) for i in range(10)]
    for n in (1, 2, 3, 4, 7, 10, 13):
        slices = slice_cohort(cohort, n)
        assert len(slices) == n
        # Contiguous: concatenation reproduces the cohort order exactly.
        assert [c for sl in slices for c in sl] == cohort
        sizes = [len(sl) for sl in slices]
        assert max(sizes) - min(sizes) <= 1
    assert slice_cohort([], 3) == [[], [], []]
    assert slice_cohort(cohort, 0) == [cohort]      # clamped to 1


def test_expected_ingest_bill():
    bill = expected_ingest(cohort=10, n_aggregators=4, update_bytes=100,
                           partial_bytes=700)
    assert bill["agg_ingest_bytes"] == 3 * 100       # ceil(10/4) frames
    assert bill["root_ingest_bytes"] == 4 * 700
    assert bill["flat_root_ingest_bytes"] == 10 * 100


def test_combine_partial_weights_is_sequential_float_sum():
    ws = [0.1, 0.7, 1e-8, 3.0]
    acc = 0.0
    for w in ws:
        acc += w
    assert combine_partial_weights(ws) == acc


# ----------------------------------------------- partial-combine parity ----
def _tree_fold(shapes, layout, updates, placement=None):
    """Simulate the tree: one folder per slice (what an AggregatorServer
    runs), then a root folder combining the partials in slice order."""
    staged = {m["client_id"]: (m, w) for m, w, _ in updates}
    root = StreamingFolder(shapes, order=[f"agg:{i}"
                                          for i in range(len(layout))],
                           placement=placement)
    for i, sl in enumerate(layout):
        leaf = StreamingFolder(shapes, order=list(sl))
        for cid in sl:
            if cid in staged:
                meta, wire = staged[cid]
                leaf.add(dict(meta), jax.tree.map(np.copy, wire))
        leaf.finalize()
        root.add_partial(f"agg:{i}", leaf.total_w, leaf.wsum,
                         leaf.loss_sum, count=leaf.count)
    root.finalize()
    return root


@pytest.mark.parametrize("present", [5, 3])  # full cohort / partial cohort
@pytest.mark.parametrize("scheme", ["dense", "topk"])
def test_partial_combine_bitwise_vs_slice_blocked_flat(scheme, present):
    shapes = _params()
    updates = [(m, w, d) for m, w, d in _topk_updates(5)][:present]
    if scheme == "dense":
        updates = [({k: v for k, v in m.items() if k != "compress"}, d, d)
                   for m, _, d in updates]
    order = [str(i) for i in range(5)]
    layout = slice_cohort(order, 2)

    flat = StreamingFolder(shapes, order=order, slices=layout)
    arrival = list(updates)
    random.Random(11).shuffle(arrival)       # arrival order must not matter
    for meta, wire, _ in arrival:
        flat.add(dict(meta), jax.tree.map(np.copy, wire))
    flat.finalize()

    tree = _tree_fold(shapes, layout, updates)
    assert tree.total_w == flat.total_w
    assert tree.loss_sum == flat.loss_sum
    assert _tree_bytes(tree.wsum) == _tree_bytes(flat.wsum)


def test_single_slice_layout_matches_historical_fold():
    """slices=[whole cohort] is bitwise identical to slices=None — the
    n_aggregators=1 tree reproduces the flat fold outright."""
    shapes = _params()
    order = [str(i) for i in range(5)]
    hist = StreamingFolder(shapes, order=order)
    one = StreamingFolder(shapes, order=order, slices=[order])
    for meta, wire, _ in _topk_updates(5):
        hist.add(dict(meta), jax.tree.map(np.copy, wire))
        one.add(dict(meta), jax.tree.map(np.copy, wire))
    m_h, w_h, l_h = hist.mean()
    m_o, w_o, l_o = one.mean()
    assert (w_h, l_h) == (w_o, l_o)
    assert _tree_bytes(m_h) == _tree_bytes(m_o)


def test_straggler_outside_layout_folds_as_trailing_block():
    shapes = _params()
    updates = _topk_updates(5)
    order = [str(i) for i in range(5)]
    layout = slice_cohort(order[:4], 2)      # id "4" admitted past layout

    flat = StreamingFolder(shapes, order=order, slices=layout)
    for meta, wire, _ in updates:
        flat.add(dict(meta), jax.tree.map(np.copy, wire))
    flat.finalize()
    assert flat.folded_ids == ["0", "1", "2", "3", "4"]

    tree = _tree_fold(shapes, layout + [["4"]], updates)
    assert _tree_bytes(tree.wsum) == _tree_bytes(flat.wsum)


@pytest.fixture(scope="module")
def placement():
    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("needs the forced 8-device CPU host")
    pl = partition.make_server_placement(
        _params(), 4, "model", "bert", devices=devs[:4])
    assert pl is not None
    return pl


def test_partial_combine_sharded_bitwise(placement):
    """The tp-sharded root combines host partials bitwise identically to
    the replicated root (slicing commutes with the adds)."""
    shapes = placement.shapes_tree()
    updates = _topk_updates(4)
    order = [str(i) for i in range(4)]
    layout = slice_cohort(order, 2)

    rep = _tree_fold(shapes, layout, updates)
    shd = _tree_fold(shapes, layout, updates, placement=placement)
    m_rep, w_rep, _ = rep.mean()
    m_shd, w_shd, _ = shd.mean()
    assert w_rep == w_shd
    host = partition.host_tree(m_shd)
    assert _tree_bytes(m_rep) == _tree_bytes(host)
    for leaf in jax.tree.leaves(m_shd):
        assert isinstance(leaf, jax.Array)


# ------------------------------------------------------ tree federation ----
def _config(num_clients=3, n_agg=0, run_kw=None, **fed_kw):
    fed = dict(strategy="fedavg", rounds=2, cohort_size=0, local_steps=3,
               batch_size=16, lr=0.1, momentum=0.0)
    fed.update(fed_kw)
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=num_clients,
                        partition="iid"),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name="agg_tree_test", backend="cpu",
                      num_aggregators=n_agg, **(run_kw or {})),
    )


def _run(cfg, n_workers, rounds=2, log_fn=None):
    """One federation run; returns (history, final params as numpy)."""
    n_agg = cfg.run.num_aggregators
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(n_workers)]
        aggs = [AggregatorServer(cfg, a, broker.host, broker.port).start()
                for a in range(n_agg)]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0)
            coord.enroll(min_devices=n_workers, timeout=20.0)
            if n_agg:
                assert coord.enroll_aggregators(timeout=20.0)
            hist = coord.fit(rounds=rounds,
                             log_fn=(lambda rec: log_fn(rec, aggs))
                             if log_fn else None)
            params = jax.tree.map(np.asarray, coord.server_state.params)
            coord.close()
            return hist, params
        finally:
            for a in aggs:
                a.stop()
            for w in workers:
                w.stop()


def _max_diff(pa, pb):
    la, lb = jax.tree.leaves(pa), jax.tree.leaves(pb)
    return max(float(np.max(np.abs(a - b))) for a, b in zip(la, lb))


@pytest.mark.parametrize("fed_kw", [{}, {"compress": "topk"}],
                         ids=["dense", "topk"])
def test_tree_federation_bitwise_vs_flat(fed_kw):
    h_flat, p_flat = _run(_config(3, 0, **fed_kw), 3)
    h_tree, p_tree = _run(_config(3, 2, **fed_kw), 3)
    for rf, rt in zip(h_flat, h_tree):
        assert rt["completed"] == rf["completed"]
        assert not rt["dropped"]
        assert rt["aggregators"] == 2          # tree-mode round record
        assert "aggregators" not in rf
    assert _max_diff(p_flat, p_tree) == 0.0


def test_tree_federation_failover_rehomes_killed_aggregator():
    reg = telemetry.get_registry()
    before = reg.counter("comm.agg_failovers_total",
                         labels={"action": "rehome"}).value

    def kill_after_first_round(rec, aggs):
        if rec["round"] == 0:
            aggs[0].stop()       # dies mid-run; later rounds must re-home

    cfg = _config(3, 2, run_kw={"agg_heartbeat_timeout": 2.0})
    hist, params = _run(cfg, 3, rounds=3, log_fn=kill_after_first_round)
    assert len(hist) == 3
    assert all(not r["dropped"] for r in hist)
    completed = hist[0]["completed"]
    # The re-homed slice keeps every device training: no cohort loss.
    assert all(r["completed"] == completed for r in hist)
    assert any(r.get("agg_failovers") for r in hist[1:])
    assert reg.counter("comm.agg_failovers_total",
                       labels={"action": "rehome"}).value > before
    assert np.isfinite(hist[-1]["train_loss"])


@pytest.mark.parametrize("kx", ["shared_seed", "dh"])
def test_tree_federation_secure_agg_exact(kx):
    """Slice-local mask groups: every pair cancels inside one partial, so
    the tree's secure mean matches the flat secure mean to float noise."""
    fed_kw = dict(secure_agg=True, secure_agg_key_exchange=kx)
    h_flat, p_flat = _run(_config(4, 0, **fed_kw), 4)
    h_tree, p_tree = _run(_config(4, 2, **fed_kw), 4)
    assert [r["completed"] for r in h_tree] == \
        [r["completed"] for r in h_flat]
    # Masks do not cancel bitwise across regrouped sums — but they DO
    # cancel (a non-recovered mask would be O(1), not O(eps)).
    assert _max_diff(p_flat, p_tree) < 5e-4


# ------------------------------------------------- fleet health plane ----
def test_tree_trace_stitches_all_three_tiers():
    """PR 12 tentpole: one round trace spans coordinator -> per-aggregator
    slice fold -> worker train, with parent links intact across BOTH
    process hops of the relay."""
    import json

    cfg = _config(3, 2)
    with MessageBroker() as broker:
        workers = [DeviceWorker(cfg, i, broker.host, broker.port).start()
                   for i in range(3)]
        aggs = [AggregatorServer(cfg, a, broker.host, broker.port).start()
                for a in range(2)]
        try:
            coord = FederatedCoordinator(cfg, broker.host, broker.port,
                                         round_timeout=60.0)
            coord.enroll(min_devices=3, timeout=20.0)
            assert coord.enroll_aggregators(timeout=20.0)
            rec = coord.run_round()
            coord.close()
        finally:
            for a in aggs:
                a.stop()
            for w in workers:
                w.stop()

    spans = coord.tracer.snapshot()
    round_sp = next(s for s in spans if s.name == "round")
    ids = {s.span_id for s in spans}
    # middle tier: one adopted fold span per aggregator, child of a
    # coordinator span, same trace id
    folds = [s for s in spans if s.name == "aggregator.fold"]
    assert {s.process for s in folds} == {"aggregator-0", "aggregator-1"}
    for f in folds:
        assert f.trace_id == round_sp.trace_id
        assert f.parent_id in ids
    # leaf tier: every completed worker's train span rode two hops up
    # and parents onto ITS aggregator's fold span
    trains = [s for s in spans if s.name == "worker.train"]
    assert len(trains) == rec["completed"]
    fold_ids = {f.span_id for f in folds}
    for t in trains:
        assert t.trace_id == round_sp.trace_id
        assert t.parent_id in fold_ids
        assert t.process.startswith("worker-")
    # per-tier phase timing landed in the round record; default records
    # carry no health_* keys (byte-stability without --health-dir)
    assert rec["phase_agg_fold_s"] > 0
    assert not any(k.startswith("health_") for k in rec)
    assert "trace_spans" not in json.dumps(rec)


def test_tree_health_ledger_attributes_devices(tmp_path):
    hdir = str(tmp_path / "health")
    cfg = _config(3, 2, run_kw={"health_dir": hdir})
    hist, _ = _run(cfg, 3)

    devices = telemetry.load_health(hdir)
    # the aggregator tier attributed observed round latency for every
    # TRAINER (of 3 workers one enrolls as the evaluator, so 2 train)
    assert len(devices) == 2
    assert all(h.lat_samples for h in devices.values())
    assert all(h.lat_ewma > 0 for h in devices.values())
    # rollup keys stamped on the round records (only with the plane on)
    assert hist[-1]["health_devices"] == 2
    assert hist[-1]["health_lat_p99_s"] > 0
    # a clean run has no offender: the worst-device key stays off (the
    # same conditional-key convention as agg_failovers)
    assert "health_worst_device" not in hist[-1]
    # the renderer shows per-aggregator slice skew for a 2-agg tree
    text = telemetry.render_health(devices)
    assert "per-aggregator slice skew" in text
