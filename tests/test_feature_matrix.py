"""Cross-feature smoke matrix: one engine round per flag combination.

Each dedicated test file covers its feature in depth; this matrix pins the
COMPOSITIONS — pairs that share engine plumbing but no dedicated test.
One tiny round each (compile-cached MLP), asserting a finite loss and a
real contribution.
"""

import numpy as np
import pytest

from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

COMBOS = {
    "fedprox_straggler_dp": dict(strategy="fedprox", prox_mu=0.01,
                                 straggler_prob=0.3, dp_clip=1.0,
                                 dp_noise_multiplier=0.2),
    "fednova_median": dict(strategy="fednova", aggregator="median",
                           straggler_prob=0.3,
                           straggler_min_fraction=0.01),
    "fednova_secure_agg": dict(strategy="fednova", secure_agg=True),
    "fedyogi_trimmed": dict(strategy="fedyogi", aggregator="trimmed_mean",
                            trim_fraction=0.25),
    "adaptive_clip_stragglers": dict(dp_clip=10.0, dp_adaptive_clip=True,
                                     straggler_prob=0.4,
                                     straggler_min_fraction=0.01),
    "krum_cohort_sampling": dict(aggregator="krum", trim_fraction=0.25,
                                 cohort_size=6),
    "secure_ring_dp": dict(secure_agg=True, secure_agg_neighbors=2,
                           dp_clip=1.0, dp_noise_multiplier=0.2),
    "fedadam_cohort": dict(strategy="fedadam", cohort_size=4),
}


@pytest.mark.parametrize("name", sorted(COMBOS))
def test_feature_combo_runs_one_round(name):
    fed = dict(strategy="fedavg", rounds=1, cohort_size=0, local_steps=2,
               batch_size=8, lr=0.1, momentum=0.0)
    fed.update(COMBOS[name])
    learner = FederatedLearner(ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=8, partition="iid",
                        max_examples_per_client=32),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32, depth=2),
        fed=FedConfig(**fed),
        run=RunConfig(name=f"matrix_{name}"),
    ))
    rec = learner.run_round()
    assert np.isfinite(rec["train_loss"]), (name, rec)
    assert rec["completed"] >= 1, (name, rec)
