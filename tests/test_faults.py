"""faults/: deterministic fault plans, the transport interposer seams,
and the comm hardening they exercise (CRC framing, bounded retry,
robustness config validation, enrollment timeout)."""

import socket
import struct

import numpy as np
import pytest

from colearn_federated_learning_tpu import telemetry
from colearn_federated_learning_tpu.comm import protocol
from colearn_federated_learning_tpu.comm.broker import (
    BrokerClient,
    MessageBroker,
)
from colearn_federated_learning_tpu.comm.enrollment import (
    EnrollmentTimeout,
    await_role,
)
from colearn_federated_learning_tpu.comm.transport import (
    RetryPolicy,
    TensorClient,
    TensorServer,
)
from colearn_federated_learning_tpu.faults import (
    FaultPlan,
    FaultSpec,
    inject,
)
from colearn_federated_learning_tpu.utils.config import (
    RunConfig,
    validate_robustness,
)


def _counter(name):
    return telemetry.get_registry().counter(name).value


@pytest.fixture
def clean_interposer():
    yield
    inject.uninstall()


# ------------------------------------------------------------------ plan ----
def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(kind="explode")
    with pytest.raises(ValueError, match="probability"):
        FaultSpec(kind="delay", probability=1.5)
    with pytest.raises(ValueError, match="site"):
        FaultSpec(kind="delay", site="middlebox")
    with pytest.raises(ValueError):
        FaultSpec(kind="delay", ms=-1)


def test_plan_json_roundtrip_and_budget():
    plan = FaultPlan([
        FaultSpec(kind="flap_reconnect", device_id="1", round=2, op="train",
                  count=2),
        FaultSpec(kind="delay", ms=50.0, count=0),       # unlimited
    ], seed=9)
    plan2 = FaultPlan.from_json(plan.to_json())
    assert plan2.seed == 9
    assert plan2.faults == plan.faults

    # Budget: the flap fires exactly twice, then its count is spent.
    assert len(plan2.match("1", 2, "train", kinds=("flap_reconnect",))) == 1
    assert len(plan2.match("1", 2, "train", kinds=("flap_reconnect",))) == 1
    assert plan2.match("1", 2, "train", kinds=("flap_reconnect",)) == []
    # Wildcards + count=0 never exhaust.
    for _ in range(5):
        assert len(plan2.match("7", 0, "eval", kinds=("delay",))) == 1
    assert plan2.fired == {0: 2, 1: 5}
    # Key mismatches never fire.
    assert plan2.match("2", 2, "train", kinds=("flap_reconnect",)) == []
    assert plan2.match("1", 3, "train", kinds=("flap_reconnect",)) == []


def test_plan_probability_is_deterministic():
    spec = [FaultSpec(kind="delay", probability=0.4, count=0)]
    fires = [
        tuple(bool(FaultPlan(spec, seed=s).match(str(d), r, "train",
                                                 kinds=("delay",)))
              for d in range(4) for r in range(8))
        for s in (3, 3, 4)
    ]
    assert fires[0] == fires[1]          # same seed → same schedule
    assert fires[0] != fires[2]          # different seed → different one
    assert any(fires[0]) and not all(fires[0])   # the gate actually gates


# ------------------------------------------------------------- protocol ----
def test_corrupt_frame_raises_and_counts():
    a, b = socket.socketpair()
    try:
        before = _counter("comm.corrupt_frames_total")
        inject.send_corrupt_frame(a)
        with pytest.raises(protocol.CorruptFrame):
            protocol.recv_msg(b)
        assert _counter("comm.corrupt_frames_total") == before + 1
        # CorruptFrame is a ValueError: per-connection handlers that
        # classify peer failures via ValueError keep working.
        assert issubclass(protocol.CorruptFrame, ValueError)
    finally:
        a.close()
        b.close()


def test_insane_header_length_is_corrupt_frame():
    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack(">I", 1 << 31))
        with pytest.raises(protocol.CorruptFrame):
            protocol.recv_msg(b)
    finally:
        a.close()
        b.close()


def test_wake_accept_honors_timeout_and_counts():
    # Grab a port with no listener: wake_accept must fail FAST (bounded by
    # the caller's timeout) and count the suppressed failure.
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    host, port = probe.getsockname()
    probe.close()
    before = _counter("comm.suppressed_oserrors_total")
    protocol.wake_accept(host, port, timeout=0.2)      # must not raise
    assert _counter("comm.suppressed_oserrors_total") == before + 1


# ------------------------------------------------------------ transport ----
def _echo(header, tree):
    return {"meta": {"ok": True}}, tree


def test_flap_is_retried_transparently(clean_interposer):
    plan = FaultPlan([FaultSpec(kind="flap_reconnect", device_id="srv",
                                op="echo", count=1)])
    inject.install(plan)
    before = _counter("comm.retry_total")
    with TensorServer(_echo, ident="srv") as srv:
        cli = TensorClient(srv.host, srv.port, ident="srv")
        tree = {"w": np.arange(4.0)}
        header, out = cli.request({"op": "echo"}, tree, timeout=5.0,
                                  retry=RetryPolicy(max_retries=2,
                                                    backoff_base=0.01))
        assert header["status"] == "ok"
        np.testing.assert_array_equal(out["w"], tree["w"])
        cli.close()
    assert plan.total_fired() == 1
    assert _counter("comm.retry_total") > before
    # Labeled roll-up: the retry is also attributed to the flaky peer.
    assert _counter("comm.retry_total{device=srv}") > 0


def test_flap_without_retry_policy_raises(clean_interposer):
    plan = FaultPlan([FaultSpec(kind="flap_reconnect", device_id="srv",
                                op="echo", count=1)])
    inject.install(plan)
    with TensorServer(_echo, ident="srv") as srv:
        cli = TensorClient(srv.host, srv.port, ident="srv")
        with pytest.raises((protocol.ConnectionClosed, OSError)):
            cli.request({"op": "echo"}, {"w": np.ones(2)}, timeout=5.0)
        cli.close()


def test_drop_request_times_out_then_recovers(clean_interposer):
    plan = FaultPlan([FaultSpec(kind="drop_request", device_id="srv",
                                op="echo", count=1)])
    inject.install(plan)
    with TensorServer(_echo, ident="srv") as srv:
        cli = TensorClient(srv.host, srv.port, ident="srv")
        # The dropped request is a genuine lost message: no reply ever
        # comes, the client times out (retry must NOT mask a timeout).
        with pytest.raises(TimeoutError):
            cli.request({"op": "echo"}, {"w": np.ones(2)}, timeout=0.5,
                        retry=RetryPolicy(max_retries=2))
        # Budget spent: the connection is still in sync and serves again.
        header, _ = cli.request({"op": "echo"}, {"w": np.ones(2)},
                                timeout=5.0)
        assert header["status"] == "ok"
        cli.close()


def test_corrupt_reply_is_retried(clean_interposer):
    plan = FaultPlan([FaultSpec(kind="corrupt_payload", device_id="srv",
                                op="echo", count=1)])
    inject.install(plan)
    before = _counter("comm.corrupt_frames_total")
    with TensorServer(_echo, ident="srv") as srv:
        cli = TensorClient(srv.host, srv.port, ident="srv")
        header, out = cli.request({"op": "echo"}, {"w": np.ones(3)},
                                  timeout=5.0,
                                  retry=RetryPolicy(max_retries=2,
                                                    backoff_base=0.01))
        assert header["status"] == "ok"
        cli.close()
    assert _counter("comm.corrupt_frames_total") == before + 1


def test_retry_deadline_is_shared(clean_interposer):
    import time

    plan = FaultPlan([FaultSpec(kind="flap_reconnect", device_id="srv",
                                op="echo", count=0)])     # flap forever
    inject.install(plan)
    with TensorServer(_echo, ident="srv") as srv:
        cli = TensorClient(srv.host, srv.port, ident="srv")
        t0 = time.monotonic()
        with pytest.raises((protocol.ConnectionClosed, OSError,
                            TimeoutError)):
            cli.request({"op": "echo"}, {"w": np.ones(2)}, timeout=10.0,
                        retry=RetryPolicy(max_retries=50,
                                          backoff_base=0.05),
                        deadline=time.monotonic() + 0.8)
        # 50 retries notwithstanding, the shared deadline bounds the call.
        assert time.monotonic() - t0 < 5.0
        cli.close()


# --------------------------------------------------------------- config ----
def test_validate_robustness_raises():
    with pytest.raises(ValueError, match="evict_after"):
        validate_robustness(_cfg(run=dict(evict_after=0)))
    with pytest.raises(ValueError, match="min_cohort_fraction"):
        validate_robustness(_cfg(fed=dict(min_cohort_fraction=1.5)))
    with pytest.raises(ValueError, match="comm_retries"):
        validate_robustness(_cfg(run=dict(comm_retries=-1)))
    with pytest.raises(ValueError, match="worker_enroll_timeout"):
        validate_robustness(_cfg(run=dict(worker_enroll_timeout=0)))
    validate_robustness(_cfg())          # defaults pass


def _cfg(fed=None, run=None):
    import dataclasses

    from colearn_federated_learning_tpu.utils.config import get_config

    cfg = get_config("mnist_mlp_fedavg")
    return cfg.replace(
        fed=dataclasses.replace(cfg.fed, **(fed or {})),
        run=dataclasses.replace(cfg.run, **(run or {})),
    )


def test_run_config_has_robustness_fields():
    run = RunConfig(name="x")
    assert run.evict_after == 3
    assert run.worker_enroll_timeout == 3600.0
    assert run.comm_retries == 2
    assert run.fault_plan is None


# ----------------------------------------------------------- enrollment ----
def test_await_role_raises_enrollment_timeout():
    with MessageBroker() as broker:
        cli = BrokerClient(broker.host, broker.port)
        cli.subscribe("colearn/role/42")
        with pytest.raises(EnrollmentTimeout, match="no role assignment"):
            await_role(cli, "42", timeout=0.3)
        assert issubclass(EnrollmentTimeout, TimeoutError)
        cli.close()


def test_broker_client_alive_flips_on_broker_death():
    broker = MessageBroker().start()
    cli = BrokerClient(broker.host, broker.port)
    assert cli.alive()
    broker.stop()
    deadline = __import__("time").monotonic() + 5.0
    while cli.alive() and __import__("time").monotonic() < deadline:
        __import__("time").sleep(0.05)
    assert not cli.alive()
    cli.close()


# ------------------------------------------------- downlink delta resync ----
def test_downlink_int8_resyncs_and_converges_under_faults():
    """Downlink delta compression under a flap/drop/crash plan: the
    dropped round opens a round gap in that worker's param cache, so the
    next delta broadcast MUST trigger a full-params resync
    (``comm.resync_total``), and the faulted int8 run must land within
    tolerance of the same-faults full-params baseline (the resync ships
    the coordinator's reconstruction, so rejoiners match their peers)."""
    import dataclasses

    from colearn_federated_learning_tpu.faults.soak import (
        default_soak_config,
        run_soak,
    )

    def plan():
        # Rebuilt per run: FaultPlan.fired mutates.
        return FaultPlan([
            FaultSpec(kind="flap_reconnect", device_id="1", round=1,
                      op="train", count=2),
            FaultSpec(kind="drop_request", device_id="2", round=2,
                      op="train"),
            FaultSpec(kind="crash_worker", device_id="3", round=4,
                      op="train"),
        ], seed=11)

    base = run_soak(rounds=7, n_workers=4, plan=plan(),
                    round_timeout=8.0)

    cfg = default_soak_config(4)
    cfg = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, compress_down="int8"))
    resync0 = _counter("comm.resync_total")
    saved0 = _counter("comm.bytes_saved_downlink")
    dn = run_soak(rounds=7, n_workers=4, plan=plan(),
                  round_timeout=8.0, config=cfg)

    # Device 2 missed round 2 entirely, so round 3's delta (base=2) found
    # a stale cache and went through the full-params resync path.
    assert _counter("comm.resync_total") - resync0 >= 1
    assert _counter("comm.bytes_saved_downlink") - saved0 > 0
    # Same fault trajectory in both runs...
    assert dn["skipped_rounds"] == base["skipped_rounds"]
    assert dn["evicted"] == base["evicted"]
    # ...and the quantized run converges next to the full-params one.
    assert base["weighted_acc"] is not None
    assert abs(dn["weighted_acc"] - base["weighted_acc"]) <= 0.1
