#!/usr/bin/env python
"""Tier-1 lint gate: `python scripts/lint.py [paths...]`.

Thin wrapper over `colearn lint` that pins the repo root to this
checkout, so CI and pre-test hooks get the checked-in pyproject config
and baseline regardless of cwd.  Fast and CPU-only: nothing on this
path imports jax or touches a device.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None):
    sys.path.insert(0, REPO_ROOT)
    from colearn_federated_learning_tpu.cli import main as cli_main

    args = list(sys.argv[1:] if argv is None else argv)
    return cli_main(["lint", "--root", REPO_ROOT, *args])


if __name__ == "__main__":
    raise SystemExit(main())
