#!/usr/bin/env python
"""Literature-anchored accuracy validation (SURVEY.md hard-part #5).

Reproduces the canonical FedAvg MNIST experiment from McMahan et al. 2017,
"Communication-Efficient Learning of Deep Networks from Decentralized
Data" (AISTATS), §3 + Table 1, with this framework's engine:

- model: the paper's "2NN" — MLP, two hidden layers of 200 units
  (199,210 params), matching ``ModelConfig(name="mlp", hidden_dim=200,
  depth=2)``;
- 100 clients, client fraction C=0.1 (cohort 10), local batch B=10,
  local epochs E=1, SGD;
- partitions: IID (shuffled deal) and "pathological non-IID" (sort by
  digit, 200 shards of 300, 2 shards per client —
  ``data/partition.pathological_partition``).

Paper targets (Table 1, 2NN, C=0.1, B=10, E=1): 97% test accuracy in
~87 rounds IID and ~664 rounds pathological non-IID.  The protocol here
accepts a 2x round budget (learning-rate tuning in the paper was per-cell;
we use one fixed lr) and asserts the SHAPE anchors:

1. IID reaches 97% within 2x the paper's rounds (<= 174);
2. non-IID also reaches 97% within 2x (<= 1328) — and needs MORE rounds
   than IID (label skew slows FedAvg, the paper's core observation).

Requires REAL MNIST staged on disk (``scripts/fetch_data.py`` →
``$COLEARN_DATA_DIR/mnist.npz``); synthetic stand-ins would validate
nothing about the literature.  Exits 3 with a message when absent.
Writes ``results/literature_mnist.json``; tests/test_literature.py runs
a shortened version of the same protocol in CI when the data is present.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from colearn_federated_learning_tpu.data import registry as data_registry
from colearn_federated_learning_tpu.fed.engine import FederatedLearner
from colearn_federated_learning_tpu.utils.config import (
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Table 1 round counts (2NN, C=0.1, E=1, B=10) — the anchors.
PAPER_ROUNDS_TO_97 = {"iid": 87, "pathological": 664}
TARGET_ACC = 0.97
BUDGET_FACTOR = 2.0  # accept <= 2x the paper (single fixed lr vs per-cell tuning)


def mcmahan_2nn_config(partition: str, rounds: int, lr: float, seed: int = 0
                       ) -> ExperimentConfig:
    return ExperimentConfig(
        data=DataConfig(dataset="mnist", num_clients=100, partition=partition),
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=200, depth=2),
        fed=FedConfig(strategy="fedavg", rounds=rounds, cohort_size=10,
                      local_epochs=1, batch_size=10, lr=lr, momentum=0.0),
        run=RunConfig(name=f"mcmahan_2nn_{partition}", seed=seed,
                      backend="auto"),
    )


def run_curve(partition: str, rounds: int, lr: float, eval_every: int,
              target: float = TARGET_ACC, seed: int = 0) -> dict:
    """Train until ``target`` test accuracy or ``rounds``; returns the curve
    and the first round index at which target was met (1-based, None if
    never)."""
    cfg = mcmahan_2nn_config(partition, rounds, lr, seed)
    dataset = data_registry.get_dataset("mnist", seed=seed)
    if dataset.source != "disk":
        print("literature validation needs REAL MNIST on disk: run "
              "scripts/fetch_data.py and set COLEARN_DATA_DIR", file=sys.stderr)
        sys.exit(3)
    learner = FederatedLearner.from_config(cfg, dataset=dataset)
    curve, reached = [], None
    t0 = time.perf_counter()
    for r in range(1, rounds + 1):
        learner.run_round(sync=False)
        if r % eval_every == 0 or r == rounds:
            _, acc = learner.evaluate()
            acc = float(acc)
            curve.append({"round": r, "test_acc": round(acc, 4)})
            if reached is None and acc >= target:
                reached = r
                break
    return {
        "partition": partition,
        "rounds_to_target": reached,
        "target_acc": target,
        "paper_rounds": PAPER_ROUNDS_TO_97[partition],
        "budget_rounds": int(PAPER_ROUNDS_TO_97[partition] * BUDGET_FACTOR),
        "curve": curve,
        "wall_seconds": round(time.perf_counter() - t0, 1),
        "platform": __import__("jax").devices()[0].platform,
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--lr", type=float, default=0.1,
                   help="client SGD lr (paper tuned per cell; 0.1 is the "
                        "standard reproduction value for the 2NN)")
    p.add_argument("--eval-every", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--only", choices=["iid", "pathological"], default=None)
    args = p.parse_args()

    parts = [args.only] if args.only else ["iid", "pathological"]
    out = {"protocol": "McMahan et al. 2017 Table 1 (2NN, C=0.1, B=10, E=1)",
           "lr": args.lr, "seed": args.seed, "recorded_unix": int(time.time()),
           "runs": []}
    ok = True
    for part in parts:
        budget = int(PAPER_ROUNDS_TO_97[part] * BUDGET_FACTOR)
        rec = run_curve(part, budget, args.lr, args.eval_every, seed=args.seed)
        rec["ok"] = rec["rounds_to_target"] is not None
        ok &= rec["ok"]
        print(json.dumps({k: rec[k] for k in
                          ("partition", "rounds_to_target", "paper_rounds",
                           "budget_rounds", "ok", "wall_seconds")}))
        out["runs"].append(rec)

    by_part = {r["partition"]: r for r in out["runs"]}
    if {"iid", "pathological"} <= by_part.keys() and ok:
        # The paper's core observation: label skew slows FedAvg.
        slower = (by_part["pathological"]["rounds_to_target"]
                  > by_part["iid"]["rounds_to_target"])
        out["noniid_slower_than_iid"] = bool(slower)
        ok &= slower

    path = os.path.join(REPO, "results", "literature_mnist.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(f"wrote {path}; ok={ok}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
