#!/usr/bin/env python
"""Mesh smoke: sharded vs replicated server on a forced 8-device CPU host.

The multichip proof for the PR 9 sharded server, runnable anywhere CI
runs: force 8 host CPU devices, build the BERT-config global model, and
measure the server plane both ways —

- ``replicated``: every chip holds the full params/opt state (the pre-PR
  layout);
- ``sharded``: params, optimizer state, and the fold live partitioned
  over a ``(model,)`` mesh (parallel/partition.ServerPlacement).

Self-checking: the sharded StreamingFolder fold must be BITWISE identical
to the replicated fold, the sharded DownlinkEncoder frame byte-identical
to the gathered frame, and per-chip server bytes strictly lower sharded
than replicated.  One JSON row per mode plus a ``compare`` row with
``hbm_ratio_sharded_over_replicated`` is written to
``results/mesh_bench.jsonl`` — the sentinel rules in pyproject.toml pin
the ratio < 1 and gather-bytes-avoided > 0, so a regression that quietly
re-replicates the server fails `colearn slo`.

Usage (CPU):
    JAX_PLATFORMS=cpu python scripts/mesh_smoke.py [--tp-size 4]
    JAX_PLATFORMS=cpu python scripts/mesh_smoke.py --check-multichip
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Must land before jax initializes (same trick as tests/conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_MULTICHIP_KEYS = {"n_devices", "rc", "ok", "skipped", "tail"}


def check_multichip_records() -> int:
    """Schema-check the committed MULTICHIP_r*.json records (the TPU-pod
    dryrun artifacts): every row carries exactly the keys downstream
    tooling reads.  Returns a process exit code."""
    paths = sorted(glob.glob(os.path.join(_REPO, "MULTICHIP_r*.json")))
    if not paths:
        print("FAIL: no MULTICHIP_r*.json records found", file=sys.stderr)
        return 1
    bad = 0
    for p in paths:
        try:
            with open(p) as f:
                row = json.load(f)
        except (OSError, ValueError) as e:
            print(f"FAIL: {os.path.basename(p)}: unreadable ({e})",
                  file=sys.stderr)
            bad += 1
            continue
        missing = _MULTICHIP_KEYS - set(row)
        if missing:
            print(f"FAIL: {os.path.basename(p)}: missing keys "
                  f"{sorted(missing)}", file=sys.stderr)
            bad += 1
            continue
        if not isinstance(row["n_devices"], int) or row["n_devices"] < 1:
            print(f"FAIL: {os.path.basename(p)}: bad n_devices "
                  f"{row['n_devices']!r}", file=sys.stderr)
            bad += 1
    print(f"multichip schema: {len(paths) - bad}/{len(paths)} records ok")
    return 1 if bad else 0


def bert_config(tp_size: int):
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, RunConfig,
    )

    return ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=8,
                        partition="iid", max_examples_per_client=8),
        model=ModelConfig(name="bert", num_classes=4, width=32, depth=2,
                          num_heads=4, seq_len=64, vocab_size=2000),
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=0,
                      local_steps=1, batch_size=4, lr=0.05, momentum=0.9),
        run=RunConfig(name="mesh_smoke", backend="cpu", seed=0,
                      tp_size=tp_size),
    )


def run_smoke(tp_size: int, out_path: str) -> int:
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from colearn_federated_learning_tpu.comm.aggregation import (
        StreamingFolder,
    )
    from colearn_federated_learning_tpu.comm.downlink import DownlinkEncoder
    from colearn_federated_learning_tpu.fed import setup as setup_lib
    from colearn_federated_learning_tpu.fed import strategies
    from colearn_federated_learning_tpu.parallel import partition

    devices = jax.devices()
    if len(devices) < tp_size:
        print(f"FAIL: need {tp_size} devices, have {len(devices)}",
              file=sys.stderr)
        return 1

    config = bert_config(tp_size)
    params = setup_lib.init_global_params(config)
    placement = partition.make_server_placement(
        params, tp_size, config.run.tp_axis, config.model.name,
        devices=devices)
    if placement is None:
        print("FAIL: make_server_placement fell back to replicated",
              file=sys.stderr)
        return 1

    rows = []

    # Replicated layout: full server state on every chip of the SAME mesh.
    rep_specs = jax.tree.map(lambda _: P(), params)
    replicated = partition.shard_tree(params, rep_specs, placement.mesh)
    rep_state = strategies.init_server_state(replicated, config.fed)
    rep_bytes = partition.bytes_per_chip(rep_state)
    rows.append({
        "bench": "mesh_smoke", "mode": "replicated", "model": "bert",
        "tp_size": 1, "n_devices": len(devices),
        "server_bytes_per_chip": int(rep_bytes),
        "gather_bytes_avoided": 0, "sharded_fraction": 0.0,
    })

    sharded = placement.shard(params)
    shd_state = strategies.init_server_state(sharded, config.fed)
    shd_bytes = partition.bytes_per_chip(shd_state)
    avoided = partition.tree_gather_avoided(sharded)
    rows.append({
        "bench": "mesh_smoke", "mode": "sharded", "model": "bert",
        "tp_size": tp_size, "n_devices": len(devices),
        "server_bytes_per_chip": int(shd_bytes),
        "gather_bytes_avoided": int(avoided),
        "sharded_fraction": round(placement.sharded_fraction(), 4),
    })

    # Self-check 1: sharded fold == replicated fold, bitwise.
    shapes = placement.shapes_tree()
    order = [str(i) for i in range(4)]
    rep_fold = StreamingFolder(shapes, order=order)
    shd_fold = StreamingFolder(shapes, order=order, placement=placement)
    for i in order:
        rng = np.random.default_rng(40 + int(i))
        delta = jax.tree.map(
            lambda w: rng.standard_normal(np.shape(w)).astype(w.dtype),
            shapes)
        meta = {"client_id": i, "weight": 1.0 + 0.5 * int(i),
                "mean_loss": 0.1}
        rep_fold.add(dict(meta), delta)
        shd_fold.add(dict(meta), delta)
    m_rep, w_rep, _ = rep_fold.mean()
    m_shd, w_shd, _ = shd_fold.mean()
    host_shd = partition.host_tree(m_shd)
    fold_ok = w_rep == w_shd and all(
        np.asarray(a).tobytes() == np.asarray(b).tobytes()
        for a, b in zip(jax.tree.leaves(m_rep), jax.tree.leaves(host_shd)))

    # Self-check 2: sharded downlink frame == gathered frame, bytewise.
    host = partition.host_tree(sharded)
    body_rep, _, _ = DownlinkEncoder("none").encode_round(1, host)
    body_shd, _, _ = DownlinkEncoder("none").encode_round(1, sharded)
    frame_ok = bytes(body_rep) == bytes(body_shd)

    ratio = shd_bytes / max(rep_bytes, 1)
    rows.append({
        "bench": "mesh_smoke", "mode": "compare", "model": "bert",
        "tp_size": tp_size, "n_devices": len(devices),
        "hbm_ratio_sharded_over_replicated": round(ratio, 4),
        "gather_bytes_avoided": int(avoided),
        "fold_bitwise_ok": bool(fold_ok),
        "frame_bytes_ok": bool(frame_ok),
    })

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "w") as f:
        for row in rows:
            print(json.dumps(row))
            f.write(json.dumps(row) + "\n")
    print(f"wrote {len(rows)} rows to {out_path}")

    if not fold_ok:
        print("FAIL: sharded fold is not bitwise identical to replicated",
              file=sys.stderr)
        return 1
    if not frame_ok:
        print("FAIL: sharded downlink frame differs from gathered frame",
              file=sys.stderr)
        return 1
    if not shd_bytes < rep_bytes:
        print(f"FAIL: sharded per-chip bytes {shd_bytes} not below "
              f"replicated {rep_bytes}", file=sys.stderr)
        return 1
    if avoided <= 0:
        print("FAIL: sharded layout avoided no gather bytes",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tp-size", type=int, default=4)
    ap.add_argument("--out", default=os.path.join(
        _REPO, "results", "mesh_bench.jsonl"))
    ap.add_argument("--check-multichip", action="store_true",
                    help="only schema-check the committed "
                         "MULTICHIP_r*.json records and exit")
    args = ap.parse_args(argv)
    if args.check_multichip:
        return check_multichip_records()
    return run_smoke(args.tp_size, args.out)


if __name__ == "__main__":
    raise SystemExit(main())
