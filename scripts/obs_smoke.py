#!/usr/bin/env python3
"""Observability-plane smoke: one tiny real federation, every plane hit.

Spawns broker + 2 workers + a coordinator (real subprocesses on real
ports, CPU) with the full observability plane opted in — flight recorder
on a fast heartbeat, Prometheus endpoint on an ephemeral port, JSONL
event stream — then:

- scrapes ``/metrics`` mid-run and validates every line against the
  Prometheus text-exposition grammar;
- captures ``/snapshot.json`` mid-run and feeds it to ``colearn top
  --once`` (replayed from a local server after the run — the CLI's
  interpreter start-up is slower than the 3-round federation, so
  pointing it at the live coordinator would race its exit);
- SIGKILLs a worker mid-run and asserts it left a parseable flight dump
  (the heartbeat-survivability contract);
- asserts the event stream carries the start event and one event per
  round;
- feeds the flight dir through ``colearn postmortem``.

A second **tree phase** then runs the same federation through a
2-aggregator tier (``--num-aggregators 2``) with ``--trace-dir`` and
``--health-dir`` opted in and asserts the fleet-health plane end to end:

- the coordinator's Chrome trace holds ONE stitched round trace whose
  spans cover all three tiers (coordinator -> aggregator-0/1 slice
  folds -> worker train spans) with intact parent links;
- the per-device health ledger is durable and non-empty (``colearn
  health`` would render it);
- the mid-run scrape carries LABELED histogram samples
  (``fed_phase_time_s{phase=...}``) that satisfy the same exposition
  grammar.

A third **async phase** (also runnable alone: ``obs_smoke.py async``,
the CI ``async-soak`` job's observability step) runs a REAL buffered-
async federation (broker + 3 workers + ``coordinate --async-buffer 2
--async-observe``) and asserts the staleness observatory end to end:

- the mid-run scrape carries the labeled staleness histogram
  (``colearn_async_staleness{...outcome=...}``) and the arrival-rate
  gauge, all passing the exposition grammar;
- the coordinator's Chrome trace stitches dispatch -> train -> fold per
  update: every ``fold_update`` span is parented on its update's
  ``dispatch_train`` context, carries τ (``tau``) in its span args, and
  shares a trace with the worker-side ``worker.train`` span.

A fourth **learning phase** runs the same federation under
``--learn-observe`` (the convergence observatory) and asserts its
end-to-end contract:

- the mid-run scrape carries the ``learn_*`` instruments — the
  update-norm gauge and the labeled trend census
  (``colearn_learn_trend_total{trend=...}``) — under the same exposition
  grammar;
- the committed event stream carries the ``conv_*`` trail: one
  ``conv_update_norm``/``conv_trend`` signal per round, with
  ``conv_cos_prev`` absent on the first round (undefined) and present
  on every later one.

Exit 0 only if every check passes.  This is the CI ``obs-smoke`` job;
the SLO sentinel gate (``colearn sentinel``) runs as its own CI step.
Pass phase names (``classic``, ``tree``, ``async``, ``learning``) as
argv to run a subset.
"""

from __future__ import annotations

import json
import os
import re
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

_CLI = "colearn_federated_learning_tpu.cli"
ROUNDS = 3
N_WORKERS = 2

# Prometheus text exposition 0.0.4: comment lines or `name{labels} value`.
_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
    r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+naif-]+)$")


def _config_flags(n_clients: int = N_WORKERS) -> list[str]:
    return ["--config", "mnist_mlp_fedavg", "--backend", "cpu",
            "--dataset", "mnist_tiny", "--partition", "iid",
            "--num-clients", str(n_clients), "--rounds", str(ROUNDS),
            "--cohort-size", "0", "--local-steps", "2",
            "--batch-size", "16", "--min-cohort-fraction", "0.5",
            "--evict-after", "2", "--seed", "0"]


def run_tree_phase(check, env: dict) -> None:
    """2-aggregator federation: stitched trace + health ledger + labeled
    histograms (the fleet-health plane's end-to-end contract)."""
    workdir = tempfile.mkdtemp(prefix="colearn_obs_tree_")
    trace_dir = os.path.join(workdir, "trace")
    health_dir = os.path.join(workdir, "health")
    cfg = _config_flags() + ["--health-dir", health_dir]
    procs: list[subprocess.Popen] = []

    def spawn(args: list[str], **kw) -> subprocess.Popen:
        p = subprocess.Popen([sys.executable, "-m", _CLI, *args],
                             env=env, **kw)
        procs.append(p)
        return p

    try:
        broker = spawn(["broker"], stdout=subprocess.PIPE, text=True)
        addr = json.loads(broker.stdout.readline())
        host, port = addr["host"], str(addr["port"])
        for i in range(N_WORKERS):
            log = open(os.path.join(workdir, f"worker{i}.log"), "ab")
            spawn(["worker", *cfg, "--client-id", str(i),
                   "--broker-host", host, "--broker-port", port],
                  stdout=log, stderr=log)
        for a in range(2):
            log = open(os.path.join(workdir, f"aggregator{a}.log"), "ab")
            spawn(["aggregator", *cfg, "--agg-id", str(a),
                   "--broker-host", host, "--broker-port", port],
                  stdout=log, stderr=log)
        coord = spawn(
            ["coordinate", *cfg, "--num-aggregators", "2",
             "--trace-dir", trace_dir, "--metrics-port", "0",
             "--broker-host", host, "--broker-port", port,
             "--min-devices", str(N_WORKERS), "--round-timeout", "30",
             "--enroll-timeout", "90", "--no-evaluator"],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)

        metrics_port = None
        scraped = False
        for line in coord.stderr:
            try:
                doc = json.loads(line.strip())
            except json.JSONDecodeError:
                continue
            if doc.get("event") == "metrics_port":
                metrics_port = int(doc["port"])
            if "round" in doc and not scraped and metrics_port:
                scraped = True
                url = f"http://127.0.0.1:{metrics_port}/metrics"
                text = urllib.request.urlopen(url, timeout=10) \
                    .read().decode("utf-8")
                lines = [ln for ln in text.splitlines() if ln]
                bad = [ln for ln in lines if not _PROM_LINE.match(ln)]
                check(not bad,
                      f"tree scrape matches the exposition grammar "
                      f"(bad: {bad[:3]})")
                labeled_hist = [
                    ln for ln in lines
                    if ln.startswith("colearn_fed_phase_time_s{")
                    and "quantile=" in ln and "phase=" in ln]
                check(bool(labeled_hist),
                      "scrape carries LABELED histogram samples "
                      "(fed_phase_time_s{phase=...})")
        rc = coord.wait(timeout=180)
        check(rc == 0, f"tree coordinator exited 0 (got {rc})")

        from colearn_federated_learning_tpu import telemetry

        # One stitched round trace: coordinator, BOTH aggregator slice
        # folds, and worker train spans, linked parent -> child.
        traces = ([os.path.join(trace_dir, f)
                   for f in sorted(os.listdir(trace_dir))
                   if f.endswith("_trace.json")]
                  if os.path.isdir(trace_dir) else [])
        check(bool(traces), "tree run wrote a Chrome-trace JSON")
        if traces:
            spans = telemetry.trace_spans(telemetry.load_trace(traces[0]))
            folds = [s for s in spans if s.name == "aggregator.fold"]
            fold_aggs = {s.process for s in folds}
            check(fold_aggs >= {"aggregator-0", "aggregator-1"},
                  f"both aggregator slice folds in the trace "
                  f"(got {sorted(fold_aggs)})")
            trace_ids = {s.trace_id for s in folds}
            stitched = False
            for tid in trace_ids:
                tier = [s for s in spans if s.trace_id == tid]
                ids = {s.span_id for s in tier}
                t_folds = [s for s in tier if s.name == "aggregator.fold"
                           and s.parent_id in ids]
                t_train = [s for s in tier if s.name == "worker.train"
                           and s.parent_id in {f.span_id for f in t_folds}]
                t_coord = [s for s in tier
                           if s.process.startswith("coordinator")]
                if len(t_folds) >= 2 and t_train and t_coord:
                    stitched = True
                    break
            check(stitched,
                  "one round trace stitches coordinator -> 2 aggregator "
                  "folds -> worker train spans with parent links")

        devices = telemetry.load_health(health_dir)
        check(bool(devices),
              f"health ledger non-empty ({len(devices)} device(s))")
        check(any(h.lat_samples for h in devices.values()),
              "health ledger attributes per-device round latency")
        sources = (sorted(os.listdir(health_dir))
                   if os.path.isdir(health_dir) else [])
        check(any(s.startswith("health_aggregator") for s in sources),
              f"aggregator tier fed the ledger (files: {sources})")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()


def run_async_phase(check, env: dict) -> None:
    """Buffered-async federation: labeled staleness exposition + the
    observatory's stitched dispatch -> train -> fold lineage traces."""
    n_workers = 3
    workdir = tempfile.mkdtemp(prefix="colearn_obs_async_")
    trace_dir = os.path.join(workdir, "trace")
    health_dir = os.path.join(workdir, "health")
    cfg = _config_flags(n_workers) + ["--health-dir", health_dir]
    procs: list[subprocess.Popen] = []

    def spawn(args: list[str], **kw) -> subprocess.Popen:
        p = subprocess.Popen([sys.executable, "-m", _CLI, *args],
                             env=env, **kw)
        procs.append(p)
        return p

    try:
        broker = spawn(["broker"], stdout=subprocess.PIPE, text=True)
        addr = json.loads(broker.stdout.readline())
        host, port = addr["host"], str(addr["port"])
        for i in range(n_workers):
            log = open(os.path.join(workdir, f"worker{i}.log"), "ab")
            spawn(["worker", *cfg, "--client-id", str(i),
                   "--broker-host", host, "--broker-port", port],
                  stdout=log, stderr=log)
        coord = spawn(
            ["coordinate", *cfg, "--async-buffer", "2", "--async-observe",
             "--trace-dir", trace_dir, "--metrics-port", "0",
             "--broker-host", host, "--broker-port", port,
             "--min-devices", str(n_workers), "--round-timeout", "30",
             "--enroll-timeout", "90", "--no-evaluator"],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)

        metrics_port = None
        scraped = False
        observed_rec = False
        for line in coord.stderr:
            try:
                doc = json.loads(line.strip())
            except json.JSONDecodeError:
                continue
            if doc.get("event") == "metrics_port":
                metrics_port = int(doc["port"])
            if "aggregation" in doc and "arrival_rate_per_s" in doc:
                observed_rec = True
            if "aggregation" in doc and not scraped and metrics_port:
                scraped = True
                url = f"http://127.0.0.1:{metrics_port}/metrics"
                text = urllib.request.urlopen(url, timeout=10) \
                    .read().decode("utf-8")
                lines = [ln for ln in text.splitlines() if ln]
                bad = [ln for ln in lines if not _PROM_LINE.match(ln)]
                check(not bad,
                      f"async scrape matches the exposition grammar "
                      f"(bad: {bad[:3]})")
                stale = [ln for ln in lines
                         if ln.startswith("colearn_async_staleness{")
                         and "outcome=" in ln]
                check(bool(stale),
                      "scrape carries the labeled staleness histogram "
                      "(async_staleness{outcome=...})")
                arrival = [ln for ln in lines if ln.startswith(
                    "colearn_async_arrival_rate_per_s")]
                check(bool(arrival),
                      "scrape carries the arrival-rate gauge")
        rc = coord.wait(timeout=180)
        check(rc == 0, f"async coordinator exited 0 (got {rc})")
        check(observed_rec,
              "observatory keys (arrival_rate_per_s) in aggregation "
              "records under --async-observe")

        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from colearn_federated_learning_tpu import telemetry

        traces = ([os.path.join(trace_dir, f)
                   for f in sorted(os.listdir(trace_dir))
                   if f.endswith("_trace.json")]
                  if os.path.isdir(trace_dir) else [])
        check(bool(traces), "async run wrote a Chrome-trace JSON")
        if traces:
            spans = telemetry.trace_spans(telemetry.load_trace(traces[0]))
            folds = [s for s in spans if s.name == "fold_update"]
            check(bool(folds), "trace carries fold_update lineage spans")
            check(all("tau" in s.attrs for s in folds),
                  "every fold_update span carries tau in its args")
            check(folds and all(s.parent_id for s in folds),
                  "every fold_update span is parented on its dispatch "
                  "context")
            # Full lineage: one trace id holds dispatch -> worker train
            # -> fold for the same update.
            stitched = False
            for f in folds:
                tier = [s for s in spans if s.trace_id == f.trace_id]
                names = {s.name for s in tier}
                if {"dispatch_train", "worker.train",
                        "fold_update"} <= names:
                    stitched = True
                    break
            check(stitched,
                  "one trace stitches dispatch_train -> worker.train -> "
                  "fold_update for an update")
            aggs = [s for s in spans if s.name == "async.aggregate"]
            check(bool(aggs) and any(s.attrs.get("link_folds")
                                     for s in aggs),
                  "async.aggregate spans cross-link their folds")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()


def run_learning_phase(check, env: dict) -> None:
    """Convergence observatory over a REAL federation (--learn-observe):
    the mid-run scrape carries the learn_* instruments and the committed
    event stream carries the conv_* trail, one signal per round."""
    workdir = tempfile.mkdtemp(prefix="colearn_obs_learn_")
    events_path = os.path.join(workdir, "events.jsonl")
    cfg = _config_flags()
    procs: list[subprocess.Popen] = []

    def spawn(args: list[str], **kw) -> subprocess.Popen:
        p = subprocess.Popen([sys.executable, "-m", _CLI, *args],
                             env=env, **kw)
        procs.append(p)
        return p

    try:
        broker = spawn(["broker"], stdout=subprocess.PIPE, text=True)
        addr = json.loads(broker.stdout.readline())
        host, port = addr["host"], str(addr["port"])
        for i in range(N_WORKERS):
            log = open(os.path.join(workdir, f"worker{i}.log"), "ab")
            spawn(["worker", *cfg, "--client-id", str(i),
                   "--broker-host", host, "--broker-port", port],
                  stdout=log, stderr=log)
        coord = spawn(
            ["coordinate", *cfg, "--learn-observe",
             "--metrics-port", "0", "--events-file", events_path,
             "--broker-host", host, "--broker-port", port,
             "--min-devices", str(N_WORKERS), "--round-timeout", "30",
             "--enroll-timeout", "90", "--no-evaluator"],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)

        metrics_port = None
        scraped = False
        for line in coord.stderr:
            try:
                doc = json.loads(line.strip())
            except json.JSONDecodeError:
                continue
            if doc.get("event") == "metrics_port":
                metrics_port = int(doc["port"])
            if "round" in doc and not scraped and metrics_port:
                scraped = True
                url = f"http://127.0.0.1:{metrics_port}/metrics"
                text = urllib.request.urlopen(url, timeout=10) \
                    .read().decode("utf-8")
                lines = [ln for ln in text.splitlines() if ln]
                bad = [ln for ln in lines if not _PROM_LINE.match(ln)]
                check(not bad,
                      f"learning scrape matches the exposition grammar "
                      f"(bad: {bad[:3]})")
                norm = [ln for ln in lines
                        if ln.startswith("colearn_learn_update_norm ")]
                check(bool(norm),
                      "scrape carries the learn_update_norm gauge")
                trend = [ln for ln in lines
                         if ln.startswith("colearn_learn_trend_total{")
                         and "trend=" in ln]
                check(bool(trend),
                      "scrape carries the labeled trend census "
                      "(learn_trend_total{trend=...})")
        rc = coord.wait(timeout=180)
        check(rc == 0, f"learning coordinator exited 0 (got {rc})")

        with open(events_path) as f:
            events = [json.loads(ln) for ln in f if ln.strip()]
        rounds = [e for e in events if e.get("event") == "round"]
        check(len(rounds) >= ROUNDS,
              f"event stream carries one event per round "
              f"({len(rounds)}/{ROUNDS})")
        trail = [e.get("conv_update_norm") for e in rounds]
        check(all(isinstance(v, (int, float)) and v > 0 for v in trail),
              f"every round event carries a conv_update_norm signal "
              f"(trail: {trail})")
        check(all("conv_trend" in e for e in rounds),
              "every round event carries a conv_trend classification")
        check(all("conv_cos_prev" in e for e in rounds[1:])
              and "conv_cos_prev" not in rounds[0],
              "conv_cos_prev absent on the first round, present after")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()


def run_classic_phase(check, env: dict) -> None:
    """Flight recorder + exporter + event stream + SIGKILL dump +
    top/postmortem over one real federation (the original smoke)."""
    workdir = tempfile.mkdtemp(prefix="colearn_obs_")
    flight_dir = os.path.join(workdir, "flight")
    events_path = os.path.join(workdir, "events.jsonl")
    cfg = _config_flags()
    obs = ["--flight-dir", flight_dir, "--flight-heartbeat", "0.5"]
    procs: list[subprocess.Popen] = []

    def spawn(args: list[str], **kw) -> subprocess.Popen:
        p = subprocess.Popen([sys.executable, "-m", _CLI, *args],
                             env=env, **kw)
        procs.append(p)
        return p

    try:
        broker = spawn(["broker"], stdout=subprocess.PIPE, text=True)
        addr = json.loads(broker.stdout.readline())
        host, port = addr["host"], str(addr["port"])
        for i in range(N_WORKERS):
            log = open(os.path.join(workdir, f"worker{i}.log"), "ab")
            spawn(["worker", *cfg, *obs, "--client-id", str(i),
                   "--broker-host", host, "--broker-port", port],
                  stdout=log, stderr=log)
        workers = procs[1:]
        coord = spawn(
            ["coordinate", *cfg, *obs,
             "--metrics-port", "0", "--events-file", events_path,
             "--broker-host", host, "--broker-port", port,
             "--min-devices", str(N_WORKERS), "--round-timeout", "25",
             "--enroll-timeout", "90", "--no-evaluator", "--elastic"],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True)

        metrics_port = None
        victim_pid = None
        scraped = False
        snapshot_body = b""
        for line in coord.stderr:
            try:
                doc = json.loads(line.strip())
            except json.JSONDecodeError:
                continue
            if doc.get("event") == "metrics_port":
                metrics_port = int(doc["port"])
            if "round" in doc and not scraped:
                scraped = True
                check(metrics_port is not None,
                      "metrics_port announced before the first round")
                if metrics_port:
                    url = f"http://127.0.0.1:{metrics_port}/metrics"
                    text = urllib.request.urlopen(url, timeout=10) \
                        .read().decode("utf-8")
                    lines = [ln for ln in text.splitlines() if ln]
                    bad = [ln for ln in lines
                           if not _PROM_LINE.match(ln)]
                    check(not bad,
                          f"every /metrics line matches the exposition "
                          f"grammar (bad: {bad[:3]})")
                    check(any(ln.startswith("colearn_") for ln in lines),
                          "scrape carries colearn_* samples")
                    snapshot_body = urllib.request.urlopen(
                        f"http://127.0.0.1:{metrics_port}/snapshot.json",
                        timeout=10).read()
                    check(bool(json.loads(snapshot_body)),
                          "/snapshot.json serves the live registry")
                # Induced kill: the dump the recorder's heartbeat left
                # behind must survive an uncatchable SIGKILL.
                victim = workers[-1]
                victim_pid = victim.pid
                time.sleep(1.0)          # > one 0.5 s heartbeat period
                victim.send_signal(signal.SIGKILL)
        rc = coord.wait(timeout=120)
        check(rc == 0, f"coordinator exited 0 (got {rc})")

        # Replay the mid-run snapshot for `colearn top --once` so the
        # render path is exercised on real federation data without
        # racing the (long-gone) coordinator's exporter.
        if snapshot_body:
            import threading
            from http.server import (BaseHTTPRequestHandler,
                                     ThreadingHTTPServer)

            class _Replay(BaseHTTPRequestHandler):
                def do_GET(self):      # noqa: N802 (stdlib handler name)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length",
                                     str(len(snapshot_body)))
                    self.end_headers()
                    self.wfile.write(snapshot_body)

                def log_message(self, fmt, *log_args):
                    pass

            srv = ThreadingHTTPServer(("127.0.0.1", 0), _Replay)
            threading.Thread(target=srv.serve_forever,
                             daemon=True).start()
            try:
                top = subprocess.run(
                    [sys.executable, "-m", _CLI, "top", "--once",
                     "--url", f"http://127.0.0.1:"
                     f"{srv.server_address[1]}/snapshot.json"],
                    env=env, capture_output=True, text=True, timeout=60)
            finally:
                srv.shutdown()
                srv.server_close()
            check(top.returncode == 0 and bool(top.stdout.strip()),
                  f"colearn top --once renders the captured snapshot"
                  f" (rc={top.returncode},"
                  f" err={top.stderr.strip()[:200]!r})")
        else:
            check(False, "no /snapshot.json captured mid-run")

        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from colearn_federated_learning_tpu.telemetry import flight

        dumps = flight.load_flight_dumps(flight_dir)
        dumped = {d.get("pid") for d in dumps if "error" not in d}
        check(victim_pid in dumped,
              f"SIGKILLed worker pid {victim_pid} left a parseable "
              f"flight dump (found pids: {sorted(dumped)})")

        with open(events_path) as f:
            events = [json.loads(ln) for ln in f if ln.strip()]
        check(any(e.get("event") == "start" for e in events),
              "event stream carries the start event")
        n_round_events = sum(1 for e in events if e.get("event") == "round")
        check(n_round_events >= ROUNDS,
              f"event stream carries one event per round "
              f"({n_round_events}/{ROUNDS})")

        pm = subprocess.run(
            [sys.executable, "-m", _CLI, "postmortem", flight_dir,
             "--format", "json"],
            env=env, capture_output=True, text=True, timeout=60)
        ok_pm = pm.returncode == 0
        if ok_pm:
            report = json.loads(pm.stdout)
            ok_pm = len(report.get("processes", [])) >= 1
        check(ok_pm, "colearn postmortem parses the flight dir")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()

_PHASES = {
    "classic": run_classic_phase,
    "tree": run_tree_phase,
    "async": run_async_phase,
    "learning": run_learning_phase,
}


def main(argv=None) -> int:
    names = list(argv if argv is not None else sys.argv[1:])
    unknown = [n for n in names if n not in _PHASES]
    if unknown:
        print(f"[obs-smoke] unknown phase(s) {unknown}; "
              f"choose from {sorted(_PHASES)}", file=sys.stderr)
        return 2
    if not names:
        names = ["classic", "tree", "async", "learning"]
    env = dict(os.environ, PYTHONUNBUFFERED="1", JAX_PLATFORMS="cpu")
    failures: list[str] = []

    def check(ok: bool, label: str) -> None:
        print(f"[obs-smoke] {'ok' if ok else 'FAIL'}: {label}",
              file=sys.stderr)
        if not ok:
            failures.append(label)

    for name in names:
        print(f"[obs-smoke] phase: {name}", file=sys.stderr)
        _PHASES[name](check, env)

    if failures:
        print(f"[obs-smoke] {len(failures)} check(s) failed",
              file=sys.stderr)
        return 1
    print("[obs-smoke] all checks passed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
