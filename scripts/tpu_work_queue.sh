#!/bin/bash
# Round-4 TPU measurement queue: run EVERYTHING that was blocked on the
# tunnel, in priority order, as soon as an accelerator answers.  Safe to
# re-run; each step is independent and failures don't stop the queue.
#
#   bash scripts/tpu_work_queue.sh [results_dir]
#
# 1. bench.py live capture (regenerates results/bench_tpu.json with the
#    headline ratio + provenance).
# 2. perf_north_star sweeps: cohort 1 / 64 / 256 baselines, then the
#    stem/norm MFU A/B at cohort 64 — all writing results/perf_*.jsonl.
# 3. Real-TPU flash kernel regression (tests/test_flash_tpu.py).
# 4. Text-config re-runs to plateau with the round-4 lr schedules
#    (agnews_bert_fedavg, femnist_vit_cross_silo via
#    scripts/run_baseline_configs.py if present).
set -u
cd "$(dirname "$0")/.."
LOG=${1:-results}/tpu_queue_$(date +%H%M%S).log
mkdir -p "$(dirname "$LOG")"
echo "[queue] logging to $LOG"

probe() {
  timeout 120 python -c "import jax; d=jax.devices()[0]; print(d.platform)" \
    2>/dev/null | tail -1
}

plat=$(probe)
if [ "$plat" != "tpu" ]; then
  echo "[queue] accelerator probe -> '$plat'; aborting (tunnel down)"
  exit 1
fi
echo "[queue] TPU up — running the measurement queue" | tee -a "$LOG"

run() {
  echo "== $* ==" | tee -a "$LOG"
  timeout 1800 "$@" >>"$LOG" 2>&1
  echo "rc=$?" | tee -a "$LOG"
}

run python bench.py
run python scripts/perf_north_star.py --rounds 100 --cohort 1
run python scripts/perf_north_star.py --rounds 30 --cohort 64
run python scripts/perf_north_star.py --rounds 20 --cohort 256
run python scripts/perf_north_star.py --rounds 30 --cohort 64 --stem space_to_depth
run python scripts/perf_north_star.py --rounds 30 --cohort 64 --norm none
run python scripts/perf_north_star.py --rounds 30 --cohort 64 --stem space_to_depth --norm none
run python -m pytest tests/test_flash_tpu.py -q
if [ -f scripts/run_baseline_configs.py ]; then
  run python scripts/run_baseline_configs.py --only agnews_bert_fedavg --rounds 40
  run python scripts/run_baseline_configs.py --only femnist_vit_cross_silo --rounds 40
fi
echo "[queue] done; see $LOG and results/*.jsonl"
