#!/usr/bin/env python
"""Fetch the real benchmark corpora into $COLEARN_DATA_DIR (network hosts).

The sandbox this framework was built in has NO network, so every committed
accuracy curve runs on the synthetic stand-ins (data/synthetic.py).  On a
machine WITH network access this script stages the real datasets in the
exact layout `data/registry.py:_load_disk` consumes — one
``<name>.npz`` per dataset with keras-style ``x_train, y_train, x_test,
y_test`` arrays — after which every config trains on real data with no
code changes:

    python scripts/fetch_data.py --out /data/colearn all
    export COLEARN_DATA_DIR=/data/colearn
    colearn train --config cifar10_cnn_fedavg

Integrity: downloads are verified against the known md5s below where the
upstream publishes one (CIFAR tarballs), and ALWAYS against the expected
row counts / per-example shapes of `registry.SPECS`.  A
``manifest.json`` records the sha256 of every staged npz so later runs
can detect drift.

Dataset notes (honest limitations):
- mnist: original IDX files via the ossci S3 mirror; parsed + verified by
  IDX magic and row counts (60000/10000 x 28x28).
- cifar10 / cifar100: cs.toronto.edu pickled tarballs, md5-verified
  (50000/10000 x 32x32x3).
- agnews: fastai CSV mirror; tokenized to 128 ids with the
  bert-base-uncased WordPiece tokenizer when `transformers` can load it
  (matches the BERT config's vocab), else a documented hash-bucket
  fallback into the same vocab size (print a warning — curves are then
  not comparable to WordPiece runs).
- femnist: staged from NIST's EMNIST ByClass (62 classes, same images).
  TRUE FEMNIST is EMNIST partitioned BY WRITER (LEAF benchmark); this
  framework partitions with Dirichlet instead, so what matters here is
  the label space + image distribution.  For writer-keyed partitions run
  LEAF's preprocessing and write the npz yourself.
- iot_traffic: no canonical public corpus auto-fetches cleanly (the
  reference's domain data is testbed captures).  Stage your own captures
  as (N, 64, 16) float windows + labels, or keep the synthetic generator,
  whose temporal class structure is documented in data/synthetic.py.
"""

from __future__ import annotations

import argparse
import gzip
import hashlib
import io
import json
import os
import pickle
import struct
import sys
import tarfile
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from colearn_federated_learning_tpu.data.registry import SPECS  # noqa: E402

MIRRORS = {
    "mnist": "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "cifar10": "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
    "cifar100": "https://www.cs.toronto.edu/~kriz/cifar-100-python.tar.gz",
    "agnews": "https://s3.amazonaws.com/fast-ai-nlp/ag_news_csv.tgz",
    "emnist": "https://biometrics.nist.gov/cs_links/EMNIST/gzip.zip",
}
MD5 = {  # upstream-published tarball md5s
    "cifar10": "c58f30108f718f92721af3b95e74349a",
    "cifar100": "eb9058c3a382ffc7106e4002c42a8d85",
}


def _download(url: str, md5: str | None = None) -> bytes:
    print(f"[fetch] GET {url}", file=sys.stderr)
    with urllib.request.urlopen(url) as r:
        blob = r.read()
    if md5 is not None:
        got = hashlib.md5(blob).hexdigest()
        if got != md5:
            raise RuntimeError(f"md5 mismatch for {url}: {got} != {md5}")
    return blob


def _parse_idx(blob: bytes) -> np.ndarray:
    """Parse an IDX (MNIST) file: magic, dims, then big-endian uint8."""
    magic, = struct.unpack(">I", blob[:4])
    ndim = magic & 0xFF
    dtype_code = (magic >> 8) & 0xFF
    if dtype_code != 0x08:                 # uint8, all MNIST/EMNIST files
        raise RuntimeError(f"unexpected IDX dtype code 0x{dtype_code:02x}")
    dims = struct.unpack(">" + "I" * ndim, blob[4:4 + 4 * ndim])
    data = np.frombuffer(blob, np.uint8, offset=4 + 4 * ndim)
    return data.reshape(dims)


def fetch_mnist() -> dict[str, np.ndarray]:
    base = MIRRORS["mnist"]
    files = {
        "x_train": "train-images-idx3-ubyte.gz",
        "y_train": "train-labels-idx1-ubyte.gz",
        "x_test": "t10k-images-idx3-ubyte.gz",
        "y_test": "t10k-labels-idx1-ubyte.gz",
    }
    out = {}
    for key, fname in files.items():
        arr = _parse_idx(gzip.decompress(_download(base + fname)))
        out[key] = arr[..., None] if key.startswith("x") else arr
    return out


def _cifar_batches(tar_blob: bytes, members: list[str], label_key: bytes):
    xs, ys = [], []
    with tarfile.open(fileobj=io.BytesIO(tar_blob), mode="r:gz") as tf:
        for m in members:
            d = pickle.loads(tf.extractfile(m).read(), encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8)
                      .reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            ys.append(np.asarray(d[label_key], np.int64))
    return np.concatenate(xs), np.concatenate(ys)


def fetch_cifar10() -> dict[str, np.ndarray]:
    blob = _download(MIRRORS["cifar10"], MD5["cifar10"])
    train = [f"cifar-10-batches-py/data_batch_{i}" for i in range(1, 6)]
    x_tr, y_tr = _cifar_batches(blob, train, b"labels")
    x_te, y_te = _cifar_batches(blob, ["cifar-10-batches-py/test_batch"],
                                b"labels")
    return dict(x_train=x_tr, y_train=y_tr, x_test=x_te, y_test=y_te)


def fetch_cifar100() -> dict[str, np.ndarray]:
    blob = _download(MIRRORS["cifar100"], MD5["cifar100"])
    x_tr, y_tr = _cifar_batches(blob, ["cifar-100-python/train"],
                                b"fine_labels")
    x_te, y_te = _cifar_batches(blob, ["cifar-100-python/test"],
                                b"fine_labels")
    return dict(x_train=x_tr, y_train=y_tr, x_test=x_te, y_test=y_te)


def _tokenize(texts: list[str], seq_len: int) -> np.ndarray:
    try:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained("bert-base-uncased")
        enc = tok(texts, max_length=seq_len, truncation=True,
                  padding="max_length", return_tensors="np")
        return enc["input_ids"].astype(np.int32)
    except Exception as e:  # noqa: BLE001
        print(f"[fetch] WARNING: bert-base-uncased tokenizer unavailable "
              f"({e}); falling back to hash-bucket token ids — curves are "
              f"NOT comparable to WordPiece runs", file=sys.stderr)
        ids = np.zeros((len(texts), seq_len), np.int32)
        for i, t in enumerate(texts):
            words = t.lower().split()[:seq_len]
            for j, w in enumerate(words):
                h = int(hashlib.md5(w.encode()).hexdigest()[:8], 16)
                ids[i, j] = 1 + h % 30_520       # 0 is padding
        return ids


def fetch_agnews() -> dict[str, np.ndarray]:
    import csv

    blob = _download(MIRRORS["agnews"])
    seq_len = SPECS["agnews"].input_shape[0]
    out = {}
    with tarfile.open(fileobj=io.BytesIO(blob), mode="r:gz") as tf:
        for split, member in (("train", "ag_news_csv/train.csv"),
                              ("test", "ag_news_csv/test.csv")):
            rows = list(csv.reader(
                io.TextIOWrapper(tf.extractfile(member), encoding="utf-8")))
            ys = np.array([int(r[0]) - 1 for r in rows], np.int64)
            texts = [" ".join(r[1:]) for r in rows]
            out[f"x_{split}"] = _tokenize(texts, seq_len)
            out[f"y_{split}"] = ys
    return out


def fetch_femnist() -> dict[str, np.ndarray]:
    import zipfile

    blob = _download(MIRRORS["emnist"])
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        def idx(name):
            with zf.open(f"gzip/{name}") as f:
                return _parse_idx(gzip.decompress(f.read()))

        x_tr = idx("emnist-byclass-train-images-idx3-ubyte.gz")
        y_tr = idx("emnist-byclass-train-labels-idx1-ubyte.gz")
        x_te = idx("emnist-byclass-test-images-idx3-ubyte.gz")
        y_te = idx("emnist-byclass-test-labels-idx1-ubyte.gz")
    # Subsample to the SPEC sizes (ByClass is ~698k/116k rows; nothing in
    # the train path truncates, so staging the full set would train on
    # ~9x the documented 80k and make disk curves incomparable).
    spec = SPECS["femnist"]
    rng = np.random.default_rng(0)
    tr = rng.permutation(len(y_tr))[:spec.n_train]
    te = rng.permutation(len(y_te))[:spec.n_test]
    # EMNIST images are stored transposed relative to MNIST orientation.
    return dict(x_train=np.transpose(x_tr[tr], (0, 2, 1))[..., None],
                y_train=y_tr[tr],
                x_test=np.transpose(x_te[te], (0, 2, 1))[..., None],
                y_test=y_te[te])


FETCHERS = {
    "mnist": fetch_mnist,
    "cifar10": fetch_cifar10,
    "cifar100": fetch_cifar100,
    "agnews": fetch_agnews,
    "femnist": fetch_femnist,
}


def _validate(name: str, arrays: dict[str, np.ndarray]) -> None:
    spec = SPECS[name]
    for split, n_expected in (("train", spec.n_train), ("test", spec.n_test)):
        x, y = arrays[f"x_{split}"], arrays[f"y_{split}"]
        if len(x) != len(y):
            raise RuntimeError(f"{name} {split}: {len(x)} x vs {len(y)} y")
        shape = x.shape[1:]
        want = spec.input_shape
        if spec.kind == "image" and shape == want[:-1] and want[-1] == 1:
            shape = shape + (1,)
        if shape != want:
            raise RuntimeError(f"{name} {split}: shape {shape} != {want}")
        if len(x) != n_expected:
            raise RuntimeError(
                f"{name} {split}: {len(x)} rows, expected {n_expected}")
        if int(y.max()) >= spec.num_classes or int(y.min()) < 0:
            raise RuntimeError(f"{name} {split}: labels outside "
                               f"[0, {spec.num_classes})")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("datasets", nargs="+",
                   choices=sorted(FETCHERS) + ["all"],
                   help="which corpora to stage")
    p.add_argument("--out", default=os.environ.get("COLEARN_DATA_DIR", ""),
                   help="target dir (default: $COLEARN_DATA_DIR)")
    args = p.parse_args()
    if not args.out:
        p.error("--out or $COLEARN_DATA_DIR required")
    names = sorted(FETCHERS) if "all" in args.datasets else args.datasets
    os.makedirs(args.out, exist_ok=True)

    manifest_path = os.path.join(args.out, "manifest.json")
    manifest = {}
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            manifest = json.load(f)
    for name in names:
        arrays = FETCHERS[name]()
        _validate(name, arrays)
        path = os.path.join(args.out, f"{name}.npz")
        np.savez_compressed(path, **arrays)
        sha = hashlib.sha256(open(path, "rb").read()).hexdigest()
        manifest[name] = {
            "sha256": sha,
            "rows": {k: int(len(v)) for k, v in arrays.items()
                     if k.startswith("x")},
        }
        print(f"[fetch] staged {path} sha256={sha[:16]}…")
    with open(manifest_path, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
    print(f"[fetch] manifest -> {manifest_path}\n"
          f"export COLEARN_DATA_DIR={args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
