#!/usr/bin/env python
"""Chaos soak: prove the comm plane's robustness machinery under faults.

Runs TWO in-process federations (faults/soak.py) with identical configs
and seeds — one fault-free baseline, one under the canned acceptance plan
(drops + delays + one corrupt frame + one mid-run crash) — then asserts:

- every scheduled round produced a round record (zero lost records);
- the only skipped rounds are the explicit sub-quorum no-ops;
- the retry/fault counters actually moved (the plan really fired);
- the faulted model's final own-shard accuracy lands within ``--tol`` of
  the fault-free baseline's.

Exit 0 iff every assertion holds; the summary JSON goes to stdout either
way.  `colearn chaos` is the one-run interactive flavor of this; the
two-run comparison here is the regression gate tests/test_chaos_soak.py
wires into tier 1.

Usage:
    JAX_PLATFORMS=cpu python scripts/chaos_soak.py [--rounds 6] [--tol 0.1]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_soak(base: dict, faulted: dict, rounds: int,
               tol: float) -> list[str]:
    """Every acceptance violation, as human-readable strings (empty =
    pass).  Shared with tests/test_chaos_soak.py so the gate and the
    script can never drift."""
    problems = []
    for name, s in (("baseline", base), ("faulted", faulted)):
        if s["rounds_run"] != rounds:
            problems.append(
                f"{name}: {s['rounds_run']}/{rounds} round records — "
                "records were lost")
    if base["skipped_rounds"]:
        problems.append(
            f"baseline skipped rounds {base['skipped_rounds']} with no "
            "faults injected")
    allowed_skips = {2}          # the canned plan's 3-drop sub-quorum round
    extra = set(faulted["skipped_rounds"]) - allowed_skips
    if extra:
        problems.append(f"faulted run skipped unexpected rounds {extra}")
    if not faulted["skipped_rounds"]:
        problems.append("the 3-drop round was NOT skipped: quorum "
                        "enforcement did not engage")
    if faulted["counters"]["fault.injected_total"] <= 0:
        problems.append("fault.injected_total is zero: the plan never "
                        "fired")
    if faulted["counters"]["comm.retry_total"] <= 0:
        problems.append("comm.retry_total is zero: no transient failure "
                        "was retried")
    if faulted["counters"]["comm.corrupt_frames_total"] <= 0:
        problems.append("comm.corrupt_frames_total is zero: the corrupt "
                        "frame was never detected")
    if "3" not in faulted["evicted"]:
        problems.append("crashed worker 3 was never evicted")
    if base["weighted_acc"] is None or faulted["weighted_acc"] is None:
        problems.append("missing final accuracy")
    else:
        # Compare on the devices BOTH runs evaluated: eviction removes the
        # crashed worker's shard from the faulted run's eval set, and an
        # aggregate over different shards is not a like-for-like verdict.
        common = sorted(set(base.get("per_client_acc", {}))
                        & set(faulted.get("per_client_acc", {})))
        if common:
            b = sum(base["per_client_acc"][c] for c in common) / len(common)
            f = sum(faulted["per_client_acc"][c]
                    for c in common) / len(common)
        else:
            b, f = base["weighted_acc"], faulted["weighted_acc"]
        if abs(b - f) > tol:
            problems.append(
                f"final accuracy drifted: baseline {b:.3f} vs faulted "
                f"{f:.3f} over {len(common) or 'all'} common clients "
                f"(tol {tol})")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--num-workers", type=int, default=4)
    ap.add_argument("--round-timeout", type=float, default=6.0)
    ap.add_argument("--seed", type=int, default=7,
                    help="fault-plan seed (the experiment seed is the "
                         "config's)")
    ap.add_argument("--tol", type=float, default=0.1,
                    help="allowed |baseline - faulted| final-accuracy gap")
    args = ap.parse_args(argv)

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    from colearn_federated_learning_tpu import faults

    log = lambda rec: print(json.dumps(rec), file=sys.stderr)
    print("# fault-free baseline", file=sys.stderr)
    base = faults.run_soak(rounds=args.rounds, n_workers=args.num_workers,
                           round_timeout=args.round_timeout, log_fn=log)
    print("# canned fault plan", file=sys.stderr)
    faulted = faults.run_soak(rounds=args.rounds,
                              n_workers=args.num_workers,
                              plan=faults.canned_plan(seed=args.seed),
                              round_timeout=args.round_timeout, log_fn=log)

    problems = check_soak(base, faulted, args.rounds, args.tol)
    print(json.dumps({"baseline": base, "faulted": faulted,
                      "problems": problems}))
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
