#!/bin/bash
# Round-5 resilient TPU measurement queue.  The round-4 queue probed ONCE
# and then ran every step blind; a mid-queue tunnel drop cost ~25 min of
# backend-init hang PER STEP (observed 2026-07-31: bench succeeded at
# 03:51, the tunnel dropped by 03:55, and each following step hung then
# died with "Unable to initialize backend 'axon': UNAVAILABLE").
#
# This version probes (90 s subprocess) BEFORE each step, skips ahead on a
# dead tunnel, sleeps between sweeps, and tracks per-step completion in a
# state dir so restarts (and tunnel recoveries) resume exactly where the
# evidence is still missing.  Run it in the background for a whole session:
#
#   bash scripts/tpu_retry_queue.sh [max_sweeps]
set -u
cd "$(dirname "$0")/.."
STATE=results/tpu_queue_state
LOG=results/tpu_retry_$(date +%H%M%S).log
mkdir -p "$STATE" results
MAX_SWEEPS=${1:-40}       # sweeps that actually ATTEMPT work (tunnel up)
MAX_IDLE_S=${2:-43200}    # total seconds allowed waiting on a dead tunnel
MAX_FAILS=${3:-3}         # park a task after N consecutive tunnel-UP failures
idle_s=0

probe() {
  # -k 10: a probe hung inside TPU plugin init can ignore SIGTERM; KILL
  # it so a dead tunnel costs 90 s, not an unbounded wait.
  timeout -k 10 90 python -c "import jax; print(jax.devices()[0].platform)" \
    2>/dev/null | tail -1
}

# name|timeout|command
TASKS=(
  "perf_c1|2100|python scripts/perf_north_star.py --rounds 100 --cohort 1"
  "perf_c64|2100|python scripts/perf_north_star.py --rounds 30 --cohort 64"
  "perf_c256|2100|python scripts/perf_north_star.py --rounds 20 --cohort 256"
  "ab_stem|2100|python scripts/perf_north_star.py --rounds 30 --cohort 64 --stem space_to_depth"
  "ab_norm|2100|python scripts/perf_north_star.py --rounds 30 --cohort 64 --norm none"
  "ab_both|2100|python scripts/perf_north_star.py --rounds 30 --cohort 64 --stem space_to_depth --norm none"
  "flash_tests|1800|python -m pytest tests/test_flash_tpu.py -q"
  "bench_live|1200|python bench.py"
  "bert_full|3300|python scripts/run_baseline_configs.py --only agnews_bert_full --rounds 50"
  "vit_3400|3300|python scripts/run_baseline_configs.py --only femnist_vit_full3400 --rounds 20"
)

echo "[retry-queue] logging to $LOG; state in $STATE/" | tee -a "$LOG"
sweep=0
while [ "$sweep" -lt "$MAX_SWEEPS" ]; do
  sweep=$((sweep + 1))
  pending=0
  for entry in "${TASKS[@]}"; do
    name=${entry%%|*}
    rest=${entry#*|}
    tmo=${rest%%|*}
    cmd=${rest#*|}
    [ -f "$STATE/$name.done" ] && continue
    # Parked: the task failed MAX_FAILS times in a row WITH the tunnel up
    # — a deterministic failure (bad flag, OOM, broken test), not tunnel
    # flap.  Retrying forever would burn the sweep budget the healthy
    # tasks need; `rm $STATE/<name>.parked` re-queues it after a fix.
    [ -f "$STATE/$name.parked" ] && continue
    pending=$((pending + 1))
    plat=$(probe)
    if [ "$plat" != "tpu" ]; then
      # A dead tunnel must NOT consume the sweep budget (the whole point
      # is to outlast downtime): un-count this sweep and bound the wait
      # by idle wall-time instead.
      sweep=$((sweep - 1))
      idle_s=$((idle_s + 210))
      if [ "$idle_s" -ge "$MAX_IDLE_S" ]; then
        echo "[retry-queue] idle budget (${MAX_IDLE_S}s) exhausted waiting for the tunnel" | tee -a "$LOG"
        exit 2
      fi
      echo "[retry-queue] probe -> '${plat:-none}' before $name; sleeping 120s (idle ${idle_s}s)" | tee -a "$LOG"
      sleep 120
      continue 2   # restart the sweep: re-probe before the FIRST pending task
    fi
    echo "== sweep $sweep: $name: $cmd ==" | tee -a "$LOG"
    timeout "$tmo" $cmd >>"$LOG" 2>&1
    rc=$?
    echo "rc=$rc ($name)" | tee -a "$LOG"
    if [ "$rc" -eq 0 ]; then
      date > "$STATE/$name.done"
      rm -f "$STATE/$name.fails"
    else
      # Count consecutive tunnel-UP failures only (the probe above just
      # said "tpu", so this rc is the task's own fault); a dead tunnel
      # never reaches this branch, so flap can't park anything.
      fails=$(( $(cat "$STATE/$name.fails" 2>/dev/null || echo 0) + 1 ))
      echo "$fails" > "$STATE/$name.fails"
      if [ "$fails" -ge "$MAX_FAILS" ]; then
        { date; echo "rc=$rc after $fails consecutive tunnel-up failures"; } \
          > "$STATE/$name.parked"
        echo "[retry-queue] PARKED $name after $fails consecutive failures (rm $STATE/$name.parked to re-queue)" | tee -a "$LOG"
      fi
    fi
  done
  if [ "$pending" -eq 0 ]; then
    parked=$(ls "$STATE"/*.parked 2>/dev/null | wc -l)
    if [ "$parked" -gt 0 ]; then
      echo "[retry-queue] done after sweep $sweep with $parked PARKED task(s): $(ls "$STATE"/*.parked 2>/dev/null | xargs -n1 basename | sed 's/\.parked//' | tr '\n' ' ')" | tee -a "$LOG"
      exit 3
    fi
    echo "[retry-queue] all tasks done after sweep $sweep" | tee -a "$LOG"
    exit 0
  fi
  echo "[retry-queue] sweep $sweep done; $pending task(s) still pending" | tee -a "$LOG"
  sleep 60
done
echo "[retry-queue] sweep budget exhausted; see $STATE/ for completion" | tee -a "$LOG"
