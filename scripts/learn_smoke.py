"""Learning-divergence sentinel smoke: the convergence-observatory
chaos gate.

Two same-seed 12-round fleetsim runs under ``--learn-observe``, each
writing its round records as a ``results/learn_events.jsonl`` stream
into a throwaway root (with the repo's pyproject.toml copied in so
``analysis.sentinel.load_rules`` finds the rule set):

1. clean — every ``live-learn-*`` sentinel must pass;
2. chaos — a one-shot 10x client-lr spike injected at round 9
   (``fed.lr_spike_round`` / ``fed.lr_spike_multiplier``, the
   config-static overlay in fed/strategies.lr_scale_for_round) must trip
   ``live-learn-divergence`` — and trip it WITHIN 3 rounds of the
   injection: the verdict is evaluated on rows truncated at round
   ``spike + 2``, so detection cannot lean on post-window history.

Exits non-zero on any violation; importable (``main()``) so the test
suite can run it in-process without a subprocess jax re-init.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROUNDS = 12
SPIKE_ROUND = 9
SPIKE_MULTIPLIER = 10.0
DETECT_WITHIN = 3          # rounds from injection to a red verdict


def _jsonable(obj):
    if hasattr(obj, "item") and not isinstance(obj, (list, dict)):
        return obj.item()          # numpy scalar
    if hasattr(obj, "tolist"):
        return obj.tolist()        # numpy array
    raise TypeError(f"not JSON-serializable: {type(obj)!r}")


def _build_fleet(seed: int, **fed_kw):
    from colearn_federated_learning_tpu import fleetsim
    from colearn_federated_learning_tpu.utils.config import (
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    fed = dict(strategy="fedavg", local_steps=2, batch_size=8, lr=0.05,
               momentum=0.0)
    fed.update(fed_kw)
    cfg = ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=32,
                          depth=1),
        fed=FedConfig(**fed),
        run=RunConfig(name="learn_smoke", seed=seed, learn_observe=True),
    )
    spec = fleetsim.PopulationSpec(num_devices=64, feature_dim=16,
                                   shard_capacity=16, min_examples=4,
                                   seed=seed)
    population = fleetsim.DevicePopulation(spec)
    traffic = fleetsim.TrafficModel(
        fleetsim.TrafficSpec(base_rate=2000.0, diurnal_amplitude=0.0,
                             seed=seed),
        spec.num_devices)
    return fleetsim.FleetSim.from_population(
        cfg, population, traffic, cohort_size=16, chunk_size=16)


def _run(label: str, seed: int = 0, **fed_kw) -> tuple[str, list]:
    """One observed fleetsim run → (sentinel root, round records)."""
    root = tempfile.mkdtemp(prefix=f"colearn_learn_smoke_{label}_")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    shutil.copy(os.path.join(repo, "pyproject.toml"),
                os.path.join(root, "pyproject.toml"))
    os.makedirs(os.path.join(root, "results"))
    fleet = _build_fleet(seed, **fed_kw)
    recs = fleet.fit(ROUNDS)
    assert len(recs) == ROUNDS
    for rec in recs:
        assert "conv_update_norm" in rec, (
            f"{label}: --learn-observe round record lost its conv_* keys: "
            f"{sorted(rec)}")
    _write_events(root, recs)
    return root, recs


def _write_events(root: str, recs: list) -> None:
    path = os.path.join(root, "results", "learn_events.jsonl")
    with open(path, "w") as f:
        for rec in recs:
            f.write(json.dumps({"event": "round", **rec},
                               default=_jsonable) + "\n")


def _learn_verdict(root: str) -> dict:
    """Evaluate ONLY the live learning sentinels — the rules that read
    the run's own event stream (the other rules, including
    fleet-learn-drift-separation, gate committed bench files this
    throwaway root does not carry)."""
    from colearn_federated_learning_tpu.analysis import sentinel

    rules = [r for r in sentinel.load_rules(root)
             if "learn_events" in r.file]
    assert len(rules) >= 3, [r.id for r in rules]
    return sentinel.evaluate_slo(root, rules)


def main() -> dict:
    # ---- clean run: every learning sentinel green -----------------------
    clean_root, clean_recs = _run("clean")
    clean = _learn_verdict(clean_root)
    assert clean["ok"], (
        "clean run tripped a learning sentinel: "
        f"{[r for r in clean['results'] if not r['ok']]}")

    # ---- chaos run: same seed, one-shot 10x lr spike at round 9 ---------
    spike_root, spike_recs = _run(
        "spike", lr_spike_round=SPIKE_ROUND,
        lr_spike_multiplier=SPIKE_MULTIPLIER)
    # Pre-spike rounds are numerically identical to the clean run (the
    # overlay is a jnp.where on the round index, same trace, same seed).
    pre = round(clean_recs[SPIKE_ROUND - 1]["conv_update_norm"], 6)
    pre_s = round(spike_recs[SPIKE_ROUND - 1]["conv_update_norm"], 6)
    assert pre == pre_s, f"pre-spike drift: clean {pre} vs spiked {pre_s}"

    # Detection deadline: the verdict must already be red with history
    # truncated DETECT_WITHIN rounds after the injection.
    cutoff = SPIKE_ROUND + DETECT_WITHIN       # rounds [0, cutoff)
    _write_events(spike_root, spike_recs[:cutoff])
    spiked = _learn_verdict(spike_root)
    div = next(r for r in spiked["results"]
               if r["id"] == "live-learn-divergence")
    assert not div["ok"], (
        f"10x lr spike at round {SPIKE_ROUND} did not trip "
        f"live-learn-divergence by round {cutoff - 1}: {div}")
    assert str(div["reason"]).startswith("above_max_ratio"), div

    out = {
        "clean_ok": clean["ok"],
        "spike_tripped": not div["ok"],
        "spike_ratio": div["value"],
        "clean_norm_r8": pre,
        "spike_norm_r9": spike_recs[SPIKE_ROUND]["conv_update_norm"],
        "roots": [clean_root, spike_root],
    }
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print(json.dumps(main(), indent=2, default=_jsonable))
