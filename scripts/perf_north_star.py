"""North-star-shaped perf run (BASELINE.json: 1000-client FedAvg CIFAR-10).

Runs the real engine on whatever accelerator is present: 1000 clients,
cohort >= 64, width-64 bf16 CNN, jit-compiled local SGD, FedAvg in-XLA.
Reports rounds/sec, client-samples/sec/chip, HBM usage, and an MFU estimate
from XLA's own cost analysis of the compiled round program.  Run with
--profile-dir to also capture a jax.profiler trace.

Every run writes a RAW record file ``results/perf_<shape>.jsonl`` (override
with --out): one ``meta`` line (device kind, shape, cost_analysis FLOPs,
compile/build timings, HBM), one line per timed round (dispatch timestamps
in the pipelined mode; true per-round latencies with --sync-per-round), and
a closing ``summary`` line.  PERF.md table rows cite these files — every
number must be traceable to a committed record.

    python scripts/perf_north_star.py [--rounds 20] [--cohort 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# v5e chip peak (bf16); see PERF.md for the derivation of the MFU estimate.
PEAK_BF16_FLOPS = {"TPU v5 lite": 197e12, "TPU v4": 275e12,
                   "TPU v5p": 459e12, "TPU v6 lite": 918e12}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--num-clients", type=int, default=1000)
    p.add_argument("--cohort", type=int, default=64)
    p.add_argument("--local-steps", type=int, default=8)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--width", type=int, default=64)
    p.add_argument("--examples-per-client", type=int, default=64)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--tp-size", type=int, default=1,
                   help="model-axis size: shard the global model (and the "
                        "server plane) over a (clients, model) mesh")
    p.add_argument("--stem", default="conv",
                   choices=["conv", "space_to_depth"],
                   help="CNN stem MFU lever (models/cnn.py)")
    p.add_argument("--norm", default="group", choices=["group", "none"],
                   help="CNN norm MFU lever")
    p.add_argument("--profile-dir", default=None)
    p.add_argument("--sync-per-round", action="store_true",
                   help="block on every round for TRUE per-round "
                        "latencies (disables the on-device pipelining "
                        "the headline number uses)")
    p.add_argument("--out", default=None,
                   help="raw JSONL record path (default: "
                        "results/perf_c<cohort>_w<width>_n<clients>.jsonl)")
    args = p.parse_args()

    import jax

    # The sandbox boot pins JAX_PLATFORMS=axon before user code runs, so
    # the env var alone cannot select CPU; honor an explicit cpu request
    # the way tests/conftest.py does (a hung tunnel otherwise blocks the
    # script forever).
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from colearn_federated_learning_tpu.data import registry as data_registry
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, RunConfig,
    )

    dev = jax.devices()[0]
    print(f"[perf] device: {dev.device_kind} ({dev.platform}) "
          f"x{len(jax.devices())}", file=sys.stderr)

    config = ExperimentConfig(
        data=DataConfig(dataset="cifar10", num_clients=args.num_clients,
                        partition="dirichlet", dirichlet_alpha=0.5,
                        max_examples_per_client=args.examples_per_client),
        model=ModelConfig(name="cnn", num_classes=10, width=args.width,
                          dtype="bfloat16", stem=args.stem, norm=args.norm),
        fed=FedConfig(strategy="fedavg", cohort_size=args.cohort,
                      local_steps=args.local_steps, batch_size=args.batch,
                      lr=0.05, momentum=0.9),
        run=RunConfig(name="north_star", backend="auto",
                      tp_size=args.tp_size, profile_dir=args.profile_dir),
    )
    dataset = data_registry.get_dataset(
        "cifar10", seed=0,
        max_train=args.num_clients * args.examples_per_client, max_test=512,
    )
    t0 = time.perf_counter()
    learner = FederatedLearner.from_config(config, dataset=dataset)
    build_s = time.perf_counter() - t0

    # XLA's own FLOP count for one compiled round (forward+backward+opt),
    # via the engine's introspection path (telemetry/runtime.py) — same
    # operands run_round passes, scan body scaled by local steps.
    cost = learner.round_cost_analysis()
    compile_s = float(cost.get("compile_s", 0.0))
    flops_per_round = float(cost.get("flops_per_round", 0.0))

    if args.profile_dir:
        learner.fit(rounds=3)                       # traces rounds 1..2
    for _ in range(args.warmup):
        learner.run_round()
    learner.finalize_history()                      # true device sync

    from colearn_federated_learning_tpu.parallel import partition

    # Report memory across the LEARNER'S MESH, not jax.devices()[0]: the
    # round program runs (and with --tp-size, the model lives sharded)
    # over every mesh chip, so chip 0 alone under-reports a multi-chip
    # run exactly when the numbers matter most.
    mesh_devices = (list(learner.mesh.devices.flat)
                    if learner.mesh is not None else [dev])
    stats = [d.memory_stats() or {} for d in mesh_devices]
    mem = {
        "bytes_in_use": max((s.get("bytes_in_use", 0) for s in stats),
                            default=0),
        "peak_bytes_in_use": max(
            (s.get("peak_bytes_in_use", s.get("bytes_in_use", 0))
             for s in stats), default=0),
        "bytes_limit": max((s.get("bytes_limit", 0) for s in stats),
                           default=0),
    }
    # Measured per-chip server-state bytes (per-shard accounting) — the
    # deterministic stand-in where memory_stats() is empty (CPU backends).
    server_bytes_per_chip = partition.bytes_per_chip(learner.server_state)
    gather_avoided = partition.tree_gather_avoided(
        learner.server_state.params)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tag = (f"perf_c{learner.cohort_size}_w{args.width}_n{args.num_clients}"
           f"_k{learner.num_steps}_b{args.batch}_e{args.examples_per_client}"
           f"{'_s2d' if args.stem == 'space_to_depth' else ''}"
           f"{'_nonorm' if args.norm == 'none' else ''}"
           f"{f'_tp{args.tp_size}' if args.tp_size > 1 else ''}"
           f"{'_sync' if args.sync_per_round else ''}")
    out_path = args.out or os.path.join(repo, "results", f"{tag}.jsonl")
    out_dir = os.path.dirname(out_path)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    rec_f = open(out_path, "w")

    def rec(obj):
        rec_f.write(json.dumps(obj) + "\n")

    rec({
        "kind": "meta",
        "recorded_unix": int(time.time()),
        "device": dev.device_kind,
        "platform": dev.platform,
        "n_devices": len(jax.devices()),
        "mesh_devices": len(mesh_devices),
        "tp_size": learner.tp_size,
        "num_clients": args.num_clients,
        "cohort": learner.cohort_size,
        "local_steps": learner.num_steps,
        "batch": args.batch,
        "width": args.width,
        "stem": args.stem,
        "norm": args.norm,
        "examples_per_client": args.examples_per_client,
        "build_s": round(build_s, 2),
        "compile_s": round(compile_s, 2),
        "cost_analysis_flops_per_round": flops_per_round,
        "hbm_used_gb": round(mem["bytes_in_use"] / 2**30, 3),
        "hbm_peak_per_chip_gb": round(mem["peak_bytes_in_use"] / 2**30, 3),
        "hbm_limit_gb": round(mem["bytes_limit"] / 2**30, 3),
        "server_bytes_per_chip": int(server_bytes_per_chip),
        "gather_bytes_avoided": int(gather_avoided),
        "timing_mode": ("sync_per_round" if args.sync_per_round
                        else "pipelined"),
    })

    # Pipelined (default): rounds queue on-device, the closing finalize (a
    # host read of round metrics) is the barrier — per-round stamps are
    # DISPATCH times, only the total is a latency.  (block_until_ready
    # does not reliably block on the remote-tunnel platform, and a
    # per-round float() costs one RPC round-trip.)  --sync-per-round
    # instead blocks each round for true per-round latencies.
    t0 = time.perf_counter()
    for i in range(args.rounds):
        r0 = time.perf_counter()
        learner.run_round(sync=args.sync_per_round)
        rec({"kind": "round", "round": i,
             ("round_s" if args.sync_per_round else "dispatch_s"):
             round(time.perf_counter() - r0, 6)})
    learner.finalize_history()
    dt = time.perf_counter() - t0
    rps = args.rounds / dt

    samples_per_round = learner.cohort_size * learner.num_steps * args.batch
    peak = PEAK_BF16_FLOPS.get(dev.device_kind, 0)
    mfu = (flops_per_round * rps / peak) if peak else 0.0

    out = {
        "kind": "summary",
        "device": dev.device_kind,
        "platform": dev.platform,
        "num_clients": args.num_clients,
        "cohort": learner.cohort_size,
        "local_steps": learner.num_steps,
        "batch": args.batch,
        "width": args.width,
        "tp_size": learner.tp_size,
        "rounds_timed": args.rounds,
        "total_s": round(dt, 4),
        "rounds_per_sec": round(rps, 4),
        "server_bytes_per_chip": int(server_bytes_per_chip),
        "gather_bytes_avoided": int(gather_avoided),
        "client_samples_per_sec_per_chip": round(rps * samples_per_round, 1),
        "flops_per_round": flops_per_round,
        "model_flops_utilization": round(mfu, 4),
    }
    rec(out)
    rec_f.close()
    print(f"[perf] raw record -> {out_path}", file=sys.stderr)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
