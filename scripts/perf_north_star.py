"""North-star-shaped perf run (BASELINE.json: 1000-client FedAvg CIFAR-10).

Runs the real engine on whatever accelerator is present: 1000 clients,
cohort >= 64, width-64 bf16 CNN, jit-compiled local SGD, FedAvg in-XLA.
Reports rounds/sec, client-samples/sec/chip, HBM usage, and an MFU estimate
from XLA's own cost analysis of the compiled round program.  Results feed
PERF.md; run with --profile-dir to also capture a jax.profiler trace.

    python scripts/perf_north_star.py [--rounds 20] [--cohort 64]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# v5e chip peak (bf16); see PERF.md for the derivation of the MFU estimate.
PEAK_BF16_FLOPS = {"TPU v5 lite": 197e12, "TPU v4": 275e12,
                   "TPU v5p": 459e12, "TPU v6 lite": 918e12}


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--num-clients", type=int, default=1000)
    p.add_argument("--cohort", type=int, default=64)
    p.add_argument("--local-steps", type=int, default=8)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--width", type=int, default=64)
    p.add_argument("--examples-per-client", type=int, default=64)
    p.add_argument("--rounds", type=int, default=20)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--profile-dir", default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from colearn_federated_learning_tpu.data import registry as data_registry
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import (
        DataConfig, ExperimentConfig, FedConfig, ModelConfig, RunConfig,
    )

    dev = jax.devices()[0]
    print(f"[perf] device: {dev.device_kind} ({dev.platform})",
          file=sys.stderr)

    config = ExperimentConfig(
        data=DataConfig(dataset="cifar10", num_clients=args.num_clients,
                        partition="dirichlet", dirichlet_alpha=0.5,
                        max_examples_per_client=args.examples_per_client),
        model=ModelConfig(name="cnn", num_classes=10, width=args.width,
                          dtype="bfloat16"),
        fed=FedConfig(strategy="fedavg", cohort_size=args.cohort,
                      local_steps=args.local_steps, batch_size=args.batch,
                      lr=0.05, momentum=0.9),
        run=RunConfig(name="north_star", backend="auto",
                      profile_dir=args.profile_dir),
    )
    dataset = data_registry.get_dataset(
        "cifar10", seed=0,
        max_train=args.num_clients * args.examples_per_client, max_test=512,
    )
    t0 = time.perf_counter()
    learner = FederatedLearner.from_config(config, dataset=dataset)
    build_s = time.perf_counter() - t0

    # XLA's own FLOP count for one compiled round (forward+backward+opt).
    t0 = time.perf_counter()
    lowered = learner._round_fn.lower(
        learner.server_state, learner.base_key, jnp.asarray(0, jnp.int32),
        *learner._device_data, None, None,
    )
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    cost = compiled.cost_analysis()
    cost = cost[0] if isinstance(cost, (list, tuple)) else (cost or {})
    # XLA cost analysis counts a while/scan BODY ONCE (trip counts are not
    # modeled), and the local-SGD scan holds essentially all the FLOPs —
    # verified empirically: the reported count is identical for
    # local_steps=1 and local_steps=8.  Scale by the step count.
    flops_per_round = float(cost.get("flops", 0.0)) * learner.num_steps

    if args.profile_dir:
        learner.fit(rounds=3)                       # traces rounds 1..2
    for _ in range(args.warmup):
        learner.run_round()
    learner.finalize_history()                      # true device sync

    # sync=False keeps the host out of the loop: rounds pipeline on-device
    # and the final finalize (a host read of round metrics) is the barrier.
    # (block_until_ready does not reliably block on the remote-tunnel
    # platform, and a per-round float() costs one RPC round-trip.)
    t0 = time.perf_counter()
    for _ in range(args.rounds):
        learner.run_round(sync=False)
    learner.finalize_history()
    dt = time.perf_counter() - t0
    rps = args.rounds / dt

    samples_per_round = learner.cohort_size * learner.num_steps * args.batch
    mem = dev.memory_stats() or {}
    hbm_used = mem.get("bytes_in_use", 0)
    hbm_limit = mem.get("bytes_limit", 0)
    peak = PEAK_BF16_FLOPS.get(dev.device_kind, 0)
    mfu = (flops_per_round * rps / peak) if peak else 0.0

    out = {
        "device": dev.device_kind,
        "platform": dev.platform,
        "num_clients": args.num_clients,
        "cohort": learner.cohort_size,
        "local_steps": learner.num_steps,
        "batch": args.batch,
        "width": args.width,
        "build_s": round(build_s, 2),
        "compile_s": round(compile_s, 2),
        "rounds_per_sec": round(rps, 4),
        "client_samples_per_sec_per_chip": round(rps * samples_per_round, 1),
        "flops_per_round": flops_per_round,
        "model_flops_utilization": round(mfu, 4),
        "hbm_used_gb": round(hbm_used / 2**30, 3),
        "hbm_limit_gb": round(hbm_limit / 2**30, 3),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
