#!/usr/bin/env python
"""Wire-plane round micro-bench: broadcast, downlink delta, uplink fast path.

Runs an in-process federation (MessageBroker + DeviceWorkers +
FederatedCoordinator — the chaos-soak topology, minus the faults) over
the bench CNN shape and measures, per round:

- ``comm.broadcast_encode_total`` delta — MUST be exactly 1 regardless of
  cohort size (the pre-PR path encoded the full model once per request,
  i.e. ``cohort`` times; that analytic "before" is recorded alongside);
- ``comm.bytes_sent`` / ``comm.bytes_saved_downlink`` deltas and the
  resulting downlink frame-vs-frame reduction with ``--down-schemes``;
- the UPLINK sweep (``--schemes`` × ``--feedback``): measured
  ``comm.bytes_received`` / ``comm.bytes_saved_uplink`` /
  ``comm.uplink_densify_avoided_total`` deltas per scheme, plus the
  streaming-fold overlap (``phase_fold_overlap_s``) so the O(k) sparse
  fold's per-contribution cost is visible next to the dense fold's;
- round latency;
- the LoRA sweep (``--lora-ranks``): rank-r factor frames priced against
  the dense update frame at the committed BERT bench config
  (``agnews_bert_fedavg``, BERT-base) via shape-only frame math — no
  110M-param alloc — plus one real 2-worker factor-uplink federation at
  a tiny BERT shape to prove the plane end to end (serialize-once
  broadcast, factor fold, periodic server merge);
- the FOLD sweep (``--fold-frames`` × host/device × batch 1/cohort):
  server-ingest throughput (updates/s) at real BERT-base shapes through
  ``StreamingFolder`` — the host oracle vs the fused device kernel
  (``ops/fold_kernel.py``), one ``wire_fold`` row per path with measured
  bitwise parity against the host accumulator; the run FAILS if any
  device row breaks parity or the batched topk8 device fold is slower
  than the host.

- the CKPT sweep (``--ckpt-tp``): durable save/restore wall-clock at
  real BERT-base weights, one ``wire_ckpt`` row per path — the
  shard-native ``StreamingCheckpointer`` (per-shard CRC-checked files,
  manifest-last commit, no host gather; the ``ckpt-save-no-gather``
  sentinel gates its measured ``gather_avoided``) vs the gathered flat
  ``RoundCheckpointer`` (host-materialize, then orbax) — both restores
  verified bitwise against the source weights.

With ``--fold-device`` the wire rounds themselves ingest through the
device kernel (``fold_device_folds_per_round`` must equal the cohort or
the run fails).  ``--check-schema`` validates every emitted row against
the published row schemas after the run; ``--check-only`` just validates
an existing ``--out`` file and exits (the CI gate over committed
results).

One JSON summary line per configuration is written to
``results/wire_bench.jsonl`` (PERF.md "Wire plane" and the SLO sentinel
rules in pyproject.toml read from there).

Usage (CPU):
    JAX_PLATFORMS=cpu python scripts/bench_wire.py
    JAX_PLATFORMS=cpu python scripts/bench_wire.py \\
        --cohorts 2,4 --schemes none,topk --feedback off,on --rounds 5
    JAX_PLATFORMS=cpu python scripts/bench_wire.py \\
        --lora-ranks 4 --lora-only --rounds 3   # CI lora-smoke shape
    JAX_PLATFORMS=cpu python scripts/bench_wire.py \\
        --fold-only --fold-frames topk8 --fold-repeats 2  # §7k fold rows
    python scripts/bench_wire.py --check-only \\
        --out results/wire_bench.jsonl           # schema-gate committed rows
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Force a multi-device CPU host BEFORE jax initializes (same trick as
# tests/conftest.py) so the --tp-sizes sweep has a mesh to shard the
# server over; harmless for the tp_size=1 rows.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

from colearn_federated_learning_tpu import telemetry  # noqa: E402
from colearn_federated_learning_tpu.utils.config import (  # noqa: E402
    DataConfig,
    ExperimentConfig,
    FedConfig,
    ModelConfig,
    RunConfig,
)

# Counters sampled as per-round deltas.
_COUNTERS = (
    "comm.broadcast_encode_total",
    "comm.bytes_sent",
    "comm.bytes_received",
    "comm.bytes_saved_downlink",
    "comm.bytes_saved_uplink",
    "comm.uplink_densify_avoided_total",
    "comm.fold_device_total",
    "comm.resync_total",
    "comm.gather_bytes_avoided_total",
)

# Schema contract for every row this bench writes; --check-schema (CI)
# validates the output (or the committed results via --check-only)
# against these, so a field rename can never silently blind the PERF.md
# tables or the SLO sentinel rules that read the JSONL.
ROW_SCHEMA = {
    "bench": str,
    "model": str,
    "dataset": str,
    "cohort": int,
    "scheme_down": str,
    "scheme_up": str,
    "feedback": bool,
    "tp_size": int,
    "fold_device": bool,
    "fold_device_folds_per_round": int,
    "rounds": int,
    "encodes_per_round": int,
    "full_frame_bytes": int,
    "downlink_frame_bytes": int,
    "downlink_reduction_x": float,
    "uplink_frame_bytes": int,
    "uplink_dense_bytes": int,
    "uplink_bytes_ratio": float,
    "uplink_reduction_x": float,
    "round_time_s_mean": float,
    "bench_wall_s": float,
}

LORA_ROW_SCHEMA = {
    "bench": str,
    "model": str,
    "cohort": int,
    "rounds": int,
    "lora_rank": int,
    "dense_params": int,
    "factor_params": int,
    "encodes_per_round": int,
    "uplink_frame_bytes": int,
    "uplink_dense_bytes": int,
    "uplink_bytes_ratio": float,
    "uplink_reduction_x": float,
    "lora_merges": int,
    "round_time_s_mean": float,
    "bench_wall_s": float,
}

# Fold-throughput rows (--fold-frames): updates/s folded through the
# StreamingFolder at BERT-base, host oracle vs device kernel, batch 1
# vs K — what the wire-fold-* sentinel rules gate.
FOLD_ROW_SCHEMA = {
    "bench": str,
    "model": str,
    "frame": str,
    "path": str,
    "batch": int,
    "cohort": int,
    "repeats": int,
    "param_count": int,
    "staged_values": int,
    "kernel_backend": str,
    "updates_per_s": float,
    "fold_wall_s": float,
    "speedup_vs_host": float,
    "parity_bitwise": bool,
    "bench_wall_s": float,
}

# Checkpoint save/restore rows (--ckpt-tp): wall-clock for a full
# BERT-base durable save and restore, the shard-native streaming path
# (per-shard files, no host gather — the ckpt-save-no-gather sentinel
# gates its gather_avoided) vs the gathered flat path.
CKPT_ROW_SCHEMA = {
    "bench": str,
    "model": str,
    "path": str,
    "tp_size": int,
    "repeats": int,
    "param_count": int,
    "param_bytes": int,
    "save_s": float,
    "restore_s": float,
    "gather_avoided": int,
    "shards_per_gen": int,
    "restore_bitwise": bool,
    "bench_wall_s": float,
}

SCHEMAS = {
    "wire_round": ROW_SCHEMA,
    "wire_lora": LORA_ROW_SCHEMA,
    "wire_fold": FOLD_ROW_SCHEMA,
    "wire_ckpt": CKPT_ROW_SCHEMA,
}


def bench_config(n_workers: int, scheme_down: str, tp_size: int = 1,
                 scheme_up: str = "none", feedback: bool = False,
                 fold_device: bool = False) -> ExperimentConfig:
    """The bench CNN shape: a width-16 conv net on mnist_tiny — big enough
    (~100 kB of float32 params) that frame encode/copy costs are visible,
    small enough to compile and train in seconds on CPU."""
    return ExperimentConfig(
        data=DataConfig(dataset="mnist_tiny", num_clients=n_workers,
                        partition="iid"),
        model=ModelConfig(name="cnn", num_classes=10, width=16),
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=0,
                      local_steps=2, batch_size=16, lr=0.05, momentum=0.0,
                      compress=scheme_up, compress_feedback=feedback,
                      compress_down=scheme_down),
        run=RunConfig(name="bench_wire", backend="cpu", seed=0,
                      tp_size=tp_size, fold_device=fold_device),
    )


def run_bench(n_workers: int, scheme_down: str, scheme_up: str,
              feedback: bool, tp_size: int, rounds: int,
              warmup_timeout: float, round_timeout: float,
              fold_device: bool = False) -> dict:
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker
    from colearn_federated_learning_tpu.utils.serialization import (
        wire_frame_length,
    )

    import jax
    import numpy as np

    config = bench_config(n_workers, scheme_down, tp_size,
                          scheme_up=scheme_up, feedback=feedback,
                          fold_device=fold_device)
    reg = telemetry.get_registry()

    broker = MessageBroker().start()
    workers = []
    coord = None
    per_round: list[dict] = []
    try:
        workers = [
            DeviceWorker(config, i, broker.host, broker.port).start()
            for i in range(n_workers)
        ]
        coord = FederatedCoordinator(config, broker.host, broker.port,
                                     round_timeout=warmup_timeout,
                                     want_evaluator=False)
        coord.enroll(min_devices=n_workers, timeout=30.0)
        coord.trainers.sort(key=lambda d: int(d.device_id))
        for w in workers:
            w.await_role(timeout=10.0)

        # Frame length of a full-params broadcast: depends only on leaf
        # shapes/dtypes (+ a round digit or two of header JSON), so one
        # sample stands for every round.
        from colearn_federated_learning_tpu.parallel import partition

        server_bytes_per_chip = int(
            partition.bytes_per_chip(coord.server_state))
        params_np = jax.tree.map(np.asarray, coord.server_state.params)
        full_len = wire_frame_length(params_np, {"round": 1, "down": "full"})
        # Uplink frame length under the configured update scheme: also
        # shape-only (compress_delta meta + leaf dtypes), so one zeros
        # sample prices every update the workers send back.
        from colearn_federated_learning_tpu.fed import compression
        zeros = jax.tree.map(np.zeros_like, params_np)
        wire_up, meta_up = compression.compress_delta(
            zeros, config.fed.compress,
            topk_fraction=config.fed.topk_fraction)
        uplink_len = wire_frame_length(
            wire_up, {"round": 1, "op": "train", **meta_up})
        uplink_dense_len = wire_frame_length(
            zeros, {"round": 1, "op": "train", "compress": "none"})

        coord.run_round()                 # warmup: jit compile + delta base
        coord.round_timeout = round_timeout
        for _ in range(rounds):
            before = {c: reg.counter(c).value for c in _COUNTERS}
            rec = coord.run_round()
            delta = {c: reg.counter(c).value - before[c] for c in _COUNTERS}
            sends = int(rec.get("completed", 0))
            per_round.append({
                "encodes": int(delta["comm.broadcast_encode_total"]),
                "bytes_sent": int(delta["comm.bytes_sent"]),
                "bytes_received": int(delta["comm.bytes_received"]),
                "bytes_saved": int(delta["comm.bytes_saved_downlink"]),
                "bytes_saved_uplink": int(
                    delta["comm.bytes_saved_uplink"]),
                "densify_avoided": int(
                    delta["comm.uplink_densify_avoided_total"]),
                "fold_device_folds": int(delta["comm.fold_device_total"]),
                "resyncs": int(delta["comm.resync_total"]),
                "gather_avoided": int(
                    delta["comm.gather_bytes_avoided_total"]),
                "sends": sends,
                "round_time_s": rec["round_time_s"],
                "fold_overlap_s": rec.get("phase_fold_overlap_s", 0.0),
            })
    finally:
        for w in workers:
            w.stop()
        broker.stop()
        if coord is not None:
            coord.close()

    encodes = [r["encodes"] for r in per_round]
    saved_per_send = (
        per_round[-1]["bytes_saved"] / max(1, per_round[-1]["sends"])
        if scheme_down != "none" else 0.0
    )
    downlink_frame = full_len - saved_per_send
    return {
        "bench": "wire_round",
        "model": "cnn-w16",
        "dataset": "mnist_tiny",
        "cohort": n_workers,
        "scheme_down": scheme_down,
        "scheme_up": scheme_up,
        "feedback": feedback,
        "tp_size": tp_size,
        # Device-resident fold (--fold-device): contributions folded
        # through the fused kernel per round (0 on the host path).
        "fold_device": fold_device,
        "fold_device_folds_per_round": int(min(
            r["fold_device_folds"] for r in per_round)),
        "rounds": rounds,
        # Sharded server (tp_size > 1): per-chip server-state bytes and
        # the per-round gather bytes the shard-wise downlink never moved.
        "server_bytes_per_chip": server_bytes_per_chip,
        "gather_bytes_avoided_per_round": int(statistics.mean(
            r["gather_avoided"] for r in per_round)),
        # Serialize-once: one broadcast encode per round, cohort-independent.
        "encodes_per_round": max(encodes),
        # The replaced path encoded the full model once PER REQUEST.
        "encodes_per_round_before": n_workers,
        "full_frame_bytes": int(full_len),
        "downlink_frame_bytes": int(downlink_frame),
        "downlink_reduction_x": round(full_len / downlink_frame, 2),
        "uplink_frame_bytes": int(uplink_len),
        "uplink_dense_bytes": int(uplink_dense_len),
        # Shape-only frame ratio/reduction — what the SLO sentinels gate.
        "uplink_bytes_ratio": round(uplink_len / uplink_dense_len, 4),
        "uplink_reduction_x": round(uplink_dense_len / uplink_len, 2),
        "uplink_bytes_per_round": int(uplink_len * statistics.mean(
            r["sends"] for r in per_round)),
        "bytes_sent_per_round": int(statistics.mean(
            r["bytes_sent"] for r in per_round)),
        # Measured coordinator-side receive bytes (train replies + enroll
        # chatter) — the ground truth the frame math must track.
        "bytes_received_per_round": int(statistics.mean(
            r["bytes_received"] for r in per_round)),
        "bytes_saved_per_round": int(statistics.mean(
            r["bytes_saved"] for r in per_round)),
        "bytes_saved_uplink_per_round": int(statistics.mean(
            r["bytes_saved_uplink"] for r in per_round)),
        "uplink_densify_avoided_per_round": int(min(
            r["densify_avoided"] for r in per_round)),
        "resyncs_total": sum(r["resyncs"] for r in per_round),
        "round_time_s_mean": round(statistics.mean(
            r["round_time_s"] for r in per_round), 4),
        "fold_overlap_s_mean": round(statistics.mean(
            r["fold_overlap_s"] for r in per_round), 4),
        "per_round": per_round,
    }


def lora_bench_config(n_workers: int, rank: int) -> ExperimentConfig:
    """Tiny BERT on the synthetic agnews_tiny split: small enough to run a
    real 2-worker factor-uplink federation in seconds on CPU, transformer
    enough that the partition-rule-driven targeting (attention QKV/out,
    MLP, embeddings) is exercised for real."""
    return ExperimentConfig(
        data=DataConfig(dataset="agnews_tiny", num_clients=n_workers,
                        partition="iid"),
        model=ModelConfig(name="bert", num_classes=4, width=32, depth=2,
                          num_heads=2, seq_len=64, vocab_size=2000),
        fed=FedConfig(strategy="fedavg", rounds=1, cohort_size=0,
                      local_steps=2, batch_size=16, lr=0.05, momentum=0.0,
                      lora_rank=rank, lora_alpha=16.0, lora_merge_every=2),
        run=RunConfig(name="bench_wire_lora", backend="cpu", seed=0),
    )


def run_lora_bench(rank: int, rounds: int, warmup_timeout: float,
                   round_timeout: float) -> dict:
    from colearn_federated_learning_tpu.comm.broker import MessageBroker
    from colearn_federated_learning_tpu.comm.coordinator import (
        FederatedCoordinator,
    )
    from colearn_federated_learning_tpu.comm.worker import DeviceWorker
    from colearn_federated_learning_tpu.fed import lora as lora_lib
    from colearn_federated_learning_tpu.models import registry as models
    from colearn_federated_learning_tpu.utils.config import get_config
    from colearn_federated_learning_tpu.utils.serialization import (
        wire_frame_length,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np

    # --- Analytic pricing at the committed BERT bench config (BERT-base
    # on agnews, utils/config.py): eval_shape gives the param shape tree
    # without materializing ~110M params, and wire_frame_length is
    # shape-only, so broadcast-zero views price both frames for free.
    bert_cfg = get_config("agnews_bert_fedavg").model
    model = models.build_model(bert_cfg)
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, bert_cfg.seq_len), jnp.int32),
                             train=False),
        jax.random.PRNGKey(0))["params"]
    params_view = jax.tree.map(
        lambda l: np.broadcast_to(np.zeros((), np.dtype(l.dtype)), l.shape),
        shapes)
    dense_params = sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(params_view))
    # key=None -> zero factors: the template carries exactly the shapes
    # a worker's factor-delta reply would.
    factors_view = lora_lib.init_factors(params_view, rank,
                                         model_name=bert_cfg.name)
    factor_params = lora_lib.count_factor_params(factors_view)
    meta = {"round": 1, "op": "train", "compress": "none"}
    dense_len = wire_frame_length(params_view, meta)
    factor_len = wire_frame_length(factors_view, meta)

    # --- One real factor-uplink federation at the tiny BERT shape.
    n_workers = 2
    config = lora_bench_config(n_workers, rank)
    reg = telemetry.get_registry()

    broker = MessageBroker().start()
    workers = []
    coord = None
    per_round: list[dict] = []
    try:
        workers = [
            DeviceWorker(config, i, broker.host, broker.port).start()
            for i in range(n_workers)
        ]
        coord = FederatedCoordinator(config, broker.host, broker.port,
                                     round_timeout=warmup_timeout,
                                     want_evaluator=False)
        coord.enroll(min_devices=n_workers, timeout=30.0)
        coord.trainers.sort(key=lambda d: int(d.device_id))
        for w in workers:
            w.await_role(timeout=10.0)

        coord.run_round()                 # warmup: jit compile
        coord.round_timeout = round_timeout
        for _ in range(rounds):
            before = {c: reg.counter(c).value for c in _COUNTERS}
            rec = coord.run_round()
            delta = {c: reg.counter(c).value - before[c] for c in _COUNTERS}
            per_round.append({
                "encodes": int(delta["comm.broadcast_encode_total"]),
                "bytes_sent": int(delta["comm.bytes_sent"]),
                "bytes_received": int(delta["comm.bytes_received"]),
                "bytes_saved_uplink": int(
                    delta["comm.bytes_saved_uplink"]),
                "resyncs": int(delta["comm.resync_total"]),
                "gather_avoided": int(
                    delta["comm.gather_bytes_avoided_total"]),
                "sends": int(rec.get("completed", 0)),
                "lora_merged": bool(rec.get("lora_merged", False)),
                "round_time_s": rec["round_time_s"],
            })
    finally:
        for w in workers:
            w.stop()
        broker.stop()
        if coord is not None:
            coord.close()

    encodes = [r["encodes"] for r in per_round]
    return {
        "bench": "wire_lora",
        # Priced model (the headline ratio) vs the smoke model the real
        # federation ran on.
        "model": "bert-base",
        "dataset": "agnews",
        "smoke_model": "bert-tiny",
        "smoke_dataset": "agnews_tiny",
        "cohort": n_workers,
        "scheme_down": "none",
        "scheme_up": "none",
        "feedback": False,
        "tp_size": 1,
        "rounds": rounds,
        "lora_rank": rank,
        "lora_alpha": 16.0,
        "dense_params": int(dense_params),
        "factor_params": int(factor_params),
        # Serialize-once must hold for the composite {base, factors}
        # broadcast too — the whereclause-free SLO sentinel reads this.
        "encodes_per_round": max(encodes),
        "encodes_per_round_before": n_workers,
        # Shape-only frame ratio/reduction at BERT-base — what the
        # wire-lora-uplink-ratio sentinel gates.
        "uplink_frame_bytes": int(factor_len),
        "uplink_dense_bytes": int(dense_len),
        "uplink_bytes_ratio": round(factor_len / dense_len, 4),
        "uplink_reduction_x": round(dense_len / factor_len, 2),
        # Measured smoke-run ground truth: factor replies really are what
        # crossed the wire, and the server really merged.
        "bytes_sent_per_round": int(statistics.mean(
            r["bytes_sent"] for r in per_round)),
        "bytes_received_per_round": int(statistics.mean(
            r["bytes_received"] for r in per_round)),
        "bytes_saved_uplink_per_round": int(statistics.mean(
            r["bytes_saved_uplink"] for r in per_round)),
        "lora_merges": sum(1 for r in per_round if r["lora_merged"]),
        "resyncs_total": sum(r["resyncs"] for r in per_round),
        "round_time_s_mean": round(statistics.mean(
            r["round_time_s"] for r in per_round), 4),
        "per_round": per_round,
    }


def run_fold_rows(frame: str, cohort: int, repeats: int,
                  topk_fraction: float = 0.01) -> list[dict]:
    """Fold-throughput rows at BERT-base: updates/s folded through the
    StreamingFolder for one frame type — the host fold (the parity
    oracle) vs the device kernel (ops/fold_kernel.py, backend resolved
    by ``auto``: the fused native lowering on a CPU host, the jitted
    XLA scan on an accelerator), device at batch=1 vs batch=cohort.
    Frame generation and staging reuse ONE synthetic wire tree per
    frame; only the fold is timed.  Every device row carries a measured
    ``parity_bitwise`` bit against the host fold of the same cohort."""
    from colearn_federated_learning_tpu.comm.aggregation import (
        StreamingFolder,
    )
    from colearn_federated_learning_tpu.fed import compression
    from colearn_federated_learning_tpu.fed import lora as lora_lib
    from colearn_federated_learning_tpu.models import registry as models
    from colearn_federated_learning_tpu.ops import fold_kernel
    from colearn_federated_learning_tpu.utils.config import get_config

    import jax
    import jax.numpy as jnp
    import numpy as np

    t0 = time.time()
    bert_cfg = get_config("agnews_bert_fedavg").model
    model = models.build_model(bert_cfg)
    shape_tree = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, bert_cfg.seq_len), jnp.int32),
                             train=False),
        jax.random.PRNGKey(0))["params"]
    params_view = jax.tree.map(
        lambda l: np.broadcast_to(np.zeros((), np.dtype(l.dtype)), l.shape),
        shape_tree)

    rng = np.random.default_rng(19)

    def rand_tree(view):
        return jax.tree.map(
            lambda l: rng.standard_normal(l.shape, dtype=np.float32),
            view)

    if frame == "dense":
        fold_shapes = params_view
        wire, cmeta = rand_tree(params_view), {"compress": "none"}
    elif frame == "topk8":
        fold_shapes = params_view
        wire, cmeta = compression.compress_delta(
            rand_tree(params_view), "topk8", topk_fraction=topk_fraction)
    elif frame.startswith("lora_r"):
        rank = int(frame[len("lora_r"):])
        factors_view = lora_lib.init_factors(params_view, rank,
                                             model_name=bert_cfg.name)
        fold_shapes = jax.tree.map(
            lambda l: np.broadcast_to(np.zeros((), np.float32), l.shape),
            factors_view)
        wire, cmeta = rand_tree(fold_shapes), {"compress": "none"}
    else:
        raise SystemExit(f"unknown fold frame {frame!r}")

    param_count = sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(params_view))
    staged_values = (
        sum(int(np.asarray(n["v"]).size)
            for n in jax.tree.leaves(
                wire, is_leaf=lambda x: isinstance(x, dict) and "v" in x))
        if frame == "topk8"
        else sum(int(np.prod(l.shape)) for l in jax.tree.leaves(wire)))
    updates = [({"client_id": str(i), "weight": 1.0 + 0.25 * i,
                 "mean_loss": 0.5, **cmeta}, wire)
               for i in range(cohort)]

    def fold_once(device, batch_max):
        f = StreamingFolder(fold_shapes,
                            order=[m["client_id"] for m, _ in updates],
                            device_fold=device)
        f._fold_batch_max = batch_max
        for meta, w in updates:
            f.add(dict(meta), w)
        f.finalize()
        return f

    def timed(device, batch_max):
        fold_once(device, batch_max)        # warmup: jit/kernel/lib caches
        t = time.perf_counter()
        for _ in range(repeats):
            folder = fold_once(device, batch_max)
        wall = time.perf_counter() - t
        return folder, wall

    host_folder, host_wall = timed(False, None)
    host_bytes = [np.asarray(l).tobytes()
                  for l in jax.tree.leaves(host_folder.wsum)]
    host_ups = cohort * repeats / host_wall
    backend = fold_kernel.resolve_backend()

    def row(path, batch, folder, wall):
        ups = cohort * repeats / wall
        parity = ([np.asarray(l).tobytes()
                   for l in jax.tree.leaves(folder.wsum)] == host_bytes)
        return {
            "bench": "wire_fold",
            "model": "bert-base",
            "frame": frame,
            "path": path,
            "batch": batch,
            "cohort": cohort,
            "repeats": repeats,
            "param_count": param_count,
            "staged_values": int(staged_values),
            "kernel_backend": backend if path == "device" else "host",
            "updates_per_s": round(ups, 2),
            "fold_wall_s": round(wall, 4),
            "speedup_vs_host": round(ups / host_ups, 3),
            "parity_bitwise": bool(parity),
            "bench_wall_s": round(time.time() - t0, 1),
        }

    rows = [row("host", 1, host_folder, host_wall)]
    for batch in (1, cohort):
        folder, wall = timed(True, batch if batch > 1 else 1)
        rows.append(row("device", batch, folder, wall))
    return rows


def run_ckpt_rows(tp_size: int, repeats: int) -> list[dict]:
    """Durable save/restore wall-clock at real BERT-base weights: the
    shard-native streaming path (each device shard writes its own
    CRC-checked file, manifest committed last, NO host gather) vs the
    gathered flat path (host-materialize the full tree, then the orbax
    ``RoundCheckpointer``).  Both restores are verified bitwise against
    the source weights via the streaming digest recipe."""
    import hashlib
    import shutil
    import tempfile

    from colearn_federated_learning_tpu.ckpt import (
        RoundCheckpointer,
        StreamingCheckpointer,
    )
    from colearn_federated_learning_tpu.ckpt.streaming import _digest_update
    from colearn_federated_learning_tpu.models import registry as models
    from colearn_federated_learning_tpu.parallel import partition
    from colearn_federated_learning_tpu.utils.config import get_config

    import jax
    import jax.numpy as jnp
    import numpy as np

    t0 = time.time()
    bert_cfg = get_config("agnews_bert_fedavg").model
    model = models.build_model(bert_cfg)
    shape_tree = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, bert_cfg.seq_len), jnp.int32),
                             train=False),
        jax.random.PRNGKey(0))["params"]
    rng = np.random.default_rng(23)
    params = jax.tree.map(
        lambda l: rng.standard_normal(l.shape).astype(l.dtype), shape_tree)
    param_count = sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    param_bytes = sum(
        l.size * l.dtype.itemsize for l in jax.tree.leaves(params))

    def digest_of(tree):
        h = hashlib.sha256()
        for leaf in jax.tree.leaves(tree):
            arr = np.asarray(leaf)
            _digest_update(h, arr.dtype, tuple(arr.shape), arr)
        return h.hexdigest()

    expected = digest_of(params)
    reg = telemetry.get_registry()

    placement = partition.make_server_placement(
        params, tp_size, "model", bert_cfg.name)
    if placement is None:
        raise SystemExit(
            f"FAIL: no server placement at tp_size={tp_size} "
            "(ckpt bench needs a sharded tree to price)")
    sharded = placement.shard(params)
    template = jax.tree.map(np.zeros_like, params)

    def row(path, save_s, restore_s, gather_avoided, shards, restored):
        return {
            "bench": "wire_ckpt",
            "model": "bert-base",
            "path": path,
            "tp_size": tp_size if path == "sharded" else 1,
            "repeats": repeats,
            "param_count": param_count,
            "param_bytes": int(param_bytes),
            "save_s": round(save_s, 4),
            "restore_s": round(restore_s, 4),
            "gather_avoided": int(gather_avoided),
            "shards_per_gen": shards,
            "restore_bitwise": digest_of(restored) == expected,
            "bench_wall_s": round(time.time() - t0, 1),
        }

    rows = []
    # --- streaming sharded leg -------------------------------------------
    stream_dir = tempfile.mkdtemp(prefix="bench_ckpt_stream_")
    flat_dir = tempfile.mkdtemp(prefix="bench_ckpt_flat_")
    try:
        stream = StreamingCheckpointer(stream_dir, max_to_keep=1)
        before = reg.counter("comm.gather_bytes_avoided_total").value
        t = time.perf_counter()
        for r in range(repeats):
            stream.save(r + 1, sharded, [])
        save_s = (time.perf_counter() - t) / repeats
        avoided = (reg.counter("comm.gather_bytes_avoided_total").value
                   - before) / repeats
        gen = os.path.join(stream_dir, f"gen_{repeats:08d}")
        shards = sum(1 for n in os.listdir(gen) if n.startswith("shard_"))
        t = time.perf_counter()
        restored, _, _ = StreamingCheckpointer(stream_dir).restore(template)
        restore_s = time.perf_counter() - t
        rows.append(row("sharded", save_s, restore_s, avoided, shards,
                        restored))

        # --- gathered flat leg -------------------------------------------
        flat = RoundCheckpointer(flat_dir, max_to_keep=1)
        t = time.perf_counter()
        for r in range(repeats):
            # The gather IS part of the cost being priced: the flat path
            # must host-materialize the full tree before it can save.
            host = jax.tree.map(np.asarray, sharded)
            flat.save(r + 1, host, [])
        save_s = (time.perf_counter() - t) / repeats
        t = time.perf_counter()
        restored, _, _ = flat.restore(template)
        restore_s = time.perf_counter() - t
        flat.close()
        rows.append(row("gathered", save_s, restore_s, 0, 0, restored))
    finally:
        shutil.rmtree(stream_dir, ignore_errors=True)
        shutil.rmtree(flat_dir, ignore_errors=True)
    return rows


def check_schema(path: str) -> int:
    """Validate every row of the bench JSONL against the schema for its
    ``bench`` tag (CI gate): required fields present, numerics numeric."""
    bad = 0
    try:
        with open(path) as f:
            rows = [json.loads(line) for line in f if line.strip()]
    except OSError as e:
        print(f"FAIL: {e}", file=sys.stderr)
        return 1
    if not rows:
        print(f"FAIL: {path} is empty", file=sys.stderr)
        return 1
    for i, row in enumerate(rows):
        schema = SCHEMAS.get(row.get("bench"))
        if schema is None:
            print(f"FAIL: row {i} unknown bench {row.get('bench')!r}",
                  file=sys.stderr)
            bad += 1
            continue
        for key, typ in schema.items():
            if key not in row:
                print(f"FAIL: row {i} ({row['bench']}) missing {key!r}",
                      file=sys.stderr)
                bad += 1
            elif typ is float and not isinstance(row[key], (int, float)):
                print(f"FAIL: row {i} {key!r} not numeric", file=sys.stderr)
                bad += 1
            elif typ is not float and not isinstance(row[key], typ):
                print(f"FAIL: row {i} {key!r} not {typ.__name__}",
                      file=sys.stderr)
                bad += 1
        if (row.get("bench") == "wire_fold" and row.get("path") == "device"
                and row.get("parity_bitwise") is not True):
            print(f"FAIL: row {i} device fold row without bitwise parity",
                  file=sys.stderr)
            bad += 1
    if not bad:
        print(f"schema ok: {len(rows)} row(s) in {path}")
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=5,
                    help="measured rounds per configuration (after 1 warmup)")
    ap.add_argument("--cohorts", default="2,4",
                    help="comma-separated cohort sizes")
    ap.add_argument("--schemes", default="int8,topk,topk8",
                    help="comma-separated UPLINK compress schemes, swept "
                         "at the largest cohort (the 'none' uplink "
                         "baseline is the plain downlink row)")
    ap.add_argument("--feedback", default="off,on",
                    help="comma-separated error-feedback settings for the "
                         "uplink sweep (off/on)")
    ap.add_argument("--down-schemes", default="none,int8",
                    help="comma-separated compress_down schemes")
    ap.add_argument("--tp-sizes", default="1,2",
                    help="comma-separated server tp_size values; sizes > 1 "
                         "shard the global model over a (model,) mesh and "
                         "are swept on the 'none' scheme only")
    ap.add_argument("--lora-ranks", default="4,8",
                    help="comma-separated LoRA ranks priced at the "
                         "BERT-base bench config (+ one real tiny-BERT "
                         "factor-uplink federation per rank); empty "
                         "string skips the sweep")
    ap.add_argument("--lora-only", action="store_true",
                    help="run only the --lora-ranks sweep (CI lora-smoke)")
    ap.add_argument("--fold-device", action="store_true",
                    help="run the e2e federation rows with the device-"
                         "resident fold (RunConfig.fold_device; the CI "
                         "wire-smoke proves one real round through it)")
    ap.add_argument("--fold-frames", default="dense,topk8,lora_r4",
                    help="comma-separated frame types for the fold-"
                         "throughput sweep at BERT-base (host vs device, "
                         "batch 1 vs K); empty string skips the sweep")
    ap.add_argument("--fold-cohort", type=int, default=4,
                    help="contributions per fold (the K in batch 1 vs K)")
    ap.add_argument("--fold-repeats", type=int, default=3,
                    help="timed folds per fold-throughput row")
    ap.add_argument("--fold-only", action="store_true",
                    help="run only the --fold-frames sweep (CI wire-smoke)")
    ap.add_argument("--ckpt-tp", type=int, default=2,
                    help="server tp_size for the wire_ckpt save/restore "
                         "rows (sharded streaming vs gathered flat at "
                         "BERT-base); 0 skips the sweep")
    ap.add_argument("--ckpt-repeats", type=int, default=2,
                    help="timed saves per wire_ckpt row")
    ap.add_argument("--ckpt-only", action="store_true",
                    help="run only the wire_ckpt rows (CI ckpt-soak)")
    ap.add_argument("--check-schema", action="store_true",
                    help="after the sweep, validate the output JSONL "
                         "against the per-bench row schemas and fail on "
                         "any mismatch")
    ap.add_argument("--check-only", action="store_true",
                    help="validate the existing --out JSONL against the "
                         "row schemas and exit (no benches run) — the CI "
                         "gate over the committed results")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "wire_bench.jsonl"))
    ap.add_argument("--warmup-timeout", type=float, default=300.0)
    ap.add_argument("--round-timeout", type=float, default=60.0)
    args = ap.parse_args(argv)

    if args.check_only:
        return check_schema(args.out)

    tp_sizes = [int(t) for t in args.tp_sizes.split(",") if t]
    cohorts = [int(c) for c in args.cohorts.split(",") if c]
    rows = []

    def bench_row(n, scheme_down, scheme_up, fb, tp):
        t0 = time.time()
        row = run_bench(n, scheme_down, scheme_up, fb, tp, args.rounds,
                        args.warmup_timeout, args.round_timeout,
                        fold_device=args.fold_device)
        row["bench_wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        print(json.dumps({k: v for k, v in row.items()
                          if k != "per_round"}))
        if row["encodes_per_round"] != 1:
            raise SystemExit(
                f"FAIL: {row['encodes_per_round']} broadcast encodes per "
                f"round at cohort {n} (want exactly 1)")
        if args.fold_device and row["fold_device_folds_per_round"] < n:
            raise SystemExit(
                f"FAIL: --fold-device round folded "
                f"{row['fold_device_folds_per_round']} of {n} "
                "contributions through the device kernel")
        if tp > 1 and row["gather_bytes_avoided_per_round"] <= 0:
            raise SystemExit(
                f"FAIL: tp_size={tp} row avoided no gather bytes "
                "(sharded downlink not engaged)")
        if scheme_up in ("topk", "topk8"):
            if row["uplink_densify_avoided_per_round"] < n:
                raise SystemExit(
                    f"FAIL: {scheme_up} uplink row folded "
                    f"{row['uplink_densify_avoided_per_round']} of {n} "
                    "contributions sparse (sparse-native fold not engaged)")
            # topk ships 8 bytes/kept entry; the topk8 hybrid (int8
            # values + per-leaf scale) ~5 — it must price strictly
            # better than plain topk at the same density.
            floor = 6.0 if scheme_up == "topk" else 9.0
            if row["uplink_reduction_x"] < floor:
                raise SystemExit(
                    f"FAIL: {scheme_up} uplink reduction "
                    f"{row['uplink_reduction_x']}x < {floor}x vs the "
                    "dense frame")
        return row

    def lora_row(rank):
        t0 = time.time()
        row = run_lora_bench(rank, args.rounds, args.warmup_timeout,
                             args.round_timeout)
        row["bench_wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        print(json.dumps({k: v for k, v in row.items()
                          if k != "per_round"}))
        if row["encodes_per_round"] != 1:
            raise SystemExit(
                f"FAIL: {row['encodes_per_round']} broadcast encodes per "
                f"round at lora rank {rank} (want exactly 1)")
        if row["uplink_reduction_x"] < 25.0:
            raise SystemExit(
                f"FAIL: rank-{rank} factor uplink reduction "
                f"{row['uplink_reduction_x']}x < 25x vs the dense "
                "BERT-base frame")
        if row["bytes_saved_uplink_per_round"] <= 0:
            raise SystemExit(
                f"FAIL: rank-{rank} smoke run saved no uplink bytes "
                "(factor replies not engaged)")
        if row["lora_merges"] < 1:
            raise SystemExit(
                f"FAIL: rank-{rank} smoke run never merged factors into "
                "the base model (lora_merge_every not engaged)")
        return row

    def fold_rows(frame):
        for row in run_fold_rows(frame, args.fold_cohort,
                                 args.fold_repeats):
            rows.append(row)
            print(json.dumps(row))
            if row["path"] == "device" and not row["parity_bitwise"]:
                raise SystemExit(
                    f"FAIL: device fold of {frame} frames diverged from "
                    "the host oracle (bitwise parity broken)")
            if (row["frame"] == "topk8" and row["path"] == "device"
                    and row["batch"] > 1
                    and row["speedup_vs_host"] < 1.0):
                raise SystemExit(
                    f"FAIL: batched device fold of topk8 frames is "
                    f"SLOWER than the host fold "
                    f"({row['speedup_vs_host']}x)")

    def ckpt_rows():
        for row in run_ckpt_rows(args.ckpt_tp, args.ckpt_repeats):
            rows.append(row)
            print(json.dumps(row))
            if not row["restore_bitwise"]:
                raise SystemExit(
                    f"FAIL: {row['path']} ckpt restore diverged bitwise "
                    "from the saved weights")
            if row["path"] == "sharded" and row["gather_avoided"] < 1:
                raise SystemExit(
                    "FAIL: sharded streaming save avoided no gather bytes "
                    "(the full tree was host-materialized)")
            if row["path"] == "sharded" and row["shards_per_gen"] < 2:
                raise SystemExit(
                    f"FAIL: streaming save wrote "
                    f"{row['shards_per_gen']} shard file(s) at "
                    f"tp_size={args.ckpt_tp} (shard-wise layout not "
                    "engaged)")

    def write_out():
        os.makedirs(os.path.dirname(args.out), exist_ok=True)
        with open(args.out, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        print(f"wrote {len(rows)} rows to {args.out}")
        return check_schema(args.out) if args.check_schema else 0

    if args.ckpt_only:
        ckpt_rows()
        return write_out()

    if args.fold_only:
        for frame in (s.strip() for s in args.fold_frames.split(",") if s):
            fold_rows(frame)
        return write_out()

    if not args.lora_only:
        # Downlink matrix (unchanged axes): cohorts × down-schemes × tp.
        for n in cohorts:
            for scheme_down in (s.strip()
                                for s in args.down_schemes.split(",") if s):
                # Sharded-server rows ride on the uncompressed scheme (the
                # encode path is byte-identical either way; one sweep axis
                # at a time keeps the matrix readable).
                for tp in (tp_sizes if scheme_down == "none" else [1]):
                    bench_row(n, scheme_down, "none", False, tp)

        # Uplink sweep at the largest cohort: scheme × feedback.  Feedback
        # on a lossless uplink is a no-op, so "none" only appears as the
        # baseline rows above.
        n_up = max(cohorts)
        for scheme_up in (s.strip() for s in args.schemes.split(",") if s):
            if scheme_up == "none":
                continue
            for fb_s in (s.strip() for s in args.feedback.split(",") if s):
                bench_row(n_up, "none", scheme_up, fb_s == "on", 1)

    # LoRA factor-uplink sweep: rank-r adapter frames vs the dense frame.
    for rank_s in (s.strip() for s in args.lora_ranks.split(",") if s):
        lora_row(int(rank_s))

    # Fold-throughput sweep at BERT-base: host vs device, batch 1 vs K.
    if not args.lora_only:
        for frame in (s.strip() for s in args.fold_frames.split(",") if s):
            fold_rows(frame)

    # Durable save/restore at BERT-base: streaming sharded vs flat.
    if not args.lora_only and args.ckpt_tp > 0:
        ckpt_rows()

    return write_out()


if __name__ == "__main__":
    raise SystemExit(main())
