"""Trace-pipeline smoke: 2 synthetic rounds with --trace-dir semantics.

Runs a tiny FederatedLearner with span tracing on, then asserts the
written Chrome-trace JSON parses, contains the expected per-round phase
spans, and that the phase spans cover (>= 95% of) the round wall time —
the end-to-end guarantee `colearn train --trace-dir` makes.  Exits
non-zero on any violation; importable (``main(tmpdir)``) so the test
suite runs it in-process without a subprocess jax re-init.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REQUIRED_PHASES = {"round", "client_update", "sync_metrics", "evaluate"}


def main(trace_dir: str | None = None) -> dict:
    from colearn_federated_learning_tpu import telemetry
    from colearn_federated_learning_tpu.fed.engine import FederatedLearner
    from colearn_federated_learning_tpu.utils.config import get_config

    trace_dir = trace_dir or tempfile.mkdtemp(prefix="colearn_trace_smoke_")
    cfg = get_config("mnist_mlp_fedavg")
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, dataset="mnist_tiny",
                                 num_clients=4),
        fed=dataclasses.replace(cfg.fed, rounds=2, local_steps=2,
                                batch_size=8, cohort_size=4),
        run=dataclasses.replace(cfg.run, backend="cpu", eval_every=1,
                                name="trace_smoke", trace_dir=trace_dir),
    )
    learner = FederatedLearner.from_config(cfg)
    learner.fit()

    path = learner.last_trace_path
    assert path, "fit() with trace_dir set did not write a trace"
    doc = telemetry.load_trace(path)           # raises if it doesn't parse
    spans = telemetry.trace_spans(doc)
    names = {s.name for s in spans}
    missing = REQUIRED_PHASES - names
    assert not missing, f"trace is missing phase spans: {sorted(missing)}"

    rounds = [s for s in spans if s.name == "round"]
    assert len(rounds) == 2, f"expected 2 round spans, got {len(rounds)}"
    round_total = sum(s.duration_s for s in rounds)
    child_total = sum(
        s.duration_s for s in spans
        if s.parent_id in {r.span_id for r in rounds}
    )
    coverage = child_total / round_total if round_total else 0.0
    assert coverage >= 0.95, (
        f"phase spans cover only {coverage:.1%} of round time"
    )
    assert doc["otherData"]["metrics"]["engine.rounds_total"] >= 2

    out = {
        "trace_file": path,
        "spans": len(spans),
        "phases": sorted(names),
        "coverage": coverage,
        "summary": telemetry.summarize_trace(doc),
    }
    return out


if __name__ == "__main__":
    result = main(sys.argv[1] if len(sys.argv) > 1 else None)
    print(result["summary"])
    print(json.dumps({k: v for k, v in result.items() if k != "summary"}))
