#!/usr/bin/env python
"""Multi-process chaos soak: prove the federation survives real SIGKILL.

Runs TWO subprocess federations (faults/procsoak.py) with identical
configs and seeds — one kill-free baseline, one under the canned kill
schedule (a worker dies and restarts; the COORDINATOR dies mid-round and
must come back with --resume) — then asserts:

- both runs produce a record for every scheduled round (the resumed
  coordinator re-ran the uncommitted round instead of losing it);
- the faulted run actually resumed (``rounds_resumed >= 1``);
- every scheduled kill was delivered;
- the faulted model's final own-shard accuracy lands within ``--tol`` of
  the baseline's on the clients both runs evaluated.

Exit 0 iff every assertion holds; the summary JSON goes to stdout either
way.  `colearn chaos --mp` is the one-run interactive flavor of this;
scripts/chaos_soak.py is the in-process (transport-interposer) gate.

Usage:
    python scripts/chaos_soak_mp.py [--rounds 6] [--num-workers 3]
                                    [--tol 0.1]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_proc_soak(base: dict, faulted: dict, rounds: int, tol: float,
                    kills: list) -> list[str]:
    """Every acceptance violation, as human-readable strings (empty =
    pass).  Shared with tests/test_procsoak.py so the gate and the script
    can never drift."""
    problems = []
    for name, s in (("baseline", base), ("faulted", faulted)):
        if s["exit_code"] != 0:
            problems.append(f"{name}: coordinator exited "
                            f"{s['exit_code']}, not 0")
        if s["rounds_run"] != rounds:
            problems.append(
                f"{name}: {s['rounds_run']}/{rounds} round records — "
                "rounds were lost across the kills")
    if base["rounds_resumed"]:
        problems.append("baseline resumed with no kills delivered")
    expect_resume = any(k.target == "coordinator" for k in kills)
    if expect_resume and faulted["rounds_resumed"] < 1:
        problems.append("coordinator was SIGKILLed but never resumed "
                        "(rounds_resumed == 0)")
    if len(faulted["kills"]) != len(kills):
        problems.append(
            f"only {len(faulted['kills'])}/{len(kills)} scheduled kills "
            "were delivered")
    if base["weighted_acc"] is None or faulted["weighted_acc"] is None:
        problems.append("missing final accuracy")
    else:
        # Compare on the clients BOTH runs evaluated — eviction can shrink
        # the faulted run's eval set while its worker restarts.
        common = sorted(set(base.get("per_client_acc", {}))
                        & set(faulted.get("per_client_acc", {})))
        if common:
            b = sum(base["per_client_acc"][c] for c in common) / len(common)
            f = sum(faulted["per_client_acc"][c]
                    for c in common) / len(common)
        else:
            b, f = base["weighted_acc"], faulted["weighted_acc"]
        if abs(b - f) > tol:
            problems.append(
                f"final accuracy drifted: baseline {b:.3f} vs faulted "
                f"{f:.3f} over {len(common) or 'all'} common clients "
                f"(tol {tol})")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--num-workers", type=int, default=3)
    ap.add_argument("--round-timeout", type=float, default=120.0)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-run wall-clock backstop in seconds")
    ap.add_argument("--tol", type=float, default=0.1,
                    help="allowed |baseline - faulted| final-accuracy gap")
    ap.add_argument("--workdir", default=None,
                    help="scratch root (default: fresh temp dirs)")
    args = ap.parse_args(argv)

    from colearn_federated_learning_tpu.faults import procsoak

    log = lambda rec: print(json.dumps(rec), file=sys.stderr)
    kills = procsoak.canned_kill_schedule(args.rounds, args.num_workers)

    def run(tag, kill_list):
        wd = (os.path.join(args.workdir, tag) if args.workdir else None)
        return procsoak.run_proc_soak(
            rounds=args.rounds, n_workers=args.num_workers,
            kills=kill_list, workdir=wd,
            round_timeout=args.round_timeout, timeout_s=args.timeout,
            log_fn=log)

    print("# kill-free baseline", file=sys.stderr)
    base = run("baseline", [])
    print(f"# kill schedule: {[k.target for k in kills]}", file=sys.stderr)
    faulted = run("faulted", kills)

    problems = check_proc_soak(base, faulted, args.rounds, args.tol, kills)
    print(json.dumps({"baseline": base, "faulted": faulted,
                      "problems": problems}))
    for p in problems:
        print(f"FAIL: {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
