#!/usr/bin/env python
"""Fleet-scale round bench: chunked-vmap rounds from 1k to 1M clients.

Sweeps cohort size over the fleetsim subsystem (one FleetSim per sweep
point, devices == cohort so every round trains the full requested
cohort) and records per point:

- ``rounds_per_sec`` / ``clients_per_sec`` — the scale headline: wall
  time is O(cohort / chunk) jitted dispatches, memory O(chunk);
- ``bytes_up_per_round`` / ``bytes_down_per_round`` — wire-codec frame
  estimates (utils.serialization.wire_frame_length x cohort), the
  measurable scale axis for the ROADMAP compression items;
- compile-excluded mean round time (round 0 is the warmup).

One JSON line per sweep point is appended to
``results/fleet_bench.jsonl`` (PERF.md "Fleet scale sweep" reads from
there).

Usage (CPU):
    JAX_PLATFORMS=cpu python scripts/bench_fleet.py
    JAX_PLATFORMS=cpu python scripts/bench_fleet.py \\
        --cohorts 1000,10000 --rounds 3 --chunk 2048
CI smoke:
    JAX_PLATFORMS=cpu python scripts/bench_fleet.py --cohorts 64,256 \\
        --rounds 2 --chunk 64 --check-schema --out results/fleet_ci.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Schema contract for every row this bench writes; --check-schema (CI)
# asserts it over the output file.
ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "cohort": int,
    "chunk": int,
    "rounds": int,
    "clients_trained": int,
    "rounds_per_sec": float,
    "clients_per_sec": float,
    "bytes_up_per_round": int,
    "bytes_down_per_round": int,
    "round_time_s_mean": float,
    "round_time_s_warmup": float,
    "train_loss": float,
    "param_count": int,
    "bench_wall_s": float,
}


def bench_config(feature_dim: int, num_classes: int):
    """A deliberately small model: the bench measures the per-client
    dispatch machinery, so the model just has to be non-trivial (two
    dense layers), not accurate."""
    from colearn_federated_learning_tpu.utils.config import (
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    return ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=num_classes,
                          hidden_dim=32, depth=1),
        fed=FedConfig(strategy="fedavg", local_steps=2, batch_size=8,
                      lr=0.05, momentum=0.0),
        run=RunConfig(name="bench_fleet", backend="cpu", seed=0),
    )


def run_point(cohort: int, rounds: int, chunk: int, seed: int) -> dict:
    import jax
    import numpy as np

    from colearn_federated_learning_tpu import fleetsim

    spec = fleetsim.PopulationSpec(
        num_devices=cohort, num_classes=10, feature_dim=16,
        shard_capacity=16, min_examples=4, seed=seed)
    population = fleetsim.DevicePopulation(spec)
    # High base rate -> ~every device available: the sweep measures
    # throughput at the REQUESTED cohort, not the traffic model.
    traffic = fleetsim.TrafficModel(
        fleetsim.TrafficSpec(base_rate=2000.0, diurnal_amplitude=0.0,
                             seed=seed),
        spec.num_devices)
    config = bench_config(spec.feature_dim, spec.num_classes)
    sim = fleetsim.FleetSim.from_population(
        config, population, traffic, cohort_size=cohort, chunk_size=chunk)

    t0 = time.time()
    history = sim.fit(rounds + 1)          # round 0 pays the jit compile
    wall = time.time() - t0
    measured = history[1:]
    times = [r["round_time_s"] for r in measured]
    clients = sum(r["clients_trained"] for r in measured)
    span = sum(times) or 1e-9
    params = jax.tree.leaves(sim.server_state.params)
    return {
        "bench": "fleet_round",
        "devices": spec.num_devices,
        "cohort": cohort,
        "chunk": sim.chunk_size,
        "rounds": len(measured),
        "clients_trained": int(clients),
        "rounds_per_sec": round(len(measured) / span, 4),
        "clients_per_sec": round(clients / span, 1),
        "bytes_up_per_round": int(statistics.mean(
            r["bytes_up_est"] for r in measured)),
        "bytes_down_per_round": int(statistics.mean(
            r["bytes_down_est"] for r in measured)),
        "round_time_s_mean": round(statistics.mean(times), 4),
        "round_time_s_warmup": round(history[0]["round_time_s"], 4),
        "train_loss": float(measured[-1]["train_loss"]),
        "param_count": int(sum(np.asarray(p).size for p in params)),
        "bench_wall_s": round(wall, 1),
    }


def check_schema(path: str) -> int:
    """Validate every row of a bench JSONL against ROW_SCHEMA (CI gate)."""
    bad = 0
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    if not rows:
        print(f"FAIL: {path} is empty", file=sys.stderr)
        return 1
    for i, row in enumerate(rows):
        for key, typ in ROW_SCHEMA.items():
            if key not in row:
                print(f"FAIL: row {i} missing {key!r}", file=sys.stderr)
                bad += 1
            elif typ is float and not isinstance(row[key], (int, float)):
                print(f"FAIL: row {i} {key!r} not numeric", file=sys.stderr)
                bad += 1
            elif typ is not float and not isinstance(row[key], typ):
                print(f"FAIL: row {i} {key!r} not {typ.__name__}",
                      file=sys.stderr)
                bad += 1
        if row.get("clients_trained", 0) <= 0:
            print(f"FAIL: row {i} trained no clients", file=sys.stderr)
            bad += 1
    if not bad:
        print(f"schema ok: {len(rows)} row(s) in {path}")
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cohorts", default="1000,10000,100000,1000000",
                    help="comma-separated cohort sizes (devices == cohort)")
    ap.add_argument("--rounds", type=int, default=2,
                    help="measured rounds per point (after 1 warmup)")
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "fleet_bench.jsonl"))
    ap.add_argument("--check-schema", action="store_true",
                    help="after the sweep, validate the output JSONL "
                         "against ROW_SCHEMA and fail on any mismatch")
    args = ap.parse_args(argv)

    rows = []
    for cohort in (int(c) for c in args.cohorts.split(",") if c):
        row = run_point(cohort, args.rounds, args.chunk, args.seed)
        rows.append(row)
        print(json.dumps(row))

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(f"wrote {len(rows)} rows to {args.out}")
    if args.check_schema:
        return check_schema(args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
