#!/usr/bin/env python
"""Fleet-scale round bench: chunked-vmap rounds from 1k to 1M clients.

Sweeps cohort size over the fleetsim subsystem (one FleetSim per sweep
point, devices == cohort so every round trains the full requested
cohort) and records per point:

- ``rounds_per_sec`` / ``clients_per_sec`` — the scale headline: wall
  time is O(cohort / chunk) jitted dispatches, memory O(chunk);
- ``bytes_up_per_round`` / ``bytes_down_per_round`` — wire-codec frame
  estimates (utils.serialization.wire_frame_length x cohort), the
  measurable scale axis for the ROADMAP compression items;
- compile-excluded mean round time (round 0 is the warmup).

One JSON line per sweep point is appended to
``results/fleet_bench.jsonl`` (PERF.md "Fleet scale sweep" reads from
there).

``--mask-sweep`` adds ``fleet_mask_cost`` rows: the analytic per-device
cost of dropout-tolerant secure aggregation (privacy/dropout.mask_cost)
at ``--mask-devices`` cohort under group-local masking, swept over
neighbor count k — per-device mask-PRG FLOPs, recovery-share bytes,
and the grouped-vs-flat pair ratio that pins the absence of an
O(cohort²) term.  The [tool.colearn.slo] sentinel bounds the new
columns.

``--uplink-sweep`` adds ``fleet_uplink_bytes`` rows: analytic uplink
frame bytes per fed.compress scheme (none/int8/topk) at
``--uplink-devices`` reporting clients — the same shape-only wire
pricing fleetsim's ``bytes_up_est`` / ``bytes_up_saved_est`` use, so
the sentinel can gate the 1M-device uplink bill without a 1M fleet.

Usage (CPU):
    JAX_PLATFORMS=cpu python scripts/bench_fleet.py
    JAX_PLATFORMS=cpu python scripts/bench_fleet.py \\
        --cohorts 1000,10000 --rounds 3 --chunk 2048
CI smoke:
    JAX_PLATFORMS=cpu python scripts/bench_fleet.py --cohorts 64,256 \\
        --rounds 2 --chunk 64 --check-schema --out results/fleet_ci.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Schema contract for every row this bench writes; --check-schema (CI)
# asserts it over the output file.  Rows carry a ``bench`` tag and are
# validated against the schema for that tag (SCHEMAS).
ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "cohort": int,
    "chunk": int,
    "rounds": int,
    "clients_trained": int,
    "rounds_per_sec": float,
    "clients_per_sec": float,
    "bytes_up_per_round": int,
    "bytes_down_per_round": int,
    "round_time_s_mean": float,
    "round_time_s_warmup": float,
    "train_loss": float,
    "param_count": int,
    "bench_wall_s": float,
}

# Masked-uplink cost rows (--mask-sweep): the analytic per-device cost
# of dropout-tolerant secure aggregation (privacy/dropout.mask_cost)
# under group-local masking, swept over neighbor count k at fleet scale.
MASK_ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "neighbors": int,
    "group_size": int,
    "param_count": int,
    "mask_flops_per_device": float,
    "share_bytes_per_device": float,
    "pairs_per_device": int,
    "flat_pairs_total": int,
    "grouped_pairs_total": int,
    "quadratic_ratio": float,
    "bench_wall_s": float,
}

# Uplink wire-cost rows (--uplink-sweep): analytic per-scheme uplink
# frame bytes at fleet scale — the same shape-only pricing the fleetsim
# estimator (fleetsim/sim.py) and the coordinator's
# comm.bytes_saved_uplink counter use, so the 1M-device point never has
# to materialize a fleet.
UPLINK_ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "scheme": str,
    "topk_fraction": float,
    "param_count": int,
    "up_frame_bytes": int,
    "up_dense_bytes": int,
    "bytes_up_est_total": int,
    "bytes_up_saved_est_total": int,
    "uplink_reduction_x": float,
    "bench_wall_s": float,
}

# Aggregator-tree ingest rows (--ingest-sweep): the root's per-round
# ingest bill and fold critical path at fleet scale, swept over the
# aggregator count N.  Bytes are analytic shape-only wire pricing
# (comm/aggregator.expected_ingest); the per-update fold cost is
# MEASURED on this host with the real StreamingFolder, then scaled —
# each aggregator folds ceil(C/N) updates in parallel while the root
# folds only N partials, so the critical path drops ~1/N.
INGEST_ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "aggregators": int,
    "param_count": int,
    "update_bytes": int,
    "partial_bytes": int,
    "agg_ingest_bytes": int,
    "root_ingest_bytes": int,
    "flat_root_ingest_bytes": int,
    "root_ingest_reduction_x": float,
    "ingest_scale_x": float,
    "fold_s_per_update": float,
    "agg_fold_s_est": float,
    "root_fold_s_est": float,
    "critical_path_fold_s_est": float,
    "flat_fold_s_est": float,
    "fold_speedup_x": float,
    "bench_wall_s": float,
}

# Buffered-async throughput rows (--async-sweep): arrival-rate vs
# straggler-tail scaling from 1k to 1M devices.  The service/arrival
# distributions are the SAME model fleetsim's fit_async simulates
# (diurnal-Poisson check-ins, lognormal service, a seeded fraction of
# chronic stragglers at a fixed multiple); the sweep evaluates them
# analytically over a deterministic device sample with a fixed-point
# waste estimate, so the 1M point never materializes a 1M fleet.  The
# headline columns: async folds track the ARRIVAL rate
# (``arrival_tracking`` = folded/arrived, ``async_updates_per_min``),
# while a sync round is bounded by the straggler TAIL
# (``sync_round_min`` = the cohort's completion-time quantile), so
# ``async_speedup_x`` holds at every scale — the sentinel pins it at
# the 1M row.
ASYNC_ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "buffer_size": int,
    "max_staleness": int,
    "rate_per_device_hr": float,
    "service_mean_min": float,
    "straggler_fraction": float,
    "straggler_multiplier": float,
    "arrival_rate_per_min": float,
    "agg_rate_per_min": float,
    "staleness_mean_est": float,
    "waste_fraction": float,
    "arrival_tracking": float,
    "async_updates_per_min": float,
    "sync_quantile": float,
    "sync_round_min": float,
    "sync_updates_per_min": float,
    "async_speedup_x": float,
    "bench_wall_s": float,
}

# Straggler-pruning gate row (--async-sweep): one MEASURED pair of
# fit_async runs (pruned vs unpruned, same seed/fleet) — pruning must
# waste measurably fewer too-stale updates at equal final loss.
ASYNC_PRUNE_ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "buffer_size": int,
    "aggregations": int,
    "max_staleness": int,
    "prune_after": int,
    "probation": int,
    "wasted_updates_unpruned": int,
    "wasted_updates_pruned": int,
    "waste_reduction_x": float,
    "pruned_total": int,
    "final_loss_unpruned": float,
    "final_loss_pruned": float,
    "loss_gap": float,
    "bench_wall_s": float,
}

# Adaptive-buffering gate row (--async-sweep): one MEASURED sweep of
# fit_async runs on the same seeded fleet — fixed buffer sizes vs
# ``buffer_size="auto"`` (K sized per aggregation from the seeded-EWMA
# arrival-rate estimator).  "Tracking" is the fold-cadence band: the
# fraction of realized fold intervals inside [target/2, 2x target] —
# fold cadence proportional to the arrival rate IS what tracking
# arrivals means for a buffered-async server (folded/arrived, by
# contrast, is trivially maximized by the largest possible K).  The
# diurnal arrival swing carries any fixed K out of the band; auto-K
# must stay in it at least as well as the best fixed K, at equal loss.
ASYNC_AUTOK_ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "aggregations": int,
    "max_staleness": int,
    "target_interval_min": float,
    "fixed_ks": str,
    "best_fixed_k": int,
    "tracking_auto": float,
    "tracking_best_fixed": float,
    "tracking_margin": float,
    "final_loss_auto": float,
    "final_loss_best_fixed": float,
    "loss_gap": float,
    "buffer_k_min_auto": int,
    "buffer_k_max_auto": int,
    "arrival_rate_per_min": float,
    "bench_wall_s": float,
}

# Cohort-drift attribution rows (--drift-sweep): the convergence
# observatory's per-cohort skew (1 - min cohort-centroid cosine vs the
# round aggregate) measured on the SAME seeded fleet twice — once with
# seeded non-IID label skew, once IID — so the committed row proves the
# signal separates data heterogeneity from sampling noise.
DRIFT_ROW_SCHEMA = {
    "bench": str,
    "devices": int,
    "rounds": int,
    "label_skew_noniid": float,
    "label_skew_iid": float,
    "cohort_skew_noniid_mean": float,
    "cohort_skew_noniid_max": float,
    "cohort_skew_iid_mean": float,
    "cohort_skew_iid_max": float,
    "skew_separation": float,
    "update_norm_final_noniid": float,
    "update_norm_final_iid": float,
    "bench_wall_s": float,
}

# Tree-async rows (--tree-async-sweep): buffered-async THROUGH the
# per-slice aggregator tree, 1k -> 1M devices.  Small fleets run
# MEASURED (fleetsim._fit_async_tree on a real seeded fleet: per-slice
# auto-K buffers, edge-folded partials root-discounted against the
# oldest constituent); large fleets price the same model ANALYTICALLY
# (per-slice arrival rates, integer-K cadence, fixed-point waste) so
# the 1M row never materializes a 1M fleet.  ``fold_tracking_min`` is
# the worst slice's cadence tracking against its achievable band — the
# sentinel floors it at 0.75 with >= 2 aggregators.
# ``rehome_slice_frac`` prices failover: the in-flight mass share one
# dead aggregator re-homes onto its siblings (1/aggregators).
TREE_ASYNC_ROW_SCHEMA = {
    "bench": str,
    "mode": str,                  # "measured" | "analytic"
    "devices": int,
    "aggregators": int,
    "target_interval_min": float,
    "max_staleness": int,
    "arrival_rate_per_min": float,
    "agg_rate_per_min": float,
    "buffer_k_mean": float,
    "fold_tracking_min": float,
    "staleness_mean": float,
    "waste_fraction": float,
    "rehome_slice_frac": float,
    "bench_wall_s": float,
}

SCHEMAS = {
    "fleet_round": ROW_SCHEMA,
    "fleet_learn_drift": DRIFT_ROW_SCHEMA,
    "fleet_mask_cost": MASK_ROW_SCHEMA,
    "fleet_uplink_bytes": UPLINK_ROW_SCHEMA,
    "fleet_ingest_scaling": INGEST_ROW_SCHEMA,
    "fleet_async": ASYNC_ROW_SCHEMA,
    "fleet_async_prune": ASYNC_PRUNE_ROW_SCHEMA,
    "fleet_async_autok": ASYNC_AUTOK_ROW_SCHEMA,
    "fleet_tree_async": TREE_ASYNC_ROW_SCHEMA,
}


def bench_config(feature_dim: int, num_classes: int):
    """A deliberately small model: the bench measures the per-client
    dispatch machinery, so the model just has to be non-trivial (two
    dense layers), not accurate."""
    from colearn_federated_learning_tpu.utils.config import (
        ExperimentConfig,
        FedConfig,
        ModelConfig,
        RunConfig,
    )

    return ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=num_classes,
                          hidden_dim=32, depth=1),
        fed=FedConfig(strategy="fedavg", local_steps=2, batch_size=8,
                      lr=0.05, momentum=0.0),
        run=RunConfig(name="bench_fleet", backend="cpu", seed=0),
    )


def run_point(cohort: int, rounds: int, chunk: int, seed: int) -> dict:
    import jax
    import numpy as np

    from colearn_federated_learning_tpu import fleetsim

    spec = fleetsim.PopulationSpec(
        num_devices=cohort, num_classes=10, feature_dim=16,
        shard_capacity=16, min_examples=4, seed=seed)
    population = fleetsim.DevicePopulation(spec)
    # High base rate -> ~every device available: the sweep measures
    # throughput at the REQUESTED cohort, not the traffic model.
    traffic = fleetsim.TrafficModel(
        fleetsim.TrafficSpec(base_rate=2000.0, diurnal_amplitude=0.0,
                             seed=seed),
        spec.num_devices)
    config = bench_config(spec.feature_dim, spec.num_classes)
    sim = fleetsim.FleetSim.from_population(
        config, population, traffic, cohort_size=cohort, chunk_size=chunk)

    t0 = time.time()
    history = sim.fit(rounds + 1)          # round 0 pays the jit compile
    wall = time.time() - t0
    measured = history[1:]
    times = [r["round_time_s"] for r in measured]
    clients = sum(r["clients_trained"] for r in measured)
    span = sum(times) or 1e-9
    params = jax.tree.leaves(sim.server_state.params)
    return {
        "bench": "fleet_round",
        "devices": spec.num_devices,
        "cohort": cohort,
        "chunk": sim.chunk_size,
        "rounds": len(measured),
        "clients_trained": int(clients),
        "rounds_per_sec": round(len(measured) / span, 4),
        "clients_per_sec": round(clients / span, 1),
        "bytes_up_per_round": int(statistics.mean(
            r["bytes_up_est"] for r in measured)),
        "bytes_down_per_round": int(statistics.mean(
            r["bytes_down_est"] for r in measured)),
        "round_time_s_mean": round(statistics.mean(times), 4),
        "round_time_s_warmup": round(history[0]["round_time_s"], 4),
        "train_loss": float(measured[-1]["train_loss"]),
        "param_count": int(sum(np.asarray(p).size for p in params)),
        "bench_wall_s": round(wall, 1),
    }


def bench_params(seed: int):
    """Parameter tree of the bench model — initialized once against a
    tiny throwaway population (the model is devices-independent, so the
    1M-cohort mask and uplink sweeps never materialize a 1M fleet)."""
    import jax.numpy as jnp

    from colearn_federated_learning_tpu import fleetsim
    from colearn_federated_learning_tpu.fed import setup as setup_lib
    from colearn_federated_learning_tpu.models import (
        registry as model_registry,
    )
    from colearn_federated_learning_tpu.utils import prng

    spec = fleetsim.PopulationSpec(
        num_devices=8, num_classes=10, feature_dim=16,
        shard_capacity=16, min_examples=4, seed=seed)
    population = fleetsim.DevicePopulation(spec)
    config = bench_config(spec.feature_dim, spec.num_classes)
    model = model_registry.build_model(
        setup_lib.local_model_config(config.model))
    return model_registry.init_params(
        model, jnp.asarray(population.example_batch(config.fed.batch_size)),
        prng.init_key(prng.experiment_key(config.run.seed)))


def bench_param_count(seed: int) -> int:
    import jax
    import numpy as np

    return int(sum(np.asarray(p).size
                   for p in jax.tree.leaves(bench_params(seed))))


def uplink_point(devices: int, scheme: str, topk_fraction: float,
                 params) -> dict:
    """One uplink wire-cost row: per-device train-reply frame bytes under
    ``scheme`` vs the dense frame, scaled to ``devices`` reporting
    clients.  Pure shape math (frame lengths depend on leaf
    shapes/dtypes, not values) — identical pricing to
    fleetsim/sim.py's ``up_frame_bytes`` / ``up_saved_bytes``."""
    import jax
    import numpy as np

    from colearn_federated_learning_tpu.fed import compression
    from colearn_federated_learning_tpu.utils.serialization import (
        wire_frame_length,
    )

    t0 = time.time()
    zeros = jax.tree.map(
        lambda p: np.zeros(np.shape(p), np.float32), params)
    dense = int(wire_frame_length(
        zeros, {"round": 0, "op": "train", "compress": "none"}))
    if scheme == "none":
        up = dense
    else:
        wire, meta = compression.compress_delta(
            zeros, scheme, topk_fraction=topk_fraction)
        up = int(wire_frame_length(wire, {"round": 0, "op": "train", **meta}))
    saved = max(0, dense - up)
    return {
        "bench": "fleet_uplink_bytes",
        "devices": devices,
        "scheme": scheme,
        "topk_fraction": float(topk_fraction),
        "param_count": int(sum(np.asarray(p).size
                               for p in jax.tree.leaves(params))),
        "up_frame_bytes": up,
        "up_dense_bytes": dense,
        "bytes_up_est_total": devices * up,
        "bytes_up_saved_est_total": devices * saved,
        "uplink_reduction_x": round(dense / up, 2),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def measured_fold_s_per_update(params, folds: int = 64) -> float:
    """Median-free per-update fold cost, measured with the REAL
    StreamingFolder (dense add + finalize, amortized) on this host.
    The tree never changes the per-update work — it changes WHERE it
    runs — so one measured constant prices every sweep row."""
    import jax
    import numpy as np

    from colearn_federated_learning_tpu.comm.aggregation import (
        StreamingFolder,
    )

    shapes = jax.tree.map(np.asarray, params)
    update = jax.tree.map(
        lambda p: np.ones(np.shape(p), np.float32), params)
    folder = StreamingFolder(shapes)
    t0 = time.perf_counter()
    for i in range(folds):
        folder.add({"client_id": str(i), "weight": 1.0, "train_loss": 0.0},
                   update)
    folder.finalize()
    return (time.perf_counter() - t0) / folds


def ingest_point(devices: int, n_aggregators: int, params,
                 fold_s_per_update: float) -> dict:
    """One aggregator-tree ingest row at ``devices`` cohort and
    ``n_aggregators`` fan-in: analytic wire bytes per tier plus the
    fold critical path derived from the measured per-update cost."""
    import jax
    import math
    import numpy as np

    from colearn_federated_learning_tpu.comm import aggregator
    from colearn_federated_learning_tpu.utils.serialization import (
        wire_frame_length,
    )

    t0 = time.time()
    zeros = jax.tree.map(
        lambda p: np.zeros(np.shape(p), np.float32), params)
    update_bytes = int(wire_frame_length(
        zeros, {"round": 0, "op": "train", "compress": "none"}))
    # A partial sum is one dense tree regardless of slice size — the
    # whole point of the tree: root ingest is N frames, not C.
    partial_bytes = int(wire_frame_length(
        zeros, {"round": 0, "op": "fold", "agg_id": 0}))
    bill = aggregator.expected_ingest(devices, n_aggregators,
                                      update_bytes, partial_bytes)
    per_agg = math.ceil(devices / max(1, n_aggregators))
    agg_fold = per_agg * fold_s_per_update
    root_fold = n_aggregators * fold_s_per_update
    flat_fold = devices * fold_s_per_update
    critical = agg_fold + root_fold
    return {
        "bench": "fleet_ingest_scaling",
        "devices": devices,
        "aggregators": n_aggregators,
        "param_count": int(sum(np.asarray(p).size
                               for p in jax.tree.leaves(params))),
        "update_bytes": update_bytes,
        "partial_bytes": partial_bytes,
        "agg_ingest_bytes": bill["agg_ingest_bytes"],
        "root_ingest_bytes": bill["root_ingest_bytes"],
        "flat_root_ingest_bytes": bill["flat_root_ingest_bytes"],
        "root_ingest_reduction_x": round(
            bill["flat_root_ingest_bytes"]
            / max(1, bill["root_ingest_bytes"]), 2),
        "ingest_scale_x": round(
            bill["flat_root_ingest_bytes"]
            / max(1, bill["agg_ingest_bytes"]), 2),
        "fold_s_per_update": round(fold_s_per_update, 9),
        "agg_fold_s_est": round(agg_fold, 4),
        "root_fold_s_est": round(root_fold, 4),
        "critical_path_fold_s_est": round(critical, 4),
        "flat_fold_s_est": round(flat_fold, 4),
        "fold_speedup_x": round(flat_fold / critical, 2),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def mask_point(devices: int, neighbors: int, group_size: int,
               param_count: int) -> dict:
    """One masked-uplink cost row: per-device PRG FLOPs + recovery-share
    bytes under group-local secure aggregation at ``devices`` cohort,
    plus the flat-graph quadratic total the layering avoids (reported as
    ``quadratic_ratio`` so the sweep can PIN the absence of an
    O(cohort²) term rather than eyeball it)."""
    from colearn_federated_learning_tpu.privacy import dropout

    t0 = time.time()
    cost = dropout.mask_cost(cohort=devices, param_count=param_count,
                             neighbors=neighbors, group_size=group_size)
    return {
        "bench": "fleet_mask_cost",
        "devices": devices,
        "neighbors": neighbors,
        "group_size": group_size,
        "param_count": param_count,
        "mask_flops_per_device": cost["mask_flops_per_device"],
        "share_bytes_per_device": cost["share_bytes_per_device"],
        "pairs_per_device": cost["pairs_per_device"],
        "flat_pairs_total": cost["flat_pairs_total"],
        "grouped_pairs_total": cost["grouped_pairs_total"],
        "quadratic_ratio": round(
            cost["flat_pairs_total"] / max(1, cost["grouped_pairs_total"]),
            2),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def async_point(devices: int, *, rate_per_device_hr: float = 2.0,
                service_mean_min: float = 10.0,
                straggler_fraction: float = 0.05,
                straggler_multiplier: float = 20.0,
                buffer_divisor: int = 16, max_staleness: int = 32,
                sync_quantile: float = 0.98, seed: int = 0,
                samples: int = 65536) -> dict:
    """One buffered-async throughput row at ``devices`` fleet scale.

    Evaluates fleetsim's fit_async service model analytically over a
    deterministic ``samples``-device draw instead of materializing the
    fleet: per-device completion window W = arrival wait (exponential at
    the diurnal base rate) + service time (lognormal sigma=0.5 around
    ``service_mean_min``, with a seeded ``straggler_fraction`` of
    chronic stragglers at ``straggler_multiplier``x).

    Async side: the coordinator folds arrivals as they land, so the
    aggregation rate is (surviving arrival rate) / buffer_size; an
    update's staleness is W x aggregation rate, and updates past
    ``max_staleness`` versions are discarded.  Waste and aggregation
    rate feed back on each other, so both come from a short fixed-point
    iteration.  Sync side: a round must wait for the cohort's
    ``sync_quantile`` completion time, which the chronic-straggler tail
    dominates at every fleet size.  ``async_speedup_x`` is the ratio of
    folded-update throughput, and stays flat from 1k to 1M because the
    async plane tracks the ARRIVAL rate while the sync plane is bounded
    by the straggler TAIL."""
    import numpy as np

    t0 = time.time()
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xA51C]))
    rate_per_min = rate_per_device_hr / 60.0
    wait = rng.exponential(1.0 / rate_per_min, size=samples)
    service = service_mean_min * rng.lognormal(0.0, 0.5, size=samples)
    n_slow = int(round(straggler_fraction * samples))
    slow = rng.permutation(samples)[:n_slow]
    service[slow] *= straggler_multiplier
    window = wait + service

    buffer_size = max(32, devices // buffer_divisor)
    arrival_rate = devices * rate_per_min
    # Fixed point: staleness depends on the aggregation rate, which
    # depends on how many arrivals survive the staleness cut.
    waste = 0.0
    agg_rate = arrival_rate / buffer_size
    for _ in range(32):
        waste = float(np.mean(window * agg_rate > max_staleness))
        agg_rate = arrival_rate * (1.0 - waste) / buffer_size
    staleness_mean = float(np.mean(
        np.minimum(window * agg_rate, max_staleness)))
    async_updates_per_min = arrival_rate * (1.0 - waste)

    sync_round_min = float(np.quantile(window, sync_quantile))
    sync_updates_per_min = devices * sync_quantile / sync_round_min

    return {
        "bench": "fleet_async",
        "devices": devices,
        "buffer_size": buffer_size,
        "max_staleness": max_staleness,
        "rate_per_device_hr": rate_per_device_hr,
        "service_mean_min": service_mean_min,
        "straggler_fraction": straggler_fraction,
        "straggler_multiplier": straggler_multiplier,
        "arrival_rate_per_min": round(arrival_rate, 4),
        "agg_rate_per_min": round(agg_rate, 6),
        "staleness_mean_est": round(staleness_mean, 3),
        "waste_fraction": round(waste, 4),
        "arrival_tracking": round(1.0 - waste, 4),
        "async_updates_per_min": round(async_updates_per_min, 4),
        "sync_quantile": sync_quantile,
        "sync_round_min": round(sync_round_min, 3),
        "sync_updates_per_min": round(sync_updates_per_min, 4),
        "async_speedup_x": round(
            async_updates_per_min / sync_updates_per_min, 3),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def async_prune_point(*, devices: int = 64, aggregations: int = 40,
                      buffer_size: int = 8, max_staleness: int = 6,
                      prune_after: int = 1, probation: int = 40,
                      seed: int = 0) -> dict:
    """One MEASURED straggler-pruning gate row: run fit_async twice on
    the same seeded fleet — pruning off, then on — and report wasted
    (too-stale, discarded) updates and tail loss for both.  The gate the
    sentinels pin: pruning must cut waste by a real factor while the
    final loss stays within a small gap of the unpruned run."""
    from colearn_federated_learning_tpu import fleetsim
    from colearn_federated_learning_tpu.utils.config import (
        ExperimentConfig, FedConfig, ModelConfig, RunConfig)

    t0 = time.time()
    spec = fleetsim.PopulationSpec(num_devices=devices, num_classes=10,
                                   feature_dim=32, shard_capacity=16,
                                   label_skew=0.7, seed=seed)
    population = fleetsim.DevicePopulation(spec)
    config = ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=64,
                          depth=2),
        fed=FedConfig(strategy="fedavg", local_steps=2, batch_size=16,
                      lr=0.05),
        run=RunConfig(name="bench-async-prune", seed=seed))

    def tail_loss(history):
        losses = [r["train_loss"] for r in history[-5:]]
        return sum(losses) / max(1, len(losses))

    results = {}
    for label, pa in (("unpruned", 0), ("pruned", prune_after)):
        traffic = fleetsim.TrafficModel(fleetsim.TrafficSpec(seed=seed),
                                        spec.num_devices)
        sim = fleetsim.FleetSim.from_population(
            config, population, traffic, cohort_size=8, chunk_size=16)
        hist = sim.fit_async(aggregations, buffer_size=buffer_size,
                             max_staleness=max_staleness, prune_after=pa,
                             probation=probation)
        results[label] = {
            "wasted": int(hist[-1]["wasted_updates_total"]),
            "loss": tail_loss(hist),
            "pruned_total": int(hist[-1].get("pruned_total", 0)),
        }
    wasted_un = results["unpruned"]["wasted"]
    wasted_pr = results["pruned"]["wasted"]
    return {
        "bench": "fleet_async_prune",
        "devices": devices,
        "buffer_size": buffer_size,
        "aggregations": aggregations,
        "max_staleness": max_staleness,
        "prune_after": prune_after,
        "probation": probation,
        "wasted_updates_unpruned": wasted_un,
        "wasted_updates_pruned": wasted_pr,
        "waste_reduction_x": round(wasted_un / max(1, wasted_pr), 3),
        "pruned_total": results["pruned"]["pruned_total"],
        "final_loss_unpruned": round(results["unpruned"]["loss"], 5),
        "final_loss_pruned": round(results["pruned"]["loss"], 5),
        "loss_gap": round(
            abs(results["pruned"]["loss"] - results["unpruned"]["loss"]),
            5),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def async_autok_point(*, devices: int = 64, aggregations: int = 120,
                      max_staleness: int = 6, fixed_ks=(4, 8, 16, 32),
                      target_interval_min: float = 10.0,
                      seed: int = 0) -> dict:
    """One MEASURED adaptive-buffering gate row: run fit_async across a
    fixed-K sweep and once with ``buffer_size="auto"`` on the same
    seeded fleet.  Tracking = fraction of realized fold intervals
    inside the target cadence band [target/2, 2x target]; 120
    aggregations span most of a diurnal cycle, so the arrival-rate
    swing carries every fixed K out of the band for part of the run
    while auto-K follows the measured rate.  The ``fleet_async_autok``
    sentinels pin tracking_margin >= 0 (auto at least matches the best
    fixed K) and loss_gap <= 0.01 (at equal model quality)."""
    from colearn_federated_learning_tpu import fleetsim
    from colearn_federated_learning_tpu.utils.config import (
        ExperimentConfig, FedConfig, ModelConfig, RunConfig)

    t0 = time.time()
    spec = fleetsim.PopulationSpec(num_devices=devices, num_classes=10,
                                   feature_dim=32, shard_capacity=16,
                                   label_skew=0.7, seed=seed)
    population = fleetsim.DevicePopulation(spec)
    config = ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=64,
                          depth=2),
        fed=FedConfig(strategy="fedavg", local_steps=2, batch_size=16,
                      lr=0.05),
        run=RunConfig(name="bench-async-autok", seed=seed))

    def tail_loss(history):
        losses = [r["train_loss"] for r in history[-5:]]
        return sum(losses) / max(1, len(losses))

    def tracking(history):
        times = [0.0] + [r["sim_time_min"] for r in history]
        ivs = [b - a for a, b in zip(times, times[1:])]
        in_band = sum(1 for iv in ivs
                      if target_interval_min / 2.0 <= iv
                      <= target_interval_min * 2.0)
        return in_band / max(1, len(ivs))

    def run(buffer_size):
        traffic = fleetsim.TrafficModel(fleetsim.TrafficSpec(seed=seed),
                                        spec.num_devices)
        sim = fleetsim.FleetSim.from_population(
            config, population, traffic, cohort_size=32, chunk_size=32)
        return sim.fit_async(aggregations, buffer_size=buffer_size,
                             max_staleness=max_staleness,
                             auto_interval_min=target_interval_min)

    fixed = {}
    for k in fixed_ks:
        hist = run(k)
        fixed[k] = {"tracking": tracking(hist), "loss": tail_loss(hist)}
    best_k = max(fixed, key=lambda k: fixed[k]["tracking"])
    auto_hist = run("auto")
    auto_tracking = tracking(auto_hist)
    auto_loss = tail_loss(auto_hist)
    auto_ks = [r["buffer_size"] for r in auto_hist]
    return {
        "bench": "fleet_async_autok",
        "devices": devices,
        "aggregations": aggregations,
        "max_staleness": max_staleness,
        "target_interval_min": target_interval_min,
        "fixed_ks": ",".join(str(k) for k in fixed_ks),
        "best_fixed_k": int(best_k),
        "tracking_auto": round(auto_tracking, 4),
        "tracking_best_fixed": round(fixed[best_k]["tracking"], 4),
        "tracking_margin": round(
            auto_tracking - fixed[best_k]["tracking"], 4),
        "final_loss_auto": round(auto_loss, 5),
        "final_loss_best_fixed": round(fixed[best_k]["loss"], 5),
        "loss_gap": round(abs(auto_loss - fixed[best_k]["loss"]), 5),
        "buffer_k_min_auto": int(min(auto_ks)),
        "buffer_k_max_auto": int(max(auto_ks)),
        "arrival_rate_per_min": round(
            auto_hist[-1]["arrival_rate_per_min"], 4),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def tree_async_measured_point(*, devices: int = 1000, aggregators: int = 2,
                              aggregations: int = 24,
                              max_staleness: int = 50,
                              prune_after: int = 2,
                              target_interval_min: float = 10.0,
                              chunk: int = 256, seed: int = 0) -> dict:
    """One MEASURED tree-async row: fleetsim's two-tier fit_async on a
    real seeded fleet — service-time-sorted slices, per-slice auto-K
    buffers, edge-folded partials staleness-discounted at the root
    against the oldest constituent.  Pruning is armed (the tree plane's
    predicted-dropout policy): chronic stragglers whose own
    contributions repeatedly exceed ``max_staleness`` stop being
    re-dispatched, which is what keeps the straggler slice's fold
    cadence in band."""
    from colearn_federated_learning_tpu import fleetsim
    from colearn_federated_learning_tpu.utils.config import (
        ExperimentConfig, FedConfig, ModelConfig, RunConfig)

    t0 = time.time()
    spec = fleetsim.PopulationSpec(num_devices=devices, num_classes=10,
                                   feature_dim=32, shard_capacity=16,
                                   label_skew=0.7, seed=seed)
    population = fleetsim.DevicePopulation(spec)
    traffic = fleetsim.TrafficModel(fleetsim.TrafficSpec(seed=seed),
                                    spec.num_devices)
    config = ExperimentConfig(
        model=ModelConfig(name="mlp", num_classes=10, hidden_dim=64,
                          depth=2),
        fed=FedConfig(strategy="fedavg", local_steps=2, batch_size=16,
                      lr=0.05),
        run=RunConfig(name="bench-tree-async", seed=seed))
    sim = fleetsim.FleetSim.from_population(
        config, population, traffic, cohort_size=chunk, chunk_size=chunk)
    hist = sim.fit_async(aggregations, buffer_size="auto",
                         max_staleness=max_staleness,
                         prune_after=prune_after,
                         auto_interval_min=target_interval_min,
                         aggregators=aggregators)
    last = hist[-1]
    arrived = last["arrival_rate_per_min"] * last["sim_time_min"]
    return {
        "bench": "fleet_tree_async",
        "mode": "measured",
        "devices": devices,
        "aggregators": aggregators,
        "target_interval_min": target_interval_min,
        "max_staleness": max_staleness,
        "arrival_rate_per_min": round(last["arrival_rate_per_min"], 4),
        "agg_rate_per_min": round(last["agg_rate_per_min"], 6),
        "buffer_k_mean": round(
            sum(r["agg_buffer_k"] for r in hist) / len(hist), 3),
        "fold_tracking_min": round(last["agg_fold_tracking_min"], 4),
        "staleness_mean": round(
            sum(r["staleness_mean"] for r in hist) / len(hist), 3),
        "waste_fraction": round(
            last["wasted_updates_total"] / max(arrived, 1e-9), 4),
        "rehome_slice_frac": round(1.0 / aggregators, 4),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def tree_async_analytic_point(devices: int, aggregators: int, *,
                              rate_per_device_hr: float = 2.0,
                              service_mean_min: float = 10.0,
                              straggler_fraction: float = 0.05,
                              straggler_multiplier: float = 20.0,
                              target_interval_min: float = 10.0,
                              max_staleness: int = 32,
                              chunk: int = 4096, seed: int = 0,
                              samples: int = 65536) -> dict:
    """One ANALYTIC tree-async row at fleet scale: the same arrival /
    service model as :func:`async_point`, sliced across ``aggregators``
    per-slice buffers.  Each slice's integer K = clip(rate x target, 1,
    chunk) sets its realized fold cadence; tracking compares that
    cadence to the slice's achievable band (a capacity-clipped K is a
    capacity limit, not mistracking — same definition as the measured
    rows).  The root applies one partial per ship, so the version rate
    is the summed ship rate, and per-contribution staleness (completion
    window x version rate) feeds the fixed-point waste estimate exactly
    as on the flat plane."""
    import numpy as np

    t0 = time.time()
    rng = np.random.default_rng(np.random.SeedSequence([seed, 0xA51C]))
    rate_per_min = rate_per_device_hr / 60.0
    wait = rng.exponential(1.0 / rate_per_min, size=samples)
    service = service_mean_min * rng.lognormal(0.0, 0.5, size=samples)
    n_slow = int(round(straggler_fraction * samples))
    slow = rng.permutation(samples)[:n_slow]
    service[slow] *= straggler_multiplier
    window = wait + service

    arrival_rate = devices * rate_per_min
    rate_slice = arrival_rate / aggregators
    k = int(np.clip(round(rate_slice * target_interval_min), 1, chunk))
    t_real = k / rate_slice
    t_eff = float(np.clip(target_interval_min, 1.0 / rate_slice,
                          chunk / rate_slice))
    r = t_real / max(t_eff, 1e-9)
    tracking = min(r, 1.0 / r) if r > 0 else 0.0
    # One root application per shipped partial: version rate is the
    # summed per-slice ship rate.
    version_rate = aggregators / t_real
    waste = 0.0
    for _ in range(32):
        waste = float(np.mean(window * version_rate > max_staleness))
        version_rate = (aggregators / t_real) * (1.0 - waste)
    staleness_mean = float(np.mean(
        np.minimum(window * version_rate, max_staleness)))
    return {
        "bench": "fleet_tree_async",
        "mode": "analytic",
        "devices": devices,
        "aggregators": aggregators,
        "target_interval_min": target_interval_min,
        "max_staleness": max_staleness,
        "arrival_rate_per_min": round(arrival_rate, 4),
        "agg_rate_per_min": round(version_rate, 6),
        "buffer_k_mean": float(k),
        "fold_tracking_min": round(tracking, 4),
        "staleness_mean": round(staleness_mean, 3),
        "waste_fraction": round(waste, 4),
        "rehome_slice_frac": round(1.0 / aggregators, 4),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def drift_point(*, devices: int = 64, rounds: int = 10,
                label_skew_noniid: float = 0.9,
                label_skew_iid: float = 0.0, seed: int = 0) -> dict:
    """One MEASURED cohort-drift attribution row: two --learn-observe
    fleetsim runs at matched seeds, differing ONLY in the population's
    label skew.  conv_cohort_skew (telemetry/convergence.cohort_skew)
    must separate the seeded non-IID fleet from the IID one — the
    acceptance evidence that the skew signal attributes drift to data
    heterogeneity rather than sampling noise.  Warmup rounds are
    excluded from the means (the first folds are dominated by init
    transients on both fleets)."""
    from colearn_federated_learning_tpu import fleetsim
    from colearn_federated_learning_tpu.utils.config import (
        ExperimentConfig, FedConfig, ModelConfig, RunConfig)

    t0 = time.time()

    def run(label_skew: float) -> list:
        spec = fleetsim.PopulationSpec(
            num_devices=devices, num_classes=10, feature_dim=32,
            shard_capacity=16, label_skew=label_skew, seed=seed)
        population = fleetsim.DevicePopulation(spec)
        traffic = fleetsim.TrafficModel(
            fleetsim.TrafficSpec(base_rate=2000.0, diurnal_amplitude=0.0,
                                 seed=seed),
            spec.num_devices)
        config = ExperimentConfig(
            model=ModelConfig(name="mlp", num_classes=10, hidden_dim=64,
                              depth=2),
            fed=FedConfig(strategy="fedavg", local_steps=2,
                          batch_size=16, lr=0.05),
            run=RunConfig(name="bench-learn-drift", seed=seed,
                          learn_observe=True))
        sim = fleetsim.FleetSim.from_population(
            config, population, traffic, cohort_size=32, chunk_size=32)
        return sim.fit(rounds)

    def skew_stats(history) -> tuple:
        vals = [r["conv_cohort_skew"] for r in history[2:]
                if "conv_cohort_skew" in r]
        assert vals, "no conv_cohort_skew in observed round records"
        return (sum(vals) / len(vals), max(vals))

    noniid = run(label_skew_noniid)
    iid = run(label_skew_iid)
    nm, nx = skew_stats(noniid)
    im, ix = skew_stats(iid)
    return {
        "bench": "fleet_learn_drift",
        "devices": devices,
        "rounds": rounds,
        "label_skew_noniid": label_skew_noniid,
        "label_skew_iid": label_skew_iid,
        "cohort_skew_noniid_mean": round(nm, 4),
        "cohort_skew_noniid_max": round(nx, 4),
        "cohort_skew_iid_mean": round(im, 4),
        "cohort_skew_iid_max": round(ix, 4),
        "skew_separation": round(nm - im, 4),
        "update_norm_final_noniid": round(
            noniid[-1]["conv_update_norm"], 5),
        "update_norm_final_iid": round(iid[-1]["conv_update_norm"], 5),
        "bench_wall_s": round(time.time() - t0, 4),
    }


def check_schema(path: str) -> int:
    """Validate every row of a bench JSONL against the schema for its
    ``bench`` tag (CI gate)."""
    bad = 0
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    if not rows:
        print(f"FAIL: {path} is empty", file=sys.stderr)
        return 1
    for i, row in enumerate(rows):
        schema = SCHEMAS.get(row.get("bench"), ROW_SCHEMA)
        for key, typ in schema.items():
            if key not in row:
                print(f"FAIL: row {i} missing {key!r}", file=sys.stderr)
                bad += 1
            elif typ is float and not isinstance(row[key], (int, float)):
                print(f"FAIL: row {i} {key!r} not numeric", file=sys.stderr)
                bad += 1
            elif typ is not float and not isinstance(row[key], typ):
                print(f"FAIL: row {i} {key!r} not {typ.__name__}",
                      file=sys.stderr)
                bad += 1
        if schema is ROW_SCHEMA and row.get("clients_trained", 0) <= 0:
            print(f"FAIL: row {i} trained no clients", file=sys.stderr)
            bad += 1
    if not bad:
        print(f"schema ok: {len(rows)} row(s) in {path}")
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cohorts", default="1000,10000,100000,1000000",
                    help="comma-separated cohort sizes (devices == cohort)")
    ap.add_argument("--rounds", type=int, default=2,
                    help="measured rounds per point (after 1 warmup)")
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results", "fleet_bench.jsonl"))
    ap.add_argument("--check-schema", action="store_true",
                    help="after the sweep, validate the output JSONL "
                         "against the per-bench schemas and fail on any "
                         "mismatch")
    ap.add_argument("--mask-sweep", action="store_true",
                    help="append fleet_mask_cost rows: the analytic "
                         "secure-agg masked-uplink cost per device at "
                         "--mask-devices, swept over --mask-neighbors "
                         "(privacy/dropout.mask_cost)")
    ap.add_argument("--mask-devices", type=int, default=1_000_000,
                    help="cohort size for the mask-cost sweep")
    ap.add_argument("--mask-neighbors", default="0,2,4,8,16",
                    help="comma-separated neighbor counts k to sweep "
                         "(0 = complete graph WITHIN the group, the row "
                         "that pins the grouped-vs-flat quadratic ratio)")
    ap.add_argument("--mask-group-size", type=int, default=1024,
                    help="group-local masking group size (0 = flat "
                         "all-cohort graph)")
    ap.add_argument("--uplink-sweep", action="store_true",
                    help="append fleet_uplink_bytes rows: analytic "
                         "per-scheme uplink frame bytes at "
                         "--uplink-devices (shape-only wire pricing, "
                         "no fleet materialized)")
    ap.add_argument("--uplink-devices", type=int, default=1_000_000,
                    help="reporting-device count for the uplink sweep")
    ap.add_argument("--uplink-schemes", default="none,int8,topk",
                    help="comma-separated fed.compress schemes to sweep")
    ap.add_argument("--uplink-topk-fraction", type=float, default=0.05,
                    help="topk density for the uplink sweep "
                         "(FedConfig.topk_fraction default)")
    ap.add_argument("--ingest-sweep", action="store_true",
                    help="append fleet_ingest_scaling rows: root ingest "
                         "bytes + fold critical path at --ingest-devices "
                         "swept over --ingest-aggregators (analytic wire "
                         "pricing x measured StreamingFolder cost)")
    ap.add_argument("--ingest-devices", type=int, default=1_000_000,
                    help="cohort size for the ingest-scaling sweep")
    ap.add_argument("--ingest-aggregators", default="1,2,4",
                    help="comma-separated aggregator counts N to sweep")
    ap.add_argument("--async-sweep", action="store_true",
                    help="append fleet_async rows (analytic buffered-"
                         "async vs sync throughput over --async-devices, "
                         "fixed-point waste estimate, no fleet "
                         "materialized) plus ONE measured "
                         "fleet_async_prune gate row (fit_async pruned "
                         "vs unpruned on the same seeded 64-device "
                         "fleet)")
    ap.add_argument("--async-devices", default="1000,10000,100000,1000000",
                    help="comma-separated fleet sizes for the async "
                         "throughput sweep")
    ap.add_argument("--tree-async-sweep", action="store_true",
                    help="append fleet_tree_async rows over "
                         "--tree-async-devices: buffered-async through "
                         "per-slice aggregator buffers — small fleets "
                         "MEASURED (fleetsim two-tier fit_async), large "
                         "fleets analytic (same arrival/service model); "
                         "fold_tracking_min is the sentinel column")
    ap.add_argument("--tree-async-devices",
                    default="1000,10000,100000,1000000",
                    help="comma-separated fleet sizes for the tree-"
                         "async sweep (<= 2000 devices run measured)")
    ap.add_argument("--drift-sweep", action="store_true",
                    help="append ONE measured fleet_learn_drift row: "
                         "conv_cohort_skew on the same seeded fleet with "
                         "non-IID (label_skew 0.9) vs IID (0.0) "
                         "populations under --learn-observe")
    ap.add_argument("--append", action="store_true",
                    help="append rows to --out instead of rewriting it "
                         "(e.g. --cohorts '' --mask-sweep --append adds "
                         "mask-cost rows next to a committed round sweep)")
    args = ap.parse_args(argv)

    rows = []
    for cohort in (int(c) for c in args.cohorts.split(",") if c):
        row = run_point(cohort, args.rounds, args.chunk, args.seed)
        rows.append(row)
        print(json.dumps(row))
    if args.mask_sweep:
        param_count = bench_param_count(args.seed)
        for k in (int(x) for x in args.mask_neighbors.split(",") if x):
            row = mask_point(args.mask_devices, k, args.mask_group_size,
                             param_count)
            rows.append(row)
            print(json.dumps(row))
    if args.uplink_sweep:
        params = bench_params(args.seed)
        for scheme in (s for s in args.uplink_schemes.split(",") if s):
            row = uplink_point(args.uplink_devices, scheme,
                               args.uplink_topk_fraction, params)
            rows.append(row)
            print(json.dumps(row))
    if args.ingest_sweep:
        params = bench_params(args.seed)
        fold_s = measured_fold_s_per_update(params)
        for n in (int(x) for x in args.ingest_aggregators.split(",") if x):
            row = ingest_point(args.ingest_devices, n, params, fold_s)
            rows.append(row)
            print(json.dumps(row))

    if args.async_sweep:
        for n in (int(x) for x in args.async_devices.split(",") if x):
            row = async_point(n, seed=args.seed)
            rows.append(row)
            print(json.dumps(row))
        row = async_prune_point(seed=args.seed)
        rows.append(row)
        print(json.dumps(row))
        row = async_autok_point(seed=args.seed)
        rows.append(row)
        print(json.dumps(row))

    if args.tree_async_sweep:
        import math

        for n in (int(x) for x in args.tree_async_devices.split(",") if x):
            # Fan-in grows with scale: 2 aggregators at 1k doubling per
            # decade to 16 at 1M (the ingest sweep's sizing).
            aggs = int(min(16, max(2, 2 ** (int(math.log10(max(n, 10)))
                                            - 2))))
            if n <= 2000:
                row = tree_async_measured_point(
                    devices=n, aggregators=aggs, seed=args.seed)
            else:
                row = tree_async_analytic_point(n, aggs, seed=args.seed)
            rows.append(row)
            print(json.dumps(row))

    if args.drift_sweep:
        row = drift_point(seed=args.seed)
        rows.append(row)
        print(json.dumps(row))

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "a" if args.append else "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(f"wrote {len(rows)} rows to {args.out}")
    if args.check_schema:
        return check_schema(args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
