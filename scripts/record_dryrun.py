"""Run ``__graft_entry__.dryrun_multichip(N)`` in a subprocess and commit
the outcome as a results/ artifact (VERDICT r4 next-round #4a: "dryrun
green at n_devices=32 — and record it").

Usage: python scripts/record_dryrun.py [N ...]   (default: 8 32)

Writes results/dryrun_multichip.json: one record per N with ok/rc/wall
seconds.  Subprocess per N because the virtual device count is fixed at
backend init.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "results", "dryrun_multichip.json")


def run_one(n: int) -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    t0 = time.perf_counter()
    r = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n}); print('OK')"],
        capture_output=True, text=True, timeout=1800, env=env, cwd=REPO,
    )
    wall = time.perf_counter() - t0
    ok = r.returncode == 0 and "OK" in r.stdout
    rec = {"n_devices": n, "ok": ok, "rc": r.returncode,
           "wall_seconds": round(wall, 1),
           "meshes": "1-D clients + 3-D (clients, seq, model) MoE-BERT"
                     if n % 4 == 0 else "1-D clients (+2-D if even)"}
    if not ok:
        rec["tail"] = (r.stdout + r.stderr)[-1000:]
    print(json.dumps(rec))
    return rec


def main() -> None:
    ns = [int(a) for a in sys.argv[1:]] or [8, 32]
    records = []
    for n in ns:
        records.append(run_one(n))
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    payload = {"recorded_unix": int(time.time()),
               "platform": "cpu (virtual devices; "
                           "xla_force_host_platform_device_count)",
               "runs": records}
    with open(OUT, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(f"wrote {OUT}")
    if not all(r["ok"] for r in records):
        sys.exit(1)


if __name__ == "__main__":
    main()
