"""Run scaled variants of the five BASELINE.json configs end-to-end and
record acc@round curves (results/<name>.jsonl + stdout summary).

BASELINE.md asks for "CIFAR-10 acc@round" evidence on every benchmark
config family.  Full-scale runs (BERT-base, ViT-B/16, 3400 clients, 100
rounds) don't fit a single v5e chip's time budget, so each variant keeps
the STRATEGY, MODEL FAMILY, PARTITION and round structure of its config and
scales width/depth/clients/rounds down; the point is end-to-end learning
curves through the real engine, not leaderboard numbers.  Data is the
registry's synthetic stand-in (class-prototype structure, genuinely
learnable; data/synthetic.py) unless real corpora are on disk.

    python scripts/run_baseline_configs.py [--out results] [--only NAME]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
    # Honor a CPU request even on hosts whose sitecustomize pins an
    # accelerator platform (env alone doesn't override it, and a dead
    # remote-TPU tunnel HANGS inside jax.devices()).
    import jax

    jax.config.update("jax_platforms", "cpu")

# Persistent host-keyed compile cache: a full-size BERT round program
# costs ~15 min of XLA:CPU compile — pay it once per HOST, not per run.
from colearn_federated_learning_tpu.utils.compile_cache import (  # noqa: E402
    enable_host_keyed_cache,
)

enable_host_keyed_cache(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _vit_tiny7(model_cfg):
    """The ONE definition of the tiny/7 stand-in for ViT-B/16 (both the
    340-client curve and the spec-N bookkeeping variant scale with it)."""
    return dataclasses.replace(model_cfg, width=192, depth=4, num_heads=3,
                               patch_size=7)


def scaled_variants():
    """name -> (scaled ExperimentConfig, note)."""
    from colearn_federated_learning_tpu.utils.config import get_config

    out = {}

    c = get_config("mnist_mlp_fedavg")
    c = c.replace(
        data=dataclasses.replace(c.data, max_examples_per_client=512),
        fed=dataclasses.replace(c.fed, rounds=20),
    )
    out["mnist_mlp_fedavg"] = (c, "full config; 512 examples/client cap")

    c = get_config("cifar10_cnn_fedavg")
    c = c.replace(
        data=dataclasses.replace(c.data, max_examples_per_client=256),
        fed=dataclasses.replace(c.fed, rounds=50),
    )
    out["cifar10_cnn_fedavg"] = (c, "full model; 50 rounds, 256 ex/client")

    c = get_config("cifar100_resnet18_fedprox")
    c = c.replace(
        data=dataclasses.replace(c.data, max_examples_per_client=128),
        fed=dataclasses.replace(c.fed, rounds=30),
    )
    out["cifar100_resnet18_fedprox"] = (c, "full ResNet-18; 30 rounds")

    c = get_config("agnews_bert_fedavg")
    c = c.replace(
        model=dataclasses.replace(c.model, width=256, depth=4, num_heads=8),
        data=dataclasses.replace(c.data, max_examples_per_client=256),
        fed=dataclasses.replace(c.fed, rounds=20, lr=1e-4),
    )
    out["agnews_bert_fedavg"] = (
        c, "BERT scaled 768x12 -> 256x4 (single-chip budget); adam 1e-4 "
           "+ the config's warmup_cosine schedule (round 4)")

    # Not a BASELINE config — the MoE family is a rebuild superset; its
    # curve documents that the expert-parallel path LEARNS, not just runs.
    c = get_config("agnews_bert_fedavg")
    c = c.replace(
        model=dataclasses.replace(c.model, name="moe_bert", width=256,
                                  depth=4, num_heads=8, num_experts=4),
        data=dataclasses.replace(c.data, max_examples_per_client=256),
        fed=dataclasses.replace(c.fed, rounds=20, lr=1e-4),
    )
    c = c.replace(run=dataclasses.replace(c.run, name="agnews_moebert"))
    out["agnews_moebert_fedavg"] = (
        c, "MoE superset: 4 experts every other block, top-2 routing")

    # Thematic parity config: the reference's actual IoT anomaly task.
    c = get_config("iot_traffic_tcn_fedavg")
    c = c.replace(
        data=dataclasses.replace(c.data, dataset="iot_traffic",
                                 max_examples_per_client=128),
        fed=dataclasses.replace(c.fed, rounds=25),
    )
    out["iot_traffic_tcn_fedavg"] = (
        c, "full TCN; 25 rounds, 128 ex/client")

    c = get_config("femnist_vit_cross_silo")
    c = c.replace(
        model=_vit_tiny7(c.model),
        data=dataclasses.replace(c.data, num_clients=340,
                                 max_examples_per_client=64),
        fed=dataclasses.replace(c.fed, rounds=20, cohort_size=32),
    )
    out["femnist_vit_cross_silo"] = (
        c, "ViT scaled B/16 -> tiny/7, 3400 -> 340 clients, cohort 32")

    # ---- FULL-SIZE variants (VERDICT r4 #2/#3): the configs at their
    # BASELINE-stated scale, for accelerator sessions.  These are the
    # "no asterisk" runs — model dims and client counts exactly as
    # specified; only examples/client and the round budget are capped
    # (the spec fixes neither).
    c = get_config("agnews_bert_fedavg")          # BERT-base 768x12
    c = c.replace(
        data=dataclasses.replace(c.data, max_examples_per_client=256),
    )
    out["agnews_bert_full"] = (
        c, "FULL BERT-base 768x12x12h seq128, 50 clients, cohort 10 "
           "(config #4 at stated size)")

    c = get_config("femnist_vit_cross_silo")      # ViT-B/16, 3400 clients
    c = c.replace(
        data=dataclasses.replace(c.data, max_examples_per_client=64),
        fed=dataclasses.replace(c.fed, rounds=20),
    )
    out["femnist_vit_full3400"] = (
        c, "FULL ViT-B/16 768x12, ALL 3400 resident clients, cohort 256 "
           "(config #5 at stated N; 64 ex/client cap)")

    # Spec-N bookkeeping proof that also fits a CPU session: all 3,400
    # resident clients and the cohort-256 round structure with the model
    # scaled down — what it demonstrates is sampling / shard packing /
    # per-client state at config #5's stated N, not model quality.
    c = get_config("femnist_vit_cross_silo")
    c = c.replace(
        model=_vit_tiny7(c.model),
        data=dataclasses.replace(c.data, max_examples_per_client=64),
        fed=dataclasses.replace(c.fed, rounds=10),
    )
    out["femnist_vit3400_scaled"] = (
        c, "ALL 3400 resident clients, cohort 256 (spec N); ViT scaled "
           "B/16 -> tiny/7 so the run fits any session")
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="results")
    p.add_argument("--only", default=None)
    p.add_argument("--rounds", type=int, default=None,
                   help="override every selected config's round count "
                        "(e.g. run the text configs to plateau)")
    p.add_argument("--max-examples", type=int, default=None,
                   help="override examples/client (scales local steps per "
                        "round: epochs * ceil(examples/batch)) - lets a "
                        "slow session trade steps-per-round for rounds")
    p.add_argument("--lr", type=float, default=None,
                   help="override the client peak lr (recipes tuned on "
                        "scaled stand-ins don't always transfer: 5e-5 "
                        "diverges on the FULL 768x12 BERT in bf16)")
    args = p.parse_args()

    import jax

    from colearn_federated_learning_tpu.fed.engine import FederatedLearner

    os.makedirs(args.out, exist_ok=True)
    dev = jax.devices()[0]
    summary = []
    for name, (cfg, note) in scaled_variants().items():
        if args.only and name != args.only:
            continue
        if args.rounds:
            cfg = cfg.replace(
                fed=dataclasses.replace(cfg.fed, rounds=args.rounds))
        if args.max_examples is not None:
            # NOT truthiness: 0 is the documented "derive from dataset
            # size" value and must round-trip.
            cfg = cfg.replace(
                data=dataclasses.replace(cfg.data,
                                         max_examples_per_client=args.max_examples))
        if args.lr is not None:
            cfg = cfg.replace(fed=dataclasses.replace(cfg.fed, lr=args.lr))
        print(f"[{name}] {note}", file=sys.stderr)
        t0 = time.perf_counter()
        learner = FederatedLearner.from_config(cfg)
        path = os.path.join(args.out, f"{name}.jsonl")
        with open(path, "w") as f:
            meta = {"config": name, "note": note,
                    "device": dev.device_kind, "platform": dev.platform,
                    "num_clients": learner.num_clients,
                    "cohort": learner.cohort_size,
                    "local_steps": learner.num_steps,
                    "rounds": cfg.fed.rounds}
            f.write(json.dumps(meta) + "\n")

            def log(rec):
                f.write(json.dumps(rec) + "\n")
                f.flush()
                if "eval_acc" in rec:
                    print(f"[{name}] round {rec['round']:3d} "
                          f"loss {rec['train_loss']:.4f} "
                          f"acc {rec['eval_acc']:.4f}", file=sys.stderr)

            hist = learner.fit(log_fn=log)
        wall = time.perf_counter() - t0
        accs = [r.get("eval_acc") for r in hist if "eval_acc" in r]
        summary.append({
            "config": name,
            "rounds": len(hist),
            "final_acc": round(accs[-1], 4) if accs else None,
            "best_acc": round(max(accs), 4) if accs else None,
            "first_acc": round(accs[0], 4) if accs else None,
            "wall_s": round(wall, 1),
            "curve": path,
        })
        print(json.dumps(summary[-1]), file=sys.stderr)
    print(json.dumps(summary, indent=2))


if __name__ == "__main__":
    main()
