"""Driver entry: headline benchmark (see colearn_federated_learning_tpu/bench.py).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from colearn_federated_learning_tpu.bench import main

if __name__ == "__main__":
    main()
