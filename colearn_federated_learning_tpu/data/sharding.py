"""Packing ragged per-client shards into static-shape stacked arrays.

Hard part #1 from SURVEY.md §7: clients own different numbers of examples,
but jit needs static shapes.  We pad every client's shard to a common
capacity ``M`` and carry a true-count vector; local training samples batch
indices modulo the true count so padding rows are never trained on, and the
FedAvg weight of a client is its true count, so padding never biases the
average either.

The leading axis of every leaf is the CLIENT axis — the axis that `vmap`
maps over on one chip and that `shard_map` shards over the device mesh.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class ClientShards:
    """Stacked, padded per-client data: leaves shaped (num_clients, M, ...)."""

    x: np.ndarray        # (C, M, *example_shape)
    y: np.ndarray        # (C, M) int32
    counts: np.ndarray   # (C,) int32 — true examples per client

    @property
    def num_clients(self) -> int:
        return self.x.shape[0]

    @property
    def capacity(self) -> int:
        return self.x.shape[1]


def pack_client_shards(
    x: np.ndarray,
    y: np.ndarray,
    parts: list[np.ndarray],
    capacity: int = 0,
) -> ClientShards:
    """Stack per-client index lists into padded (C, M, ...) arrays.

    ``capacity`` defaults to the largest shard.  Padding rows repeat the
    client's own data (cyclic tiling) rather than zeros, so even an
    out-of-range gather during debugging yields valid examples; correctness
    does not depend on it because sampling is always taken modulo
    ``counts``.
    """
    sizes = [len(p) for p in parts]
    if min(sizes) == 0:
        raise ValueError("pack_client_shards: a client has zero examples")
    cap = capacity or max(sizes)
    C = len(parts)
    # One fused (C*cap,) index vector, then a single row gather — the bulk
    # memcpy runs thread-parallel in the native library when available
    # (native/src/gather.cpp; the 3400-client config moves GBs here).
    tiled_all = np.empty((C, cap), dtype=np.int64)
    counts = np.zeros((C,), dtype=np.int32)
    for c, idx in enumerate(parts):
        take = np.asarray(idx[:cap])
        reps = int(np.ceil(cap / len(take)))
        tiled_all[c] = np.tile(take, reps)[:cap]
        counts[c] = min(len(idx), cap)
    from colearn_federated_learning_tpu import native

    flat = tiled_all.reshape(-1)
    xs = native.gather_rows(np.ascontiguousarray(x), flat)
    xs = xs.reshape((C, cap) + x.shape[1:])
    ys = np.asarray(y, np.int32)[tiled_all]
    return ClientShards(x=xs, y=ys, counts=counts)


def pad_clients_to_multiple(shards: ClientShards, multiple: int) -> ClientShards:
    """Pad the client axis so it divides the device mesh evenly.

    Ghost clients get count 0, which zeroes their FedAvg weight — they train
    on garbage (copies of client 0's rows) but contribute nothing.
    """
    C = shards.num_clients
    rem = (-C) % multiple
    if rem == 0:
        return shards
    pad_x = np.repeat(shards.x[:1], rem, axis=0)
    pad_y = np.repeat(shards.y[:1], rem, axis=0)
    return ClientShards(
        x=np.concatenate([shards.x, pad_x], axis=0),
        y=np.concatenate([shards.y, pad_y], axis=0),
        counts=np.concatenate([shards.counts, np.zeros(rem, np.int32)]),
    )
