"""Data layer: dataset registry, non-IID partitioning, client shard packing."""

from colearn_federated_learning_tpu.data.partition import (  # noqa: F401
    dirichlet_partition,
    iid_partition,
    partition_counts,
)
from colearn_federated_learning_tpu.data.registry import get_dataset  # noqa: F401
from colearn_federated_learning_tpu.data.sharding import (  # noqa: F401
    ClientShards,
    pack_client_shards,
)
