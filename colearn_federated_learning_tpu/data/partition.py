"""Per-client dataset partitioning (IID and Dirichlet non-IID).

The reference assumes each IoT device already owns its local shard and only
negotiates dataset identity over MQTT (SURVEY.md §2 "Data loaders:
per-client (non-IID) partitioning").  In simulation we materialize the
partition: IID round-robin, or the standard Dirichlet(α) label-skew scheme
used by BASELINE config #2 ("100 non-IID clients (Dirichlet α=0.5)").

Partitioning is host-side preprocessing (runs once, feeds static-shape
device arrays), so it uses numpy, not jit.
"""

from __future__ import annotations

import numpy as np


def iid_partition(n_examples: int, num_clients: int, seed: int = 0) -> list[np.ndarray]:
    """Shuffle and deal examples round-robin; sizes differ by at most 1."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n_examples)
    return [np.sort(perm[c::num_clients]) for c in range(num_clients)]


def dirichlet_partition(
    labels: np.ndarray,
    num_clients: int,
    alpha: float,
    seed: int = 0,
    min_per_client: int = 1,
) -> list[np.ndarray]:
    """Label-skewed split: for each class, proportions ~ Dirichlet(α).

    Small α → each client sees few classes (highly non-IID); large α → IID.
    Re-draws until every client holds at least ``min_per_client`` examples so
    downstream static-shape packing never sees an empty shard.
    """
    labels = np.asarray(labels)
    n_classes = int(labels.max()) + 1
    rng = np.random.default_rng(seed)

    for _attempt in range(100):
        shards: list[list[np.ndarray]] = [[] for _ in range(num_clients)]
        for c in range(n_classes):
            idx = np.flatnonzero(labels == c)
            rng.shuffle(idx)
            props = rng.dirichlet(np.full(num_clients, alpha))
            cuts = (np.cumsum(props) * len(idx)).astype(int)[:-1]
            for client, part in enumerate(np.split(idx, cuts)):
                shards[client].append(part)
        out = [np.sort(np.concatenate(s)) if s else np.empty(0, np.int64) for s in shards]
        if min(len(s) for s in out) >= min_per_client:
            return out
    raise RuntimeError(
        f"dirichlet_partition: could not give every one of {num_clients} clients "
        f">= {min_per_client} examples (alpha={alpha}, n={len(labels)})"
    )


def pathological_partition(
    labels: np.ndarray,
    num_clients: int,
    shards_per_client: int = 2,
    seed: int = 0,
) -> list[np.ndarray]:
    """McMahan et al. (2017) §3 "pathological non-IID" split: sort the
    examples by label, cut them into ``num_clients * shards_per_client``
    equal contiguous shards, and deal each client ``shards_per_client``
    shards at random — so most clients see only ``shards_per_client``
    distinct digits.  This is the partition behind the paper's Table 1
    non-IID rows, which scripts/validate_literature.py reproduces as the
    framework's literature anchor (SURVEY.md hard-part #5).

    A stable mergesort keeps equal-label runs in index order, so the split
    is deterministic given (labels, seed).
    """
    labels = np.asarray(labels)
    n = len(labels)
    n_shards = num_clients * shards_per_client
    if n_shards > n:
        raise ValueError(
            f"need >= {n_shards} examples for {num_clients} clients x "
            f"{shards_per_client} shards, have {n}"
        )
    order = np.argsort(labels, kind="stable")
    shard_ids = np.random.default_rng(seed).permutation(n_shards)
    bounds = np.linspace(0, n, n_shards + 1).astype(int)
    return [
        np.sort(np.concatenate([
            order[bounds[s]:bounds[s + 1]]
            for s in shard_ids[c * shards_per_client:(c + 1) * shards_per_client]
        ]))
        for c in range(num_clients)
    ]


def partition_counts(parts: list[np.ndarray]) -> np.ndarray:
    return np.array([len(p) for p in parts], dtype=np.int32)


def label_distribution(labels: np.ndarray, parts: list[np.ndarray], n_classes: int) -> np.ndarray:
    """(num_clients, n_classes) histogram — used by tests to assert skew."""
    out = np.zeros((len(parts), n_classes), dtype=np.int64)
    for i, p in enumerate(parts):
        binc = np.bincount(labels[p], minlength=n_classes)
        out[i] = binc[:n_classes]
    return out
