"""Deterministic synthetic datasets with the shapes of the benchmark corpora.

This sandbox has no network egress and no dataset files on disk, so the
registry falls back to class-conditional synthetic data whose shapes/dtypes
match MNIST / CIFAR-10 / CIFAR-100 / AG-News / FEMNIST.  The generator is a
fixed random class-prototype plus noise, which makes the tasks genuinely
learnable — accuracy curves rise across federated rounds, exercising the
same code paths a real corpus would (the reference validated by watching
accuracy curves, SURVEY.md §4).

Generation is numpy on host: it runs once at startup and produces the
static-shape arrays the jit path consumes.
"""

from __future__ import annotations

import numpy as np


def synthetic_image_classification(
    n: int,
    image_shape: tuple[int, int, int],
    n_classes: int,
    seed: int = 0,
    noise: float = 0.35,
    proto_seed: int = 1234,
) -> tuple[np.ndarray, np.ndarray]:
    """Images = smoothed class prototype + Gaussian noise, in [0, 1].

    Prototypes are low-frequency random fields so conv nets (and patching
    ViTs) have spatial structure to exploit, not just a per-pixel bias.
    ``proto_seed`` is SEPARATE from ``seed`` so train and test splits share
    one class structure (generalization is real) while drawing disjoint
    samples.
    """
    rng = np.random.default_rng(seed)
    h, w, c = image_shape
    # Low-res prototype upsampled → low-frequency spatial structure.
    lo = max(2, h // 4), max(2, w // 4)
    proto_rng = np.random.default_rng(proto_seed)
    protos_lo = proto_rng.normal(0.5, 0.5, size=(n_classes, *lo, c))
    protos = np.stack(
        [
            np.kron(p, np.ones((h // lo[0] + 1, w // lo[1] + 1))[..., None])[:h, :w, :]
            for p in protos_lo
        ]
    )
    y = rng.integers(0, n_classes, size=n).astype(np.int32)
    x = protos[y] + rng.normal(0.0, noise, size=(n, h, w, c))
    x = np.clip(x, 0.0, 1.0).astype(np.float32)
    return x, y


def synthetic_text_classification(
    n: int,
    seq_len: int,
    vocab_size: int,
    n_classes: int,
    seed: int = 0,
    signal_tokens: int = 48,
) -> tuple[np.ndarray, np.ndarray]:
    """Token sequences where each class over-samples its own token bucket.

    Shapes match a wordpiece-tokenized AG-News batch: int32 ids of
    (n, seq_len) with id 0 reserved for padding.
    """
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, size=n).astype(np.int32)
    # Class-specific "topic vocabulary" buckets, disjoint, above id 1000.
    base = 1000
    buckets = [
        np.arange(base + k * signal_tokens, base + (k + 1) * signal_tokens)
        for k in range(n_classes)
    ]
    x = rng.integers(1, vocab_size, size=(n, seq_len)).astype(np.int32)
    topic_mask = rng.random((n, seq_len)) < 0.3
    for k in range(n_classes):
        rows = y == k
        topical = rng.choice(buckets[k], size=(int(rows.sum()), seq_len))
        x[rows] = np.where(topic_mask[rows], topical, x[rows])
    # Variable lengths with 0-padding, like real tokenized text.
    lengths = rng.integers(seq_len // 4, seq_len + 1, size=n)
    pad = np.arange(seq_len)[None, :] >= lengths[:, None]
    x[pad] = 0
    return x, y


def synthetic_traffic_classification(
    n: int,
    shape: tuple[int, int],
    n_classes: int,
    seed: int = 0,
    noise: float = 0.4,
    proto_seed: int = 1234,
) -> tuple[np.ndarray, np.ndarray]:
    """IoT network-traffic-like sequences: (T, F) feature windows.

    CoLearn's actual task is network-anomaly detection on IoT traffic
    (SURVEY.md §0); with no corpora on disk this generator produces
    class-conditional TEMPORAL structure a temporal conv net can exploit:
    each class is a smooth per-feature random walk (think rolling
    byte/packet-rate statistics) plus class-specific periodic bursts
    (think beaconing/scan periodicity) — signals that distinguish attack
    families in real flow data.
    """
    t_len, n_feat = shape
    rng = np.random.default_rng(seed)
    proto_rng = np.random.default_rng(proto_seed)
    # Smooth per-class baselines: cumulative sums, normalized.
    base = np.cumsum(
        proto_rng.normal(0.0, 1.0, size=(n_classes, t_len, n_feat)), axis=1
    )
    base /= np.abs(base).max(axis=(1, 2), keepdims=True) + 1e-6
    # Class-periodic bursts on a per-class subset of features.
    t = np.arange(t_len)[None, :, None]
    periods = proto_rng.integers(3, max(4, t_len // 4),
                                 size=(n_classes, 1, 1))
    phase = proto_rng.uniform(0, 2 * np.pi, size=(n_classes, 1, n_feat))
    gates = (proto_rng.uniform(size=(n_classes, 1, n_feat)) < 0.5)
    bursts = np.sin(2 * np.pi * t / periods + phase) * gates
    protos = (base + 0.7 * bursts).astype(np.float32)

    y = rng.integers(0, n_classes, size=n).astype(np.int32)
    x = protos[y] + rng.normal(0.0, noise, size=(n, t_len, n_feat))
    return x.astype(np.float32), y
