"""Dataset registry for the five benchmark corpora (BASELINE.json configs).

Resolution order per dataset name:
1. A real on-disk copy: ``$COLEARN_DATA_DIR/<name>.npz`` with arrays
   ``x_train, y_train, x_test, y_test`` (the standard keras-style layout).
2. Deterministic synthetic data with identical shapes (data/synthetic.py) —
   required because this sandbox has no network and no dataset files.

Either way the caller receives static-shape numpy arrays; everything after
this point is jit-compatible.
"""

from __future__ import annotations

import dataclasses
import os
import zlib

import numpy as np

from colearn_federated_learning_tpu.data import synthetic


@dataclasses.dataclass(frozen=True)
class DatasetSpec:
    name: str
    kind: str                      # "image" | "text" | "timeseries"
    input_shape: tuple[int, ...]   # per-example shape: image HWC,
                                   # text (seq_len,), timeseries (T, F)
    num_classes: int
    n_train: int                   # synthetic fallback sizes
    n_test: int
    vocab_size: int = 0            # text only


SPECS: dict[str, DatasetSpec] = {
    "mnist": DatasetSpec("mnist", "image", (28, 28, 1), 10, 60_000, 10_000),
    "cifar10": DatasetSpec("cifar10", "image", (32, 32, 3), 10, 50_000, 10_000),
    "cifar100": DatasetSpec("cifar100", "image", (32, 32, 3), 100, 50_000, 10_000),
    "femnist": DatasetSpec("femnist", "image", (28, 28, 1), 62, 80_000, 10_000),
    "agnews": DatasetSpec("agnews", "text", (128,), 4, 120_000, 7_600),
    # Tiny variants for tests / smoke runs (same shapes, far fewer rows).
    "mnist_tiny": DatasetSpec("mnist_tiny", "image", (28, 28, 1), 10, 2_000, 400),
    "cifar10_tiny": DatasetSpec("cifar10_tiny", "image", (32, 32, 3), 10, 2_000, 400),
    "agnews_tiny": DatasetSpec("agnews_tiny", "text", (64,), 4, 1_000, 200, vocab_size=2_000),
    # IoT traffic windows (T, F) — the reference's ACTUAL task domain
    # (network-anomaly detection at the edge, SURVEY.md §0); 8 classes =
    # benign + 7 attack families.
    "iot_traffic": DatasetSpec("iot_traffic", "timeseries", (64, 16), 8,
                               40_000, 8_000),
    "iot_traffic_tiny": DatasetSpec("iot_traffic_tiny", "timeseries",
                                    (64, 16), 8, 2_000, 400),
}


@dataclasses.dataclass
class Dataset:
    spec: DatasetSpec
    x_train: np.ndarray
    y_train: np.ndarray
    x_test: np.ndarray
    y_test: np.ndarray
    source: str  # "disk" | "synthetic"


def _load_disk(spec: DatasetSpec) -> Dataset | None:
    """Load ``$COLEARN_DATA_DIR/<name>.npz`` (keras-style arrays written by
    ``scripts/fetch_data.py``).  A present-but-malformed file raises — a
    user who staged real data must never silently train on synthetic."""
    root = os.environ.get("COLEARN_DATA_DIR", "")
    if not root:
        return None
    path = os.path.join(root, f"{spec.name}.npz")
    if not os.path.exists(path):
        return None
    arrays = {}
    with np.load(path) as z:
        missing = [k for k in ("x_train", "y_train", "x_test", "y_test")
                   if k not in z]
        if missing:
            raise ValueError(f"{path} is missing arrays {missing} "
                             "(expected the keras-style x/y train/test "
                             "layout)")
        for split in ("train", "test"):
            x, y = z[f"x_{split}"], z[f"y_{split}"]
            want = spec.input_shape
            # Accept trailing-singleton-channel omission for grayscale
            # images ((N, 28, 28) on disk vs spec (28, 28, 1)).
            if (spec.kind == "image" and x.ndim == len(want)
                    and want[-1] == 1 and x.shape[1:] == want[:-1]):
                x = x[..., None]
            if x.shape[1:] != want:
                raise ValueError(
                    f"{path}: x_{split} per-example shape {x.shape[1:]} "
                    f"does not match the {spec.name} spec {want}")
            if len(x) != len(y):
                raise ValueError(
                    f"{path}: x_{split}/y_{split} row counts differ "
                    f"({len(x)} vs {len(y)})")
            if spec.kind == "image" and x.dtype == np.uint8:
                x = x.astype(np.float32) / 255.0   # keras raw-byte layout
            y = y.reshape(-1)
            # Range-check BEFORE the int32 cast: a corrupt wide integer
            # must not wrap into the valid range and pass.
            if y.size and (int(y.min()) < 0
                           or int(y.max()) >= spec.num_classes):
                raise ValueError(
                    f"{path}: y_{split} labels outside "
                    f"[0, {spec.num_classes})")
            arrays[f"x_{split}"], arrays[f"y_{split}"] = x, y.astype(np.int32)
    return Dataset(spec, arrays["x_train"], arrays["y_train"],
                   arrays["x_test"], arrays["y_test"], "disk")


def _make_synthetic(spec: DatasetSpec, seed: int) -> Dataset:
    # proto_seed shared across splits: one class structure, disjoint draws.
    proto_seed = 7919 * seed + zlib.crc32(spec.name.encode()) % 10_000
    if spec.kind == "timeseries":
        x_tr, y_tr = synthetic.synthetic_traffic_classification(
            spec.n_train, spec.input_shape, spec.num_classes, seed=seed,
            proto_seed=proto_seed,
        )
        x_te, y_te = synthetic.synthetic_traffic_classification(
            spec.n_test, spec.input_shape, spec.num_classes, seed=seed + 1,
            proto_seed=proto_seed,
        )
        return Dataset(spec, x_tr, y_tr, x_te, y_te, "synthetic")
    if spec.kind == "image":
        x_tr, y_tr = synthetic.synthetic_image_classification(
            spec.n_train, spec.input_shape, spec.num_classes, seed=seed,
            proto_seed=proto_seed,
        )
        x_te, y_te = synthetic.synthetic_image_classification(
            spec.n_test, spec.input_shape, spec.num_classes, seed=seed + 1,
            proto_seed=proto_seed,
        )
    else:
        vocab = spec.vocab_size or 30_522
        x_tr, y_tr = synthetic.synthetic_text_classification(
            spec.n_train, spec.input_shape[0], vocab, spec.num_classes, seed=seed
        )
        x_te, y_te = synthetic.synthetic_text_classification(
            spec.n_test, spec.input_shape[0], vocab, spec.num_classes, seed=seed + 1
        )
    return Dataset(spec, x_tr, y_tr, x_te, y_te, "synthetic")


def get_dataset(name: str, seed: int = 0, max_train: int = 0, max_test: int = 0) -> Dataset:
    if name not in SPECS:
        raise KeyError(f"unknown dataset {name!r}; available: {sorted(SPECS)}")
    spec = SPECS[name]
    ds = _load_disk(spec) or _make_synthetic(spec, seed)
    if max_train and len(ds.x_train) > max_train:
        ds = dataclasses.replace(ds, x_train=ds.x_train[:max_train], y_train=ds.y_train[:max_train])
    if max_test and len(ds.x_test) > max_test:
        ds = dataclasses.replace(ds, x_test=ds.x_test[:max_test], y_test=ds.y_test[:max_test])
    return ds
