"""Find jit-traced regions in a module, without importing it.

A "traced region" is a function body that jax will TRACE rather than
run: Python side effects inside one silently execute once at trace time
and never again (CL001), and host syncs inside one either error out or
force a device round-trip per call (CL006).

Detection is per-file and name-based (no cross-module resolution — a
linter that imported jax to resolve objects would drag device init into
a gate that must stay CPU-only and fast):

- decorators: ``@jax.jit``, ``@jit``, ``@jax.pmap``, ``@pmap``,
  ``@partial(jax.jit, ...)`` / ``@functools.partial(jax.jit, ...)``;
- call sites: ``jax.jit(f)``, ``jit(f)``, ``pmap(f)``,
  ``shard_map(f, ...)`` (both ``jax.shard_map`` and the
  ``utils/jax_compat`` shim import the same name) — where ``f`` is a
  lambda or a Name that resolves to a function defined in this file;
- nesting: everything lexically inside a traced function is traced.
"""

from __future__ import annotations

import ast
from typing import Iterator

TRACER_NAMES = {"jit", "pmap", "shard_map"}


def _call_traces(func: ast.expr) -> bool:
    """Does this call expression's callee name a tracing transform?"""
    if isinstance(func, ast.Name):
        return func.id in TRACER_NAMES
    if isinstance(func, ast.Attribute):
        # jax.jit / jax.pmap / jax_compat.shard_map / jax.experimental...
        return func.attr in TRACER_NAMES
    return False


def _decorator_traces(dec: ast.expr) -> bool:
    if isinstance(dec, (ast.Name, ast.Attribute)):
        return _call_traces(dec)
    if isinstance(dec, ast.Call):
        if _call_traces(dec.func):                 # @jax.jit(static_...)
            return True
        # @partial(jax.jit, ...) / @functools.partial(jax.jit, ...)
        callee = dec.func
        is_partial = (
            (isinstance(callee, ast.Name) and callee.id == "partial")
            or (isinstance(callee, ast.Attribute)
                and callee.attr == "partial")
        )
        if is_partial and dec.args:
            return _call_traces(dec.args[0])
    return False


def _function_defs_by_name(tree: ast.AST) -> dict:
    """Every def in the file, keyed by name (all scopes flattened — good
    enough for single-file heuristics; a false merge only widens the
    scanned region)."""
    out: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


def traced_regions(tree: ast.AST) -> list:
    """The function/lambda nodes whose bodies jax traces in this file."""
    defs = _function_defs_by_name(tree)
    regions: list = []
    seen: set = set()

    def add(node: ast.AST) -> None:
        if id(node) not in seen:
            seen.add(id(node))
            regions.append(node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_traces(d) for d in node.decorator_list):
                add(node)
        elif isinstance(node, ast.Call) and _call_traces(node.func):
            if not node.args:
                continue
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                add(target)
            elif isinstance(target, ast.Name):
                for fn in defs.get(target.id, ()):
                    add(fn)
            elif isinstance(target, ast.Call) and _call_traces(target.func):
                # jax.jit(shard_map(inner, ...)) — handled when the inner
                # call is visited by the walk; nothing extra here.
                pass
    return regions


def walk_region(region: ast.AST) -> Iterator[ast.AST]:
    """Every node lexically inside a traced function body (decorators and
    default expressions run eagerly at def time, so they are skipped)."""
    if isinstance(region, ast.Lambda):
        yield from ast.walk(region.body)
        return
    for stmt in region.body:
        yield from ast.walk(stmt)


def dotted_name(node: ast.expr) -> str:
    """``a.b.c`` for Name/Attribute chains, "" otherwise."""
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""
