"""Render a LintResult for humans (terminal) or machines (JSON)."""

from __future__ import annotations

import json

from colearn_federated_learning_tpu.analysis.engine import LintResult


def render_text(result: LintResult) -> str:
    lines = [f.render() for f in result.findings]
    counts = ", ".join(
        f"{rule}={n}" for rule, n in
        sorted(result.to_dict()["counts"].items()))
    tail = (f"{len(result.findings)} finding(s)"
            + (f" [{counts}]" if counts else "")
            + f" in {result.files} file(s)"
            + f"; {result.suppressed} suppressed"
            + f", {result.baselined} baselined")
    if not result.findings:
        return f"colearn lint: clean — {tail}"
    return "\n".join(lines) + f"\n\ncolearn lint: {tail}"


def render_json(result: LintResult) -> str:
    return json.dumps(result.to_dict(), indent=2, sort_keys=True)
