"""Render a LintResult for humans (terminal) or machines (JSON/SARIF)."""

from __future__ import annotations

import json

from colearn_federated_learning_tpu.analysis.engine import (
    DEAD_SUPPRESSION_RULE,
    PARSE_ERROR_RULE,
    UNREASONED_SUPPRESSION_RULE,
    LintResult,
    registered_rules,
)


def render_text(result: LintResult) -> str:
    lines = [f.render() for f in result.findings]
    counts = ", ".join(
        f"{rule}={n}" for rule, n in
        sorted(result.to_dict()["counts"].items()))
    tail = (f"{len(result.findings)} finding(s)"
            + (f" [{counts}]" if counts else "")
            + f" in {result.files} file(s)"
            + f"; {result.suppressed} suppressed"
            + f", {result.baselined} baselined")
    if not result.findings:
        return f"colearn lint: clean — {tail}"
    return "\n".join(lines) + f"\n\ncolearn lint: {tail}"


def render_json(result: LintResult) -> str:
    return json.dumps(result.to_dict(), indent=2, sort_keys=True)


# Engine-level pseudo-rules have no Rule class in the registry; SARIF
# still needs a rules-table entry for every result.ruleId it emits.
_PSEUDO_RULE_TITLES = {
    DEAD_SUPPRESSION_RULE: "dead suppression (noqa with nothing to silence)",
    UNREASONED_SUPPRESSION_RULE: "suppression without a reason string",
    PARSE_ERROR_RULE: "file does not parse",
}


def render_sarif(result: LintResult) -> str:
    """SARIF 2.1.0 — one run, one result per finding, a rules table
    covering every emitted ruleId (code-scanning UIs key on it)."""
    titles = {rid: cls.title for rid, cls in registered_rules().items()}
    titles.update(_PSEUDO_RULE_TITLES)
    used = sorted({f.rule for f in result.findings})
    rules = [{
        "id": rid,
        "shortDescription": {"text": titles.get(rid, rid)},
    } for rid in used]
    results = [{
        "ruleId": f.rule,
        "ruleIndex": used.index(f.rule),
        "level": "error",
        "message": {"text": (f.message + (f"  hint: {f.hint}"
                                          if f.hint else ""))},
        "partialFingerprints": {"colearnFingerprint/v1": f.fingerprint()},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": f.line,
                           "startColumn": f.col + 1,
                           "snippet": {"text": f.line_text}},
            },
        }],
    } for f in result.findings]
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "colearn-lint",
                "informationUri":
                    "https://github.com/colearn-tpu/colearn-tpu",
                "rules": rules,
            }},
            "results": results,
            "properties": {
                "files": result.files,
                "suppressed": result.suppressed,
                "baselined": result.baselined,
            },
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True)
