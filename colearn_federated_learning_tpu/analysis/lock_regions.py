"""Lock-region indexing shared by the concurrency rules (CL017–CL021).

Modeled on jit_regions: pure AST, single file, no imports of the linted
code.  For every class in a file this builds a :class:`ClassLockIndex`
that answers the questions the concurrency rules ask:

- which ``self._*`` attributes are locks / condition variables
  (``threading.Lock/RLock/Condition`` or the ``faults.lockwitness``
  factories assigned in ``__init__``, plus any lock-ish name used as
  ``with self._x:``);
- which locks are lexically held at any AST node (``with self._lock:``
  nesting; the held set RESETS inside nested ``def``/``lambda`` bodies
  because those run later, on whichever thread calls them);
- the acquire-while-holding edge set (for the lock-order graph);
- every read/write of a ``self._*`` attribute with the held set at the
  access site (for GuardedBy inference);
- which methods are thread entry points (passed bare — not called — as
  a call argument: ``threading.Thread(target=self._loop)``,
  ``threading.Timer(1, self._tick)``, ``pool.submit(self._work)``,
  server-callback ctors) and which methods those entries reach through
  ``self.m()`` calls.

Two annotation forms extend the inference where the AST cannot see:

- ``# colearn: holds(_lock[, _other])`` on a ``def`` line declares a
  caller-holds contract — the whole function body is treated as holding
  those locks (the caller-side ``with`` is the acquire site).
- ``# colearn: guarded-by(_lock)`` on a ``self._attr = ...`` assignment
  pins the attribute's guard explicitly instead of relying on counting.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

_HOLDS_RE = re.compile(
    r"#\s*colearn:\s*holds\(\s*(?P<locks>[A-Za-z_]\w*"
    r"(?:\s*,\s*[A-Za-z_]\w*)*)\s*\)"
)
_GUARDED_RE = re.compile(
    r"#\s*colearn:\s*guarded-by\(\s*(?P<lock>[A-Za-z_]\w*)\s*\)"
)

# threading.X ctor tails that create a lock-like primitive, and the
# faults.lockwitness factory names that stand in for them.
_LOCK_TAILS = {"Lock", "RLock"}
_CV_TAILS = {"Condition"}
_WITNESS_LOCK_TAILS = {"lock", "rlock"}
_WITNESS_CV_TAILS = {"condition"}
# fallback: `with self._x:` on a name that looks like a lock
_LOCKISH_NAME = re.compile(r"lock|mutex|_cv$|_cond", re.IGNORECASE)

# collection initializers recognized for CL021 ("guarded" is the
# faults.lockwitness stamp around a literal)
_COLLECTION_CTORS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                     "deque", "Counter", "guarded"}
# method tails that mutate a collection in place (count as writes)
MUTATOR_TAILS = {"append", "appendleft", "add", "pop", "popleft", "popitem",
                 "clear", "update", "discard", "remove", "setdefault",
                 "extend", "insert"}


def self_attr(node: ast.AST) -> Optional[str]:
    """``'_x'`` when ``node`` is the attribute access ``self._x``."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@dataclasses.dataclass
class Access:
    """One read or write of ``self.<attr>`` inside a method body."""

    attr: str
    node: ast.AST
    kind: str                 # "read" | "write"
    held: FrozenSet[str]
    method: str


class ClassLockIndex:
    """Lock facts for one ``class`` body (see module docstring)."""

    def __init__(self, classdef: ast.ClassDef, comments: Dict[int, str]):
        self.classdef = classdef
        self.name = classdef.name
        self.comments = comments
        self.methods: Dict[str, ast.AST] = {}
        self.locks: Set[str] = set()
        self.conditions: Set[str] = set()
        self.guard_annotations: Dict[str, str] = {}
        self.collections: Set[str] = set()
        self.accesses: List[Access] = []
        self.edges: Set[Tuple[str, str]] = set()
        self.edge_sites: Dict[Tuple[str, str], ast.AST] = {}
        self.calls: Dict[str, Set[str]] = {}
        self.entry_methods: Set[str] = set()
        self._held: Dict[int, FrozenSet[str]] = {}
        self._consumed: Set[int] = set()
        self._build()

    # ------------------------------------------------------------- build --
    def _build(self) -> None:
        for node in self.classdef.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[node.name] = node
        self._scan_init()
        self._scan_with_locks()
        for name, fn in self.methods.items():
            base = self._holds_annotation(fn)
            for child in ast.iter_child_nodes(fn):
                self._visit(child, frozenset(base), name)
        self._scan_entries()

    def _holds_annotation(self, fn: ast.AST) -> Set[str]:
        m = _HOLDS_RE.search(self.comments.get(fn.lineno, ""))
        if not m:
            return set()
        names = {n.strip() for n in m.group("locks").split(",")}
        self.locks.update(names)
        return names

    def _scan_init(self) -> None:
        """Lock ctors, guarded-by annotations and collection literals in
        ``__init__`` (the only place attributes are born)."""
        init = self.methods.get("__init__")
        targets: Iterator[ast.AST] = (
            ast.walk(init) if init is not None else iter(()))
        for node in targets:
            if isinstance(node, ast.Assign):
                attrs = [a for a in map(self_attr, node.targets) if a]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                attrs = [a for a in (self_attr(node.target),) if a]
            else:
                continue
            if not attrs:
                continue
            is_lock, is_cv = self._lock_ctor(node.value)
            for attr in attrs:
                if is_lock or is_cv:
                    self.locks.add(attr)
                    if is_cv:
                        self.conditions.add(attr)
                if self._collection_init(node.value):
                    self.collections.add(attr)
                # the annotation may sit on any line of a wrapped
                # assignment statement
                for ln in range(node.lineno,
                                (node.end_lineno or node.lineno) + 1):
                    m = _GUARDED_RE.search(self.comments.get(ln, ""))
                    if m:
                        self.guard_annotations[attr] = m.group("lock")
                        self.locks.add(m.group("lock"))
                        break

    @staticmethod
    def _lock_ctor(value: ast.AST) -> Tuple[bool, bool]:
        if not isinstance(value, ast.Call):
            return False, False
        tail = (value.func.attr if isinstance(value.func, ast.Attribute)
                else value.func.id if isinstance(value.func, ast.Name)
                else "")
        # lockwitness.condition(...) vs threading.Condition(...)
        if tail in _CV_TAILS or tail in _WITNESS_CV_TAILS:
            return True, True
        if tail in _LOCK_TAILS or tail in _WITNESS_LOCK_TAILS:
            return True, False
        return False, False

    @staticmethod
    def _collection_init(value: ast.AST) -> bool:
        if isinstance(value, (ast.Dict, ast.List, ast.Set,
                              ast.DictComp, ast.ListComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            tail = (value.func.attr
                    if isinstance(value.func, ast.Attribute)
                    else value.func.id
                    if isinstance(value.func, ast.Name) else "")
            return tail in _COLLECTION_CTORS
        return False

    def _scan_with_locks(self) -> None:
        """Heuristic: any lock-ish name used as ``with self._x:`` counts as
        a lock even without a visible ctor (e.g. passed in)."""
        for node in ast.walk(self.classdef):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                attr = self_attr(item.context_expr)
                if attr and _LOCKISH_NAME.search(attr):
                    self.locks.add(attr)
                    if attr.endswith(("_cv", "_cond")) or "cond" in attr:
                        self.conditions.add(attr)

    def _scan_entries(self) -> None:
        """Methods passed bare as call arguments run on other threads
        (Thread targets, Timer callbacks, executor submissions, server
        handler ctors)."""
        for node in ast.walk(self.classdef):
            if not isinstance(node, ast.Call):
                continue
            candidates = list(node.args) + [kw.value for kw in node.keywords]
            for arg in candidates:
                attr = self_attr(arg)
                if attr and attr in self.methods:
                    self.entry_methods.add(attr)

    # ------------------------------------------------------------- visit --
    def _visit(self, node: ast.AST, held: FrozenSet[str],
               method: str) -> None:
        self._held[id(node)] = held
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later, on whichever thread calls it — the
            # enclosing held set does not apply (unless annotated).
            inner = frozenset(self._holds_annotation(node))
            for child in ast.iter_child_nodes(node):
                self._visit(child, inner, method)
            return
        if isinstance(node, ast.Lambda):
            for child in ast.iter_child_nodes(node):
                self._visit(child, frozenset(), method)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                self._visit(item.context_expr, held, method)
                if item.optional_vars is not None:
                    self._visit(item.optional_vars, held, method)
                attr = self_attr(item.context_expr)
                if attr and attr in self.locks:
                    acquired.append(attr)
            inner_held = held
            for attr in acquired:
                for h in inner_held:
                    if h != attr:
                        edge = (h, attr)
                        self.edges.add(edge)
                        self.edge_sites.setdefault(edge, node)
                inner_held = inner_held | {attr}
            for stmt in node.body:
                self._visit(stmt, inner_held, method)
            return
        # writes through subscripts / attribute stores / mutator calls
        if isinstance(node, (ast.Subscript,)) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            attr = self_attr(node.value)
            if attr is not None:
                self._record(attr, node, "write", held, method)
                self._consumed.add(id(node.value))
        if isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in MUTATOR_TAILS):
                attr = self_attr(func.value)
                if attr is not None:
                    self._record(attr, node, "write", held, method)
                    self._consumed.add(id(func.value))
            fattr = self_attr(func)
            if fattr and fattr in self.methods:
                self.calls.setdefault(method, set()).add(fattr)
        attr = self_attr(node)
        if attr is not None and id(node) not in self._consumed:
            kind = ("write" if isinstance(node.ctx, (ast.Store, ast.Del))
                    else "read")
            self._record(attr, node, kind, held, method)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held, method)

    def _record(self, attr: str, node: ast.AST, kind: str,
                held: FrozenSet[str], method: str) -> None:
        if attr in self.locks or attr in self.methods:
            return
        self.accesses.append(Access(attr=attr, node=node, kind=kind,
                                    held=held, method=method))

    # -------------------------------------------------------------- query --
    def held_at(self, node: ast.AST) -> FrozenSet[str]:
        return self._held.get(id(node), frozenset())

    def reachable_methods(self) -> Set[str]:
        """Methods reachable from a thread entry via ``self.m()`` calls."""
        seen: Set[str] = set()
        frontier = list(self.entry_methods)
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            frontier.extend(self.calls.get(m, ()))
        return seen

    def inferred_guards(self, min_locked: int = 2) -> Dict[str, Set[str]]:
        """``{attr: {locks}}`` — a lock guards an attribute when at least
        ``min_locked`` accesses happen under it (outside ``__init__``) and
        the attribute is written somewhere outside ``__init__``.  Explicit
        ``guarded-by`` annotations override counting."""
        out: Dict[str, Set[str]] = {}
        per_attr: Dict[str, List[Access]] = {}
        for acc in self.accesses:
            if acc.method == "__init__":
                continue
            per_attr.setdefault(acc.attr, []).append(acc)
        for attr, accs in per_attr.items():
            if attr in self.guard_annotations:
                out[attr] = {self.guard_annotations[attr]}
                continue
            if not any(a.kind == "write" for a in accs):
                continue
            counts: Dict[str, int] = {}
            for a in accs:
                for lock in a.held:
                    counts[lock] = counts.get(lock, 0) + 1
            guards = {lock for lock, n in counts.items() if n >= min_locked}
            if guards:
                out[attr] = guards
        # annotated attrs with zero non-init accesses still get a guard
        for attr, lock in self.guard_annotations.items():
            out.setdefault(attr, {lock})
        return out

    def cycles(self) -> List[List[str]]:
        """Elementary cycles in the acquire-while-holding graph, each in a
        canonical rotation (deterministic report order)."""
        graph: Dict[str, List[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, []).append(b)
        for targets in graph.values():
            targets.sort()
        found: Set[Tuple[str, ...]] = set()
        out: List[List[str]] = []

        def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
            for nxt in graph.get(node, ()):
                if nxt in on_path:
                    cycle = path[path.index(nxt):]
                    pivot = cycle.index(min(cycle))
                    canon = tuple(cycle[pivot:] + cycle[:pivot])
                    if canon not in found:
                        found.add(canon)
                        out.append(list(canon))
                    continue
                dfs(nxt, path + [nxt], on_path | {nxt})

        for start in sorted(graph):
            dfs(start, [start], {start})
        return out


def class_indexes(ctx) -> List[ClassLockIndex]:
    """Per-class lock indexes for a FileContext, cached on the context so
    the five concurrency rules share one pass."""
    cached = getattr(ctx, "_lock_indexes", None)
    if cached is None:
        cached = [ClassLockIndex(node, ctx.comments)
                  for node in ast.walk(ctx.tree)
                  if isinstance(node, ast.ClassDef)]
        ctx._lock_indexes = cached
    return cached
