"""Static analysis for CoLearn: `colearn lint` (see engine.py, rules.py).

Kept lazy on purpose: importing the package must not import the rule set
(or anything heavyweight) so telemetry/registry.py can depend on
``analysis.metric_catalog`` without dragging the linter into the runtime
import graph.
"""

__all__ = ["engine", "findings", "jit_regions", "metric_catalog",
           "reporters", "rules"]
