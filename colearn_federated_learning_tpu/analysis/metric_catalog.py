"""Canonical catalog of every metric name the codebase may register.

One declared list, imported by BOTH the runtime registry
(telemetry/registry.py — optional strict mode, label-name validation)
and the CL005 lint rule (analysis/rules.py), so a counter-name typo
(``comm.retry_totl``) is a lint error at review time instead of a
silently-empty series the chaos-soak gate never sees.

Keep this module dependency-free: it is imported by telemetry/registry,
which every layer (including jit-adjacent code) pulls in.

Entries ending in ``.*`` are prefix wildcards for families minted at
runtime (``fault.injected.<kind>``).  Labeled instruments
(``comm.retry_total{device=3}``) are validated on the base name — the
label suffix is stripped by :func:`base_name`.
"""

from __future__ import annotations

# ---------------------------------------------------------------- catalog --
# Counters -----------------------------------------------------------------
COUNTERS = (
    # checkpoint plane (ckpt/manager.py, ckpt/wal.py, ckpt/streaming.py)
    "ckpt.saves_total",
    "ckpt.restores_total",
    "ckpt.wal_appends_total",
    "ckpt.wal_torn_tail_total",            # in-flight append lost to a kill
    "ckpt.wal_uncommitted_discarded_total",  # logged rounds past the ckpt
    "ckpt.shards_written_total",           # streaming per-shard files committed
    "ckpt.save_aborted_total",             # save ended before manifest commit
    "ckpt.resharded_resumes_total",        # restore re-cut onto a different tp
    # torn/missing/CRC-bad generations skipped by streaming recovery;
    # labeled {reason=missing_manifest|torn_manifest|missing_shard|
    # torn_shard|crc_mismatch}
    "ckpt.generations_discarded_total",
    # engine plane (fed/engine.py, fed/local.py)
    "engine.rounds_total",
    "local.trainers_built",
    # comm plane (comm/protocol.py, comm/transport.py, comm/worker.py)
    "comm.messages_sent",
    "comm.messages_received",
    "comm.bytes_sent",
    "comm.bytes_received",
    "comm.corrupt_frames_total",
    "comm.suppressed_oserrors_total",
    "comm.retry_total",              # labeled per device: {device=<id>}
    "comm.reenroll_total",
    "comm.reconnect_failures_total",
    # wire fast path (comm/downlink.py, comm/coordinator.py,
    # comm/aggregation.py)
    "comm.broadcast_encode_total",   # CLW1 encodes of a broadcast frame
    "comm.bytes_saved_downlink",     # delta vs full-params payload bytes
    "comm.bytes_saved_uplink",       # compressed vs dense train-reply bytes
    "comm.uplink_densify_avoided_total",  # contributions folded sparse (O(k))
    "comm.fold_device_total",           # contributions folded on-device
    "comm.resync_total",             # worker cache misses → full re-send
    # sharded server plane (parallel/partition.py, comm/downlink.py):
    # per-chip replication bytes the gather-free downlink never
    # materialized (per-shard host reads instead of a full-tree gather)
    "comm.gather_bytes_avoided_total",
    # key exchange & broker healing (comm/keyexchange.py, comm/coordinator.py)
    "comm.keyexchange_rejected_total",  # labeled {reason=zero|identity|...}
    "comm.broker_reconnects_total",     # labeled {outcome=ok|failed}
    # aggregator tree (comm/aggregator.py, comm/coordinator.py)
    "comm.agg_folds_total",             # labeled {agg=<id>}: partials folded
    "comm.agg_failovers_total",         # labeled {action=rehome|drop}
    "comm.agg_heartbeat_expired_total",  # stale heartbeat seen at dispatch
    "comm.agg_partials_folded_total",   # root-side, labeled {agg=<id>}
    # health ledger (telemetry/health.py)
    "health.ledger_appends_total",
    "health.ledger_compactions_total",
    # durable enrollment + challenge-on-resume (ckpt/wal.py EnrollmentLedger,
    # comm/coordinator.py verify_resumed_devices)
    "comm.enroll_ledger_appends_total",
    "comm.enroll_challenge_rejected_total",  # labeled {reason=not_in_ledger|
    #                                          bad_tag|unreachable|...}
    # dropout-tolerant secure aggregation (privacy/dropout.py,
    # comm/coordinator.py share phase + mask recovery)
    "privacy.shares_distributed_total",     # encrypted share blobs relayed
    "privacy.shares_collected_total",       # reveal shares received back
    "privacy.self_masks_removed_total",     # b_u reconstructions applied
    "privacy.masks_recovered_total",        # labeled {device=<dropped id>}
    "privacy.share_recovery_failures_total",  # labeled {stage=<where>}
    # fault plane (faults/inject.py)
    "fault.injected_total",
    "fault.injected.*",              # per-kind family
    # federation round outcomes (comm/coordinator.py)
    "fed.rounds_total",
    "fed.clients_dropped",
    "fed.clients_evicted",
    "fed.rounds_skipped_quorum",
    "fed.rounds_resumed_total",      # --resume restored a checkpoint
    # tp_size degraded to a replicated layout (fed/engine.py from_config,
    # parallel/partition.py make_server_placement); labeled
    # {reason=indivisible_devices|insufficient_devices|rules_matched_nothing}
    "fed.mesh_fallback_total",
    # file & hierarchical planes (fed/offline.py, fed/hierarchical.py)
    "fed.offline_updates_rejected_total",  # labeled {reason=torn|stale|...}
    "fed.offline_residual_resets_total",   # labeled {reason=stale|...}
    "fed.hier_groups_dropped_total",       # labeled per group: {group=g1}
    # LoRA adapter plane (fed/lora.py, comm/coordinator.py): server-side
    # B·A·(α/r) merges of aggregated factors into the global model
    "fed.lora_merges_total",
    # buffered-async plane (comm/async_coordinator.py)
    "async.dispatch_failures",
    "async.aggregations_total",
    "async.updates_discarded_stale",
    "async.devices_pruned_total",      # labeled {reason=straggler|...}
    "async.devices_readmitted_total",  # probation expiry re-admissions
    "fed.devices_evicted_total",       # dead-pump eviction, labeled {device=}
    # staleness observatory (comm/async_coordinator.py)
    "async.contribution_mass",       # Σ(1+τ)^-α, labeled {outcome=folded|...}
    "async.pump_stalls_total",       # dispatch slower than timeout/2, {device=}
    "async.buffer_resizes_total",    # auto-K changed the fold threshold
    # buffered-async aggregator tree (comm/aggregator.py buffered ops,
    # comm/async_coordinator.py tree mode)
    "comm.agg_buffer_staged_total",   # labeled {agg=<id>}: abuf contributions
    "comm.agg_buffer_dedup_total",    # duplicate dedup-key overwrites, {agg=}
    "comm.agg_partials_shipped_total",  # adrain partials sent up, {agg=<id>}
    "comm.agg_rehomed_total",         # contributions re-sent to a sibling
    "async.partials_folded_total",    # root-side tree folds, {agg=<id>}
    "async.partials_discarded_stale",  # whole partial past max_staleness
    # fleet simulation (fleetsim/sim.py)
    "fleetsim.rounds_total",
    "fleetsim.clients_trained_total",
    "fleetsim.async_aggregations_total",
    "fleetsim.async_updates_discarded_total",  # too-stale at fold time
    "fleetsim.async_devices_pruned_total",
    "fleetsim.async_contribution_mass",   # labeled {outcome=folded|discarded}
    "fleetsim.async_buffer_resizes_total",  # auto-K resizes (virtual clock)
    "fleetsim.async_partials_folded_total",   # two-tier mode, {agg=<slice>}
    "fleetsim.async_partials_discarded_total",  # whole partial too stale
    "fleetsim.bytes_up_est_total",     # wire-codec frame estimate, uplink
    "fleetsim.bytes_down_est_total",   # wire-codec frame estimate, downlink
    "fleetsim.bytes_gather_avoided_est_total",  # sharded-downlink estimate
    "fleetsim.bytes_up_saved_est_total",  # uplink-codec savings estimate
    # runtime observability plane (telemetry/runtime.py, telemetry/flight.py)
    "telemetry.compile_total",       # labeled {fn=<name>}: distinct XLA sigs
    "telemetry.recompile_total",     # labeled {fn,reason=shape|dtype|structure}
    "flight.dumps_total",            # flight-recorder dump writes
    "export.scrapes_total",          # /metrics + /snapshot.json hits
    "export.events_written_total",   # JSONL event-stream lines
    # convergence observatory (telemetry/convergence.py export_metrics):
    # per-fold trend classification census, labeled {trend=progress|...}
    "learn.trend_total",
)

# Gauges -------------------------------------------------------------------
GAUGES = (
    "engine.h2d_transfer_s",
    "local.steps_per_round",
    "fleetsim.devices",
    "fleetsim.chunk_size",
    "fleetsim.available_fraction",
    "fleetsim.async_buffer_size",
    "fleetsim.async_sim_minutes",   # simulated-clock minutes elapsed
    # sharded server: measured per-chip server-state bytes (per-shard
    # accounting via parallel/partition.bytes_per_chip — deterministic
    # even where memory_stats() is empty)
    "comm.server_bytes_per_chip",
    # uplink error feedback (comm/worker.py): norm of the carried
    # compression residual — should stay bounded round over round
    "fed.uplink_residual_norm",
    # adaptive topk (comm/worker.py _adapt_topk): the per-round density
    # the controller actually used, inside [topk_min, topk_max]
    "fed.topk_fraction_effective",
    # LoRA adapter plane (comm/coordinator.py): configured rank and the
    # trainable factor-parameter count it induces on the global model
    "fed.lora_rank",
    "fed.lora_factor_params",
    # live HBM sampling (telemetry/runtime.py; empty on CPU backends)
    "runtime.hbm_bytes_in_use",
    "runtime.hbm_bytes_limit",
    "runtime.hbm_peak_bytes_in_use",
    # aggregator tier visibility (comm/coordinator.py → `colearn top`)
    "comm.agg_heartbeat_age_s",      # labeled {agg=<id>}: announce staleness
    "comm.agg_slice_devices",        # labeled {agg=<id>}: dispatch slice size
    # buffered-async aggregator tree: per-slice buffer visibility
    "comm.agg_buffer_k",             # labeled {agg=<id>}: auto-K in force
    "comm.agg_buffer_occupancy",     # labeled {agg=<id>}: staged, undrained
    "comm.agg_arrival_rate_per_s",   # labeled {agg=<id>}: slice-local EWMA
    # staleness observatory (comm/async_coordinator.py, telemetry/arrival.py)
    "async.buffer_target",           # K in force for the current aggregation
    "async.buffer_occupancy",        # updates folded into the open buffer
    "async.pending_updates",         # arrived-but-unfolded queue depth
    "async.pumps",                   # labeled {state=wait|train|retry|...}
    "async.arrival_rate_per_s",      # seeded-EWMA; labeled {device=} children
    "fleetsim.async_arrival_rate_per_min",  # same estimator, virtual clock
    # health ledger exports (telemetry/health.py export_gauges)
    "health.devices_tracked",
    "health.device_score",           # labeled {device=<id>}: offender rank
    "health.device_latency_ewma_s",  # labeled {device=<id>}
    # convergence observatory (telemetry/convergence.py export_metrics):
    # learning-health signals computed from the materialized aggregate
    "learn.update_norm",             # ‖mean update‖ of the latest fold
    "learn.update_norm_ewma",        # trend baseline the classifier uses
    "learn.step_size",               # ‖mean update‖ × server_lr
    "learn.cos_prev",                # cosine to the previous mean update
    "learn.cohort_skew",             # 1 − min cohort-centroid cosine
)

# Histograms ---------------------------------------------------------------
HISTOGRAMS = (
    "ckpt.save_s",
    "ckpt.restore_s",
    "engine.round_time_s",
    "fed.round_time_s",
    "fed.phase_time_s",      # labeled {phase=broadcast_collect|aggregate|...}
    "async.agg_time_s",
    "async.staleness",       # labeled {outcome=folded|discarded}: τ per update
    "fleetsim.async_staleness",      # same, on the simulated clock
    "fleetsim.round_time_s",
    "comm.agg_fold_time_s",  # labeled {agg=<id>}: middle-tier slice folds
    # convergence observatory: distribution of per-fold update norms
    "learn.update_norm_dist",
)

# Counters whose soak-window delta faults/soak.py reports (a curated
# subset of COUNTERS — declared here so the soak gate and the catalog
# cannot drift apart).
SOAK_DELTA_COUNTERS = (
    "comm.retry_total",
    "comm.corrupt_frames_total",
    "comm.reconnect_failures_total",
    "fault.injected_total",
    "fed.rounds_skipped_quorum",
)

# Additional deltas the SECURE soak flavor reports (faults/soak.py
# run_secure_soak).  Kept separate from SOAK_DELTA_COUNTERS so the
# classic chaos-soak report — and the tests pinning it — are unchanged.
SECURE_SOAK_DELTA_COUNTERS = (
    "privacy.shares_distributed_total",
    "privacy.shares_collected_total",
    "privacy.self_masks_removed_total",
    "privacy.masks_recovered_total",
    "privacy.share_recovery_failures_total",
    "fed.rounds_skipped_quorum",
    "fault.injected_total",
)

METRICS: frozenset = frozenset(COUNTERS) | frozenset(GAUGES) | frozenset(
    HISTOGRAMS
)

assert set(SOAK_DELTA_COUNTERS) <= set(COUNTERS)
assert set(SECURE_SOAK_DELTA_COUNTERS) <= set(COUNTERS)

_WILDCARDS = tuple(sorted(m[:-1] for m in METRICS if m.endswith(".*")))


def base_name(name: str) -> str:
    """Strip a ``{label=value,...}`` suffix: the catalog declares base
    names; labels are free-form attribution."""
    brace = name.find("{")
    return name if brace < 0 else name[:brace]


def is_known(name: str) -> bool:
    """True when ``name`` (label suffix ignored) is declared here, either
    exactly or under a ``family.*`` wildcard."""
    base = base_name(name)
    if base in METRICS:
        return True
    return any(base.startswith(w) for w in _WILDCARDS)


# ------------------------------------------------------------ record keys --
# Round/aggregation-record keys the comm/ and fleetsim/ hot paths may
# stamp (comm/coordinator.py, comm/async_coordinator.py,
# fleetsim/sim.py).  The CL016 lint rule (analysis/rules.py) validates
# every literal key stored into those records against this tuple, so a
# record-key typo ("train_los") is a lint error instead of a silently
# forked series downstream sentinels and `colearn converge` never match.
RECORD_KEYS_LIST = (
    # sync federation round record (comm/coordinator.py)
    "round", "completed", "cohort", "dropped", "evicted", "train_loss",
    "total_weight", "phase_broadcast_collect_s", "phase_aggregate_s",
    "phase_fold_overlap_s", "round_time_s", "retries",
    # conditional sync keys (feature-gated; default records byte-identical)
    "unmask_failed", "skipped_quorum", "bytes_saved_uplink",
    "uplink_densify_avoided", "lora_merged", "aggregators",
    "phase_agg_fold_s", "agg_failovers", "dp_epsilon", "dp_delta",
    # per-client evaluation report (comm/coordinator.py)
    "num_clients_evaluated", "per_client",
    # challenge-on-resume report (comm/coordinator.py
    # verify_resumed_devices)
    "verified", "rejected",
    # buffered-async aggregation record (comm/async_coordinator.py)
    "aggregation", "model_version", "buffer_size", "staleness_mean",
    "staleness_max", "discarded", "contributors", "agg_time_s",
    "phase_collect_s", "phase_apply_s",
    # observe-gated async keys
    "mass_folded", "mass_discarded", "arrival_rate_per_s",
    "staleness_p50", "staleness_p90", "staleness_p99", "pruned",
    "dp_z_eff",
    # tree-async keys (comm/async_coordinator.py tree mode + fleetsim
    # two-tier fit_async; absent unless num_aggregators/aggregators > 0,
    # so default records stay byte-identical)
    "agg_id", "agg_buffer_k", "agg_buffer_staged", "agg_buffer_rate_per_s",
    "oldest_version", "folded_keys", "rehomed_devices", "rehomed_total",
    "agg_fold_tracking_min",
    # fleetsim sync round record (fleetsim/sim.py run_round)
    "cohort_requested", "clients_trained", "bytes_down_est",
    "bytes_up_est", "bytes_gather_avoided_est", "bytes_up_saved_est",
    "available_fraction", "straggled", "corrupted",
    # fleetsim async record extras (fleetsim/sim.py fit_async)
    "sim_time_min", "arrival_rate_per_min", "agg_rate_per_min",
    "wasted_updates_total", "arrival_rate_ewma_per_min", "pruned_total",
    # fleetsim compile-census report (DeviceFleetSim.compile_counts)
    "chunk", "finish", "fold", "obs_chunk",
    # health-ledger summary keys (telemetry/health.py health_record_keys)
    "health_devices", "health_lat_p99_s", "health_worst_device",
    "health_worst_score",
    # convergence observatory (telemetry/convergence.py; --learn-observe)
    "conv_update_norm",      # ‖mean update‖ of the materialized aggregate
    "conv_step_size",        # ‖mean update‖ × server_lr
    "conv_norm_ewma",        # trend baseline at classification time
    "conv_trend",            # warmup|progress|plateau|divergence|oscillation
    "conv_cos_prev",         # cosine to previous update (absent round 0)
    "conv_norm_median",      # fleetsim per-device skew (updates visible)
    "conv_norm_p90",
    "conv_norm_anomalies",   # devices with norm > anomaly_ratio × median
    "conv_cohort_skew",      # 1 − min cohort-centroid cosine vs aggregate
    "conv_cohort_cos_min",
)

RECORD_KEYS: frozenset = frozenset(RECORD_KEYS_LIST)

assert len(RECORD_KEYS) == len(RECORD_KEYS_LIST), "duplicate record key"


def is_known_record_key(key: str) -> bool:
    """True when ``key`` is a declared round-record key."""
    return key in RECORD_KEYS
