"""The colearn rule set (CL001–CL023).

Each rule is ~30 lines: subclass :class:`~.engine.Rule`, set ``id`` /
``title`` / ``hint``, yield :class:`~.findings.Finding` objects from
``check(ctx)``, and decorate with ``@register``.  Rules are pure AST
heuristics — single-file, name-based, no imports of the linted code —
so false positives are possible and are handled with a justified
``# colearn: noqa(RULE)`` on the offending line.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from colearn_federated_learning_tpu.analysis import lock_regions
from colearn_federated_learning_tpu.analysis import metric_catalog
from colearn_federated_learning_tpu.analysis.engine import (
    FileContext,
    Rule,
    register,
)
from colearn_federated_learning_tpu.analysis.findings import Finding
from colearn_federated_learning_tpu.analysis.jit_regions import (
    dotted_name,
    traced_regions,
    walk_region,
)


def _enclosing_functions(tree: ast.AST) -> dict:
    """``{id(node): (outer, ..., innermost FunctionDef)}`` for every node."""
    out: dict = {}

    def visit(node: ast.AST, stack: tuple) -> None:
        out[id(node)] = stack
        child_stack = stack
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            child_stack = stack + (node,)
        for child in ast.iter_child_nodes(node):
            visit(child, child_stack)

    visit(tree, ())
    return out


def _has_timeout_param(fn: ast.AST) -> bool:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return any("timeout" in n or "deadline" in n for n in names)


def _has_kwarg(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


# ----------------------------------------------------------------- CL001 --
@register
class JitPurity(Rule):
    """Side effects inside a traced function run once at trace time and
    then never again — prints vanish, timers freeze, counters under-count."""

    id = "CL001"
    title = "side effect inside a jit/pmap/shard_map-traced function"
    hint = ("hoist the side effect out of the traced function (use "
            "jax.debug.print/callback if it must stay)")

    _LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                    "critical", "log"}

    def _effect(self, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name) and func.id == "print":
            return "print()"
        dotted = dotted_name(func)
        for prefix in ("time.", "random.", "np.random.", "numpy.random.",
                       "logging."):
            if dotted.startswith(prefix):
                return f"{dotted}()"
        if dotted.endswith(".get_registry") or dotted == "get_registry":
            return "metrics registry access"
        if isinstance(func, ast.Attribute):
            if func.attr in ("inc", "observe"):
                return f"metrics counter mutation .{func.attr}()"
            base = dotted_name(func.value).lower()
            if func.attr in self._LOG_METHODS and "log" in base:
                return f"{dotted}()"
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for region in traced_regions(ctx.tree):
            for node in walk_region(region):
                if not isinstance(node, ast.Call):
                    continue
                effect = self._effect(node)
                if effect:
                    yield self.finding(
                        ctx, node,
                        f"{effect} inside a traced function: runs once at "
                        "trace time, never per step")


# ----------------------------------------------------------------- CL002 --
@register
class SocketTimeout(Rule):
    """Every blocking socket op in comm/ must carry an explicit timeout
    (or live in a function that accepts one), so a dead peer costs a
    bounded slice of the round deadline, never the whole round."""

    id = "CL002"
    title = "blocking socket operation without an explicit timeout"
    hint = ("pass timeout= (or add a timeout/deadline parameter to the "
            "enclosing function and settimeout before the call)")

    _CLIENT_CTORS = {"BrokerClient", "TensorClient"}
    _BLOCKING_ATTRS = {"accept", "recv", "recv_into"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dir("comm"):
            return
        enclosing = _enclosing_functions(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            tail = dotted.rsplit(".", 1)[-1]
            if _has_kwarg(node, "timeout"):
                continue
            if (dotted.endswith("create_connection")
                    or tail == "connect"
                    or tail in self._CLIENT_CTORS):
                if tail == "connect" and len(node.args) >= 3:
                    continue      # connect(host, port, timeout) positional
            elif (tail in self._BLOCKING_ATTRS
                    and isinstance(node.func, ast.Attribute)):
                # raw socket .accept()/.recv(n) have no timeout arg: require
                # a timeout-bearing enclosing function (which is expected
                # to settimeout the socket) or a justified noqa.
                pass
            else:
                continue
            fns = enclosing.get(id(node), ())
            if any(_has_timeout_param(fn) for fn in fns):
                continue
            yield self.finding(
                ctx, node,
                f"{dotted or tail}() without an explicit timeout: a dead "
                "peer blocks forever")


# ----------------------------------------------------------------- CL003 --
@register
class SwallowedError(Rule):
    """Bare ``except:`` and pass-only handlers hide real failures in the
    planes where failures are the whole point (comm, faults, engine)."""

    id = "CL003"
    title = "bare except / silently swallowed error"
    hint = ("narrow the exception type and count or log it "
            "(comm.protocol.close_quietly for socket teardown)")

    def _applies(self, ctx: FileContext) -> bool:
        return (ctx.in_dir("comm") or ctx.in_dir("faults")
                or ctx.relpath.endswith("fed/engine.py"))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not self._applies(ctx):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield self.finding(
                    ctx, node,
                    "bare `except:` also catches SystemExit/KeyboardInterrupt")
                continue
            if all(isinstance(s, (ast.Pass, ast.Continue))
                   for s in node.body):
                caught = dotted_name(node.type) or "exception"
                yield self.finding(
                    ctx, node,
                    f"`except {caught}` swallows the error with no count, "
                    "log, or re-raise")


# ----------------------------------------------------------------- CL004 --
@register
class Nondeterminism(Rule):
    """Fault injection replays byte-identically from a seed; wall-clock
    and unseeded RNG calls break that contract."""

    id = "CL004"
    title = "nondeterministic source in a seeded code path"
    hint = ("thread the plan's seeded rng / use time.monotonic for "
            "durations only")

    _WALL_CLOCK = {"time.time", "datetime.now", "datetime.datetime.now",
                   "datetime.utcnow", "datetime.datetime.utcnow"}
    _SEEDED_CTORS = {"Random", "default_rng", "RandomState"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dir("faults"):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = dotted_name(node.func)
            tail = dotted.rsplit(".", 1)[-1]
            if dotted in self._WALL_CLOCK:
                yield self.finding(
                    ctx, node,
                    f"{dotted}() is wall-clock: replay of a seeded fault "
                    "plan diverges")
            elif dotted.startswith(("random.", "np.random.",
                                    "numpy.random.")):
                if tail in self._SEEDED_CTORS and (node.args
                                                   or node.keywords):
                    continue          # random.Random(seed) etc. — seeded
                yield self.finding(
                    ctx, node,
                    f"{dotted}() draws from global/unseeded RNG state")


# ----------------------------------------------------------------- CL005 --
@register
class MetricNameDrift(Rule):
    """Every literal metric name handed to the registry must be declared
    in analysis/metric_catalog.py — a typo'd counter is a silently-empty
    series the chaos-soak gate never sees."""

    id = "CL005"
    title = "metric name not declared in the catalog"
    hint = "add it to analysis/metric_catalog.py (or fix the typo)"

    _REGISTRY_METHODS = {"counter", "gauge", "histogram"}

    def _first_name_arg(self, call: ast.Call):
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg == "name":
                return kw.value
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if "analysis" in ctx.parts:
            return  # the catalog itself and its tooling
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._REGISTRY_METHODS):
                continue
            arg = self._first_name_arg(node)
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if not metric_catalog.is_known(arg.value):
                    yield self.finding(
                        ctx, node,
                        f"metric name {arg.value!r} is not in the catalog")
            elif isinstance(arg, ast.JoinedStr):
                # f"fault.injected.{kind}" — validate the static prefix
                # against the catalog's `family.*` wildcards.
                prefix = ""
                for part in arg.values:
                    if isinstance(part, ast.Constant):
                        prefix += str(part.value)
                    else:
                        break
                if not metric_catalog.is_known(prefix + "x"):
                    yield self.finding(
                        ctx, node,
                        f"dynamic metric name with prefix {prefix!r} matches "
                        "no `family.*` wildcard in the catalog")
            elif arg is not None:
                # A plain-variable name used to slip through unvalidated —
                # the exact hole a typo'd series hides in.  Loops over a
                # catalog-declared tuple (metric_catalog.SOAK_DELTA_COUNTERS)
                # carry a justified noqa.
                yield self.finding(
                    ctx, node,
                    "non-literal metric name: the catalog cannot validate "
                    "it — inline the literal, use an f-string with a "
                    "`family.*` prefix, or iterate a catalog-declared "
                    "tuple with a justified noqa")


# ----------------------------------------------------------------- CL006 --
@register
class HostSyncInHotLoop(Rule):
    """``float(x)`` / ``np.asarray`` / ``.block_until_ready()`` force a
    device→host sync; inside traced code they trace-error or silently
    constant-fold, and inside a marked hot loop they serialize the
    pipeline (see PERF.md)."""

    id = "CL006"
    title = "host synchronization inside a traced region or hot loop"
    hint = ("batch the transfer after the loop / keep values on device; "
            "mark intentional syncs with `# colearn: noqa(CL006)`")

    _SYNC_CALLS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
                   "jax.device_get"}

    def _sync(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        func = node.func
        if isinstance(func, ast.Name) and func.id == "float":
            if node.args and not isinstance(node.args[0], ast.Constant):
                return "float()"
            return None
        dotted = dotted_name(func)
        if dotted in self._SYNC_CALLS:
            return f"{dotted}()"
        if isinstance(func, ast.Attribute) and func.attr in (
                "block_until_ready", "item"):
            return f".{func.attr}()"
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for region in traced_regions(ctx.tree):
            for node in walk_region(region):
                what = self._sync(node)
                if what:
                    yield self.finding(
                        ctx, node,
                        f"{what} inside a traced function forces a host "
                        "sync (or fails to trace)")
        hot = ctx.hot_lines()
        if not hot:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.For, ast.While)) and node.lineno in hot:
                for inner in ast.walk(node):
                    what = self._sync(inner)
                    if what:
                        yield self.finding(
                            ctx, inner,
                            f"{what} inside a `# colearn: hot` loop "
                            "serializes the device pipeline")


# ----------------------------------------------------------------- CL007 --
@register
class SerializeInFanOutLoop(Rule):
    """The coordinator's broadcast is serialize-ONCE: one CLW1 encode per
    round, shared read-only by every cohort send (comm/downlink.py).  A
    ``pytree_to_bytes`` (or npz save) inside a ``# colearn: hot`` fan-out
    loop re-encodes the full model per device per round — exactly the
    O(cohort) host cost the fast path removed.  Guards that invariant the
    way CL006 guards host syncs."""

    id = "CL007"
    title = "per-request serialization inside a hot fan-out loop"
    hint = ("encode once before the loop and hand every send the shared "
            "frame via request(body=...) — see comm/downlink."
            "DownlinkEncoder; mark a justified per-iteration encode with "
            "`# colearn: noqa(CL007)`")

    _ENCODERS = {"pytree_to_bytes", "save_pytree_npz"}
    # Fan-outs submit via comprehensions as often as statement loops.
    _LOOPS = (ast.For, ast.While, ast.ListComp, ast.SetComp, ast.DictComp,
              ast.GeneratorExp)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        hot = ctx.hot_lines()
        if not hot:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, self._LOOPS) and node.lineno in hot):
                continue
            for inner in ast.walk(node):
                if not isinstance(inner, ast.Call):
                    continue
                tail = dotted_name(inner.func).rsplit(".", 1)[-1]
                if tail in self._ENCODERS:
                    yield self.finding(
                        ctx, inner,
                        f"{tail}() inside a `# colearn: hot` fan-out loop "
                        "re-encodes the full model per request; encode "
                        "once and pass request(body=...)")


# ----------------------------------------------------------------- CL008 --
@register
class NonAtomicExchangeWrite(Rule):
    """The file-exchange plane (fed/) hands artifacts to OTHER processes
    by path: a reader (or a SIGKILL mid-write) that lands between open
    and close sees a torn file.  Every exchange write must go through a
    temp file + ``os.replace`` so readers only ever observe complete
    artifacts (utils.serialization.atomic_save_pytree_npz)."""

    id = "CL008"
    title = "non-atomic write on a file-exchange path"
    hint = ("write via utils.serialization.atomic_save_pytree_npz (or "
            "temp file + os.replace in the same function); mark a "
            "single-process scratch write with `# colearn: noqa(CL008)`")

    # Explicit dotted forms for the numpy writers so a method named
    # `.save()` on some manager object (orbax is atomic internally)
    # doesn't trip the rule; save_pytree_npz is unambiguous at any depth.
    _NP_WRITERS = {"np.savez", "numpy.savez", "np.savez_compressed",
                   "numpy.savez_compressed", "np.save", "numpy.save"}

    def _is_writer(self, call: ast.Call) -> Optional[str]:
        dotted = dotted_name(call.func)
        if dotted in self._NP_WRITERS:
            return dotted
        if dotted.rsplit(".", 1)[-1] == "save_pytree_npz":
            return "save_pytree_npz"
        if isinstance(call.func, ast.Name) and call.func.id == "open":
            mode = None
            if len(call.args) >= 2:
                mode = call.args[1]
            for kw in call.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and "w" in mode.value):
                return f"open(..., {mode.value!r})"
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dir("fed"):
            return
        enclosing = _enclosing_functions(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            writer = self._is_writer(node)
            if writer is None:
                continue
            fns = enclosing.get(id(node), ())
            atomic = False
            for fn in fns:
                for inner in ast.walk(fn):
                    if (isinstance(inner, ast.Call)
                            and dotted_name(inner.func) == "os.replace"):
                        atomic = True
                        break
                if atomic:
                    break
            if atomic:
                continue
            yield self.finding(
                ctx, node,
                f"{writer} writes an exchange file in place: a reader or "
                "kill mid-write sees a torn artifact; use temp file + "
                "os.replace")


# ----------------------------------------------------------------- CL009 --
@register
class PerClientLoopInFleetHotPath(Rule):
    """fleetsim exists to make simulated clients a ``jax.vmap`` axis
    (fleetsim/sim.py): the ONLY Python loop a hot fleet path may contain
    iterates over fixed-size CHUNKS, each dispatching one jitted vmapped
    step.  A per-client/per-device Python loop — or a ``local_update``
    call per iteration — re-creates the one-at-a-time engine inside the
    subsystem built to kill it, and at fleet scale turns a ~250-dispatch
    million-client round into a million dispatches."""

    id = "CL009"
    title = "per-client Python loop in a fleetsim hot path"
    hint = ("make clients a vmap axis: materialize the chunk and call the "
            "jitted chunk step once per CHUNK (see fleetsim/sim."
            "FleetSim.run_round); mark a justified host-side loop with "
            "`# colearn: noqa(CL009)`")

    _TRAINERS = {"local_update", "scaffold_update"}
    _WORDS = ("client", "device")
    _LOOPS = (ast.For, ast.While, ast.ListComp, ast.SetComp, ast.DictComp,
              ast.GeneratorExp)

    def _idents(self, node: ast.AST) -> Iterator[str]:
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                yield n.id
            elif isinstance(n, ast.Attribute):
                yield n.attr

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dir("fleetsim"):
            return
        hot = ctx.hot_lines()
        if not hot:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, self._LOOPS) and node.lineno in hot):
                continue
            # (a) the loop head names a per-client/per-device quantity.
            if isinstance(node, ast.For):
                head: tuple = (node.target, node.iter)
            elif isinstance(node, ast.While):
                head = (node.test,)
            else:
                head = tuple(part for comp in node.generators
                             for part in (comp.target, comp.iter))
            per_client = [i for h in head for i in self._idents(h)
                          if any(w in i.lower() for w in self._WORDS)]
            if per_client:
                yield self.finding(
                    ctx, node,
                    f"`# colearn: hot` loop iterates per "
                    f"{per_client[0]!r}: clients must be a vmap axis — "
                    "loop over chunks")
                continue
            # (b) one local-training call per iteration.
            for inner in ast.walk(node):
                if not isinstance(inner, ast.Call):
                    continue
                tail = dotted_name(inner.func).rsplit(".", 1)[-1]
                if tail in self._TRAINERS:
                    yield self.finding(
                        ctx, inner,
                        f"{tail}() called once per iteration of a "
                        "`# colearn: hot` loop; vmap it over the chunk "
                        "instead")


# ----------------------------------------------------------------- CL010 --
@register
class NoPrintInLibrary(Rule):
    """Library code has two sanctioned output planes — the metrics
    registry and the JSONL event/record streams; a stray ``print()`` to
    stdout interleaves with the machine-readable stdout contract the CLI
    maintains (round records, bench JSON) and corrupts downstream
    parsers.  CLI entry surfaces own stdout and are exempt; stderr
    diagnostics and ``__main__``-guarded debug mains are allowed."""

    id = "CL010"
    title = "print() to stdout in library code"
    hint = ("route through the metrics/event plane, or print to stderr "
            "(`print(..., file=sys.stderr)`); CLI entry modules are "
            "exempt by name")

    # Modules whose contract IS stdout (subcommand surface, bench JSON).
    _EXEMPT_FILES = {"cli.py", "bench.py"}

    @staticmethod
    def _is_main_guard(test: ast.AST) -> bool:
        return (isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and test.left.id == "__name__"
                and any(isinstance(c, ast.Constant)
                        and c.value == "__main__"
                        for c in test.comparators))

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.parts and ctx.parts[-1] in self._EXEMPT_FILES:
            return
        if ctx.in_dir("scripts"):
            return
        guarded: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.If) and self._is_main_guard(node.test):
                for inner in ast.walk(node):
                    guarded.add(id(inner))
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                continue
            if id(node) in guarded:
                continue
            file_kw = next((kw.value for kw in node.keywords
                            if kw.arg == "file"), None)
            if file_kw is not None and dotted_name(file_kw) != "sys.stdout":
                continue              # explicit non-stdout sink
            yield self.finding(
                ctx, node,
                "print() to stdout in library code interleaves with the "
                "machine-readable stdout contract; use the metrics/event "
                "plane or stderr")


# ----------------------------------------------------------------- CL011 --
@register
class PerPairLoopInMaskingHotPath(Rule):
    """Secure-aggregation mask expansion is ONE vectorized dispatch:
    build the (P, 2) pair-key table, then a single
    ``pairwise_mask_with_keys`` / ``mask_update_with_keys`` call expands
    every pair's PRG stream inside one jitted ``fori_loop``
    (privacy/secure_agg.py).  A Python loop that calls a mask expander
    once per pair pays a dispatch — and, called eagerly, a full
    retrace+compile — per pair; under the secure chaos soak that turned
    sub-second rounds into deadline blowouts.  Deriving the pair KEYS
    per pair (``shared_secret`` / ``pair_prng_key``, one scalar modexp
    each) is the sanctioned loop shape and is exempt."""

    id = "CL011"
    title = "per-pair Python loop in a hot masking path"
    hint = ("build the pair-key table once and make a single "
            "*_with_keys call (privacy/secure_agg."
            "pairwise_mask_with_keys); mark a justified per-pair loop "
            "with `# colearn: noqa(CL011)`")

    _EXPANDERS = {"pairwise_mask", "mask_update", "mask_scalar",
                  "pairwise_mask_with_keys", "mask_update_with_keys",
                  "_sample_tree"}
    _KEY_DERIVATION = {"shared_secret", "pair_prng_key"}
    _WORDS = ("pair", "partner", "peer", "neighbor")
    _LOOPS = (ast.For, ast.While, ast.ListComp, ast.SetComp, ast.DictComp,
              ast.GeneratorExp)

    def _idents(self, node: ast.AST) -> Iterator[str]:
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                yield n.id
            elif isinstance(n, ast.Attribute):
                yield n.attr

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not (ctx.in_dir("privacy") or ctx.in_dir("comm")):
            return
        hot = ctx.hot_lines()
        if not hot:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, self._LOOPS) and node.lineno in hot):
                continue
            tails = {dotted_name(inner.func).rsplit(".", 1)[-1]
                     for inner in ast.walk(node)
                     if isinstance(inner, ast.Call)}
            # (a) a mask expander called once per iteration.
            expanded = sorted(tails & self._EXPANDERS)
            if expanded:
                yield self.finding(
                    ctx, node,
                    f"{expanded[0]}() called once per iteration of a "
                    "`# colearn: hot` loop: one dispatch (and possibly "
                    "one retrace) per pair — make a single *_with_keys "
                    "call over the pair-key table")
                continue
            # (b) the loop head names a per-pair quantity (and the body
            # is not just the sanctioned scalar key derivation).
            if tails & self._KEY_DERIVATION:
                continue
            if isinstance(node, ast.For):
                head: tuple = (node.target, node.iter)
            elif isinstance(node, ast.While):
                head = (node.test,)
            else:
                head = tuple(part for comp in node.generators
                             for part in (comp.target, comp.iter))
            per_pair = [i for h in head for i in self._idents(h)
                        if any(w in i.lower() for w in self._WORDS)]
            if per_pair:
                yield self.finding(
                    ctx, node,
                    f"`# colearn: hot` loop iterates per "
                    f"{per_pair[0]!r}: pairs must be a table axis — "
                    "expand every mask in one *_with_keys dispatch")


# ----------------------------------------------------------------- CL012 --
@register
class FullTreeGatherInHotWirePath(Rule):
    """The sharded-server wire path (PR 9) never gathers the full model:
    the downlink encoder and the streaming fold read/scatter PER-DEVICE
    shards (parallel/partition.host_leaf / ServerPlacement.slice_tree),
    so no chip ever materializes a replicated copy and multi-host meshes
    stay legal.  A ``jax.device_get(...)`` — or the tree-mapped
    ``np.asarray`` full-tree-gather idiom — inside a ``# colearn: hot``
    region of the comm plane reintroduces exactly the O(model) gather the
    refactor removed."""

    id = "CL012"
    title = "full-tree gather on a hot downlink/aggregation path"
    hint = ("read per-device shards instead (parallel/partition."
            "host_tree, comm/downlink.host_params) or stage per-shard "
            "slices (ServerPlacement.slice_tree); mark a justified "
            "host-side conversion with `# colearn: noqa(CL012)`")

    _GATHERS = {"jax.device_get", "device_get"}
    _CONVERTERS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
                   "jnp.asarray"}
    _TREE_MAPS = {"jax.tree.map", "jax.tree_map", "jax.tree_util.tree_map",
                  "tree.map", "tree_map"}
    # Hot markers land on statement heads: defs, loops, withs, or the
    # offending statement line itself.
    _REGIONS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.For, ast.While,
                ast.With)

    def _gather(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        dotted = dotted_name(node.func)
        if dotted in self._GATHERS:
            return f"{dotted}()"
        if dotted in self._TREE_MAPS and node.args:
            first = dotted_name(node.args[0])
            if first in self._CONVERTERS:
                return f"{dotted}({first}, ...)"
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dir("comm"):
            return
        hot = ctx.hot_lines()
        if not hot:
            return
        seen: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, self._REGIONS) and node.lineno in hot:
                inners: Iterator[ast.AST] = ast.walk(node)
            elif isinstance(node, ast.Call) and node.lineno in hot:
                inners = iter((node,))
            else:
                continue
            for inner in inners:
                what = self._gather(inner)
                if what is None:
                    continue
                key = (inner.lineno, inner.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, inner,
                    f"{what} inside a `# colearn: hot` wire path gathers "
                    "the full tree to one host buffer per chip; read "
                    "per-device shards (partition.host_tree) or stage "
                    "per-shard slices instead")


# ----------------------------------------------------------------- CL013 --
@register
class FullShapeMaterializeInHotAggregation(Rule):
    """The sparse-native uplink fold (PR 10) stages topk contributions as
    (indices, values) and scatter-adds them at finalize: per-contribution
    host cost is O(k), not O(model).  Densifying a compressed update —
    a ``decompress_delta`` call, or allocating a full-shape buffer
    (``np.zeros`` / ``np.empty`` / ``np.full`` / ``*_like``) per update —
    inside a ``# colearn: hot`` aggregation/wire region of the comm plane
    reintroduces exactly the O(model)-per-client work the fast path
    removed.  The once-per-round accumulator allocation at finalize is
    fine (it is not hot); the int8 dequantize is inherently dense (every
    entry carries signal) and keeps a justified noqa."""

    id = "CL013"
    title = "full-shape materialization on a hot aggregation path"
    hint = ("stage sparse (indices, values) and scatter-add at finalize "
            "(StreamingFolder._stage_topk / ServerPlacement."
            "partition_flat_indices); mark an inherently-dense decode "
            "with `# colearn: noqa(CL013)`")

    _ALLOCATORS = {"np.zeros", "numpy.zeros", "np.empty", "numpy.empty",
                   "np.full", "numpy.full", "np.zeros_like",
                   "numpy.zeros_like", "np.full_like", "numpy.full_like",
                   "jnp.zeros", "jnp.zeros_like"}
    _REGIONS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.For, ast.While,
                ast.With)

    def _materialize(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        dotted = dotted_name(node.func)
        if dotted.rsplit(".", 1)[-1] == "decompress_delta":
            return (f"{dotted}() densifies a compressed update to full "
                    "model shape")
        if dotted in self._ALLOCATORS and node.args:
            return f"{dotted}(...) allocates a full-shape buffer"
        return None

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dir("comm"):
            return
        hot = ctx.hot_lines()
        if not hot:
            return
        seen: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, self._REGIONS) and node.lineno in hot:
                inners: Iterator[ast.AST] = ast.walk(node)
            elif isinstance(node, ast.Call) and node.lineno in hot:
                inners = iter((node,))
            else:
                continue
            for inner in inners:
                what = self._materialize(inner)
                if what is None:
                    continue
                key = (inner.lineno, inner.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, inner,
                    f"{what} inside a `# colearn: hot` aggregation path — "
                    "O(model) host work per update; stage sparse "
                    "(indices, values) and scatter-add at finalize "
                    "(StreamingFolder._stage_topk)")


# ----------------------------------------------------------------- CL014 --
@register
class UnattributedTimingInHotWirePath(Rule):
    """The fleet health plane (PR 12) attributes every hot-path duration
    to a named sink: a tracer span (stitched into the round trace), a
    registry histogram (``fed.phase_time_s`` / ``comm.agg_fold_time_s``),
    or an accumulated stat shipped in round meta (``fold_s``).  A raw
    wall-clock delta — ``time.time() - t0`` computed in a ``# colearn:
    hot`` comm region and not fed into one of those sinks — is a timing
    measurement the health ledger, ``colearn top``, and the sentinel
    windows never see: it ages into a print/log or a local nobody reads.
    Accumulations (``self.fold_s += perf_counter() - t0``) and deltas
    passed straight into ``observe``/``set``/``record``/``inc`` are
    attributed and stay clean."""

    id = "CL014"
    title = "unattributed wall-clock delta on a hot wire path"
    hint = ("time it with `tracer.span(...)` or feed the delta to a "
            "registry histogram (fed.phase_time_s) / the health ledger; "
            "mark a justified raw delta with `# colearn: noqa(CL014)`")

    _CLOCKS = {"time.time", "time.monotonic", "time.perf_counter",
               "perf_counter", "monotonic"}
    _SINKS = {"observe", "set", "record", "inc", "set_attr"}
    _REGIONS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.For, ast.While,
                ast.With)

    def _delta(self, node: ast.AST) -> Optional[str]:
        # A duration is clock-call-minus-start; deadline arithmetic
        # (``deadline - time.monotonic()``) keeps the clock on the right
        # and is budget bookkeeping, not a measurement.
        if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)):
            return None
        if not isinstance(node.left, ast.Call):
            return None
        dotted = dotted_name(node.left.func)
        if dotted not in self._CLOCKS:
            return None
        return f"{dotted}() - ..."

    def _attributed(self, tree: ast.AST) -> set:
        """ids of every node under an AugAssign value (stat accumulation)
        or a metric-sink call argument — deltas landing there are fed to
        a named series and exempt."""
        out: set = set()
        for node in ast.walk(tree):
            roots: tuple = ()
            if isinstance(node, ast.AugAssign):
                roots = (node.value,)
            elif isinstance(node, ast.Call):
                # ``reg.histogram(...).observe(dt)`` roots the attribute
                # chain at a Call, so read the attr directly rather than
                # via dotted_name (which needs a Name root).
                func = node.func
                tail = (func.attr if isinstance(func, ast.Attribute)
                        else dotted_name(func))
                if tail in self._SINKS:
                    roots = tuple(node.args) + tuple(
                        kw.value for kw in node.keywords)
            for root in roots:
                out.update(id(n) for n in ast.walk(root))
        return out

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dir("comm"):
            return
        hot = ctx.hot_lines()
        if not hot:
            return
        attributed = self._attributed(ctx.tree)
        seen: set = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, self._REGIONS) and node.lineno in hot:
                inners: Iterator[ast.AST] = ast.walk(node)
            elif node.__class__ is ast.BinOp and node.lineno in hot:
                inners = iter((node,))
            else:
                continue
            for inner in inners:
                what = self._delta(inner)
                if what is None or id(inner) in attributed:
                    continue
                key = (inner.lineno, inner.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, inner,
                    f"{what} inside a `# colearn: hot` wire path is a "
                    "duration no sink ever sees; route it through a "
                    "tracer span or a registry histogram so the health "
                    "plane can attribute it")


# ----------------------------------------------------------------- CL015 --
@register
class UninterruptibleBackoffSleep(Rule):
    """A bare ``time.sleep()`` inside a comm retry/dispatch loop is an
    uninterruptible stall: ``close()``/``stop()`` cannot wake the thread,
    so shutdown blocks for a full backoff (and the chaos gate's SIGKILL
    relaunch inherits a zombie that finishes its nap before noticing the
    socket died).  Every backoff in the comm plane waits on a
    ``threading.Event`` (``self._stop.wait(delay)``/``_closing.wait``)
    instead — same delay when idle, immediate wakeup on teardown.  Sleeps
    outside loops (test fixtures, one-shot startup grace) are not
    backoffs and stay clean."""

    id = "CL015"
    title = "uninterruptible time.sleep() in a comm retry/dispatch loop"
    hint = ("wait on the owner's stop event instead: "
            "`if self._stop.wait(delay): return` wakes on shutdown; "
            "mark a justified bare sleep with `# colearn: noqa(CL015)`")

    _SLEEPS = {"time.sleep", "sleep"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.in_dir("comm"):
            return
        loops = [n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.For, ast.While))]
        in_loop: set = set()
        for loop in loops:
            in_loop.update(id(n) for n in ast.walk(loop))
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and id(node) in in_loop):
                continue
            if dotted_name(node.func) not in self._SLEEPS:
                continue
            yield self.finding(
                ctx, node,
                "bare time.sleep() in a retry/dispatch loop cannot be "
                "interrupted by close()/stop(): the backoff outlives "
                "teardown; wait on the stop Event so shutdown wakes it")


# ----------------------------------------------------------------- CL016 --
@register
class RecordKeyDrift(Rule):
    """Every literal round-record key the comm/fleetsim hot paths stamp
    must be declared in analysis/metric_catalog.RECORD_KEYS — a typo'd
    key ("train_los") forks a series that sentinels, `colearn converge`,
    and the bench harness silently never match."""

    id = "CL016"
    title = "round-record key not declared in the catalog"
    hint = ("add it to RECORD_KEYS in analysis/metric_catalog.py "
            "(or fix the typo)")

    # The hot-path files whose rec/out dicts ARE round records.  Other
    # comm files use `out` for wire headers etc. — out of scope.
    _FILES = {"coordinator.py", "async_coordinator.py", "sim.py"}
    _RECORD_NAMES = {"rec", "out", "record"}

    def _is_record(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Name)
                and node.id in self._RECORD_NAMES)

    def _check_key(self, ctx, node, key) -> Iterator[Finding]:
        if isinstance(key, str) and not metric_catalog.is_known_record_key(
                key):
            yield self.finding(
                ctx, node,
                f"record key {key!r} is not in RECORD_KEYS")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not (ctx.in_dir("comm") or ctx.in_dir("fleetsim")):
            return
        if ctx.parts[-1] not in self._FILES:
            return
        for node in ast.walk(ctx.tree):
            # rec["key"] = ... / out["key"] = ...
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Subscript)
                            and self._is_record(tgt.value)
                            and isinstance(tgt.slice, ast.Constant)):
                        yield from self._check_key(
                            ctx, node, tgt.slice.value)
                    # rec = {"key": ...} / out = {...}
                    if self._is_record(tgt) and isinstance(
                            node.value, ast.Dict):
                        for k in node.value.keys:
                            if isinstance(k, ast.Constant):
                                yield from self._check_key(
                                    ctx, node, k.value)
            # rec.update(key=..., ...) / out.update({"key": ...})
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "update"
                    and self._is_record(node.func.value)):
                for kw in node.keywords:
                    if kw.arg is not None:       # **expr stays unvalidated
                        yield from self._check_key(ctx, node, kw.arg)
                for arg in node.args:
                    if isinstance(arg, ast.Dict):
                        for k in arg.keys:
                            if isinstance(k, ast.Constant):
                                yield from self._check_key(
                                    ctx, node, k.value)


# ------------------------------------------------------- CL017–CL021 ------
# Concurrency family.  All five share the per-class lock index built by
# analysis.lock_regions and are scoped to the threaded planes: comm/,
# telemetry/, faults/.

_CONCURRENCY_DIRS = ("comm", "telemetry", "faults")


def _concurrency_scope(ctx: FileContext) -> bool:
    return any(ctx.in_dir(d) for d in _CONCURRENCY_DIRS)


# ----------------------------------------------------------------- CL017 --
@register
class GuardedByInference(Rule):
    """An attribute consistently touched under one lock but read/written
    bare on a thread-reachable path is a data race waiting for a chaos
    soak to find it — flag it now, statically."""

    id = "CL017"
    title = "unguarded access to a lock-guarded attribute"
    hint = ("acquire the guarding lock around the access, or pin the "
            "contract with `# colearn: guarded-by(_lock)` / a reasoned "
            "noqa citing a witness-clean soak")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _concurrency_scope(ctx):
            return
        for idx in lock_regions.class_indexes(ctx):
            if not idx.locks:
                continue
            guards = idx.inferred_guards()
            reachable = idx.reachable_methods()
            for acc in idx.accesses:
                attr_guards = guards.get(acc.attr)
                if not attr_guards or acc.method == "__init__":
                    continue
                if acc.held & attr_guards:
                    continue
                if acc.method not in reachable:
                    continue
                locks = "/".join(sorted(attr_guards))
                yield self.finding(
                    ctx, acc.node,
                    f"{idx.name}.{acc.attr} is guarded by {locks} "
                    f"elsewhere but {acc.kind} without it in "
                    f"thread-reachable `{acc.method}`")


# ----------------------------------------------------------------- CL018 --
@register
class LockOrderCycle(Rule):
    """Two threads acquiring the same locks in opposite orders deadlock;
    the acquire-while-holding graph must stay a DAG."""

    id = "CL018"
    title = "lock-order cycle (deadlock potential)"
    hint = ("break the cycle: always acquire these locks in one global "
            "order, or narrow one critical section so the nesting "
            "disappears")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _concurrency_scope(ctx):
            return
        for idx in lock_regions.class_indexes(ctx):
            for cycle in idx.cycles():
                ring = " -> ".join(cycle + [cycle[0]])
                first_edge = (cycle[0], cycle[1 % len(cycle)])
                site = idx.edge_sites.get(
                    first_edge) or idx.classdef
                yield self.finding(
                    ctx, site,
                    f"{idx.name} acquires locks in a cycle: {ring}")


# ----------------------------------------------------------------- CL019 --
@register
class BlockingWhileHolding(Rule):
    """Sleeping, socket I/O, or broker RPC inside a critical section
    stalls every thread contending for the lock (and turned a lock into
    a convoy in the async plane more than once)."""

    id = "CL019"
    title = "blocking call while holding a lock"
    hint = ("move the blocking call outside the `with self._lock:` "
            "block — snapshot state under the lock, do I/O bare, merge "
            "results back under the lock")

    _BLOCKING_TAILS = {
        "sleep", "recv", "recv_into", "recvfrom", "send", "sendall",
        "sendto", "accept", "connect", "create_connection", "request",
        "publish", "subscribe", "select", "acquire", "wait",
        "fetch_aggregators",
    }
    _BLOCKING_CTORS = {"BrokerClient", "TensorClient", "TensorServer"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _concurrency_scope(ctx):
            return
        for idx in lock_regions.class_indexes(ctx):
            if not idx.locks:
                continue
            for node in ast.walk(idx.classdef):
                if not isinstance(node, ast.Call):
                    continue
                held = idx.held_at(node)
                if not held:
                    continue
                func = node.func
                tail = (func.attr if isinstance(func, ast.Attribute)
                        else func.id if isinstance(func, ast.Name)
                        else "")
                if tail == "wait":
                    # waiting on the very condition you hold is the CV
                    # protocol (CL020 checks the predicate loop).
                    recv = lock_regions.self_attr(
                        func.value) if isinstance(
                            func, ast.Attribute) else None
                    if recv is not None and recv in held:
                        continue
                if tail in self._BLOCKING_TAILS or (
                        isinstance(func, ast.Name)
                        and func.id in self._BLOCKING_CTORS):
                    locks = "/".join(sorted(held))
                    what = tail or getattr(func, "id", "call")
                    yield self.finding(
                        ctx, node,
                        f"{idx.name} calls blocking `{what}` while "
                        f"holding {locks}")


# ----------------------------------------------------------------- CL020 --
@register
class CvWaitWithoutPredicateLoop(Rule):
    """`Condition.wait` wakes spuriously and after stolen wakeups; a
    wait that is not re-checked in a `while` loop acts on stale state."""

    id = "CL020"
    title = "Condition.wait outside a predicate loop"
    hint = ("wrap the wait: `while not predicate: cv.wait(timeout)` "
            "(or use cv.wait_for(predicate, timeout))")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _concurrency_scope(ctx):
            return
        for idx in lock_regions.class_indexes(ctx):
            if not idx.conditions:
                continue
            for name, fn in idx.methods.items():
                yield from self._scan(ctx, idx, fn, in_while=False)

    def _scan(self, ctx, idx, node, in_while) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # nested body runs elsewhere: loop context does not carry
                yield from self._scan(ctx, idx, child, in_while=False)
                continue
            inner = in_while or isinstance(child, ast.While)
            if (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr == "wait"):
                recv = lock_regions.self_attr(child.func.value)
                if recv in idx.conditions and not in_while:
                    yield self.finding(
                        ctx, child,
                        f"{idx.name}.{recv}.wait() outside a `while` "
                        f"predicate loop")
            yield from self._scan(ctx, idx, child, inner)


# ----------------------------------------------------------------- CL021 --
@register
class UnlockedIteration(Rule):
    """Iterating a shared dict/list/set while another thread mutates it
    raises `RuntimeError: changed size during iteration` — or worse,
    silently skips entries."""

    id = "CL021"
    title = "iteration over a guarded collection without its lock"
    hint = ("hold the guard while iterating, or snapshot first "
            "(`list(self._x.items())` under the lock, iterate the copy)")

    _VIEW_TAILS = {"items", "keys", "values"}

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _concurrency_scope(ctx):
            return
        for idx in lock_regions.class_indexes(ctx):
            if not idx.locks or not idx.collections:
                continue
            guards = idx.inferred_guards()
            shared = {a: g for a, g in guards.items()
                      if a in idx.collections}
            if not shared:
                continue
            for node in ast.walk(idx.classdef):
                iters = self._iter_exprs(node)
                for expr in iters:
                    attr = self._iterated_attr(expr)
                    if attr is None or attr not in shared:
                        continue
                    if idx.held_at(node) & shared[attr]:
                        continue
                    locks = "/".join(sorted(shared[attr]))
                    yield self.finding(
                        ctx, expr,
                        f"{idx.name}.{attr} iterated without {locks}")

    @staticmethod
    def _iter_exprs(node: ast.AST) -> list:
        if isinstance(node, ast.For):
            return [node.iter]
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            return [gen.iter for gen in node.generators]
        return []

    def _iterated_attr(self, expr: ast.AST) -> Optional[str]:
        """``self._x`` or ``self._x.items()/keys()/values()`` — a
        `list(...)`/`sorted(...)` wrapper counts as a snapshot and is
        not reported (it still races in theory, but is the conventional
        copy idiom and completes in one pass)."""
        attr = lock_regions.self_attr(expr)
        if attr is not None:
            return attr
        if (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr in self._VIEW_TAILS):
            return lock_regions.self_attr(expr.func.value)
        return None


# ----------------------------------------------------------------- CL023 --
@register
class NonDurableCheckpointWrite(NonAtomicExchangeWrite):
    """CL008 keeps exchange READERS from seeing torn files (tmp +
    ``os.replace``); the durable-state plane — ckpt/ generations and the
    fed/offline.py exchange root — must also survive POWER LOSS.  A
    rename without an fsync can reach the directory before the data
    blocks do, so a crash leaves a complete-looking file of stale or
    zero bytes that passes every existence check and fails on read.
    Every durable write must fsync the temp file BEFORE the rename (the
    ckpt/streaming._atomic_write / utils.serialization.
    atomic_save_pytree_npz discipline)."""

    id = "CL023"
    title = "durable-state write without fsync-before-rename"
    hint = ("route the write through an atomic helper (ckpt/streaming."
            "_atomic_write, utils.serialization.atomic_save_pytree_npz) "
            "or add os.fsync before the os.replace in the same function; "
            "mark a justified non-durable write with "
            "`# colearn: noqa(CL023)`")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        in_offline = ctx.in_dir("fed") and ctx.parts[-1] == "offline.py"
        if not (ctx.in_dir("ckpt") or in_offline):
            return
        enclosing = _enclosing_functions(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            writer = self._is_writer(node)
            if writer is None:
                continue
            if self._durable(enclosing.get(id(node), ())):
                continue
            yield self.finding(
                ctx, node,
                f"{writer} writes durable state without tmp + fsync + "
                "os.replace: a crash can surface a torn — or "
                "complete-looking but stale — file")

    @staticmethod
    def _durable(fns: tuple) -> bool:
        """True when an enclosing function either performs the full
        fsync-then-replace dance itself or hands the bytes to an
        ``*atomic*``-named helper that owns it."""
        for fn in fns:
            replaced = synced = False
            for inner in ast.walk(fn):
                if not isinstance(inner, ast.Call):
                    continue
                dotted = dotted_name(inner.func)
                if "atomic" in dotted.rsplit(".", 1)[-1]:
                    return True
                if dotted == "os.replace":
                    replaced = True
                elif dotted == "os.fsync":
                    synced = True
            if replaced and synced:
                return True
        return False
